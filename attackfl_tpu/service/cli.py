"""``attackfl-tpu serve`` (the daemon) and ``attackfl-tpu job`` (the
jax-free client).

``serve`` promotes the CLI into the persistent run service: it reads the
config's ``service:`` section for defaults (every flag overrides), binds
the control plane (``--port 0`` = ephemeral, the ACTUAL port is printed
and published in ``<spool>/service.json``), replays the queue (crash
recovery), and then serves until SIGTERM/SIGINT — which triggers the
graceful drain: in-flight rounds finish, unfinished jobs are requeued
for the next daemon, and the process exits 0.

``job`` talks to a live service over HTTP (or reads the spool's
discovery file to find it) without importing jax: ``submit`` posts a
config (YAML file or the service's base config) and prints the job id,
``list``/``status`` render the queue, ``cancel`` stops a job at the next
round boundary, ``wait`` polls until a terminal state (the smoke
script's building block).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
import urllib.error
import urllib.request

from attackfl_tpu.telemetry import print_with_color

TERMINAL_STATES = ("done", "failed", "cancelled")


def serve_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="attackfl-tpu serve",
        description="Persistent run service: durable job queue + "
                    "supervised workers + HTTP control plane.")
    parser.add_argument("--spool", type=str, default=None,
                        help="spool directory (queue + per-job dirs + "
                             "shared ledger + service events); default: "
                             "service.spool-dir from --config, else "
                             "./service-spool")
    parser.add_argument("--config", type=str, default=None,
                        help="base config.yaml: its service: section "
                             "seeds the flags below; its other sections "
                             "are the default job config for submissions "
                             "that send none")
    parser.add_argument("--port", type=int, default=None,
                        help="control-plane port (0 = ephemeral; the "
                             "actual port is printed and written to "
                             "<spool>/service.json)")
    parser.add_argument("--host", type=str, default=None)
    parser.add_argument("--max-workers", type=int, default=None,
                        help="max concurrent runs (admission control)")
    parser.add_argument("--queue-depth", type=int, default=None,
                        help="max queued+running jobs; submission beyond "
                             "this is an explicit 429 rejection")
    parser.add_argument("--worker-retries", type=int, default=None,
                        help="restarts (with exponential backoff) before "
                             "a crashing job is marked failed")
    parser.add_argument("--worker-backoff", type=float, default=None,
                        metavar="SECONDS", help="backoff base (doubles "
                        "per restart, capped)")
    parser.add_argument("--inject-faults", type=str, default=None,
                        metavar="PLAN",
                        help="service chaos plan (kinds: worker_death "
                             "queue_torn submit_flood preempt_storm "
                             "estimate_skew; same grammar as "
                             "run --inject-faults)")
    parser.add_argument("--no-run-monitors", action="store_true",
                        help="skip the per-run monitor (stall watchdog + "
                             "per-run /metrics on ephemeral ports)")
    parser.add_argument("--compile-cache", type=str, default=None,
                        metavar="DIR", help="persistent compile cache "
                        "shared by every worker (ATTACKFL_COMPILE_CACHE "
                        "also works)")
    parser.add_argument("--drain-grace", type=float, default=None,
                        metavar="SECONDS",
                        help="SIGTERM: how long the drain waits for "
                             "in-flight rounds before exiting anyway "
                             "(the next daemon's replay recovers)")
    parser.add_argument("--no-scheduler", action="store_true",
                        help="disable the preemptive scheduler: restore "
                             "the oldest-first dispatch loop")
    parser.add_argument("--aging-rate", type=float, default=None,
                        metavar="PTS_PER_S",
                        help="scheduler aging: effective-priority points "
                             "per waiting second (starvation bound "
                             "scales as 1/rate)")
    parser.add_argument("--shed-horizon", type=float, default=None,
                        metavar="SECONDS",
                        help="shed submissions whose predicted backlog "
                             "exceeds this (429 + priced retry-after); "
                             "0 = never shed")
    parser.add_argument("--once", action="store_true",
                        help="exit once the queue is empty and idle "
                             "(batch mode / smoke tests) instead of "
                             "serving forever")
    args = parser.parse_args(argv)

    from attackfl_tpu.config import Config, load_config

    base_raw: dict = {}
    if args.config:
        import yaml

        with open(args.config) as fh:
            base_raw = yaml.safe_load(fh) or {}
        cfg = load_config(args.config)
    else:
        cfg = Config()
    svc = cfg.service
    spool = args.spool or svc.spool_dir or "./service-spool"
    drain_grace = (svc.drain_grace_seconds if args.drain_grace is None
                   else args.drain_grace)
    fault_plan = ()
    if args.inject_faults is not None:
        from attackfl_tpu.faults.plan import parse_fault_plan

        fault_plan = parse_fault_plan(args.inject_faults)

    from attackfl_tpu.service.daemon import RunService

    service = RunService(
        spool,
        port=svc.port if args.port is None else args.port,
        host=args.host or svc.host,
        max_workers=(svc.max_workers if args.max_workers is None
                     else args.max_workers),
        queue_depth=(svc.queue_depth if args.queue_depth is None
                     else args.queue_depth),
        worker_retries=(svc.worker_retries if args.worker_retries is None
                        else args.worker_retries),
        worker_backoff=(svc.worker_backoff if args.worker_backoff is None
                        else args.worker_backoff),
        worker_backoff_cap=svc.worker_backoff_cap,
        run_monitors=svc.run_monitors and not args.no_run_monitors,
        fault_plan=fault_plan,
        compile_cache_dir=(args.compile_cache
                           or os.environ.get("ATTACKFL_COMPILE_CACHE")
                           or cfg.compile_cache_dir),
        base_config=base_raw,
        scheduler=svc.scheduler and not args.no_scheduler,
        sched_aging_rate=(svc.sched_aging_rate if args.aging_rate is None
                          else args.aging_rate),
        sched_min_runtime=svc.sched_min_runtime,
        sched_shed_horizon=(svc.sched_shed_horizon
                            if args.shed_horizon is None
                            else args.shed_horizon),
        sched_breaker_attempts=svc.sched_breaker_attempts,
        sched_default_cost=svc.sched_default_cost,
    )
    service.start()
    print_with_color(
        f"[serve] http://localhost:{service.port} "
        "(/healthz /jobs /submit /cancel /metrics /runs /schedule) — "
        f"spool {spool} — submit with `attackfl-tpu job submit`", "cyan")

    draining = {"flag": False}

    def on_signal(signum, frame):
        # SIGTERM/SIGINT: graceful drain — finish in-flight rounds,
        # checkpoint, requeue, exit (kill -9 is the replay's job)
        draining["flag"] = True

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    try:
        while not draining["flag"]:
            if args.once and service_idle(service):
                break
            time.sleep(0.2)
    finally:
        if draining["flag"]:
            print_with_color(
                "[serve] drain requested: finishing in-flight rounds, "
                "requeueing the rest", "yellow")
            service.drain(timeout=drain_grace)
        service.close()
    return 0


def service_idle(service) -> bool:
    """True when nothing is running and nothing is claimable."""
    code, payload = service.health()
    jobs = payload.get("jobs", {})
    return (payload.get("active_runs", 0) == 0
            and jobs.get("queued", 0) == 0
            and jobs.get("running", 0) == 0)


# ---------------------------------------------------------------------------
# job client (jax-free)
# ---------------------------------------------------------------------------


def _discover_url(args) -> str:
    if args.url:
        return args.url.rstrip("/")
    if args.spool:
        path = os.path.join(args.spool, "service.json")
        try:
            with open(path) as fh:
                return str(json.load(fh)["url"]).rstrip("/")
        except (OSError, ValueError, KeyError):
            raise SystemExit(
                f"no service discovery file at {path}; is the daemon "
                "running? (pass --url explicitly otherwise)")
    return "http://127.0.0.1:8781"


def _request(url: str, method: str = "GET", body: dict | None = None,
             timeout: float = 10.0) -> tuple[int, dict]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read().decode() or "{}")
        except ValueError:
            return e.code, {"error": f"http {e.code}"}


def job_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="attackfl-tpu job",
        description="Run-service client: submit/list/status/cancel/wait "
                    "(jax-free; talks HTTP to a live `attackfl-tpu "
                    "serve`).")
    parser.add_argument("command",
                        choices=["submit", "list", "status", "cancel",
                                 "wait"])
    parser.add_argument("job_id", nargs="?", default=None,
                        help="job id (status/cancel/wait)")
    parser.add_argument("--url", type=str, default=None,
                        help="service base URL (printed at serve start)")
    parser.add_argument("--spool", type=str, default=None,
                        help="spool dir: reads <spool>/service.json for "
                             "the URL instead of --url")
    parser.add_argument("--config", type=str, default=None,
                        help="submit: job config.yaml (omitted = the "
                             "service's base config)")
    parser.add_argument("--rounds", type=int, default=None,
                        help="submit: round-count override")
    parser.add_argument("--name", type=str, default=None,
                        help="submit: human-readable job label")
    parser.add_argument("--priority", type=str, default=None,
                        choices=["high", "normal", "low"],
                        help="submit: scheduler priority class "
                             "(default normal)")
    parser.add_argument("--timeout", type=int, default=600,
                        help="wait: seconds before giving up (exit 3)")
    parser.add_argument("--interval", type=float, default=0.5,
                        help="wait: poll period in seconds")
    args = parser.parse_args(argv)
    base = _discover_url(args)

    if args.command == "submit":
        spec: dict = {}
        if args.config:
            import yaml

            with open(args.config) as fh:
                spec["config"] = yaml.safe_load(fh) or {}
        if args.rounds is not None:
            spec["num_rounds"] = args.rounds
        if args.name:
            spec["name"] = args.name
        if args.priority:
            spec["priority"] = args.priority
        code, payload = _request(base + "/submit", "POST", spec)
        if code != 200:
            retry = payload.get("retry_after_seconds")
            hint = f" (retry in ~{retry}s)" if retry is not None else ""
            print(f"submit rejected ({code}): {payload.get('error')}{hint}",
                  file=sys.stderr)
            return 1
        print(payload["job_id"])
        return 0

    if args.command == "list":
        code, payload = _request(base + "/jobs")
        for job in payload.get("jobs", []):
            rounds = job.get("num_rounds") or "-"
            print(f"{job['job_id']}  {job['state']:<9}  rounds={rounds}  "
                  f"attempts={job.get('attempts', 0)}  "
                  f"{job.get('name', '')}".rstrip())
        return 0

    if args.job_id is None:
        print(f"{args.command} needs a job id", file=sys.stderr)
        return 2

    if args.command == "status":
        code, payload = _request(base + f"/status?job={args.job_id}")
        print(json.dumps(payload, indent=1))
        return 0 if code == 200 else 1

    if args.command == "cancel":
        code, payload = _request(base + f"/cancel?job={args.job_id}",
                                 "POST")
        print(json.dumps(payload))
        return 0 if code == 200 else 1

    # wait: poll until terminal (exit 0 done / 1 failed-cancelled /
    # 2 unknown job / 3 timeout)
    deadline = time.monotonic() + args.timeout
    interval = args.interval
    while True:
        code, payload = _request(base + f"/status?job={args.job_id}")
        if code == 404:
            print(payload.get("error", "no such job"), file=sys.stderr)
            return 2
        state = payload.get("state")
        if state in TERMINAL_STATES:
            print(json.dumps(payload, indent=1))
            return 0 if state == "done" else 1
        if time.monotonic() > deadline:
            print(f"timed out waiting for {args.job_id} "
                  f"(state {state})", file=sys.stderr)
            return 3
        time.sleep(min(max(interval, 0.05), 5))
