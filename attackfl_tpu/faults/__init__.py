"""Seeded, plan-driven fault injection + the recovery layer that
survives it (ISSUE 6 tentpole).

``plan`` declares *what* fails and *when* (a :class:`FaultSpec` per
failure, scheduled on the deterministic broadcast/round clocks so chaos
runs replay bit-identically); ``inject`` implements *how*: device-side
builders that compile NaN storms and forced-dropout cohorts into the
jitted round program through the existing ok-flag path, plus the
:class:`HostFaultInjector` the checkpoint/monitor layers consult for
write errors, torn files, writer-thread death and watchdog stalls.

Everything here only ever makes things fail — the recovery machinery it
exercises (manifest checkpoints with torn-file fallback, the async-writer
supervisor, retry-with-backoff, pipelined-executor demotion) lives with
the subsystems it hardens (``utils/checkpoint.py``,
``training/engine.py``) and runs whether or not a fault plan is loaded.
"""

from attackfl_tpu.faults.plan import (  # noqa: F401
    DEVICE_FAULT_KINDS,
    FAULT_KINDS,
    HOST_FAULT_KINDS,
    FaultSpec,
    faults_from_config,
    parse_fault_plan,
)
