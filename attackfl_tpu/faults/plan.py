"""Deterministic fault plans: what fails, when, and at whom.

A plan is a tuple of :class:`FaultSpec` entries living on
``Config.faults`` (YAML ``faults:`` section or the ``--inject-faults``
CLI flag).  Every spec is pinned to a clock the simulation already
carries — the broadcast counter for device-side faults (it advances on
retries, so a stormed broadcast fails once and the retry runs clean) and
the completed-round counter for host-side persistence faults — which
makes chaos runs replayable: the same config + plan produces the same
failures at the same points, bit for bit.

Kinds
-----
Device-side (compiled into the jitted round program; identical on the
synchronous, fused and pipelined executors):

* ``nan_storm`` — overwrite the selected clients' post-training deltas
  with non-finite values and clear their ok flags, riding the existing
  ok-flag path: training fails, the genuine-leak pool keeps the previous
  round, the round retries.
* ``dropout`` — force the selected clients to drop this broadcast
  (round size 0, all batches masked): the deterministic seed of the
  ROADMAP client-sampling axis.  Selecting every client fails the round
  (no reporters), like the probabilistic straggler path.

Host-side (consulted by the checkpoint/monitor layers through
:class:`~attackfl_tpu.faults.inject.HostFaultInjector`):

* ``ckpt_write_error`` — the next ``count`` checkpoint write attempts at
  or after the given round raise ``OSError`` (exercises bounded
  retry-with-backoff, then the fail-open path).
* ``ckpt_torn`` — truncate the round's checkpoint entry right after it
  was durably recorded (a torn file whose manifest hash no longer
  matches; resume must detect it and fall back to the previous entry).
* ``writer_death`` — kill the async checkpoint writer thread before the
  round's submit (the supervisor must restart it).
* ``monitor_stall`` — rewind the live monitor's heartbeat past the stall
  threshold so the watchdog deterministically fires.

Service-side (ISSUE 8 — consulted by :mod:`attackfl_tpu.service` through
the same :class:`~attackfl_tpu.faults.inject.HostFaultInjector`, so every
run-service recovery path is deterministically chaos-testable):

* ``worker_death`` — the worker executing a run raises once its job
  reaches ``round`` completed rounds (the per-round stop hook is the
  seam): the service must restart it with bounded backoff and the
  restarted attempt must resume from the newest valid checkpoint.
* ``queue_torn`` — truncate the job queue's ``round``-th status publish
  right after it lands (a torn spool entry whose seal no longer
  verifies); queue replay must detect it and requeue the job instead of
  trusting — or silently dropping — the entry.
* ``submit_flood`` — on the ``round``-th submission, inject ``count``
  duplicate submissions: admission control must reject the overflow
  explicitly (a ``job`` event per rejection), never drop it silently.

Scheduler-side (ISSUE 15 — consulted by :mod:`attackfl_tpu.scheduler`):

* ``preempt_storm`` — on the first scheduler tick at or after ``round``
  that has running jobs, force-preempt up to ``count`` of them (healthy
  jobs, no priority justification): every victim must checkpoint at its
  safe seam, requeue, and later resume byte-identical — the chaos gate
  kills the daemon mid-storm on top of this;
* ``estimate_skew`` — from the ``round``-th pricing call onward,
  multiply every cost-model price by ``count``: packing and shed
  decisions must stay explicit and the service functional when the
  estimates are badly wrong (the 2x contract's failure mode, amplified).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

DEVICE_FAULT_KINDS = ("nan_storm", "dropout")
HOST_FAULT_KINDS = (
    "ckpt_write_error", "ckpt_torn", "writer_death", "monitor_stall",
)
SERVICE_FAULT_KINDS = ("worker_death", "queue_torn", "submit_flood")
SCHEDULER_FAULT_KINDS = ("preempt_storm", "estimate_skew")
FAULT_KINDS = (DEVICE_FAULT_KINDS + HOST_FAULT_KINDS + SERVICE_FAULT_KINDS
               + SCHEDULER_FAULT_KINDS)


@dataclass(frozen=True)
class FaultSpec:
    """One scheduled failure.

    ``round`` is 1-based: the broadcast number for device-side kinds (the
    clock attacks already key on), the completed-round number for
    host-side kinds (the clock checkpoints key on), and the service's own
    deterministic counters for service-side kinds (a job's completed
    rounds for ``worker_death``, the n-th status publish for
    ``queue_torn``, the n-th submission for ``submit_flood``).
    ``clients`` selects the target cohort for device-side kinds (empty =
    every client); ``count`` is how many consecutive write attempts fail
    for ``ckpt_write_error``, how many duplicate submissions a
    ``submit_flood`` injects, how many running jobs a ``preempt_storm``
    force-preempts (scheduler tick clock), and the price multiplier an
    ``estimate_skew`` applies (pricing-call clock).
    """

    kind: str
    round: int
    clients: tuple[int, ...] = ()
    count: int = 1

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"Unknown fault kind {self.kind!r}; choose from {FAULT_KINDS}")
        if self.round < 1:
            raise ValueError(
                f"fault round must be >= 1 (1-based clock), got {self.round}")
        if self.count < 1:
            raise ValueError(f"fault count must be >= 1, got {self.count}")
        if self.clients and self.kind not in DEVICE_FAULT_KINDS:
            raise ValueError(
                f"fault kind {self.kind!r} takes no client cohort")
        object.__setattr__(
            self, "clients", tuple(int(c) for c in self.clients))

    def describe(self) -> dict[str, Any]:
        """JSON-ready record for ``fault`` events / the run header."""
        out: dict[str, Any] = {"fault": self.kind, "round": self.round}
        if self.clients:
            out["clients"] = list(self.clients)
        if self.kind in ("ckpt_write_error", "submit_flood",
                         "preempt_storm", "estimate_skew"):
            out["count"] = self.count
        return out


def parse_fault_plan(spec: str) -> tuple[FaultSpec, ...]:
    """Parse the ``--inject-faults`` CLI grammar.

    ``kind@round[:key=value]...`` entries separated by ``;``, e.g.::

        nan_storm@3:clients=0,1;ckpt_write_error@2:count=2;writer_death@4

    ``clients`` is a comma-separated index list; unknown keys and
    malformed entries raise ``ValueError`` (a typo'd chaos plan must not
    silently run fault-free).
    """
    specs: list[FaultSpec] = []
    for raw_entry in spec.split(";"):
        entry = raw_entry.strip()
        if not entry:
            continue
        head, *opts = entry.split(":")
        kind, sep, round_text = head.partition("@")
        if not sep:
            raise ValueError(
                f"fault entry {entry!r} needs 'kind@round' (e.g. "
                "'nan_storm@3')")
        try:
            round_no = int(round_text)
        except ValueError:
            raise ValueError(
                f"fault entry {entry!r}: round {round_text!r} is not an "
                "integer") from None
        kwargs: dict[str, Any] = {}
        for opt in opts:
            key, sep, value = opt.partition("=")
            if not sep:
                raise ValueError(
                    f"fault entry {entry!r}: option {opt!r} needs key=value")
            key = key.strip()
            if key == "clients":
                kwargs["clients"] = tuple(
                    int(c) for c in value.split(",") if c.strip())
            elif key == "count":
                kwargs["count"] = int(value)
            else:
                raise ValueError(
                    f"fault entry {entry!r}: unknown option {key!r} "
                    "(have: clients, count)")
        specs.append(FaultSpec(kind=kind.strip(), round=round_no, **kwargs))
    return tuple(specs)


def faults_from_config(raw: Sequence[Any]) -> tuple[FaultSpec, ...]:
    """Build a plan from the YAML ``faults:`` section — a list of
    ``{kind, round, clients?, count?}`` mappings."""
    specs: list[FaultSpec] = []
    for item in raw or []:
        if not isinstance(item, dict):
            raise ValueError(
                f"faults: entries must be mappings, got {item!r}")
        unknown = set(item) - {"kind", "round", "clients", "count"}
        if unknown:
            raise ValueError(
                f"faults: entry has unknown key(s) {sorted(unknown)}")
        specs.append(FaultSpec(
            kind=str(item.get("kind", "")),
            round=int(item.get("round", 0)),
            clients=tuple(int(c) for c in item.get("clients", []) or []),
            count=int(item.get("count", 1)),
        ))
    return tuple(specs)


def device_specs(plan: Sequence[FaultSpec], kind: str) -> list[FaultSpec]:
    """The plan's entries of one device-side kind."""
    if kind not in DEVICE_FAULT_KINDS:
        raise ValueError(f"{kind!r} is not a device-side fault kind")
    return [s for s in plan if s.kind == kind]
