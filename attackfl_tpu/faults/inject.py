"""Fault injection: device-side mask builders + the host-side injector.

Device side: :func:`build_client_fault_fn` resolves a plan's
``nan_storm``/``dropout`` specs at program-BUILD time into two static
arrays (per-spec fire rounds + per-spec client masks) and returns a pure
traced function ``broadcast_number -> (C,) bool`` — the jitted round
program then carries the whole schedule as constants and a handful of
compares/selects, so the synchronous, fused and pipelined executors all
inject identically with zero host work per round.  Everything in this
file that runs under trace is sync-free (held to the host-sync lint like
the training package).

Host side: :class:`HostFaultInjector` is the single object the
checkpoint manager, the async-writer wiring, the round loops and the run
service (ISSUE 8 — worker supervision, queue publish, admission control)
consult.  It owns the consumable fault state (remaining
``ckpt_write_error`` counts, fired-once latches) and emits the schema'd
``fault`` event for every injection so a chaos run's event log is its
own ground truth.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from attackfl_tpu.faults.plan import DEVICE_FAULT_KINDS, FaultSpec, device_specs


class WorkerDeathError(RuntimeError):
    """Injected run-service worker crash (``worker_death`` fault): raised
    out of the worker's per-round stop hook so it propagates through the
    run's ``finally`` chain (checkpoint drain, run_end, ledger record)
    exactly like a real mid-run crash that Python can still observe —
    the harsher no-cleanup crash class is covered by the kill -9 chaos
    test."""


def build_client_fault_fn(
    plan: Sequence[FaultSpec], num_clients: int, kind: str
) -> Callable[[jnp.ndarray], jnp.ndarray] | None:
    """``broadcast_number -> (C,) bool`` fire mask for one device-side
    kind, or None when the plan schedules none (the round program then
    contains no injection ops at all)."""
    specs = device_specs(plan, kind)
    if not specs:
        return None
    rounds = np.zeros((len(specs),), np.int32)
    masks = np.zeros((len(specs), num_clients), bool)
    for i, spec in enumerate(specs):
        rounds[i] = spec.round
        if spec.clients:
            for cid in spec.clients:
                if not 0 <= cid < num_clients:
                    raise ValueError(
                        f"fault {kind}@{spec.round}: client {cid} out of "
                        f"range [0, {num_clients})")
                masks[i, cid] = True
        else:
            masks[i, :] = True  # empty cohort = every client
    rounds_arr = jnp.asarray(rounds)
    masks_arr = jnp.asarray(masks)

    def fire_mask(broadcast_number: jnp.ndarray) -> jnp.ndarray:
        hit = broadcast_number == rounds_arr  # (k,)
        return jnp.any(hit[:, None] & masks_arr, axis=0)  # (C,)

    return fire_mask


def apply_nan_storm(storm: jnp.ndarray, stacked: Any, ok: jnp.ndarray
                    ) -> tuple[Any, jnp.ndarray]:
    """Overwrite stormed clients' stacked deltas with NaN and clear their
    ok flags — the same per-client failure shape a genuinely diverging
    client produces, so every downstream guard (train_ok, leak-pool
    select, accept-select rollback, non-finite numerics provenance) is
    exercised through its existing path."""

    def poison(x):
        if not jnp.issubdtype(x.dtype, jnp.inexact):
            return x  # integer leaves (none today) cannot hold NaN
        sel = storm.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.where(sel, jnp.asarray(jnp.nan, x.dtype), x)

    return jax.tree.map(poison, stacked), ok & ~storm


class HostFaultInjector:
    """Plan-driven host-side failures, consulted at the persistence and
    monitoring seams.

    Construction is cheap and side-effect free; each ``maybe_*`` method
    is a no-op unless the plan armed that kind for the given round.
    Injections fire exactly once per (kind, round) — except
    ``ckpt_write_error``, which fails ``count`` consecutive attempts —
    and every firing emits a ``fault`` event (``action="injected"``)
    plus a ``faults_injected`` counter bump.  Methods may be called from
    the async writer thread; the event log is lock-serialized and the
    consumable state is only ever touched under the caller's
    single-writer discipline.
    """

    def __init__(self, plan: Sequence[FaultSpec], telemetry):
        self._tel = telemetry
        self._plan = tuple(plan)
        self._write_errors: dict[int, int] = {}
        for spec in self._plan:
            if spec.kind == "ckpt_write_error":
                self._write_errors[spec.round] = spec.count
        self._fired: set[tuple[str, int]] = set()
        self._device_noted: set[tuple[str, int]] = set()

    def describe(self) -> list[dict[str, Any]]:
        """JSON-ready plan for the telemetry run header."""
        return [spec.describe() for spec in self._plan]

    def _specs(self, kind: str, round_no: int) -> list[FaultSpec]:
        return [s for s in self._plan
                if s.kind == kind and s.round == round_no]

    def _emit(self, kind: str, round_no: int, **details: Any) -> None:
        self._tel.counters.inc("faults_injected")
        self._tel.events.emit("fault", fault=kind, action="injected",
                              round=round_no, **details)

    # ---- device-side bookkeeping ------------------------------------
    def note_round_resolved(self, broadcast_number: int) -> None:
        """Record device-side injections once their round resolves on
        host.  The injection itself already happened inside the jitted
        program; this writes the plan's ground truth next to the round
        event so forensics never has to re-derive the schedule."""
        for kind in DEVICE_FAULT_KINDS:
            for spec in self._specs(kind, broadcast_number):
                key = (kind, broadcast_number)
                if key in self._device_noted:
                    continue
                self._device_noted.add(key)
                self._emit(kind, broadcast_number,
                           clients=list(spec.clients), device_side=True)

    # ---- checkpoint seams -------------------------------------------
    def on_checkpoint_write(self, round_no: int) -> None:
        """Called at the top of every checkpoint write ATTEMPT (inside
        the manager's retry loop).  Raises OSError while the armed
        ``ckpt_write_error`` budget for this round lasts."""
        for armed_round, remaining in list(self._write_errors.items()):
            if round_no >= armed_round and remaining > 0:
                self._write_errors[armed_round] = remaining - 1
                self._emit("ckpt_write_error", round_no,
                           remaining=remaining - 1)
                raise OSError(
                    f"injected checkpoint write error (fault plan, "
                    f"round {round_no})")

    def after_checkpoint_write(self, round_no: int, entry_path: str) -> None:
        """Called after a round's entry file is durably recorded.  A
        ``ckpt_torn`` spec truncates the file to half its bytes — the
        manifest keeps the full-content hash, so loads must reject the
        entry and fall back."""
        for _spec in self._specs("ckpt_torn", round_no):
            key = ("ckpt_torn", round_no)
            if key in self._fired:
                continue
            self._fired.add(key)
            try:
                import os

                size = os.path.getsize(entry_path)
                with open(entry_path, "r+b") as fh:
                    fh.truncate(max(size // 2, 1))
            except OSError:
                continue  # nothing to tear (write itself failed)
            self._emit("ckpt_torn", round_no, path=entry_path,
                       truncated_to=max(size // 2, 1), original_bytes=size)

    def maybe_kill_writer(self, round_no: int, writer) -> None:
        """Kill the async checkpoint writer thread when armed (the
        supervisor inside :class:`AsyncCheckpointWriter` restarts it on
        the next submit/drain)."""
        if writer is None:
            return
        for _spec in self._specs("writer_death", round_no):
            key = ("writer_death", round_no)
            if key in self._fired:
                continue
            self._fired.add(key)
            writer.inject_thread_death()
            self._emit("writer_death", round_no)

    # ---- run-service seams (ISSUE 8) --------------------------------
    def maybe_worker_death(self, completed_rounds: int) -> None:
        """Called from the service worker's per-round stop hook.  Raises
        :class:`WorkerDeathError` once when an armed ``worker_death``
        round is reached — the worker's supervisor must catch it, back
        off, and restart the job with ``--resume`` semantics."""
        for _spec in self._specs("worker_death", completed_rounds):
            key = ("worker_death", completed_rounds)
            if key in self._fired:
                continue
            self._fired.add(key)
            self._emit("worker_death", completed_rounds)
            raise WorkerDeathError(
                f"injected worker death (fault plan, after "
                f"{completed_rounds} completed rounds)")

    def on_status_publish(self, seq: int, path: str) -> None:
        """Called after the job queue's ``seq``-th status publish landed.
        A ``queue_torn`` spec truncates the entry to half its bytes — the
        seal keeps the honest hash, so replay must reject the entry and
        requeue the job from its spec + newest checkpoint."""
        for _spec in self._specs("queue_torn", seq):
            key = ("queue_torn", seq)
            if key in self._fired:
                continue
            self._fired.add(key)
            try:
                import os

                size = os.path.getsize(path)
                with open(path, "r+b") as fh:
                    fh.truncate(max(size // 2, 1))
            except OSError:
                continue  # nothing to tear (publish itself failed)
            self._emit("queue_torn", seq, path=path,
                       truncated_to=max(size // 2, 1), original_bytes=size)

    def flood_count(self, seq: int) -> int:
        """Called at the top of the queue's ``seq``-th submission.  An
        armed ``submit_flood`` returns how many duplicate submissions to
        inject (admission control must reject the overflow explicitly);
        0 otherwise."""
        for spec in self._specs("submit_flood", seq):
            key = ("submit_flood", seq)
            if key in self._fired:
                continue
            self._fired.add(key)
            self._emit("submit_flood", seq, count=spec.count)
            return spec.count
        return 0

    # ---- scheduler seams (ISSUE 15) ---------------------------------
    def preempt_storm_count(self, tick: int) -> int:
        """Called from the scheduler's dispatch tick WHEN it has running
        jobs.  An armed ``preempt_storm`` fires once at the first such
        tick at or after its round and returns how many running jobs to
        force-preempt; 0 otherwise."""
        for spec in self._plan:
            if spec.kind != "preempt_storm" or tick < spec.round:
                continue
            key = ("preempt_storm", spec.round)
            if key in self._fired:
                continue
            self._fired.add(key)
            self._emit("preempt_storm", tick, count=spec.count)
            return spec.count
        return 0

    def estimate_skew_factor(self, seq: int) -> float:
        """Called per pricing call (``seq`` is the pricer's 1-based call
        counter).  From an armed ``estimate_skew``'s round onward every
        price is multiplied by its ``count`` — a PERSISTENT skew (a
        wrong cost model stays wrong), evented once at first effect."""
        factor = 1.0
        for spec in self._plan:
            if spec.kind != "estimate_skew" or seq < spec.round:
                continue
            key = ("estimate_skew", spec.round)
            if key not in self._fired:
                self._fired.add(key)
                self._emit("estimate_skew", seq, factor=spec.count)
            # host plan value (never a device array) — multiplying into
            # the float seed keeps this off the host-sync lint's radar
            factor *= spec.count
        return factor

    # ---- monitor seam -----------------------------------------------
    def maybe_stall_monitor(self, round_no: int, monitor) -> None:
        """Rewind the watchdog heartbeat past its threshold so the stall
        path (503 /healthz, ``stall`` event) fires deterministically."""
        if monitor is None:
            return
        for _spec in self._specs("monitor_stall", round_no):
            key = ("monitor_stall", round_no)
            if key in self._fired:
                continue
            self._fired.add(key)
            seconds = monitor.simulate_hang()
            self._emit("monitor_stall", round_no, rewound_seconds=seconds)
