"""Model-poisoning attacks as pure JAX tensor programs.

The reference implements five attacks as Python loops over state_dicts,
executed inside malicious client processes on genuine models the server
leaks to them (src/Utils.py:52-214, invoked from
RpcClient.malicious_training, src/RpcClient.py:119-145).  Here each attack
is a pure function of the *stacked* leaked genuine updates (leading axis =
leaked models), with the γ binary searches expressed as
``jax.lax.while_loop`` — fully jittable and vmap-able over many attackers.

Semantics parity notes:
* ``distance`` is the reference's ``compute_distance`` — a SUM of per-leaf
  L2 norms, not a global norm (src/Utils.py:30-49).  Pass
  ``matrix_spectral=True`` to reproduce torch's ord=2 spectral norm on 2-D
  leaves (see ops/pytree._leaf_norm).
* statistics use Bessel-corrected std (torch.std default, Utils.py:90).
* the γ loop returns the candidate from the *final iteration* whether or
  not it satisfied the constraint — exactly the reference's loop structure
  (Utils.py:118-131,152-165,190-203).
* the reference aliases genuine_models[0] and mutates it while searching
  (Utils.py:121,154,192,209 — flagged in SURVEY.md §2 as a bug); we
  evaluate candidates against the *unmodified* genuine set.  For Min-Sum
  this means distances to all k models are counted rather than k-1.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from attackfl_tpu.ops import pytree as pt

DEFAULT_RANDOM_SIGMA = 1e6  # Utils.py:52
DEFAULT_LIE_Z = 0.74  # Utils.py:207, README.md:124
DEFAULT_GAMMA = 50.0  # Utils.py:101,135,169
DEFAULT_TAU = 1.0


def random_attack(own_params: Any, rng: jax.Array, perturbation: float = DEFAULT_RANDOM_SIGMA) -> Any:
    """Add N(0, perturbation²) noise to every parameter
    (reference: create_random_base_model, Utils.py:52-57)."""
    leaves, treedef = jax.tree.flatten(own_params)
    keys = jax.random.split(rng, len(leaves))
    noisy = [
        leaf + jax.random.normal(k, leaf.shape, leaf.dtype) * perturbation
        for leaf, k in zip(leaves, keys)
    ]
    return jax.tree.unflatten(treedef, noisy)


def lie_attack(genuine_stacked: Any, z: float = DEFAULT_LIE_Z) -> Any:
    """Little-Is-Enough: per-element mean + z·std over the leaked models
    (reference: create_LIE_state_dict, Utils.py:207-214)."""
    mean = pt.tree_mean(genuine_stacked)
    std = pt.tree_std(genuine_stacked, ddof=1)
    return jax.tree.map(lambda m, s: m + z * s, mean, std)


def _gamma_search(
    genuine_stacked: Any,
    perturbation: Any,
    max_distance: jnp.ndarray,
    accepts,
    gamma0: float,
    tau: float,
):
    """Shared γ binary search (reference loop shape, Utils.py:115-131).

    ``accepts(candidate) -> bool`` checks the constraint; the candidate is
    ``mean - γ·perturbation``.  Returns the candidate of the last iteration.
    """
    mean = pt.tree_mean(genuine_stacked)

    def candidate_for(gamma):
        return jax.tree.map(lambda m, p: m - gamma * p, mean, perturbation)

    def cond(carry):
        gamma, gamma_succ, step, last_tried = carry
        return jnp.abs(gamma_succ - gamma) > tau

    def body(carry):
        gamma, gamma_succ, step, _ = carry
        ok = accepts(candidate_for(gamma), max_distance)
        new_succ = jnp.where(ok, gamma, gamma_succ)
        new_gamma = jnp.where(ok, gamma + step / 2.0, gamma - step / 2.0)
        return (new_gamma, new_succ, step / 2.0, gamma)

    init = (jnp.asarray(gamma0), jnp.asarray(0.0), jnp.asarray(gamma0), jnp.asarray(gamma0))
    _, _, _, last_tried = jax.lax.while_loop(cond, body, init)
    return candidate_for(last_tried)


def min_max_attack(
    genuine_stacked: Any,
    gamma0: float = DEFAULT_GAMMA,
    tau: float = DEFAULT_TAU,
    matrix_spectral: bool = False,
) -> Any:
    """Min-Max (Shejwalkar & Houmansadr 2021): candidate = mean − γ·std with
    the largest γ keeping max distance-to-any-genuine below the max pairwise
    genuine distance (reference: create_min_max_model, Utils.py:135-166)."""
    std = pt.tree_std(genuine_stacked, ddof=1)
    pair = pt.pairwise_ref_distance(genuine_stacked, matrix_spectral)
    max_distance = jnp.max(pair)

    def accepts(candidate, max_d):
        d = pt.distance_to_each(candidate, genuine_stacked, matrix_spectral)
        return jnp.max(d) < max_d

    return _gamma_search(genuine_stacked, std, max_distance, accepts, gamma0, tau)


def min_sum_attack(
    genuine_stacked: Any,
    gamma0: float = DEFAULT_GAMMA,
    tau: float = DEFAULT_TAU,
    matrix_spectral: bool = False,
) -> Any:
    """Min-Sum: constraint on the SUM of squared distances vs the max
    per-genuine-model sum (reference: create_min_sum_model,
    Utils.py:169-204)."""
    std = pt.tree_std(genuine_stacked, ddof=1)
    pair = pt.pairwise_ref_distance(genuine_stacked, matrix_spectral)
    # per-model sum over squared distances to the others (diag is 0)
    sums = jnp.sum(jnp.square(pair), axis=1)
    max_distance = jnp.max(sums)

    def accepts(candidate, max_d):
        d = pt.distance_to_each(candidate, genuine_stacked, matrix_spectral)
        return jnp.sum(jnp.square(d)) < max_d

    return _gamma_search(genuine_stacked, std, max_distance, accepts, gamma0, tau)


def opt_fang_attack(
    genuine_stacked: Any,
    gamma0: float = DEFAULT_GAMMA,
    tau: float = DEFAULT_TAU,
    matrix_spectral: bool = False,
) -> Any:
    """Opt-Fang (Fang et al. 2020 optimized variant): perturbation direction
    is sign(mean) under the Min-Max acceptance rule
    (reference: create_opt_fang_model, Utils.py:101-132)."""
    mean = pt.tree_mean(genuine_stacked)
    sign = jax.tree.map(jnp.sign, mean)
    pair = pt.pairwise_ref_distance(genuine_stacked, matrix_spectral)
    max_distance = jnp.max(pair)

    def accepts(candidate, max_d):
        d = pt.distance_to_each(candidate, genuine_stacked, matrix_spectral)
        return jnp.max(d) < max_d

    return _gamma_search(genuine_stacked, sign, max_distance, accepts, gamma0, tau)


def apply_attack(
    mode: str,
    own_params: Any,
    genuine_stacked: Any,
    rng: jax.Array,
    args: tuple[float, ...] = (),
    matrix_spectral: bool = False,
) -> Any:
    """Dispatch by attack-mode string (reference: RpcClient.py:119-145).

    γ-search attacks degrade to the attacker's own params when fewer than
    two genuine models were leaked (Utils.py:102,136,170); the round engine
    enforces that with a static leak count.
    """
    num_leaked = jax.tree.leaves(genuine_stacked)[0].shape[0] if genuine_stacked is not None else 0
    if mode == "none":
        # clean-baseline sentinel (ISSUE 17): never fires.  round_step
        # skips `none` groups before the leak gather, so this branch only
        # serves direct callers — the honest no-op is the attacker's own
        # (genuinely trained) params.
        return own_params
    if mode == "Random":
        sigma = args[0] if args else DEFAULT_RANDOM_SIGMA
        return random_attack(own_params, rng, sigma)
    if mode == "LIE":
        z = args[0] if args else DEFAULT_LIE_Z
        return lie_attack(genuine_stacked, z)
    if mode in ("Min-Max", "Min-Sum", "Opt-Fang"):
        if num_leaked <= 1:
            return own_params
        gamma0 = args[0] if len(args) > 0 else DEFAULT_GAMMA
        tau = args[1] if len(args) > 1 else DEFAULT_TAU
        fn = {"Min-Max": min_max_attack, "Min-Sum": min_sum_attack, "Opt-Fang": opt_fang_attack}[mode]
        return fn(genuine_stacked, gamma0, tau, matrix_spectral)
    raise ValueError(f"Attack client not contain '{mode}' algorithm.")
