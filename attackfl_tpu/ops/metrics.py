"""Device-side numerics: the in-graph half of the numerics engine (ISSUE 4).

The telemetry layer (``attackfl_tpu/telemetry``) records what the host can
see — wall times, event lifecycles, defense verdicts.  This module computes
what the NUMBERS are doing *inside* the jitted round: per-cohort
update-norm distributions, genuine-vs-malicious separation margins, global
weight-norm drift, loss drift, non-finite provenance (count, affected
clients, first offending layer) and a fixed-bucket histogram — all as ONE
``(M,)`` float32 row per round, written into a device-resident ring buffer
carried in the simulation state.

Nothing here ever materializes a device value on host
(``scripts/check_host_sync.py`` lints this file): the host-side half — the
k-rounds-late drainer that turns ring rows into schema-v3 ``metric``
events — lives in :mod:`attackfl_tpu.telemetry.numerics`.

Design (FedJAX-style accumulated metric pytrees — PAPERS.md; Federated AD
argues round quantities should be first-class traced values): the metric
registry is declarative and resolved at *program-build* time into a static
slot :class:`MetricsLayout`, so the compute fn is shape-stable, rng-free
and side-effect-free.  Closing it over ``round_step`` / the fused body /
``_pipeline_step_fn`` therefore cannot perturb the params math — the
bit-identical-params guarantee tested in ``tests/test_numerics.py``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from attackfl_tpu.ops import pytree as pt

# Fixed log-spaced histogram bucket edges for per-client update norms.
# 15 internal edges -> 16 buckets: (-inf, 1e-3), [1e-3, ..), ..,
# [1e3, inf).  Static by design: rows from different rounds (and runs) are
# directly comparable, and bucketing stays one cheap searchsorted inside
# the jitted round.
HIST_EDGES = tuple(np.logspace(-3.0, 3.0, 15).tolist())
NUM_HIST_BUCKETS = len(HIST_EDGES) + 1


@dataclass(frozen=True)
class MetricsLayout:
    """Static slot layout of one numerics row (host-side metadata only).

    A row is ``len(names)`` scalar gauge slots followed by
    ``NUM_HIST_BUCKETS`` histogram-count slots.  ``leaf_names`` maps the
    ``first_nonfinite_leaf`` slot's index back to a parameter-tree layer
    name; ``cohorts`` records which client cohorts have update-norm
    distribution slots.
    """

    names: tuple[str, ...]
    leaf_names: tuple[str, ...]
    cohorts: tuple[str, ...]
    hist_edges: tuple[float, ...] = field(default=HIST_EDGES)

    @property
    def size(self) -> int:
        return len(self.names) + NUM_HIST_BUCKETS

    def index(self, name: str) -> int:
        return self.names.index(name)


def build_layout(params_template, has_attackers: bool) -> MetricsLayout:
    """Resolve the metric registry for one configuration.

    ``params_template`` is the (unstacked) client/target params tree —
    concrete arrays or ShapeDtypeStructs; only its structure and leaf
    paths are read.  ``has_attackers`` adds the malicious cohort and the
    separation-margin slots (statically — an attack-free run pays no dead
    slots).
    """
    leaves = jax.tree_util.tree_flatten_with_path(params_template)[0]
    leaf_names = tuple(pt.path_name(p) for p, _ in leaves)
    cohorts = ("all", "genuine") + (("malicious",) if has_attackers else ())
    names: list[str] = ["broadcast", "ok", "train_loss", "loss_delta"]
    for cohort in cohorts:
        names += [f"update_norm_{cohort}_p50", f"update_norm_{cohort}_p95",
                  f"update_norm_{cohort}_max"]
    if has_attackers:
        names += ["sep_cosine", "sep_l2", "sep_margin"]
    names += ["global_norm", "global_drift",
              "nonfinite_count", "nonfinite_clients", "first_nonfinite_leaf"]
    return MetricsLayout(tuple(names), leaf_names, cohorts)


def masked_distribution(values: jnp.ndarray, mask: jnp.ndarray):
    """p50 / p95 / max of ``values[mask]`` with a dynamic mask and static
    shapes (traced-safe): masked entries sort to +inf, percentiles use
    numpy's linear interpolation over the first ``n = sum(mask)`` sorted
    entries.  An empty cohort yields NaN on every statistic.
    """
    c = values.shape[0]
    n = jnp.sum(mask.astype(jnp.int32))
    order = jnp.sort(jnp.where(mask, values, jnp.inf))

    def pick(i):
        return order[jnp.clip(i, 0, c - 1)]

    def pct(q):
        rank = (n - 1).astype(jnp.float32) * q
        lo = jnp.floor(rank).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, n - 1)
        frac = rank - lo.astype(jnp.float32)
        value = pick(lo) * (1.0 - frac) + pick(hi) * frac
        return jnp.where(n > 0, value, jnp.nan)

    maximum = jnp.where(n > 0, pick(n - 1), jnp.nan)
    return pct(0.5), pct(0.95), maximum


class Numerics:
    """Traced numerics programs for one Simulator configuration.

    ``genuine_mask`` / ``attacker_mask`` are host (C,) bool arrays — the
    static attacker geometry.  ``window`` is the ring-buffer depth: the
    host drainer may resolve rows up to ``window`` rounds late; rows older
    than that are overwritten (counted, not silently lost — see
    :class:`attackfl_tpu.telemetry.numerics.NumericsDrainer`).

    Every method here is pure and traced-safe; none consumes rng or
    touches the params math.
    """

    def __init__(self, layout: MetricsLayout, genuine_mask, attacker_mask,
                 window: int):
        self.layout = layout
        self.genuine_mask = genuine_mask
        self.attacker_mask = attacker_mask
        self.has_attackers = bool(np.any(attacker_mask))
        self.window = int(window)

    # ------------------------------------------------------------------
    # ring buffer
    # ------------------------------------------------------------------

    def init_state(self) -> dict:
        """Fresh device-resident ring state, carried inside the round
        state pytree (donation-safe: every round's write aliases the
        buffer in place under jit)."""
        return {
            "buffer": jnp.full((self.window, self.layout.size), jnp.nan,
                               jnp.float32),
            "cursor": jnp.zeros((), jnp.int32),
            "prev_loss": jnp.full((), jnp.nan, jnp.float32),
        }

    def write(self, num_state: dict, row: jnp.ndarray, loss) -> dict:
        """Write one row at ``cursor % window`` and advance the cursor
        (traced; the cursor's host mirror is the drainer's round count)."""
        cursor = num_state["cursor"]
        buffer = jax.lax.dynamic_update_slice(
            num_state["buffer"], row[None, :],
            (jnp.mod(cursor, self.window), jnp.int32(0)))
        return {"buffer": buffer, "cursor": cursor + 1,
                "prev_loss": jnp.asarray(loss, jnp.float32)}

    # ------------------------------------------------------------------
    # the metric row
    # ------------------------------------------------------------------

    def compute_row(self, base, old_ref, new_ref, stacked, sizes,
                    prev_loss, loss, ok, broadcast) -> jnp.ndarray:
        """One round's (M,) float32 metrics row (traced).

        ``base`` is the broadcast reference the per-client updates are
        measured against, as a PYTREE with the same leaf structure as
        ``stacked``: the global params (leaves broadcast across the client
        axis) on the plain path, or the per-client generated params
        (stacked leaves) in hyper mode.  ``old_ref`` / ``new_ref`` are the
        server-side trees (global or hypernetwork params) before/after
        the round's ACCEPTED outcome — a failed round therefore shows
        zero drift, exactly like the accept-select keeps the old params.

        The big reductions stream LEAF BY LEAF — nothing ever
        materializes the concatenated (C, P) update matrix (for the bench
        workload that one concat plus its temporaries cost more than the
        entire round).  Pass 1 is a bare Σd² per (leaf, client) — ONE
        fused traversal of the stacked updates, no elementwise isfinite
        pass: a non-finite element makes its leaf's partial sum
        non-finite, so the (L, C) partial-sum matrix doubles as the
        provenance signal at (client, layer) granularity.  Pass 2
        (attacked runs only) folds the genuine/malicious cohort mean
        geometry into three Gram scalars — the cosine and L2 separation
        fall out of those without ever building a mean vector.
        """
        layout = self.layout
        leaves = jax.tree.leaves(stacked)
        base_leaves = jax.tree.leaves(base)
        c = leaves[0].shape[0]
        reporting = sizes > 0

        # ---- pass 1: per-(leaf, client) Σd² — one traversal -------------
        sq_mat = jnp.stack([
            jnp.sum(jnp.square((x - b).astype(jnp.float32).reshape(c, -1)),
                    axis=1)
            for x, b in zip(leaves, base_leaves)])  # (L, C), tiny
        # non-finite provenance falls out of the partial sums: NaN/Inf
        # anywhere in a (leaf, client) block makes that entry non-finite.
        # Counts are therefore at (client, layer) granularity — the
        # resolution the report and first_nonfinite_leaf actually use —
        # and a poisoned block contributes 0 to the client's norm, so one
        # NaN client cannot poison the cohort statistics: its row is
        # excluded from every cohort via `valid` and surfaces in the
        # provenance slots instead
        leaf_finite = jnp.isfinite(sq_mat)
        norms = jnp.sqrt(jnp.sum(jnp.where(leaf_finite, sq_mat, 0.0),
                                 axis=0))
        bad_mat = ~leaf_finite
        leaf_bad = jnp.sum(bad_mat, axis=1)        # (L,) clients hit/leaf
        bad_per_client = jnp.sum(bad_mat, axis=0)  # (C,) leaves hit/client
        finite = bad_per_client == 0
        valid = reporting & finite

        genuine = valid & jnp.asarray(self.genuine_mask)
        slots: dict[str, jnp.ndarray] = {
            "broadcast": jnp.asarray(broadcast),
            "ok": jnp.asarray(ok),
            "train_loss": jnp.asarray(loss),
            "loss_delta": jnp.asarray(loss) - prev_loss,
        }
        cohort_masks = {"all": valid, "genuine": genuine}
        if self.has_attackers:
            cohort_masks["malicious"] = valid & jnp.asarray(self.attacker_mask)
        for cohort in layout.cohorts:
            p50, p95, mx = masked_distribution(norms, cohort_masks[cohort])
            slots[f"update_norm_{cohort}_p50"] = p50
            slots[f"update_norm_{cohort}_p95"] = p95
            slots[f"update_norm_{cohort}_max"] = mx

        if self.has_attackers:
            malicious = cohort_masks["malicious"]
            n_gen = jnp.sum(genuine.astype(jnp.float32))
            n_mal = jnp.sum(malicious.astype(jnp.float32))
            # ---- pass 2: cohort mean geometry as Gram scalars ----------
            # s_x = Σ_c mask_c · d_c, so mean_x = s_x / n_x and every
            # separation quantity is a function of ⟨s_gen,s_gen⟩,
            # ⟨s_mal,s_mal⟩, ⟨s_gen,s_mal⟩ — one (2,C)@(C,leaf) matmul
            # per leaf, never a materialized mean vector.  Invalid
            # clients' rows are forced to zero (a 0-weight dot against a
            # NaN row would still be NaN) — whole-row zeroing matches the
            # cohort semantics: an invalid client contributes nothing.
            weights = jnp.stack([genuine.astype(jnp.float32),
                                 malicious.astype(jnp.float32)])
            gram = jnp.zeros((2, 2), jnp.float32)
            for x, b in zip(leaves, base_leaves):
                d = (x - b).astype(jnp.float32).reshape(c, -1)
                s = weights @ jnp.where(valid[:, None], d, 0.0)
                gram += s @ s.T
            gg, gm, mm = gram[0, 0], gram[0, 1], gram[1, 1]
            both = (n_gen > 0) & (n_mal > 0)
            cos = gm / jnp.maximum(jnp.sqrt(gg * mm), 1e-30)  # scale-free
            l2_sq = (gg / jnp.maximum(n_gen, 1.0) ** 2
                     - 2.0 * gm / jnp.maximum(n_gen * n_mal, 1.0)
                     + mm / jnp.maximum(n_mal, 1.0) ** 2)
            gen_norm = (jnp.sum(norms * genuine.astype(norms.dtype))
                        / jnp.maximum(n_gen, 1.0))
            mal_norm = (jnp.sum(norms * malicious.astype(norms.dtype))
                        / jnp.maximum(n_mal, 1.0))
            slots["sep_cosine"] = jnp.where(both, cos, jnp.nan)
            slots["sep_l2"] = jnp.where(
                both, jnp.sqrt(jnp.maximum(l2_sq, 0.0)), jnp.nan)
            # how much louder the attacker cohort is than the genuine one
            slots["sep_margin"] = jnp.where(both, mal_norm - gen_norm, jnp.nan)

        # server-tree norms are C× smaller than the client reductions —
        # per-leaf sums, again without a concat
        new_sq = sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                     for x in jax.tree.leaves(new_ref))
        drift_sq = sum(
            jnp.sum(jnp.square(n.astype(jnp.float32)
                               - o.astype(jnp.float32)))
            for n, o in zip(jax.tree.leaves(new_ref),
                            jax.tree.leaves(old_ref)))
        slots["global_norm"] = jnp.sqrt(new_sq)
        slots["global_drift"] = jnp.sqrt(drift_sq)

        # non-finite provenance: total (client, layer) hits, affected
        # clients, and the FIRST leaf (layer) holding one —
        # layout.leaf_names maps the index back to a layer name on host
        total_bad = jnp.sum(leaf_bad)
        slots["nonfinite_count"] = total_bad
        slots["nonfinite_clients"] = jnp.sum(reporting & ~finite)
        slots["first_nonfinite_leaf"] = jnp.where(
            total_bad > 0, jnp.argmax(leaf_bad > 0), -1)

        scalar = jnp.stack([jnp.asarray(slots[name]).astype(jnp.float32)
                            for name in layout.names])
        edges = jnp.asarray(layout.hist_edges, jnp.float32)
        bucket = jnp.searchsorted(edges, norms.astype(jnp.float32),
                                  side="right")
        hist = jnp.sum(
            jax.nn.one_hot(bucket, NUM_HIST_BUCKETS, dtype=jnp.float32)
            * valid[:, None].astype(jnp.float32), axis=0)
        return jnp.concatenate([scalar, hist])

    def step(self, num_state, base, old_ref, new_ref, stacked, sizes,
             loss, ok, broadcast):
        """compute_row + ring write in one traced call.  Returns
        ``(new_num_state, row)`` — the row is what the fused/pipelined
        bodies surface through their metrics output (resolved by the
        path's existing late sync), while the ring is what the sync path
        drains in batches."""
        row = self.compute_row(base, old_ref, new_ref, stacked, sizes,
                               num_state["prev_loss"], loss, ok, broadcast)
        return self.write(num_state, row, loss), row
