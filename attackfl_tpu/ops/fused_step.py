"""Pallas TPU mega-kernel: the whole local-training minibatch step as ONE
kernel.

The framework's hot loop is the reference's client SGD loop
(/root/reference/client.py:80-107) vmapped over clients: per minibatch,
forward + backward + grad-clip + Adam.  Under XLA that is ~150 small
kernels per step, each ~5-10us latency-bound — the step cost is kernel
COUNT, not FLOPs (profiled: 585 steps x ~1.1ms at 100 clients on one
chip).  This module hand-fuses the entire step for the flagship ICU
TransformerModel into a single Pallas program: grid (client-chunks,
minibatches), each step computing forward, hand-derived backward, global-
norm clip and Adam for G clients' [B, 23] batches, with params/m/v blocks
RESIDENT in VMEM across the minibatch grid axis (index map invariant along
it) so HBM sees each chunk's state once per epoch.

Mosaic-lowering constraints shape the implementation (discovered on real
TPU hardware; the interpret path accepts much more than Mosaic does):
* no gathers/scatters: every parameter row access is a static slice
  (``vecs[:, i:i+1, :w]``), and gradients are assembled with keepdims
  reductions + ``concatenate`` instead of ``.at[].set``;
* no rank-changing reshapes on the lane dim: the scalar loss/logit chain
  stays in ``[G, B, 1]`` space end-to-end;
* no lane-dim slicing of the input: instead of slicing vitals/labs
  columns out of the batch, the input projections are stored as padded
  [32, D] matrices whose rows sit at the data-column offsets
  (``IN_OFFS``), so ``z1 = batch @ W_ext`` runs on the MXU directly; the
  weight rows outside each branch's span are zero and their gradients are
  masked, keeping them inert under Adam.

Exactness:
* attention uses the seq-len-1 identity (models/layers.Seq1Attention):
  softmax over one key is the constant 1; q/k receive exactly zero grad
  and are not even passed in (Adam leaves zero-grad params untouched);
* gelu = tanh approximation (flax default, same as the JAX path);
* LayerNorm eps 1e-6 (flax), Adam b1 .9 / b2 .999 / eps 1e-8 with bias
  correction, clip-by-global-norm across ALL leaves — matching optax
  (`clip_by_global_norm` then `adam`, training/local.make_optimizer);
* dropout masks come from the TPU hardware PRNG with elementwise
  inverted-dropout semantics (a different stream than the JAX path, and
  elementwise rather than per-head on the attention value — same rate and
  distribution; parity is metric-level, SURVEY.md §7).

With dropout rates forced to 0 the kernel is deterministic and is tested
against jax.grad of the flax model (tests/test_pallas_step.py).
Reference semantics being fused: client.train_ICU
(/root/reference/client.py:74-112) with per-round Adam state and the
clip-before-backward bug fixed (SURVEY.md §2 quirks).
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

D = 64          # model width
FF = 8          # ffn dim 6, padded to 8 (pad cols/rows stay zero)
NV = 26         # [64]-vector slots in `vecs`
NIN = 32        # padded input-projection rows (data block has 32 columns)
B1, B2, EPS = 0.9, 0.999, 1e-8
LN_EPS = 1e-6
_GELU_C = math.sqrt(2.0 / math.pi)

# vecs slot indices (per branch b in {0: vitals, 1: labs}: base = 11*b)
S_BD, S_BV, S_BO, S_B1F, S_B2F, S_G1, S_BE1, S_G2, S_BE2, S_G3, S_BE3 = range(11)
S_BF1, S_BF2, S_WOUT, S_BOUT = 22, 23, 24, 25

# Explicit donation contract of the eager per-epoch dispatch (packed
# params / Adam m / Adam v donated in place across epochs — the per-client
# optimizer state never holds two HBM copies).  A module constant so the
# static donation analyzer (attackfl_tpu/analysis) and readers see the
# policy without digging through the jit call; the donated groups are
# rebound from the call's results in the same statement, which is exactly
# the pattern the donation-after-use rule requires.
EPOCH_DONATE_ARGNUMS = (0, 1, 2)

BRANCHES = ("vitals", "labs")
IN_DIMS = (7, 16)
IN_OFFS = (0, 7)   # column offsets of each branch's features in the batch
COL_LABEL, COL_MASK = 23, 24
GROUP_ORDER = ("w_in", "w_sq", "w_ff1", "w_ff2", "w_h1", "w_h2", "vecs")
N_G = len(GROUP_ORDER)


# ---------------------------------------------------------------------------
# packed parameter layout: 38 active leaves -> 7 dense groups
# ---------------------------------------------------------------------------

def pack_params(stacked: Any) -> dict[str, jnp.ndarray]:
    """Stacked TransformerModel params [C, ...] -> packed dense groups.

    ``w_in`` slot b is a [NIN, D] matrix whose rows IN_OFFS[b] ..
    IN_OFFS[b]+IN_DIMS[b] hold the branch's input kernel and every other
    row is zero, so the kernel can project the full 32-column batch block
    without lane slicing.
    """
    p = stacked
    C = p["fc1"]["kernel"].shape[0]
    f32 = jnp.float32

    w_in = jnp.zeros((C, 2, NIN, D), f32)
    w_sq = jnp.zeros((C, 4, D, D), f32)
    w_ff1 = jnp.zeros((C, 2, D, FF), f32)
    w_ff2 = jnp.zeros((C, 2, FF, D), f32)
    vecs = jnp.zeros((C, NV, D), f32)

    for b, (name, f, off) in enumerate(zip(BRANCHES, IN_DIMS, IN_OFFS)):
        blk = p[f"{name}_transformer"]
        w_in = w_in.at[:, b, off:off + f, :].set(p[f"{name}_dense"]["kernel"])
        w_sq = w_sq.at[:, 2 * b].set(blk["attention"]["value"]["kernel"].reshape(C, D, D))
        w_sq = w_sq.at[:, 2 * b + 1].set(blk["attention"]["out"]["kernel"].reshape(C, D, D))
        w_ff1 = w_ff1.at[:, b, :, :6].set(blk["ffn_dense1"]["kernel"])
        w_ff2 = w_ff2.at[:, b, :6, :].set(blk["ffn_dense2"]["kernel"])
        base = 11 * b
        vecs = vecs.at[:, base + S_BD].set(p[f"{name}_dense"]["bias"])
        vecs = vecs.at[:, base + S_BV].set(blk["attention"]["value"]["bias"].reshape(C, D))
        vecs = vecs.at[:, base + S_BO].set(blk["attention"]["out"]["bias"])
        vecs = vecs.at[:, base + S_B1F, :6].set(blk["ffn_dense1"]["bias"])
        vecs = vecs.at[:, base + S_B2F].set(blk["ffn_dense2"]["bias"])
        vecs = vecs.at[:, base + S_G1].set(blk["attention_norm"]["scale"])
        vecs = vecs.at[:, base + S_BE1].set(blk["attention_norm"]["bias"])
        vecs = vecs.at[:, base + S_G2].set(blk["ffn_norm"]["scale"])
        vecs = vecs.at[:, base + S_BE2].set(blk["ffn_norm"]["bias"])
        vecs = vecs.at[:, base + S_G3].set(p[f"{name}_bn"]["scale"])
        vecs = vecs.at[:, base + S_BE3].set(p[f"{name}_bn"]["bias"])

    vecs = vecs.at[:, S_BF1].set(p["fc1"]["bias"])
    vecs = vecs.at[:, S_BF2, :32].set(p["fc2"]["bias"])
    vecs = vecs.at[:, S_WOUT, :32].set(p["output"]["kernel"][:, :, 0])
    vecs = vecs.at[:, S_BOUT, :1].set(p["output"]["bias"])

    return {"w_in": w_in, "w_sq": w_sq, "w_ff1": w_ff1, "w_ff2": w_ff2,
            "w_h1": p["fc1"]["kernel"].astype(f32),
            "w_h2": p["fc2"]["kernel"].astype(f32), "vecs": vecs}


def unpack_params(groups: dict[str, jnp.ndarray], template: Any) -> Any:
    """Packed groups -> stacked pytree shaped like ``template``.

    Inert attention q/k leaves pass through from ``template`` unchanged —
    exactly what their zero gradients would do under Adam.
    """
    C = groups["w_h1"].shape[0]
    out = jax.tree.map(lambda x: x, template)  # fresh nested dicts
    vecs = groups["vecs"]

    for b, (name, f, off) in enumerate(zip(BRANCHES, IN_DIMS, IN_OFFS)):
        base = 11 * b
        blk = out[f"{name}_transformer"]
        out[f"{name}_dense"]["kernel"] = groups["w_in"][:, b, off:off + f, :]
        out[f"{name}_dense"]["bias"] = vecs[:, base + S_BD]
        blk["attention"]["value"]["kernel"] = groups["w_sq"][:, 2 * b].reshape(C, D, 4, 16)
        blk["attention"]["value"]["bias"] = vecs[:, base + S_BV].reshape(C, 4, 16)
        blk["attention"]["out"]["kernel"] = groups["w_sq"][:, 2 * b + 1].reshape(C, 4, 16, D)
        blk["attention"]["out"]["bias"] = vecs[:, base + S_BO]
        blk["ffn_dense1"]["kernel"] = groups["w_ff1"][:, b, :, :6]
        blk["ffn_dense1"]["bias"] = vecs[:, base + S_B1F, :6]
        blk["ffn_dense2"]["kernel"] = groups["w_ff2"][:, b, :6, :]
        blk["ffn_dense2"]["bias"] = vecs[:, base + S_B2F]
        blk["attention_norm"]["scale"] = vecs[:, base + S_G1]
        blk["attention_norm"]["bias"] = vecs[:, base + S_BE1]
        blk["ffn_norm"]["scale"] = vecs[:, base + S_G2]
        blk["ffn_norm"]["bias"] = vecs[:, base + S_BE2]
        out[f"{name}_bn"]["scale"] = vecs[:, base + S_G3]
        out[f"{name}_bn"]["bias"] = vecs[:, base + S_BE3]

    out["fc1"]["kernel"] = groups["w_h1"]
    out["fc1"]["bias"] = vecs[:, S_BF1]
    out["fc2"]["kernel"] = groups["w_h2"]
    out["fc2"]["bias"] = vecs[:, S_BF2, :32]
    out["output"]["kernel"] = vecs[:, S_WOUT, :32][..., None]
    out["output"]["bias"] = vecs[:, S_BOUT, :1]
    return out


# ---------------------------------------------------------------------------
# kernel math helpers (plain jnp, traced inside the kernel)
# ---------------------------------------------------------------------------

def _gelu(x):
    t = jnp.tanh(_GELU_C * (x + 0.044715 * x * x * x))
    return 0.5 * x * (1.0 + t)


def _gelu_grad(x):
    t = jnp.tanh(_GELU_C * (x + 0.044715 * x * x * x))
    return 0.5 * (1.0 + t) + 0.5 * x * (1.0 - t * t) * _GELU_C * (1.0 + 0.134145 * x * x)


def _ln_fwd(r, g, b):
    mu = jnp.mean(r, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(r - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + LN_EPS)
    xhat = (r - mu) * rstd
    return xhat * g + b, xhat, rstd


def _ln_bwd(dy, xhat, rstd, g):
    """dg/db come back as [G, 1, D] rows (keepdims — Mosaic-friendly:
    no rank-changing reshape when assembling the vecs gradient)."""
    dyg = dy * g
    dg = jnp.sum(dy * xhat, axis=-2, keepdims=True)
    db = jnp.sum(dy, axis=-2, keepdims=True)
    dx = (dyg - jnp.mean(dyg, axis=-1, keepdims=True)
          - xhat * jnp.mean(dyg * xhat, axis=-1, keepdims=True)) * rstd
    return dx, dg, db


def _bmm(x, w):
    """[G,B,K] @ [G,K,N] -> [G,B,N]."""
    return jax.lax.dot_general(x, w, (((2,), (1,)), ((0,), (0,))),
                               preferred_element_type=jnp.float32)


def _bmm_dw(x, dz):
    """[G,B,K], [G,B,N] -> [G,K,N] (contract batch)."""
    return jax.lax.dot_general(x, dz, (((1,), (1,)), ((0,), (0,))),
                               preferred_element_type=jnp.float32)


def _bmm_dx(dz, w):
    """[G,B,N], [G,K,N] -> [G,B,K] (contract features)."""
    return jax.lax.dot_general(dz, w, (((2,), (2,)), ((0,), (0,))),
                               preferred_element_type=jnp.float32)


def _mask(shape, rate):
    """Elementwise inverted-dropout mask from the TPU hardware PRNG."""
    bits = pltpu.prng_random_bits(shape)
    thr = np.uint32(min(int(rate * 2.0 ** 32), 2 ** 32 - 1))
    return jnp.where(bits >= thr, np.float32(1.0 / (1.0 - rate)), np.float32(0.0))


def _sl(x, i):
    """x[:, i] for static i without a gather: unit slice + squeeze (the
    squeeze only drops a unit middle dim — minor layout unchanged)."""
    return jnp.squeeze(x[:, i:i + 1], axis=1)


def _row(vecs, i, w=D):
    """vecs[:, i] as a broadcastable [G, 1, w] row without a gather."""
    return vecs[:, i:i + 1, :w]


def _pad_row(x):
    """[G, 1, w] -> [G, 1, D] by zero-extending the lane dim."""
    w = x.shape[-1]
    if w == D:
        return x
    return jnp.concatenate(
        [x, jnp.zeros((x.shape[0], 1, D - w), jnp.float32)], axis=-1)


def _col(data, c):
    """Column c of the [G, B, 32] batch as [G, B, 1] (iota-select +
    reduce; integer indexing would be an unsupported 3D gather)."""
    sel = jax.lax.broadcasted_iota(jnp.int32, data.shape, 2) == c
    return jnp.sum(jnp.where(sel, data, 0.0), axis=-1, keepdims=True)


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------

def _train_step_kernel(sc_ref, *refs, lr, clip, drop_attn, drop_block,
                       drop_head, g_clients, batch_b):
    p_in, m_in, v_in = refs[:N_G], refs[N_G:2 * N_G], refs[2 * N_G:3 * N_G]
    batch_ref = refs[3 * N_G]
    loss_ref = refs[3 * N_G + 1]
    p_out = refs[3 * N_G + 2:4 * N_G + 2]
    m_out = refs[4 * N_G + 2:5 * N_G + 2]
    v_out = refs[5 * N_G + 2:6 * N_G + 2]

    i, j = pl.program_id(0), pl.program_id(1)
    G, B = g_clients, batch_b
    dropout = drop_attn > 0.0 or drop_block > 0.0 or drop_head > 0.0

    # First minibatch of this client chunk: copy state into the resident
    # output blocks (read AND written from here on; flushed at chunk end)
    # and zero the loss accumulator.
    @pl.when(j == 0)
    def _():
        for src, dst in zip(p_in + m_in + v_in, p_out + m_out + v_out):
            dst[...] = src[...]
        loss_ref[...] = jnp.zeros_like(loss_ref)

    pd = {k: ref[...] for k, ref in zip(GROUP_ORDER, p_out)}
    data = batch_ref[...].reshape(G, B, 32)
    y = _col(data, COL_LABEL)                                 # [G,B,1]
    msk = _col(data, COL_MASK)                                # [G,B,1]

    if dropout:
        pltpu.prng_seed(sc_ref[0] + (sc_ref[1] + j) * 7919 + i * 104729)

    vecs = pd["vecs"]
    ones = functools.partial(jnp.ones, dtype=jnp.float32)

    # ---------------- forward ----------------
    stash, xb = [], []
    for b in range(2):
        base = 11 * b
        # full-width input projection: rows outside this branch's span are
        # zero, so label/mask columns contribute nothing (see pack_params)
        z1 = _bmm(data, _sl(pd["w_in"], b)) + _row(vecs, base + S_BD)
        x1 = _gelu(z1)
        v_ = _bmm(x1, _sl(pd["w_sq"], 2 * b)) + _row(vecs, base + S_BV)
        mw = _mask((G, B, D), drop_attn) if drop_attn > 0.0 else ones((G, B, D))
        vd = v_ * mw
        a = _bmm(vd, _sl(pd["w_sq"], 2 * b + 1)) + _row(vecs, base + S_BO)
        m1 = _mask((G, B, D), drop_block) if drop_block > 0.0 else ones((G, B, D))
        r1 = x1 + a * m1
        g1 = _row(vecs, base + S_G1)
        x2, xhat1, rstd1 = _ln_fwd(r1, g1, _row(vecs, base + S_BE1))
        z2 = _bmm(x2, _sl(pd["w_ff1"], b)) + _row(vecs, base + S_B1F, FF)
        h = _gelu(z2)
        mf = _mask((G, B, FF), drop_block) if drop_block > 0.0 else ones((G, B, FF))
        hd = h * mf
        yf = _bmm(hd, _sl(pd["w_ff2"], b)) + _row(vecs, base + S_B2F)
        m2 = _mask((G, B, D), drop_block) if drop_block > 0.0 else ones((G, B, D))
        r2 = x2 + yf * m2
        g2 = _row(vecs, base + S_G2)
        x3, xhat2, rstd2 = _ln_fwd(r2, g2, _row(vecs, base + S_BE2))
        g3 = _row(vecs, base + S_G3)
        xb_b, xhat3, rstd3 = _ln_fwd(x3, g3, _row(vecs, base + S_BE3))
        xb.append(xb_b)
        stash.append((z1, x1, mw, vd, m1, xhat1, rstd1, g1, x2, z2, mf,
                      hd, m2, xhat2, rstd2, g2, xhat3, rstd3, g3))

    cc = jnp.concatenate(xb, axis=-1)                         # [G,B,128]
    z4 = _bmm(cc, pd["w_h1"]) + _row(vecs, S_BF1)
    x4 = _gelu(z4)
    m4 = _mask((G, B, D), drop_head) if drop_head > 0.0 else ones((G, B, D))
    x4d = x4 * m4
    z5 = _bmm(x4d, pd["w_h2"]) + _row(vecs, S_BF2, 32)
    x5 = _gelu(z5)                                            # [G,B,32]
    wo = _row(vecs, S_WOUT, 32)                               # [G,1,32]
    z6 = (jnp.sum(x5 * wo, axis=-1, keepdims=True)
          + _row(vecs, S_BOUT, 1))                            # [G,B,1]
    prob = jax.nn.sigmoid(z6)                                 # [G,B,1]
    lo, hi = np.float32(1e-7), np.float32(1.0 - 1e-7)
    pc = jnp.clip(prob, lo, hi)

    msum = jnp.maximum(jnp.sum(msk, axis=1, keepdims=True), 1.0)  # [G,1,1]
    per = -(y * jnp.log(pc) + (1.0 - y) * jnp.log(1.0 - pc))
    loss_step = jnp.sum(per * msk, axis=1, keepdims=True) / msum  # [G,1,1]
    # accumulate into column 0 of the resident (G, 1, 128) loss block (a
    # dynamic-column store crashes the Mosaic compiler): per-step losses
    # are summed (NaN propagates, preserving the tripwire) and the host
    # divides by nb for the epoch mean.  The block is 3D so every
    # per-client scalar stays [G, 1, 1] — a [G, 1] layout (sublane=G,
    # lane=1) hard-crashes the Mosaic layout engine.
    col0 = jax.lax.broadcasted_iota(jnp.int32, loss_ref.shape, 2) == 0
    loss_ref[...] = loss_ref[...] + jnp.where(col0, loss_step, 0.0)

    # ---------------- backward ----------------
    within = ((prob > lo) & (prob < hi)).astype(jnp.float32)
    dpc = msk * (pc - y) / (pc * (1.0 - pc)) / msum
    dz6 = dpc * within * prob * (1.0 - prob)                  # [G,B,1]
    g_wout = jnp.sum(x5 * dz6, axis=1, keepdims=True)         # [G,1,32]
    g_bout = jnp.sum(dz6, axis=1, keepdims=True)              # [G,1,1]
    dx5 = dz6 * wo                                            # [G,B,32]
    dz5 = dx5 * _gelu_grad(z5)
    g_wh2 = _bmm_dw(x4d, dz5)
    g_bf2 = jnp.sum(dz5, axis=1, keepdims=True)               # [G,1,32]
    dx4 = _bmm_dx(dz5, pd["w_h2"]) * m4
    dz4 = dx4 * _gelu_grad(z4)
    g_wh1 = _bmm_dw(cc, dz4)
    g_bf1 = jnp.sum(dz4, axis=1, keepdims=True)               # [G,1,D]
    dcc = _bmm_dx(dz4, pd["w_h1"])

    rows: list = [None] * NV
    g_win_parts, g_wsq_parts, g_wff1_parts, g_wff2_parts = [], [], [], []

    for b in range(2):
        base = 11 * b
        (z1, x1, mw, vd, m1, xhat1, rstd1, g1, x2, z2, mf,
         hd, m2, xhat2, rstd2, g2, xhat3, rstd3, g3) = stash[b]
        dxb = dcc[:, :, b * D:(b + 1) * D]
        dx3, dg3, db3 = _ln_bwd(dxb, xhat3, rstd3, g3)
        dr2, dg2, db2 = _ln_bwd(dx3, xhat2, rstd2, g2)
        dyf = dr2 * m2
        g_wff2_parts.append(_bmm_dw(hd, dyf))
        db2f = jnp.sum(dyf, axis=1, keepdims=True)            # [G,1,D]
        dz2 = _bmm_dx(dyf, _sl(pd["w_ff2"], b)) * mf * _gelu_grad(z2)
        g_wff1_parts.append(_bmm_dw(x2, dz2))
        db1f = jnp.sum(dz2, axis=1, keepdims=True)            # [G,1,FF]
        dx2 = dr2 + _bmm_dx(dz2, _sl(pd["w_ff1"], b))
        dr1, dg1, db1 = _ln_bwd(dx2, xhat1, rstd1, g1)
        da = dr1 * m1
        g_wsq_o = _bmm_dw(vd, da)
        dbo = jnp.sum(da, axis=1, keepdims=True)
        dv = _bmm_dx(da, _sl(pd["w_sq"], 2 * b + 1)) * mw
        g_wsq_v = _bmm_dw(x1, dv)
        g_wsq_parts.extend([g_wsq_v, g_wsq_o])
        dbv = jnp.sum(dv, axis=1, keepdims=True)
        dx1 = dr1 + _bmm_dx(dv, _sl(pd["w_sq"], 2 * b))
        dz1 = dx1 * _gelu_grad(z1)
        # full-width input grad, masked to this branch's row span so the
        # zero padding rows (incl. label/mask columns) never train
        g_full = _bmm_dw(data, dz1)                           # [G,32,D]
        row_id = jax.lax.broadcasted_iota(jnp.int32, g_full.shape, 1)
        off, f = IN_OFFS[b], IN_DIMS[b]
        g_win_parts.append(
            jnp.where((row_id >= off) & (row_id < off + f), g_full, 0.0))
        rows[base + S_BD] = jnp.sum(dz1, axis=1, keepdims=True)
        rows[base + S_BV] = dbv
        rows[base + S_BO] = dbo
        rows[base + S_B1F] = _pad_row(db1f)
        rows[base + S_B2F] = db2f
        rows[base + S_G1] = dg1
        rows[base + S_BE1] = db1
        rows[base + S_G2] = dg2
        rows[base + S_BE2] = db2
        rows[base + S_G3] = dg3
        rows[base + S_BE3] = db3

    rows[S_BF1] = g_bf1
    rows[S_BF2] = _pad_row(g_bf2)
    rows[S_WOUT] = _pad_row(g_wout)
    rows[S_BOUT] = _pad_row(g_bout)
    g_vecs = jnp.concatenate(rows, axis=1)                    # [G,NV,D]

    def _stack1(parts):
        return jnp.concatenate([p[:, None] for p in parts], axis=1)

    grads = {"w_in": _stack1(g_win_parts), "w_sq": _stack1(g_wsq_parts),
             "w_ff1": _stack1(g_wff1_parts), "w_ff2": _stack1(g_wff2_parts),
             "w_h1": g_wh1, "w_h2": g_wh2, "vecs": g_vecs}

    # ---------------- clip + Adam ----------------
    # every per-client scalar lives in [G,1,1] — see the loss-block note
    if clip > 0.0:
        gn2 = jnp.zeros((G, 1, 1), jnp.float32)
        for k in GROUP_ORDER:
            g = grads[k]
            # one axis at a time — Mosaic rejects multi-trailing-dim reduces
            s = jnp.sum(g * g, axis=-1, keepdims=True)
            s = jnp.sum(s, axis=-2, keepdims=True)
            if g.ndim == 4:
                s = jnp.sum(s, axis=1)                        # [G,1,1]
            gn2 = gn2 + s
        scale = jnp.minimum(1.0, clip / jnp.maximum(jnp.sqrt(gn2), 1e-12))
    else:
        scale = jnp.ones((G, 1, 1), jnp.float32)
    scale4 = scale[:, None]                                   # [G,1,1,1]

    # bias correction via exp/log — Mosaic has no powf lowering
    t = (sc_ref[1] + j + 1).astype(jnp.float32)
    bc1 = 1.0 - jnp.exp(t * np.float32(math.log(B1)))
    bc2 = 1.0 - jnp.exp(t * np.float32(math.log(B2)))
    for k, mp, vp, pp in zip(GROUP_ORDER, m_out, v_out, p_out):
        g = grads[k] * (scale4 if grads[k].ndim == 4 else scale)
        m_new = B1 * mp[...] + (1.0 - B1) * g
        v_new = B2 * vp[...] + (1.0 - B2) * (g * g)
        mp[...] = m_new
        vp[...] = v_new
        pp[...] = pp[...] - lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + EPS)


# ---------------------------------------------------------------------------
# host-side wrapper
# ---------------------------------------------------------------------------

def run_epoch(groups_p, groups_m, groups_v, batches, seed, t_offset, *,
              lr, clip, drop_attn=0.1, drop_block=0.1, drop_head=0.3,
              g_clients=8, interpret=False):
    """One epoch of fused Adam steps.

    groups_*: dicts of packed [C_pad, ...] arrays (C_pad % g_clients == 0).
    batches: [C_pad, nb, B, 32] pre-gathered minibatches
             (cols 0:7 vitals, 7:23 labs, 23 label, 24 mask).
    Returns (new_p, new_m, new_v, loss_sums [C_pad] — per-client SUM of the
    nb per-step masked-mean losses; divide by nb for the epoch mean).
    """
    C_pad, nb, B, _ = batches.shape
    G = g_clients
    assert C_pad % G == 0, (C_pad, G)
    assert G % 8 == 0, "loss block layout requires g_clients % 8 == 0"
    chunks = C_pad // G

    p_list = [groups_p[k] for k in GROUP_ORDER]
    m_list = [groups_m[k] for k in GROUP_ORDER]
    v_list = [groups_v[k] for k in GROUP_ORDER]

    def gspec(arr):
        nd = arr.ndim
        return pl.BlockSpec((G,) + arr.shape[1:],
                            lambda i, j, sc, nd=nd: (i,) + (0,) * (nd - 1),
                            memory_space=pltpu.VMEM)

    state_specs = [gspec(a) for a in p_list + m_list + v_list]
    batch_spec = pl.BlockSpec((G, 1, B, 32), lambda i, j, sc: (i, j, 0, 0),
                              memory_space=pltpu.VMEM)
    loss_spec = pl.BlockSpec((G, 1, 128), lambda i, j, sc: (i, 0, 0),
                             memory_space=pltpu.VMEM)

    out_shapes = ([jax.ShapeDtypeStruct((C_pad, 1, 128), jnp.float32)]
                  + [jax.ShapeDtypeStruct(a.shape, a.dtype)
                     for a in p_list + m_list + v_list])
    out_specs = [loss_spec] + state_specs

    # inputs (after the scalar-prefetch arg): 21 state arrays, then batches.
    # alias state input k -> output k+1 (output 0 is the loss).
    aliases = {1 + k: 1 + k for k in range(3 * N_G)}

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(chunks, nb),
        in_specs=state_specs + [batch_spec],
        out_specs=out_specs,
    )
    kernel = functools.partial(
        _train_step_kernel, lr=float(lr), clip=float(clip),
        drop_attn=float(drop_attn), drop_block=float(drop_block),
        drop_head=float(drop_head), g_clients=G, batch_b=B,
    )
    sc = jnp.asarray([seed, t_offset, 0], jnp.int32)
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        input_output_aliases=aliases,
        interpret=interpret,
    )(sc, *p_list, *m_list, *v_list, batches)

    loss_sums = outs[0][:, 0, 0]
    new_p = dict(zip(GROUP_ORDER, outs[1:1 + N_G]))
    new_m = dict(zip(GROUP_ORDER, outs[1 + N_G:1 + 2 * N_G]))
    new_v = dict(zip(GROUP_ORDER, outs[1 + 2 * N_G:1 + 3 * N_G]))
    return new_p, new_m, new_v, loss_sums


def zeros_like_groups(groups: dict[str, jnp.ndarray]) -> dict[str, jnp.ndarray]:
    return {k: jnp.zeros_like(v) for k, v in groups.items()}


def build_fused_local_update(dataset, *, epochs, batch_size, lr,
                             clip_grad_norm, dropout=(0.1, 0.1, 0.3),
                             g_clients=8, interpret=False):
    """Drop-in batched replacement for vmap(build_local_update(...)).

    Returns ``batched(params, keys [C], idx [C, hi], mask [C, hi]) ->
    (stacked_params [C, ...], ok [C] bool, loss [C])`` with the same
    shuffling/padding semantics as training/local.build_local_update (the
    per-epoch permutation of the PADDED index array, scattered mask rows,
    fixed nb steps — see its docstring); only the dropout stream differs
    (hardware PRNG inside the kernel vs flax threefry/rbg).
    """
    if interpret:
        # the TPU hardware-PRNG primitives (prng_seed/prng_random_bits)
        # have no CPU interpret lowering — interpret mode is the CI
        # correctness path, so it runs dropout-off (the deterministic
        # configuration the parity test checks); hardware runs keep dropout
        dropout = (0.0, 0.0, 0.0)
    feats = jnp.concatenate(
        [dataset["vitals"], dataset["labs"], dataset["label"][:, None]], axis=1
    ).astype(jnp.float32)                                     # [N, 24]
    B = batch_size
    G = g_clients

    def batched(params, keys, idx, mask):
        C, hi = idx.shape
        nb = -(-hi // B)
        pad = nb * B - hi
        C_pad = -(-C // G) * G

        # broadcast unstacked params ([...]) to the client axis ([C, ...])
        stacked = params
        if params["fc1"]["kernel"].ndim == 2:
            stacked = jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (C,) + x.shape), params)
        padded = jax.tree.map(
            lambda x: jnp.concatenate(
                [x, jnp.zeros((C_pad - C,) + x.shape[1:], x.dtype)], axis=0)
            if C_pad != C else x,
            stacked)

        gp = pack_params(padded)
        gm = zeros_like_groups(gp)
        gv = zeros_like_groups(gp)

        # Donate the packed params/m/v groups across epoch dispatches: the
        # pallas_call already aliases state in->out WITHIN one epoch
        # (input_output_aliases below); jit donation extends that to the
        # eager multi-epoch loop (tests / tpu_validate_pallas), so the
        # per-client optimizer state never holds two HBM copies.  Under an
        # outer jit (the engine's round_step) this inlines and the hint is
        # a no-op.  Interpret mode stays unjitted — it is the CPU
        # correctness path, and donation buys nothing there.
        step_fn = run_epoch
        if not interpret:
            step_fn = jax.jit(
                functools.partial(
                    run_epoch, lr=lr,
                    clip=clip_grad_norm if clip_grad_norm else 0.0,
                    drop_attn=dropout[0], drop_block=dropout[1],
                    drop_head=dropout[2], g_clients=G, interpret=False),
                donate_argnums=EPOCH_DONATE_ARGNUMS)

        # same per-client key schedule as the JAX path (local.py):
        # per client: epoch keys = split(rng, E); per epoch (k_perm, k_drop)
        eks = jax.vmap(lambda k: jax.random.split(k, epochs))(keys)  # [C,E,...]
        seed0 = jax.random.randint(keys[0], (), 0, np.int32(2 ** 31 - 1))

        loss_sums = None
        ok = jnp.ones((C,), bool)
        for e in range(epochs):
            k_perm = jax.vmap(lambda k: jax.random.split(k[e])[0])(eks)
            perms = jax.vmap(lambda k: jax.random.permutation(k, hi))(k_perm)
            p_idx = jnp.take_along_axis(idx, perms, axis=1)
            p_msk = jnp.take_along_axis(mask.astype(jnp.float32), perms, axis=1)
            bidx = jnp.pad(p_idx, ((0, 0), (0, pad))).reshape(C, nb, B)
            bmsk = jnp.pad(p_msk, ((0, 0), (0, pad))).reshape(C, nb, B)
            batch = jnp.concatenate(
                [feats[bidx],                                  # [C,nb,B,24]
                 bmsk[..., None],
                 jnp.zeros((C, nb, B, 7), jnp.float32)], axis=-1)
            if C_pad != C:
                batch = jnp.concatenate(
                    [batch, jnp.zeros((C_pad - C, nb, B, 32), jnp.float32)],
                    axis=0)
            if interpret:
                gp, gm, gv, sums = run_epoch(
                    gp, gm, gv, batch, seed0 + np.int32(e), e * nb,
                    lr=lr, clip=clip_grad_norm if clip_grad_norm else 0.0,
                    drop_attn=dropout[0], drop_block=dropout[1],
                    drop_head=dropout[2], g_clients=G, interpret=True)
            else:
                gp, gm, gv, sums = step_fn(
                    gp, gm, gv, batch, seed0 + np.int32(e),
                    jnp.asarray(e * nb, jnp.int32))
            ok = ok & jnp.isfinite(sums[:C])
            loss_sums = sums
        new_stacked = unpack_params(gp, padded)
        if C_pad != C:
            new_stacked = jax.tree.map(lambda x: x[:C], new_stacked)
        return new_stacked, ok, loss_sums[:C] / nb

    return batched
