"""Host-side filtering defenses: GMM gradient filter, FLTracer, and the
hypernetwork embedding anomaly detector.

These mirror the reference's defense layer that ran on numpy/sklearn
outside the training loop (GMM: server.py:352-372 + src/Utils.py:257-323;
FLTracer: src/Utils.py:359-369, dispatch commented out at server.py:395-435
but live here; hyper-detection: server.py:496-536 + src/Utils.py:389-436).
They consume flat client-update matrices pulled off-device once per round;
the expensive part (flattening) happens on-device in the jitted step.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from attackfl_tpu.ops.stats import (
    GaussianMixture,
    dbscan_labels,
    mahalanobis,
    median_abs_deviation,
    pca_fit_transform,
)


# ---------------------------------------------------------------------------
# GMM-based gradient filtering
# ---------------------------------------------------------------------------

def gmm_filter(
    client_vectors: np.ndarray,
    attacker_mask: np.ndarray,
    n_components: int = 2,
    md_sigma: float = 3.0,
    max_dim: int = 16,
    seed: int = 0,
) -> np.ndarray:
    """Return a benign-client boolean mask.

    Reference semantics (server.py:352-372 + src/Utils.py:257-323): fit a
    2-component full-covariance GMM on all flat client updates (using the
    *ground-truth* attacker labels to calibrate a Mahalanobis threshold
    from the benign population) and keep clients within the threshold.

    Divergences (documented fixes — the reference recipe is inoperative as
    written):
    * The reference fits a PxP covariance on a handful of P≈10⁴⁺-dim
      vectors — singular and O(P²) memory.  We first project to
      ``min(n_clients-1, max_dim)`` PCA dims.
    * The reference thresholds each client's distance to its OWN argmax
      component (Utils.py:311-323) — attackers clustered into their own
      component always sit near that component's mean and always pass; and
      its threshold (3·std of benign distances to hardcoded component 0,
      server.py:361) depends on arbitrary component ordering.  We measure
      every client against the benign-majority component and use
      mean + md_sigma·std of the benign distances as the cutoff, which
      makes the filter actually reject poisoned updates.
    """
    x = np.asarray(client_vectors, dtype=np.float64)
    attacker_mask = np.asarray(attacker_mask, dtype=bool)
    n = x.shape[0]
    k = max(1, min(n - 1, max_dim))
    z = pca_fit_transform(x, k)

    gmm = GaussianMixture(n_components=n_components, seed=seed).fit(z)
    hard = gmm.predict_proba(z).argmax(axis=1)

    benign_idx = np.flatnonzero(~attacker_mask)
    counts = np.bincount(hard[benign_idx], minlength=n_components)
    benign_comp = int(np.argmax(counts))
    mean_b = gmm.means_[benign_comp]
    cov_b = gmm.covariances_[benign_comp]

    benign_md = np.array([mahalanobis(z[i], mean_b, cov_b) for i in benign_idx])
    threshold = float(np.mean(benign_md)) + md_sigma * float(np.std(benign_md))

    md = np.array([mahalanobis(z[i], mean_b, cov_b) for i in range(n)])
    return md <= threshold


# ---------------------------------------------------------------------------
# FLTracer
# ---------------------------------------------------------------------------

def fltracer_anomalies(weight_matrix: np.ndarray, threshold: float = 2.5) -> np.ndarray:
    """PCA(1) + MAD robust z-score anomaly indices
    (reference: fltracer_detect_anomalies, src/Utils.py:363-369)."""
    z = pca_fit_transform(np.asarray(weight_matrix, dtype=np.float64), 1)[:, 0]
    mad = median_abs_deviation(z)
    med = np.median(z)
    scores = np.abs(z - med) / (1.4826 * mad + 1e-6)
    return np.flatnonzero(scores > threshold)


# ---------------------------------------------------------------------------
# Hypernetwork embedding anomaly detection
# ---------------------------------------------------------------------------

def cosine_drift_anomaly(history: np.ndarray, current: np.ndarray, k: float = 2.0) -> bool:
    """Phase-1 detector (reference: cosine, src/Utils.py:391-416).

    ``history`` (H, E) holds a client's past embeddings, ``current`` (E,)
    the new one.  The client is anomalous when its cosine similarity to the
    mean normalized history direction falls below μ − k·σ of the history's
    own similarities.
    """
    history = np.asarray(history, dtype=np.float64)
    current = np.asarray(current, dtype=np.float64).reshape(-1)
    if history.shape[0] == 0:
        return False
    hist_norm = history / np.linalg.norm(history, axis=1, keepdims=True)
    mean_dir = hist_norm.mean(axis=0)
    cur_unit = current / np.linalg.norm(current)
    cos_cur = float(cur_unit @ mean_dir / (np.linalg.norm(cur_unit) * np.linalg.norm(mean_dir)))
    cos_hist = (history @ mean_dir) / (
        np.linalg.norm(history, axis=1) * np.linalg.norm(mean_dir)
    )
    mu, sigma = float(np.mean(cos_hist)), max(float(np.std(cos_hist)), 1e-6)
    return cos_cur < mu - k * sigma


def dbscan_outlier_clients(
    emb_before: np.ndarray,
    emb_after: np.ndarray,
    selected_clients: list[int],
    n_components: int = 3,
    eps: float = 0.008,
    min_samples: int = 3,
) -> list[int]:
    """Phase-2 detector (reference: DBSCAN_phase2, src/Utils.py:419-436):
    PCA + DBSCAN on per-client embedding deltas between consecutive rounds;
    outliers are DBSCAN noise points (label −1)."""
    delta = np.asarray(emb_after, dtype=np.float64) - np.asarray(emb_before, dtype=np.float64)
    delta = delta.reshape(delta.shape[0], -1)
    z = pca_fit_transform(delta, n_components)
    labels = dbscan_labels(z, eps=eps, min_samples=min_samples)
    return [selected_clients[i] for i in np.flatnonzero(labels == -1)]


class HyperDetector:
    """Stateful embedding-history tracker driving both phases
    (reference: server.py:132-134,496-536).

    Keeps a deque of the last ``cosine_search`` embeddings per client,
    persists them to ``all_embeddings.npy`` each round (server.py:519-522),
    and from ``start_round`` on returns the set of clients flagged by BOTH
    the cosine drift and the DBSCAN phase (intersection, server.py:531).
    """

    def __init__(self, total_clients: int, cosine_search: int = 10,
                 n_components: int = 3, eps: float = 0.008, min_samples: int = 3,
                 start_round: int = 18, save_path: str | None = "all_embeddings.npy"):
        self.history = [deque(maxlen=cosine_search) for _ in range(total_clients)]
        self.n_components = n_components
        self.eps = eps
        self.min_samples = min_samples
        self.start_round = start_round
        self.save_path = save_path

    def observe(self, round_number: int, selected_clients: list[int],
                embeddings: np.ndarray) -> list[int]:
        """Record this round's embeddings (rows follow ``selected_clients``)
        and return the client indices to remove (may be empty)."""
        cosine_flagged: list[int] = []
        active = round_number >= self.start_round

        for row, client in enumerate(selected_clients):
            cur = np.asarray(embeddings[row], dtype=np.float64).reshape(-1)
            hist = np.array(self.history[client]) if self.history[client] else np.empty((0, cur.shape[0]))
            if active and cosine_drift_anomaly(hist, cur):
                cosine_flagged.append(client)
            self.history[client].append(cur)

        if self.save_path:
            np.save(self.save_path,
                    np.array([list(dq) for dq in self.history], dtype=object),
                    allow_pickle=True)

        if not active:
            return []
        # need at least two rounds of history for the delta phase
        if any(len(self.history[c]) < 2 for c in selected_clients):
            return []
        before = np.stack([self.history[c][-2] for c in selected_clients])
        after = np.stack([self.history[c][-1] for c in selected_clients])
        db_flagged = dbscan_outlier_clients(
            before, after, selected_clients,
            n_components=self.n_components, eps=self.eps, min_samples=self.min_samples,
        )
        return sorted(set(cosine_flagged) & set(db_flagged))
