"""Self-contained statistical primitives: PCA, Gaussian-mixture EM, DBSCAN,
median absolute deviation.

The reference leans on sklearn/scipy for its defense layer (GMM filter,
FLTracer's PCA+MAD, hyper-detection's PCA+DBSCAN — src/Utils.py:6-10).
Those libraries are not part of this framework's guaranteed dependency set,
and the problems are tiny (≤ clients × small dims, once per round), so the
algorithms are implemented here directly in numpy.  They run host-side,
outside the jitted round step, exactly like the reference ran them outside
its training loops.
"""

from __future__ import annotations

import numpy as np


# ---------------------------------------------------------------------------
# PCA
# ---------------------------------------------------------------------------

def pca_fit_transform(x: np.ndarray, n_components: int) -> np.ndarray:
    """Project rows of ``x`` (N, D) onto their top principal components.

    Matches sklearn.decomposition.PCA.fit_transform up to component sign:
    center, SVD, project.
    """
    x = np.asarray(x, dtype=np.float64)
    mean = x.mean(axis=0)
    xc = x - mean
    # SVD of the centered data: xc = U S Vt; scores = U S
    u, s, _vt = np.linalg.svd(xc, full_matrices=False)
    k = min(n_components, s.shape[0])
    scores = u[:, :k] * s[:k]
    if k < n_components:  # degenerate rank: pad with zeros
        scores = np.concatenate(
            [scores, np.zeros((x.shape[0], n_components - k))], axis=1
        )
    return scores


# ---------------------------------------------------------------------------
# MAD
# ---------------------------------------------------------------------------

def median_abs_deviation(x: np.ndarray) -> float:
    """scipy.stats.median_abs_deviation with default (unscaled) behavior."""
    x = np.asarray(x, dtype=np.float64)
    return float(np.median(np.abs(x - np.median(x))))


# ---------------------------------------------------------------------------
# Gaussian mixture (EM, full covariance)
# ---------------------------------------------------------------------------

class GaussianMixture:
    """Minimal full-covariance GMM with the sklearn attributes the defense
    layer needs: ``means_``, ``covariances_``, ``predict_proba``.

    Init: means seeded from k distinct random data points, points hard-
    assigned to the nearest mean (one k-means-like step), then EM.
    ``reg_covar`` keeps covariances invertible exactly like sklearn's
    regularization (needed because the reference fits P-dim covariances on
    a handful of client vectors).
    """

    def __init__(self, n_components: int = 2, n_iter: int = 50,
                 reg_covar: float = 1e-6, seed: int = 0):
        self.n_components = n_components
        self.n_iter = n_iter
        self.reg_covar = reg_covar
        self.seed = seed
        self.means_: np.ndarray | None = None
        self.covariances_: np.ndarray | None = None
        self.weights_: np.ndarray | None = None

    def fit(self, x: np.ndarray) -> "GaussianMixture":
        x = np.asarray(x, dtype=np.float64)
        n, d = x.shape
        rng = np.random.default_rng(self.seed)
        # seed means from distinct data points, hard-assign to nearest
        seeds = rng.choice(n, size=min(self.n_components, n), replace=False)
        centers = x[seeds]
        if centers.shape[0] < self.n_components:  # fewer points than comps
            centers = np.concatenate(
                [centers, centers[: self.n_components - centers.shape[0]] + 1e-3]
            )
        dists = np.linalg.norm(x[:, None, :] - centers[None, :, :], axis=-1)
        assign = dists.argmin(axis=1)
        for k in range(self.n_components):
            if not np.any(assign == k):
                assign[rng.integers(n)] = k
        resp = np.eye(self.n_components)[assign]

        for _ in range(self.n_iter):
            # M step
            nk = resp.sum(axis=0) + 1e-10
            self.weights_ = nk / n
            self.means_ = (resp.T @ x) / nk[:, None]
            covs = []
            for k in range(self.n_components):
                diff = x - self.means_[k]
                cov = (resp[:, k : k + 1] * diff).T @ diff / nk[k]
                cov[np.diag_indices(d)] += self.reg_covar
                covs.append(cov)
            self.covariances_ = np.stack(covs)
            # E step
            log_resp = self._log_prob(x) + np.log(self.weights_ + 1e-300)
            log_resp -= log_resp.max(axis=1, keepdims=True)
            resp = np.exp(log_resp)
            resp /= resp.sum(axis=1, keepdims=True)
        return self

    def _log_prob(self, x: np.ndarray) -> np.ndarray:
        n, d = x.shape
        out = np.empty((n, self.n_components))
        for k in range(self.n_components):
            diff = x - self.means_[k]
            cov = self.covariances_[k]
            sign, logdet = np.linalg.slogdet(cov)
            if sign <= 0:
                cov = cov + np.eye(d) * self.reg_covar * 10
                sign, logdet = np.linalg.slogdet(cov)
            solve = np.linalg.solve(cov, diff.T).T
            maha = np.sum(diff * solve, axis=1)
            out[:, k] = -0.5 * (d * np.log(2 * np.pi) + logdet + maha)
        return out

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        log_p = self._log_prob(x) + np.log(self.weights_ + 1e-300)
        log_p -= log_p.max(axis=1, keepdims=True)
        p = np.exp(log_p)
        return p / p.sum(axis=1, keepdims=True)


def mahalanobis(x: np.ndarray, mean: np.ndarray, cov: np.ndarray) -> float:
    """Mahalanobis distance of one vector to a Gaussian (reference:
    calculate_md, src/Utils.py:304-309).  Uses solve instead of explicit
    inverse, with diagonal regularization for singular covariances."""
    diff = np.asarray(x, dtype=np.float64) - mean
    d = diff.shape[0]
    try:
        solve = np.linalg.solve(cov, diff)
    except np.linalg.LinAlgError:
        solve = np.linalg.solve(cov + np.eye(d) * 1e-6, diff)
    return float(np.sqrt(max(diff @ solve, 0.0)))


# ---------------------------------------------------------------------------
# DBSCAN
# ---------------------------------------------------------------------------

def dbscan_labels(x: np.ndarray, eps: float, min_samples: int) -> np.ndarray:
    """DBSCAN cluster labels; noise = -1.  Semantics match
    sklearn.cluster.DBSCAN (euclidean, min_samples includes the point
    itself).  O(N²) neighbor search — N is the client count."""
    x = np.asarray(x, dtype=np.float64)
    n = x.shape[0]
    dist = np.linalg.norm(x[:, None, :] - x[None, :, :], axis=-1)
    neighbors = [np.flatnonzero(dist[i] <= eps) for i in range(n)]
    core = np.array([len(nb) >= min_samples for nb in neighbors])

    labels = np.full(n, -1, dtype=np.int64)
    cluster = 0
    for i in range(n):
        if labels[i] != -1 or not core[i]:
            continue
        # BFS over density-reachable points
        labels[i] = cluster
        frontier = list(neighbors[i])
        while frontier:
            j = frontier.pop()
            if labels[j] == -1:
                labels[j] = cluster
                if core[j]:
                    frontier.extend(k for k in neighbors[j] if labels[k] == -1)
        cluster += 1
    return labels
