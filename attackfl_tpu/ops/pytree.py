"""Pytree utilities: the framework's equivalent of the reference's
state_dict arithmetic (reference: src/Utils.py:30-49,218-226,250-255,360-361).

Client model parameters are JAX pytrees; N clients are the *leading axis* of
every leaf ("stacked" trees).  All aggregation/attack math reduces along
that axis, which under pjit sharding compiles to ICI collectives.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


# ---------------------------------------------------------------------------
# stacking
# ---------------------------------------------------------------------------

def tree_stack(trees: list[Pytree]) -> Pytree:
    """Stack a list of identical-structure trees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def tree_take(tree: Pytree, idx) -> Pytree:
    """Index / gather along the leading (client) axis of a stacked tree.

    With a scalar index this is also the inverse of :func:`tree_stack`
    one client at a time."""
    return jax.tree.map(lambda x: jnp.take(x, idx, axis=0), tree)


def tree_broadcast(tree: Pytree, n: int) -> Pytree:
    """Replicate a single tree across a new leading client axis of size n."""
    return jax.tree.map(lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), tree)


# ---------------------------------------------------------------------------
# flattening
# ---------------------------------------------------------------------------

def tree_ravel(tree: Pytree) -> jnp.ndarray:
    """Concatenate all leaves into one flat vector.

    Equivalent of the reference's ``state_dict_to_vector`` /
    ``flatten_state_dict`` / ``get_weight_vector`` trio
    (src/Utils.py:225-226,250-255,360-361).  Leaf order is jax.tree order
    (stable for a fixed structure).
    """
    leaves = jax.tree.leaves(tree)
    return jnp.concatenate([jnp.ravel(x) for x in leaves]) if leaves else jnp.zeros((0,))


def tree_ravel_stacked(stacked: Pytree) -> jnp.ndarray:
    """Flatten a stacked tree to a (N, P) matrix, one row per client."""
    leaves = jax.tree.leaves(stacked)
    n = leaves[0].shape[0]
    return jnp.concatenate([x.reshape(n, -1) for x in leaves], axis=1)


# ---------------------------------------------------------------------------
# norms & distances
# ---------------------------------------------------------------------------

def _leaf_norm(diff: jnp.ndarray, matrix_spectral: bool) -> jnp.ndarray:
    """Per-leaf norm used by :func:`ref_distance`.

    The reference computes ``torch.linalg.norm(diff, ord=2)`` per tensor
    (src/Utils.py:47) — for 1-D tensors that is the vector L2 norm, but for
    2-D tensors torch gives the *spectral* norm (largest singular value).
    ``matrix_spectral=True`` reproduces that behavior exactly; the default
    False uses the Frobenius norm on every leaf, which is the textbook
    Min-Max/Min-Sum distance and is well-defined for >2-D leaves (where the
    reference would raise).
    """
    if matrix_spectral and diff.ndim == 2:
        return jnp.linalg.norm(diff, ord=2)
    return jnp.sqrt(jnp.sum(jnp.square(diff)))


def ref_distance(a: Pytree, b: Pytree, matrix_spectral: bool = False) -> jnp.ndarray:
    """Sum over leaves of the per-leaf norm of (a - b).

    This is the reference's ``compute_distance`` (src/Utils.py:30-49):
    NOT a global L2 norm but a sum of per-tensor norms.  All γ-search
    attacks and their acceptance thresholds use this metric.
    """
    total = jnp.asarray(0.0)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        total = total + _leaf_norm(x - y, matrix_spectral)
    return total


def pairwise_ref_distance(stacked: Pytree, matrix_spectral: bool = False) -> jnp.ndarray:
    """(N, N) matrix of :func:`ref_distance` between all stacked rows.

    The default Frobenius path uses the Gram identity
    ``||xi−xj||² = ||xi||² + ||xj||² − 2⟨xi,xj⟩`` per leaf, avoiding the
    (N, N, leaf) broadcast tensor (which would OOM for big models under a
    vmap over attackers); only the opt-in spectral path materializes diffs.

    Two conditioning guards keep the identity honest in f32:

    * rows are centered (per leaf) first — distances are translation-
      invariant, and the expansion's cancellation error scales with
      ``||xi||·||xj||·eps``, which for stacked FL updates is dominated by
      the broadcast global params every row shares.  Centering removes
      that common component, so the norms entering the subtraction are
      the (small) deviations whose differences we actually want.
    * the diagonal is pinned to exactly 0: mathematically
      ``||xi−xi|| = 0``, but the expansion leaves ``O(||xi||²·eps)``
      residue whose sqrt (~||xi||·3e-4) exceeded the naive formulation's
      error by 10x (the old test failure: 3.3e-3 where the true distance
      is 0.0).
    """
    leaves = jax.tree.leaves(stacked)
    n = leaves[0].shape[0]
    total = jnp.zeros((n, n))
    eye = jnp.eye(n, dtype=bool)
    for x in leaves:
        if matrix_spectral and x.ndim - 1 == 2:
            diff = x[:, None] - x[None, :]  # (N, N, r, c)
            norms = jnp.linalg.norm(diff, ord=2, axis=(-2, -1))
        else:
            flat = x.reshape(n, -1)
            flat = flat - jnp.mean(flat, axis=0, keepdims=True)
            sq_norms = jnp.sum(jnp.square(flat), axis=1)
            gram = flat @ flat.T
            sq = sq_norms[:, None] + sq_norms[None, :] - 2.0 * gram
            norms = jnp.sqrt(jnp.where(eye, 0.0, jnp.maximum(sq, 0.0)))
        total = total + norms
    return total


def distance_to_each(candidate: Pytree, stacked: Pytree, matrix_spectral: bool = False) -> jnp.ndarray:
    """(N,) vector of ref_distance(candidate, stacked[i])."""
    leaves_c = jax.tree.leaves(candidate)
    leaves_s = jax.tree.leaves(stacked)
    n = leaves_s[0].shape[0]
    total = jnp.zeros((n,))
    for c, s in zip(leaves_c, leaves_s):
        diff = s - c[None]
        if matrix_spectral and c.ndim == 2:
            norms = jnp.linalg.norm(diff, ord=2, axis=(-2, -1))
        else:
            norms = jnp.sqrt(jnp.sum(jnp.square(diff.reshape(n, -1)), axis=-1))
        total = total + norms
    return total


# ---------------------------------------------------------------------------
# statistics along the client axis
# ---------------------------------------------------------------------------

def tree_mean(stacked: Pytree, axis: int = 0) -> Pytree:
    return jax.tree.map(lambda x: jnp.mean(x, axis=axis), stacked)


def tree_std(stacked: Pytree, axis: int = 0, ddof: int = 1) -> Pytree:
    """Per-element std along the client axis.

    ``ddof=1`` (Bessel-corrected) matches ``torch.std``'s default used by
    the reference's LIE/Min-Max/Min-Sum statistics (src/Utils.py:90).
    When the axis has a single element the sample std is undefined
    (torch returns NaN); we return zeros so a 1-model leak degrades to the
    mean rather than poisoning the run with NaNs.
    """

    def _std(x):
        n = x.shape[axis]
        if n <= ddof:
            return jnp.zeros(x.shape[:axis] + x.shape[axis + 1 :], x.dtype)
        return jnp.std(x, axis=axis, ddof=ddof)

    return jax.tree.map(_std, stacked)


def tree_weighted_mean(stacked: Pytree, weights: jnp.ndarray) -> Pytree:
    """Weighted mean along the client axis; weights (N,) are normalized
    by their sum (size-weighted FedAvg, reference: server.py:766-772)."""
    w = weights / jnp.sum(weights)

    def wmean(x):
        wb = w.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        return jnp.sum(x * wb, axis=0)

    return jax.tree.map(wmean, stacked)


def path_name(path) -> str:
    """Canonical leaf name from a tree_util key path ("a/b/kernel").

    Part of the hypernetwork head-naming and checkpoint contract — keep the
    single definition here.
    """
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k))) for k in path)
