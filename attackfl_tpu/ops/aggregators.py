"""Robust aggregation rules over the stacked client axis.

Each aggregator is a pure function ``(stacked_params, sizes, ...) ->
global_params`` replacing the reference's server-side dispatch
(server.py:286-494).  Reductions run along the leading client axis; under
pjit sharding they compile to ICI collectives — this file IS the
"distributed communication backend" of the framework.

All reference int-dtype special cases (floor-division averaging,
server.py:770-772) are dropped: every model in the zoo is purely float
(the branches were dead defense — SURVEY.md §7 "Hard parts").
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from attackfl_tpu.ops import pytree as pt


def fedavg(stacked: Any, sizes: jnp.ndarray) -> Any:
    """Size-weighted mean (reference: avg_all_parameters,
    server.py:751-775)."""
    return pt.tree_weighted_mean(stacked, sizes.astype(jnp.float32))


def mean_aggregation(stacked: Any, mask: jnp.ndarray | None = None) -> Any:
    """Unweighted mean of (optionally mask-selected) clients (reference:
    avg_selected_parameters, server.py:777-797, used after GMM filtering —
    the engine's gmm mode calls this with the survivor mask)."""
    if mask is None:
        return pt.tree_mean(stacked)
    return pt.tree_weighted_mean(stacked, mask)


def _row_mask(mask: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a (C,) client mask over a (C, ...) stacked leaf."""
    return mask.astype(bool).reshape((-1,) + (1,) * (x.ndim - 1))


def _valid_bad(mask: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Per-element flag: some VALID client contributed a non-finite value.
    The masked aggregators sort masked rows to +inf and must neutralize
    only those inserted sentinels — a diverged valid client's inf/NaN has
    to poison the aggregate (→ NaN tripwire → failed round), exactly as it
    would on the unmasked path."""
    return jnp.any(~jnp.isfinite(x) & _row_mask(mask, x), axis=0)


def median_aggregation(stacked: Any, mask: jnp.ndarray | None = None) -> Any:
    """Per-element median across clients (reference: median_aggregation,
    src/Utils.py:344-357).

    torch.median picks the lower of two middle values for even counts;
    we match that rather than jnp.median's midpoint interpolation.

    ``mask`` (C,), if given, excludes clients (dropped stragglers —
    ADVICE r3 #2: a dropped client's row equals the unchanged broadcast
    params and would otherwise vote "no change"): masked rows sort to
    +inf and the lower-middle index is taken over the valid count only.
    Static shapes throughout — the valid count is a traced scalar used
    as a dynamic index, which XLA lowers to a dynamic-slice.
    """
    if mask is None:
        def med(x):
            n = x.shape[0]
            sorted_x = jnp.sort(x, axis=0)
            return sorted_x[(n - 1) // 2]
    else:
        v = jnp.sum(mask).astype(jnp.int32)

        def med(x):
            sorted_x = jnp.sort(jnp.where(_row_mask(mask, x), x, jnp.inf),
                                axis=0)
            out = jnp.take(sorted_x, (v - 1) // 2, axis=0)
            return jnp.where(_valid_bad(mask, x), jnp.nan, out)

    return jax.tree.map(med, stacked)


def trimmed_mean(stacked: Any, trim_ratio: float = 0.1,
                 mask: jnp.ndarray | None = None) -> Any:
    """Per-element sort, drop k = floor(n·ratio) at each end, mean the rest
    (reference: trimmed_mean_aggregation, src/Utils.py:267-302).

    With ``mask`` the trim operates over valid rows only (masked rows
    sort to +inf); k and the kept window become traced scalars selected
    via an iota comparison so shapes stay static.  An over-trimmed valid
    count (2k >= v) yields 0/0 = NaN, which the engine's NaN tripwire
    turns into a failed round — the dynamic analog of the static
    ValueError below."""
    n = jax.tree.leaves(stacked)[0].shape[0]
    if mask is None:
        k = int(n * trim_ratio)
        if 2 * k >= n:
            raise ValueError("Too few clients for the chosen trim ratio.")

        def trim(x):
            sorted_x = jnp.sort(x, axis=0)
            return jnp.mean(sorted_x[k : n - k], axis=0)
    else:
        v = jnp.sum(mask).astype(jnp.int32)
        kd = jnp.floor(v * trim_ratio).astype(jnp.int32)

        def trim(x):
            sorted_x = jnp.sort(jnp.where(_row_mask(mask, x), x, jnp.inf),
                                axis=0)
            i = jnp.arange(n).reshape((-1,) + (1,) * (x.ndim - 1))
            w = ((i >= kd) & (i < v - kd)).astype(x.dtype)
            finite = jnp.where(jnp.isfinite(sorted_x), sorted_x, 0.0)
            out = jnp.sum(finite * w, axis=0) / (v - 2 * kd).astype(x.dtype)
            return jnp.where(_valid_bad(mask, x), jnp.nan, out)

    return jax.tree.map(trim, stacked)


def krum_select(stacked: Any, f: int = 0,
                mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Krum score argmin (Blanchard et al. 2017).

    score_i = sum of the n−f−2 smallest squared L2 distances to the other
    clients; returns the index of the minimal-score client (reference:
    krum, src/Utils.py:326-342; f wiring server.py:384 — note the reference
    effectively always uses f=0, SURVEY.md §2 row 15).

    With ``mask`` (C,), dropped clients are excluded on both sides:
    distances to them become +inf (sorted last, selected out by an iota
    window of length v−f−2 over the valid count v) and their own scores
    become +inf so they are never chosen."""
    flat = pt.tree_ravel_stacked(stacked)  # (N, P)
    n = flat.shape[0]
    sq = jnp.sum(jnp.square(flat[:, None, :] - flat[None, :, :]), axis=-1)  # (N, N)
    # exclude self-distance (0 on the diagonal) the way the reference's
    # j != i loop does, then take the n-f-2 smallest of the rest
    sq = jnp.where(jnp.eye(n, dtype=bool), jnp.inf, sq)
    if mask is None:
        closest = jnp.sort(sq, axis=1)[:, : max(n - f - 2, 1)]
        scores = jnp.sum(closest, axis=1)
        return jnp.argmin(scores)
    valid = mask.astype(bool)
    v = jnp.sum(mask).astype(jnp.int32)
    m_neigh = jnp.maximum(v - f - 2, 1)
    sorted_sq = jnp.sort(jnp.where(valid[None, :], sq, jnp.inf), axis=1)
    w = (jnp.arange(n)[None, :] < m_neigh).astype(flat.dtype)
    finite = jnp.where(jnp.isfinite(sorted_sq), sorted_sq, 0.0)
    scores = jnp.sum(finite * w, axis=1)
    # the finite-zeroing above must only neutralize the inserted +inf
    # sentinels; a candidate whose OWN params are non-finite (diverged)
    # would otherwise look maximally close — poison its score so it is
    # never selected.  Flag by own params, NOT by non-finite distances:
    # distances are symmetric, so distance-based flagging would poison
    # every client and degenerate argmin to index 0 (possibly a masked
    # row).  Innocents' inf distances TO a diverged peer sort outside the
    # m_neigh window (v-f-2 <= v-1-#diverged finite entries for f>=0 with
    # one diverged client; with several, the zeroed tail only lowers all
    # innocents' scores uniformly enough to keep selection sane).
    bad = jnp.any(~jnp.isfinite(flat), axis=1)
    scores = jnp.where(bad, jnp.inf, scores)
    return jnp.argmin(jnp.where(valid, scores, jnp.inf))


def krum(stacked: Any, f: int = 0, mask: jnp.ndarray | None = None) -> Any:
    """Return the selected client's full parameter tree."""
    return pt.tree_take(stacked, krum_select(stacked, f, mask))


def shieldfl_weights(stacked: Any, eps: float = 1e-6,
                     mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """ShieldFL's per-client weights (the defense's actual decision,
    exposed for forensic attribution): normalize flat client vectors,
    reference = their mean, weight_i ∝ 1/(1 − cos_i + ε)."""
    flat = pt.tree_ravel_stacked(stacked)
    unit = flat / (jnp.linalg.norm(flat, axis=1, keepdims=True) + 1e-8)
    if mask is None:
        ref = jnp.mean(unit, axis=0)
    else:
        ref = jnp.sum(unit * mask[:, None], axis=0) / jnp.maximum(
            jnp.sum(mask), 1.0)
    cos = (unit @ ref) / (jnp.linalg.norm(unit, axis=1) * jnp.linalg.norm(ref) + 1e-12)
    weights = 1.0 / (1.0 - cos + eps)
    if mask is not None:
        weights = weights * mask
    return weights


def shieldfl(stacked: Any, eps: float = 1e-6,
             mask: jnp.ndarray | None = None) -> Any:
    """ShieldFL-style cosine-deviation weighting (reference inline code,
    server.py:306-350): weighted average of raw params under
    :func:`shieldfl_weights`.  With ``mask``, dropped clients are excluded
    from the reference direction and zero-weighted in the average."""
    return pt.tree_weighted_mean(stacked, shieldfl_weights(stacked, eps, mask))


def byzantine_keep(stacked: Any, threshold: float = 0.9,
                   mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """The byzantine-tolerance keep weights (exposed for forensic
    attribution): cosine-vs-anchor filter with the reference's
    fall-back-to-everyone semantics (see :func:`byzantine_tolerance`)."""
    flat = pt.tree_ravel_stacked(stacked)  # (N, P)
    if mask is None:
        maskf = jnp.ones((flat.shape[0],), flat.dtype)
    else:
        maskf = mask.astype(flat.dtype)
    anchor = flat[jnp.argmax(maskf)]  # first valid client (0 when unmasked)
    cos = (flat @ anchor) / (
        jnp.linalg.norm(flat, axis=1) * jnp.linalg.norm(anchor) + 1e-12)
    keep = (cos >= threshold).astype(flat.dtype) * maskf
    keep = jnp.where(jnp.sum(keep) > 0, keep, maskf)
    # degenerate all-zero participation mask (every client dropped): the
    # maskf fallback is itself all-zero and tree_weighted_mean would
    # divide by sum(weights)=0 → NaN params (ADVICE.md finding 1).  Fall
    # back to an unweighted mean; callers fail such rounds upstream, but
    # the fused scan body evaluates the aggregate unconditionally and must
    # not see NaNs it didn't create.
    return jnp.where(jnp.sum(maskf) > 0, keep, jnp.ones_like(maskf))


def byzantine_tolerance(stacked: Any, threshold: float = 0.9,
                        mask: jnp.ndarray | None = None) -> Any:
    """Cosine-threshold filter + unweighted mean (reference:
    byzantine_tolerance_aggregation, src/Utils.py:228-248 — dead code there,
    imported at server.py:25 but never dispatched; live here as mode
    "byzantine" for completeness, like the fltracer branch).

    Reference semantics kept exactly: the FIRST model is the trusted
    anchor ("Giả sử mô hình đầu tiên là mô hình gốc" — assume the first is
    the original); keep clients whose flat-vector cosine vs the anchor is
    ``>= threshold`` (the anchor always keeps itself at cos 1.0); if the
    filter empties, fall back to ALL models; average the survivors
    UNWEIGHTED (sum/len over state_dict keys).

    With ``mask`` (C,), dropped clients cannot be the anchor (it moves to
    the first valid row) and are zero-weighted; the fallback is to all
    *valid* clients.  Soft-mask weighting keeps shapes static.
    """
    return pt.tree_weighted_mean(stacked,
                                 byzantine_keep(stacked, threshold, mask))


# ---------------------------------------------------------------------------
# ScionFL
# ---------------------------------------------------------------------------

def quantize_vector(rng: jax.Array, vec: jnp.ndarray):
    """Stochastic 1-bit quantization (reference: quantize_vector,
    src/Utils.py:372-376): Bernoulli on min-max-normalized values."""
    smin, smax = jnp.min(vec), jnp.max(vec)
    probs = (vec - smin) / (smax - smin + 1e-6)
    sigma = jax.random.bernoulli(rng, probs).astype(vec.dtype)
    return sigma, smin, smax


def quantized_l2(sigma: jnp.ndarray, smin, smax) -> jnp.ndarray:
    """L2 norm of the dequantized vector from bit counts
    (reference: l2_norm, src/Utils.py:378-381)."""
    ones = jnp.sum(sigma)
    zeros = sigma.shape[0] - ones
    return jnp.sqrt(zeros * jnp.square(smin) + ones * jnp.square(smax))


def dequantize(sigma: jnp.ndarray, smin, smax) -> jnp.ndarray:
    return smin + sigma * (smax - smin)


def scionfl_weights(
    stacked: Any,
    sizes: jnp.ndarray,
    rng: jax.Array,
    mu_threshold: float = 3.0,
    topk_ratio: float = 0.5,
) -> jnp.ndarray:
    """ScionFL's per-client aggregation weights (the decision, exposed for
    forensic attribution — same ``rng`` reproduces the same stochastic
    quantization and therefore the same filter as the aggregate)."""
    flat = pt.tree_ravel_stacked(stacked)  # (N, P)
    n = flat.shape[0]
    keys = jax.random.split(rng, n)
    sigma, smin, smax = jax.vmap(quantize_vector)(keys, flat)

    l2 = jax.vmap(quantized_l2)(sigma, smin, smax)
    l2_avg = jnp.mean(l2)
    factor = jnp.where(l2 > mu_threshold * l2_avg, (mu_threshold * l2_avg) / l2, 1.0)
    smin, smax = smin * factor, smax * factor

    deq = jax.vmap(dequantize)(sigma, smin, smax)  # (N, P)
    agg = jnp.mean(deq, axis=0)

    cos = (deq @ agg) / (jnp.linalg.norm(deq, axis=1) * jnp.linalg.norm(agg) + 1e-12)
    dist = 1.0 - cos
    # reference threshold: sorted desc, element at index int(topk*n)
    thresh = jnp.sort(dist)[::-1][jnp.minimum(int(topk_ratio * n), n - 1)]
    benign = dist > thresh

    weights = jnp.where(benign, sizes.astype(jnp.float32), 0.0)
    # fall back to all clients if the filter empties (degenerate ties)
    return jnp.where(jnp.sum(weights) > 0, weights,
                     sizes.astype(jnp.float32))


def scionfl(
    stacked: Any,
    sizes: jnp.ndarray,
    rng: jax.Array,
    mu_threshold: float = 3.0,
    topk_ratio: float = 0.5,
) -> Any:
    """ScionFL aggregation (reference: server.py:436-492).

    1. per-client stochastic 1-bit quantization of the flat update;
    2. L2-norm clipping at mu_threshold × mean norm (scales smin/smax);
    3. dequantize + mean -> aggregate direction;
    4. cosine-distance filtering: keep clients with distance ABOVE the
       (1−topk)-quantile — the reference keeps the *most dissimilar* half
       (``s > threshold``, server.py:466); replicated verbatim;
    5. size-weighted FedAvg of the survivors (soft mask: excluded clients
       get zero weight so shapes stay static).
    """
    return pt.tree_weighted_mean(
        stacked,
        scionfl_weights(stacked, sizes, rng, mu_threshold, topk_ratio))


# ---------------------------------------------------------------------------
# FLTrust combine (root training lives in training/fltrust.py)
# ---------------------------------------------------------------------------

def fltrust_trust(client_deltas: Any, root_delta: Any) -> jnp.ndarray:
    """FLTrust's per-client trust scores trust_i = ReLU(cos(Δ_i, Δ_root))
    (exposed for forensic attribution: trust 0 means the client's update
    contributed nothing to the aggregate — the defense removed it)."""
    flat_deltas = pt.tree_ravel_stacked(client_deltas)  # (N, P)
    flat_root = pt.tree_ravel(root_delta)  # (P,)
    norms = jnp.linalg.norm(flat_deltas, axis=1)
    cos = (flat_deltas @ flat_root) / (
        norms * jnp.linalg.norm(flat_root) + 1e-12)
    return jnp.maximum(cos, 0.0)


def fltrust_combine(global_params: Any, client_deltas: Any, root_delta: Any) -> Any:
    """Trust-weighted combination (reference: train_FLTrust,
    server.py:703-743): trust_i = ReLU(cos(Δ_i, Δ_root)); each client delta
    scaled to the root-delta norm; global += Σ trust_i·scaled_i / Σ trust.
    """
    flat_deltas = pt.tree_ravel_stacked(client_deltas)  # (N, P)
    flat_root = pt.tree_ravel(root_delta)  # (P,)
    norm_root = jnp.linalg.norm(flat_root)
    norms = jnp.linalg.norm(flat_deltas, axis=1)
    cos = (flat_deltas @ flat_root) / (norms * norm_root + 1e-12)
    trust = jnp.maximum(cos, 0.0)
    scale = (norm_root / (norms + 1e-6)) * trust

    def combine(g, d):
        s = scale.reshape((-1,) + (1,) * (d.ndim - 1))
        upd = jnp.sum(d * s, axis=0) / (jnp.sum(trust) + 1e-6)
        return g + upd

    return jax.tree.map(combine, global_params, client_deltas)
