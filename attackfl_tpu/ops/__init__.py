from attackfl_tpu.ops import pytree  # noqa: F401
