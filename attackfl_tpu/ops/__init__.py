from attackfl_tpu.ops import metrics, pytree  # noqa: F401
