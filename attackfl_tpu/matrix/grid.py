"""Grid spec: the (attack × defense × seed) cross product, made static.

A :class:`GridSpec` names the sweep's three axes; :func:`expand_cells`
turns it into the flat cell list the executor partitions into compile
groups:

* **batched** — defenses whose aggregate is bit-stable under ``vmap``
  (measured: per-cell outputs byte-equal to the standalone program).
  These cells share ONE vmapped body per attack with a ``lax.switch``
  defense dispatch.
* **mapped** — FLTrust: shape-compatible, but its in-aggregate root
  training lowers to different XLA (batched matmuls) under vmap and
  drifts at FP epsilon (~1e-8 measured on CPU), breaking the per-cell
  bit-identity contract.  Its cells run inside the SAME compiled program
  through ``lax.map`` — sequential per cell, each slice the unbatched
  body, bit-identical by construction.
* **host** — gmm / fltracer filter with sklearn-style host code between
  training and aggregation; their cells fall back to per-cell
  synchronous runs with a warning, exactly like the pipelined executor
  does today.
* **special** — hyper: its state pytree (hnet params + opt state) is
  structure-incompatible with the plain cells, so each hyper cell runs
  per-cell on its own compiled fused program (per-cell specialization).

The parity contract pins two base-config requirements, both validated by
:meth:`GridSpec.validate_base`:

* ``prng_impl`` must be ``threefry2x32`` — threefry keys are
  vmap-invariant; rbg keys are NOT (jax's RngBitGenerator returns
  different bits under vmap, measured as ~1e-2 divergence), so a
  batched rbg cell could never match its standalone run.
* ``partition`` must be ``iid`` — dirichlet pools derive from
  ``random_seed``, which is the grid's per-cell axis: the batched
  program shares one pool while standalone cell configs would each
  build their own.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from attackfl_tpu.config import ATTACK_MODES, AttackSpec, Config

# Defense classification (see module doc).  byzantine and fltracer were
# dead code in the reference but are live modes here, so the full grid a
# user can request is every non-hyper AGGREGATION_MODE.
BATCHED_DEFENSES = ("fedavg", "median", "trimmed_mean", "krum", "shieldfl",
                    "scionfl", "byzantine")
MAPPED_DEFENSES = ("FLTrust",)
HOST_DEFENSES = ("gmm", "fltracer")
SPECIAL_DEFENSES = ("hyper",)
ALL_DEFENSES = (BATCHED_DEFENSES + MAPPED_DEFENSES + HOST_DEFENSES
                + SPECIAL_DEFENSES)


def defense_group(defense: str) -> str:
    if defense in BATCHED_DEFENSES:
        return "batched"
    if defense in MAPPED_DEFENSES:
        return "mapped"
    if defense in HOST_DEFENSES:
        return "host"
    if defense in SPECIAL_DEFENSES:
        return "special"
    raise ValueError(
        f"unknown defense {defense!r}; choose from {ALL_DEFENSES}")


@dataclass(frozen=True)
class Cell:
    """One grid cell: an attack spec, a defense mode, a seed."""

    attack: AttackSpec
    defense: str
    seed: int

    @property
    def key(self) -> str:
        """Flat cell identity, stable across processes — the ledger's
        ``cell`` key and the per-cell directory name."""
        return f"{self.attack.mode}x{self.defense}.s{self.seed}"

    @property
    def group(self) -> str:
        return defense_group(self.defense)

    def describe(self) -> dict[str, Any]:
        return {"attack": self.attack.mode, "defense": self.defense,
                "seed": self.seed, "group": self.group}


@dataclass(frozen=True)
class GridSpec:
    """The sweep's static geometry.

    ``attacks`` fix everything about the attacker cohort EXCEPT the mode
    (indices, activation round, args may differ per spec) — the cohort
    SIZE must match across specs so every cell shares one state
    structure (same genuine count => same leak-pool shape).
    """

    attacks: tuple[AttackSpec, ...]
    defenses: tuple[str, ...]
    seeds: tuple[int, ...]
    rounds: int = 3
    chunk: int = 4  # rounds per compiled-scan dispatch

    def __post_init__(self):
        if not self.attacks or not self.defenses or not self.seeds:
            raise ValueError("matrix grid needs >= 1 attack, defense, seed")
        for defense in self.defenses:
            defense_group(defense)  # raises on unknown
        if len(set(self.defenses)) != len(self.defenses):
            raise ValueError(f"duplicate defenses in {self.defenses}")
        if len(set(self.seeds)) != len(self.seeds):
            raise ValueError(f"duplicate seeds in {self.seeds}")
        modes = [a.mode for a in self.attacks]
        if len(set(modes)) != len(modes):
            raise ValueError(f"duplicate attack modes in {modes}")
        sizes = {len(a.client_ids) or a.num_clients for a in self.attacks}
        if len(sizes) != 1:
            raise ValueError(
                "every attack spec must claim the same number of clients "
                f"(one shared state structure per sweep), got {sizes}")
        if self.rounds < 1 or self.chunk < 1:
            raise ValueError("rounds and chunk must be >= 1")

    @property
    def n_cells(self) -> int:
        return len(self.attacks) * len(self.defenses) * len(self.seeds)

    def describe(self) -> dict[str, Any]:
        return {
            "attacks": [a.mode for a in self.attacks],
            "defenses": list(self.defenses),
            "seeds": list(self.seeds),
            "rounds": self.rounds,
            "n_cells": self.n_cells,
        }

    def validate_base(self, cfg: Config) -> None:
        """The parity-contract preconditions (see module doc)."""
        if cfg.prng_impl != "threefry2x32":
            raise ValueError(
                f"matrix sweeps need prng_impl 'threefry2x32', got "
                f"{cfg.prng_impl!r}: threefry keys are vmap-invariant; rbg "
                "keys return different bits under vmap, so a batched cell "
                "could never match its standalone run bit-for-bit")
        if cfg.partition != "iid":
            raise ValueError(
                "matrix sweeps need partition 'iid': dirichlet pools "
                "derive from random_seed, which is the grid's per-cell "
                "seed axis")
        if cfg.local_backend != "xla":
            raise ValueError(
                "matrix sweeps run on local_backend 'xla' (the pallas "
                "kernel is a single-workload fast path)")
        if cfg.hyper_detection.enable and any(
                d == "hyper" for d in self.defenses):
            raise ValueError(
                "hyper-detection runs DBSCAN on host per round; drop "
                "'hyper' from the grid or disable hyper-detection")
        if cfg.validation_async:
            raise ValueError(
                "matrix sweeps validate in-program (the fused-body "
                "cadence); validation_async does not apply")


def expand_cells(spec: GridSpec) -> list[Cell]:
    """The flat cell list, attack-major then defense then seed — a
    deterministic order every consumer (ledger, status, parity tests)
    shares."""
    return [Cell(attack=a, defense=d, seed=s)
            for a in spec.attacks for d in spec.defenses for s in spec.seeds]


def cell_config(base: Config, cell: Cell, rounds: int | None = None,
                **overrides: Any) -> Config:
    """The standalone config a cell's parity twin runs with: the base
    workload, this cell's defense as the mode, this cell's attack as the
    only attacker spec, this cell's seed.  ``attackfl-tpu run`` on this
    config must produce bit-identical final params to the cell's slice
    of the sweep.  ``data_seed`` is pinned to the sweep's base seed: the
    grid's seed axis varies the simulation stream only — every cell saw
    the ONE shared dataset."""
    return base.replace(
        mode=cell.defense,
        attacks=(cell.attack,),
        random_seed=cell.seed,
        data_seed=(base.data_seed if base.data_seed is not None
                   else base.random_seed),
        num_round=rounds if rounds is not None else base.num_round,
        **overrides,
    )


def _attack_from_entry(entry: Any, default_clients: int,
                       default_round: int) -> AttackSpec:
    if isinstance(entry, str):
        return AttackSpec(mode=entry, num_clients=default_clients,
                          attack_round=default_round)
    if isinstance(entry, dict):
        # AttackSpec normalizes args to floats itself (config.py)
        return AttackSpec(
            mode=str(entry.get("mode", "LIE")),
            num_clients=int(entry.get("num-clients", default_clients)),
            client_ids=tuple(entry.get("client-ids", []) or []),
            attack_round=int(entry.get("attack-round", default_round)),
            args=tuple(entry.get("args", []) or []),
        )
    raise ValueError(f"bad matrix attack entry {entry!r}")


def grid_from_dict(raw: dict[str, Any]) -> GridSpec:
    """Parse a ``matrix:`` config section (or a standalone grid file)::

        matrix:
          attacks: [LIE, Random, Min-Max]      # or full mappings
          attack-clients: 1                    # shorthand cohort size
          attack-round: 2                      # shorthand activation
          defenses: [fedavg, krum, median]
          seeds: [1, 2]
          rounds: 5
          chunk: 4
    """
    if not isinstance(raw, dict):
        raise ValueError(f"matrix grid must be a mapping, got {type(raw)}")
    default_clients = int(raw.get("attack-clients", 1))
    default_round = int(raw.get("attack-round", 2))
    attacks = tuple(_attack_from_entry(e, default_clients, default_round)
                    for e in (raw.get("attacks") or list(ATTACK_MODES)))
    defenses = tuple(str(d) for d in (raw.get("defenses") or ["fedavg"]))
    seeds = tuple(int(s) for s in (raw.get("seeds") or [1]))
    kw: dict[str, Any] = {}
    if "rounds" in raw:
        kw["rounds"] = int(raw["rounds"])
    if "chunk" in raw:
        kw["chunk"] = int(raw["chunk"])
    return GridSpec(attacks=attacks, defenses=defenses, seeds=seeds, **kw)
