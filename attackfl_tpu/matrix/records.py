"""Per-cell ledger records: one sweep submit -> k×45 records.

Distills the executor's per-cell round histories (already host Python —
the chunk resolution materialized them) into one ledger record per cell,
all sharing a ``sweep_id``.  Jax-free and sync-free by construction:
this is pure dict-shaping over values the executor hands in.

Cell records join the cross-run ledger on TWO keys:

* ``fingerprint`` — the fingerprint of the cell's STANDALONE config
  (:func:`attackfl_tpu.matrix.grid.cell_config`), so a matrix cell and
  its standalone parity twin share a baseline pool (their params are
  bit-identical by contract, like sync/pipelined runs today);
* ``cell`` — the flat cell key.  The rolling-baseline selector
  (:func:`attackfl_tpu.ledger.compare.rolling_baseline`) matches peers
  on it, so two cells that happen to share a config fingerprint can
  never cross-contaminate each other's baselines (the ISSUE 9
  satellite).
"""

from __future__ import annotations

from typing import Any

from attackfl_tpu.ledger.record import LEDGER_SCHEMA_VERSION
from attackfl_tpu.matrix.grid import Cell, cell_config
from attackfl_tpu.utils.fingerprint import config_fingerprint

# final-quality keys lifted from a cell's last ok round, when present
_QUALITY_KEYS = ("roc_auc", "accuracy", "nll", "train_loss")


def summarize_cell_events(events: list[dict[str, Any]]
                          ) -> dict[str, Any]:
    """Forensics / numerics / lifecycle-count blocks for ONE cell's
    event slice, shaped exactly like ``derive_record``'s
    (:mod:`attackfl_tpu.ledger.record`) so the science outcome join
    reads matrix cells and standalone runs with one code path.  Returns
    ``{}`` when the slice measured nothing (telemetry off, batched cell
    without numerics, pre-v13 artifact)."""
    from attackfl_tpu.telemetry.forensics import forensics_summary
    from attackfl_tpu.telemetry.numerics import numerics_summary

    out: dict[str, Any] = {}
    forensics = forensics_summary(events)
    if forensics is not None:
        out["forensics"] = {k: forensics.get(k) for k in
                            ("tpr", "fpr", "precision", "rounds",
                             "attack_rounds", "rollbacks")}
    numerics = numerics_summary(events)
    if numerics is not None:
        numerics_out: dict[str, Any] = {
            "rounds": numerics.get("rounds"),
            "nonfinite_total": numerics.get("nonfinite_total"),
            **(numerics.get("final") or {}),
        }
        separation = numerics.get("separation")
        if separation:
            numerics_out["sep_margin_mean"] = separation.get("margin_mean")
            numerics_out["sep_margin_min"] = separation.get("margin_min")
        out["numerics"] = numerics_out
    counts = {
        "rollbacks": sum(1 for e in events
                         if e.get("kind") == "rollback"),
        "degrades": sum(1 for e in events if e.get("kind") == "degrade"),
    }
    if any(counts.values()):
        out["counts"] = counts
    return out


def cell_event_summaries(events: list[dict[str, Any]]
                         ) -> dict[str, dict[str, Any]]:
    """Group a sweep spool's events by their ``cell`` stamp and
    summarize each slice.  Batched cells' drainer events arrive already
    stamped (``matrix_exec._CellTelemetry``); a fallback cell's own
    spool is not — the executor stamps those at read time before
    calling this."""
    by_cell: dict[str, list[dict[str, Any]]] = {}
    for event in events:
        cell = event.get("cell")
        if isinstance(cell, str):
            by_cell.setdefault(cell, []).append(event)
    out: dict[str, dict[str, Any]] = {}
    for cell, chunk in by_cell.items():
        summary = summarize_cell_events(chunk)
        if summary:
            out[cell] = summary
    return out


def _final_quality(history: list[dict[str, Any]]) -> dict[str, float]:
    final: dict[str, float] = {}
    for entry in history:
        for key in _QUALITY_KEYS:
            value = entry.get(key)
            if (isinstance(value, (int, float))
                    and not isinstance(value, bool) and value == value):
                final[key] = round(value, 6)
    return final


def cell_record(
    *,
    sweep_id: str,
    cell: Cell,
    base_cfg,
    rounds: int,
    history: list[dict[str, Any]],
    run_id: str | None,
    ts: float | None,
    wall_s: float,
    n_cells: int,
    executor: str = "matrix",
    resumed: bool = False,
    provenance: dict[str, Any] | None = None,
    programs: dict[str, Any] | None = None,
    event_summary: dict[str, Any] | None = None,
) -> dict[str, Any]:
    """One cell's ledger record (``ledger_schema`` 1, ``source``
    "matrix").  ``wall_s`` is the SWEEP wall clock: cells share every
    dispatch, so the honest per-cell attribution is the amortized share
    — recorded as such, never dressed up as a standalone measurement.
    ``programs`` (ISSUE 11) is the sweep's program-profile capture — the
    grid program covers every device cell, so each cell record carries
    the SHARED profile (flops/bytes/peak memory of the whole grid
    dispatch), folded into a static ``utilization`` block.
    ``event_summary`` (ISSUE 17) is :func:`summarize_cell_events`'s
    output for this cell — forensics/numerics blocks plus extra
    lifecycle counts, merged in so the science outcome join sees the
    same columns a standalone run's record carries."""
    cfg = cell_config(base_cfg, cell, rounds=rounds)
    ok_rounds = sum(1 for h in history if h.get("ok"))
    amortized = wall_s / max(n_cells, 1)
    record: dict[str, Any] = {
        "ledger_schema": LEDGER_SCHEMA_VERSION,
        "ts": ts,
        "source": "matrix",
        "run_id": run_id,
        "executor": executor,
        "resumed": resumed,
        "fingerprint": config_fingerprint(cfg),
        "sweep_id": sweep_id,
        "cell": cell.key,
        "cell_detail": cell.describe(),
        "mode": cell.defense,
        "model": base_cfg.model,
        "data_name": base_cfg.data_name,
        "total_clients": base_cfg.total_clients,
        "rounds": len(history),
        "ok_rounds": ok_rounds,
        "wall_seconds": round(wall_s, 6),
        "rounds_per_sec_steady": (
            round(len(history) / wall_s, 6) if wall_s > 0 else None),
        "time_attribution": {
            "wall_s": round(wall_s, 6),
            "amortized_cell_wall_s": round(amortized, 6),
        },
        "counts": {
            "rounds_failed": len(history) - ok_rounds,
        },
        "final": _final_quality(history),
    }
    if event_summary:
        for section in ("forensics", "numerics"):
            if event_summary.get(section):
                record[section] = dict(event_summary[section])
        record["counts"].update(event_summary.get("counts") or {})
    if programs:
        from attackfl_tpu.costmodel.roofline import utilization_summary

        record["programs"] = programs
        device_kind = next((p.get("device_kind") for p in programs.values()
                            if isinstance(p, dict)
                            and p.get("device_kind")), "")
        utilization = utilization_summary(programs, None, device_kind)
        if utilization is not None:
            record["utilization"] = utilization
    record.update(provenance or {})
    return record


def sweep_records(
    *,
    sweep_id: str,
    cells: list[Cell],
    histories: dict[str, list[dict[str, Any]]],
    base_cfg,
    rounds: int,
    run_id: str | None,
    ts: float | None,
    wall_s: float,
    resumed: bool = False,
    provenance: dict[str, Any] | None = None,
    programs: dict[str, Any] | None = None,
    event_summaries: dict[str, dict[str, Any]] | None = None,
) -> list[dict[str, Any]]:
    """Records for every cell that has a history, in grid order."""
    summaries = event_summaries or {}
    return [
        cell_record(
            sweep_id=sweep_id, cell=cell, base_cfg=base_cfg, rounds=rounds,
            history=histories.get(cell.key) or [], run_id=run_id, ts=ts,
            wall_s=wall_s, n_cells=len(cells), resumed=resumed,
            provenance=provenance, programs=programs,
            event_summary=summaries.get(cell.key))
        for cell in cells if cell.key in histories
    ]
