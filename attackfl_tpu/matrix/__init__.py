"""Scenario matrix engine (ISSUE 9): one compiled program per sweep.

The paper's result table is a cross product — attacks × defenses × seeds
— and running it as 45×k serial processes pays 45×k compiles plus 45×k
rounds of dispatch/ledger/telemetry plumbing.  This package compiles the
whole grid ONCE and runs it as one device program:

* :mod:`attackfl_tpu.matrix.grid` — the grid spec (attack specs, defense
  modes, seeds), cell expansion, and per-cell standalone configs (the
  parity contract: every cell's final params are bit-identical to a
  standalone ``attackfl-tpu run`` of its cell config);
* :mod:`attackfl_tpu.matrix.program` — the traced-only batched round
  body: per attack, vmap over the (defense × seed) cell axis with a
  ``lax.switch`` defense dispatch for the vmap-bit-stable defenses, and
  ``lax.map`` (sequential, unbatched per cell — bit-identical by
  construction) for FLTrust, whose in-aggregate root training XLA lowers
  differently when batched;
* :mod:`attackfl_tpu.matrix.records` — per-cell ledger records sharing a
  ``sweep_id`` (k×45 records from one submit);
* :mod:`attackfl_tpu.matrix.cli` — ``attackfl-tpu matrix run|status``.

The executor itself lives in :mod:`attackfl_tpu.training.matrix_exec`
(``MatrixRun``) because the host-side chunk resolution is an audited
sync point under the host-sync lint, exactly like the engine's existing
executors; everything in THIS package is traced-only / sync-free (linted
with NO allowlist).
"""

from attackfl_tpu.matrix.grid import (  # noqa: F401
    BATCHED_DEFENSES, HOST_DEFENSES, MAPPED_DEFENSES, Cell, GridSpec,
    cell_config, expand_cells, grid_from_dict,
)
