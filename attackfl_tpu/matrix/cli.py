"""``attackfl-tpu matrix run|status``: the sweep front door.

``run`` reads the grid from the config's ``matrix:`` section (see
:func:`attackfl_tpu.matrix.grid.grid_from_dict` for the format), lets
flags override each axis, and executes the whole (attack × defense ×
seed) grid as one compiled program
(:class:`attackfl_tpu.training.matrix_exec.MatrixRun`).  ``status`` is
jax-free: it reads the sweep's ledger records (all sharing a
``sweep_id``) and renders the grid's completion/quality table.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

from attackfl_tpu.telemetry import print_with_color


def _parse_list(text: str) -> list[str]:
    return [item.strip() for item in text.split(",") if item.strip()]


def run_main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="attackfl-tpu matrix run",
        description="Run a full (attack x defense x seed) sweep as one "
                    "compiled program.")
    parser.add_argument("--config", type=str, default="config.yaml")
    parser.add_argument("--attacks", type=str, default=None,
                        help="comma list of attack modes (overrides the "
                             "config's matrix.attacks)")
    parser.add_argument("--defenses", type=str, default=None,
                        help="comma list of defense modes")
    parser.add_argument("--seeds", type=str, default=None,
                        help="comma list of seeds")
    parser.add_argument("--rounds", type=int, default=None)
    parser.add_argument("--chunk", type=int, default=None,
                        help="rounds per compiled-scan dispatch")
    parser.add_argument("--sweep-dir", type=str, default=None,
                        help="sweep working directory (telemetry + "
                             "checkpoints + per-cell fallback dirs; "
                             "default: the config's log_path)")
    parser.add_argument("--sweep-id", type=str, default=None,
                        help="explicit sweep id (default: random)")
    parser.add_argument("--resume", action="store_true",
                        help="continue an interrupted sweep from its "
                             "newest valid checkpoint (byte-identical "
                             "grid)")
    parser.add_argument("--mesh", action="store_true",
                        help="shard the sweep's CELL axis across all "
                             "visible devices (cells are embarrassingly "
                             "parallel; per-cell results stay "
                             "bit-identical)")
    args = parser.parse_args(argv)

    import yaml

    from attackfl_tpu.config import load_config
    from attackfl_tpu.matrix.grid import grid_from_dict

    cfg = load_config(args.config)
    with open(args.config) as fh:
        raw = yaml.safe_load(fh) or {}
    grid_raw = dict(raw.get("matrix") or {})
    if args.attacks:
        grid_raw["attacks"] = _parse_list(args.attacks)
    if args.defenses:
        grid_raw["defenses"] = _parse_list(args.defenses)
    if args.seeds:
        grid_raw["seeds"] = [int(s) for s in _parse_list(args.seeds)]
    if args.rounds is not None:
        grid_raw["rounds"] = args.rounds
    if args.chunk is not None:
        grid_raw["chunk"] = args.chunk
    grid = grid_from_dict(grid_raw)

    overrides: dict[str, Any] = {}
    if args.sweep_dir:
        overrides["log_path"] = args.sweep_dir
        overrides["checkpoint_dir"] = args.sweep_dir
    if args.resume:
        overrides["resume"] = True
    if cfg.prng_impl != "threefry2x32":
        # the batched grid needs vmap-invariant keys (grid.validate_base)
        print_with_color(
            f"[matrix] prng_impl {cfg.prng_impl!r} is not vmap-invariant; "
            "forcing threefry2x32 for this sweep", "yellow")
        overrides["prng_impl"] = "threefry2x32"
    if overrides:
        cfg = cfg.replace(**overrides)

    from attackfl_tpu.training.matrix_exec import MatrixRun

    runner = MatrixRun(cfg, grid, sweep_id=args.sweep_id,
                       use_mesh=args.mesh)
    print_with_color(
        f"[matrix] sweep {runner.sweep_id}: {grid.n_cells} cells "
        f"({len(runner.device_cells)} in the compiled grid, "
        f"{len(runner.fallback_cells)} per-cell fallback"
        + (f"; cell axis over {runner.mesh.size} devices"
           if runner.mesh is not None else "") + ")", "cyan")
    try:
        final_params, histories = runner.run()
    finally:
        if runner.telemetry.enabled:
            print_with_color(
                f"Telemetry: {runner.telemetry.events.path} — per-cell "
                f"records: `attackfl-tpu matrix status --sweep-id "
                f"{runner.sweep_id}`", "cyan")
        runner.close()
    ok_cells = sum(
        1 for h in histories.values()
        if sum(1 for e in h if e.get("ok")) >= grid.rounds)
    print_with_color(
        f"[matrix] sweep {runner.sweep_id} finished: "
        f"{len(histories)}/{grid.n_cells} cells ran, "
        f"{ok_cells} completed all {grid.rounds} rounds", "green")
    return 0


def status_main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="attackfl-tpu matrix status",
        description="Render a sweep's per-cell ledger records as a grid "
                    "table (jax-free).")
    parser.add_argument("--dir", type=str, default=None,
                        help="ledger directory (default: "
                             "$ATTACKFL_LEDGER_DIR or ./ledger)")
    parser.add_argument("--sweep-id", type=str, default=None,
                        help="sweep to show (default: the newest)")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    from attackfl_tpu.ledger.store import LedgerStore, resolve_ledger_dir

    store = LedgerStore(args.dir or resolve_ledger_dir())
    records, _ = store.load()
    cells = [r for r in records if r.get("source") == "matrix"
             and r.get("sweep_id")]
    if not cells:
        print(f"no matrix records in {store.directory!r}", file=sys.stderr)
        return 2
    sweep_id = args.sweep_id or cells[-1]["sweep_id"]
    cells = [r for r in cells if r.get("sweep_id") == sweep_id]
    if not cells:
        print(f"no records for sweep {sweep_id!r}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(cells, indent=1))
        return 0
    # the science join supplies the quality/damage columns (ISSUE 17):
    # damage = the cell's `none`-baseline quality minus its own
    from attackfl_tpu.science.outcomes import outcome_rows

    joined = {row["cell"]: row
              for row in outcome_rows(cells, sweep_id=sweep_id)}
    print(f"sweep {sweep_id}: {len(cells)} cell record(s)")
    print(f"{'cell':<30}{'rounds':>8}{'ok':>5}{'roc_auc':>9}"
          f"{'accuracy':>10}{'loss':>9}{'quality':>9}{'damage':>9}")
    for record in cells:
        final = record.get("final") or {}
        row = joined.get(record.get("cell")) or {}

        def fmt(value) -> str:
            return (f"{value:.4f}" if isinstance(value, (int, float))
                    and not isinstance(value, bool) else "-")

        print(f"{str(record.get('cell'))[:29]:<30}"
              f"{record.get('rounds', 0):>8}"
              f"{record.get('ok_rounds', 0):>5}"
              f"{fmt(final.get('roc_auc')):>9}"
              f"{fmt(final.get('accuracy')):>10}"
              f"{fmt(final.get('train_loss')):>9}"
              f"{fmt(row.get('quality')):>9}"
              f"{fmt(row.get('damage')):>9}")
    return 0


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args or args[0] in ("-h", "--help"):
        print("usage: attackfl-tpu matrix run|status [args]\n"
              "  run     execute a sweep (grid from the config's matrix: "
              "section + flag overrides)\n"
              "  status  per-cell completion/quality table from the "
              "sweep's ledger records")
        return 0 if args else 2
    if args[0] == "run":
        return run_main(args[1:])
    if args[0] == "status":
        return status_main(args[1:])
    print(f"unknown matrix command {args[0]!r}", file=sys.stderr)
    return 2
