"""The batched matrix round body — traced-only, sync-free.

One sweep round is ONE device program assembled from static compile
groups (attack-major, matching :func:`attackfl_tpu.matrix.grid.
expand_cells`):

* per attack mode, ONE ``round_step`` is built (the attack geometry is
  static program structure) and its cells vmap over the (defense × seed)
  axis — the per-cell defense is a ``lax.switch`` over the grid's
  shape-compatible aggregate branches, driven by a per-cell index array;
* FLTrust cells ride ``lax.map`` over the same body (sequential slices,
  unbatched — the bit-identity rationale lives in
  :mod:`attackfl_tpu.matrix.grid`).

The cell body mirrors the engine's fused scan body
(``Simulator._build_fused_body``, plain branch) operation for
operation — same rng split pattern, same validation cadence gate, same
accept-select, same train-failed metric masking — because the parity
contract (cell == standalone run, bit-for-bit) is only as strong as
that mirror.  ``tests/test_matrix.py`` enforces it against both the
sync and fused standalone executors.

Everything here is traced: the host-sync lint runs over this package
with NO allowlist.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp


def build_cell_body(
    round_step: Callable,
    branches: list[Callable],
    num_clients: int,
    eval_fn: Callable | None,
    val_every: int,
    numerics_step: Callable | None,
) -> Callable:
    """One cell's round as a pure function of (cell_state, defense_idx).

    ``branches`` are uniform-signature aggregates
    ``(global_params, stacked, sizes, weights_mask, rng) -> new_global``;
    a single-branch list skips the switch entirely (the mapped/FLTrust
    group, where the defense is static).  The body is the engine's fused
    plain-mode body with the aggregate dispatch swapped for the switch.
    """
    wmask = jnp.ones((num_clients,), jnp.float32)
    val_every = max(int(val_every), 1)

    def gated_eval(b, make_ev):
        # validation cadence on the broadcast clock — the same gate the
        # fused body applies, so skipped rounds pay no eval FLOPs and
        # report NaN metrics
        if val_every == 1:
            return make_ev(None)
        struct = jax.eval_shape(make_ev, None)

        def skip(_):
            return {
                k: (jnp.ones(s.shape, s.dtype) if k == "ok"
                    else jnp.full(s.shape, jnp.nan, s.dtype))
                for k, s in struct.items()
            }

        return jax.lax.cond(b % val_every == 0, make_ev, skip, None)

    def accept(flag, new, old):
        return jax.tree.map(lambda n, o: jnp.where(flag, n, o), new, old)

    def body(state, defense_idx):
        rng, k_round, k_agg = jax.random.split(state["rng"], 3)
        b = state["broadcasts"] + 1
        stacked, sizes, new_gen, train_ok, loss = round_step(
            state["global_params"], state["prev_genuine"],
            state["have_genuine"], k_round, b,
        )
        round_mask = wmask * (sizes > 0)
        if len(branches) == 1:
            new_global = branches[0](
                state["global_params"], stacked, sizes, round_mask, k_agg)
        else:
            new_global = jax.lax.switch(
                defense_idx, branches,
                state["global_params"], stacked, sizes, round_mask, k_agg)
        ok = train_ok & jnp.any(round_mask > 0)
        metrics = {"train_loss": loss}
        if eval_fn is not None:
            ev = gated_eval(b, lambda _: eval_fn(params=new_global))
            ok = ok & ev.pop("ok")
            # train-failed rounds mask their val metrics to NaN (history
            # parity with the per-round path, same as the fused body)
            metrics.update(
                {k: jnp.where(train_ok, v, jnp.nan) for k, v in ev.items()})
        new_state = {
            "global_params": accept(ok, new_global, state["global_params"]),
            # round_step selects the leak pool internally (ok-gated)
            "prev_genuine": new_gen,
            "have_genuine": state["have_genuine"] | train_ok,
            "rng": rng,
            "completed_rounds": state["completed_rounds"]
            + ok.astype(jnp.int32),
            "broadcasts": b,
        }
        if numerics_step is not None:
            new_state["numerics"], metrics["numerics_row"] = numerics_step(
                state["numerics"], state["global_params"],
                new_state["global_params"], stacked, sizes, loss, ok, b)
        metrics["ok"] = ok
        return new_state, metrics

    return body


def build_matrix_body(groups: dict[str, dict[str, Any]]) -> Callable:
    """The whole grid's round as one traced function over the grouped
    state pytree.

    ``groups`` maps a stable group name (``"<attack>:batched"`` /
    ``"<attack>:mapped"``) to ``{"body": cell_body, "kind":
    "batched"|"mapped", "defense_idx": jnp.ndarray | None}``.  Batched
    groups vmap the body over their stacked cell axis (defense_idx is
    the per-cell switch driver); mapped groups ``lax.map`` it (their
    body closed over a single static branch — defense_idx unused).

    The returned callable has the scan-body shape
    ``(state, _) -> (state, metrics)`` so the executor can wrap it in
    ``lax.scan`` for chunked dispatch exactly like the fused executor.
    """
    # static iteration order: group name — deterministic program
    # structure across processes (a set here would be a retrace hazard)
    names = sorted(groups)

    def matrix_body(state, _):
        new_state: dict[str, Any] = {}
        metrics: dict[str, Any] = {}
        for name in names:
            group = groups[name]
            body = group["body"]
            if group["kind"] == "batched":
                didx = group["defense_idx"]
                new_state[name], metrics[name] = jax.vmap(body)(
                    state[name], didx)
            else:
                new_state[name], metrics[name] = jax.lax.map(
                    lambda s, b=body: b(s, jnp.asarray(0)), state[name])
        return new_state, metrics

    return matrix_body
