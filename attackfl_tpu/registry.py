"""Name-keyed model registry.

The reference resolves the config's ``model:`` string by reflection on its
model module — ``getattr(src.Model, model_name)`` at server.py:139-142,
src/RpcClient.py:74-77 and src/Validation.py:25-28 — making class names part
of the public API.  This registry preserves that contract (same names:
``CNNModel``, ``RNNModel``, ``TransformerModel``, ``TransformerClassifier``)
with an explicit table instead of reflection.
"""

from __future__ import annotations

from typing import Callable

MODEL_REGISTRY: dict[str, Callable] = {}


def register_model(name: str) -> Callable:
    def deco(cls):
        MODEL_REGISTRY[name] = cls
        return cls

    return deco


def get_model(name: str, **kwargs):
    """Instantiate a registered model by name (the reference's
    ``getattr(src.Model, name)()`` call)."""
    # Import for side-effect registration on first use.
    import attackfl_tpu.models  # noqa: F401

    if name not in MODEL_REGISTRY:
        raise ValueError(
            f"Model name '{name}' is not valid. Registered: {sorted(MODEL_REGISTRY)}"
        )
    return MODEL_REGISTRY[name](**kwargs)
