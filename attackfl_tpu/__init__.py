"""attackfl_tpu — a TPU-native federated-learning poisoning-attack framework.

A ground-up JAX/XLA re-design of the capabilities of the reference FL
poisoning testbed (``filrg/attackFL``).  Where the reference runs one OS
process per client and ships pickled tensors through RabbitMQ
(reference: server.py:100-185, src/RpcClient.py:16-188), this framework
runs the *entire* federation in-process on a TPU mesh:

* Clients are a **leading pytree axis** — N client replicas stacked into one
  parameter pytree, locally trained with ``jax.vmap`` and sharded across
  devices with ``jax.sharding`` / ``shard_map`` over a ``clients`` mesh axis.
* "Broadcast" is sharding-implied replication, "collect + aggregate" is a
  reduction along the client axis compiled to XLA collectives over ICI —
  there is no broker, no serialization, no pickle in the hot path.
* Attacks (Random / LIE / Min-Max / Min-Sum / Opt-Fang) are pure tensor
  programs over the stacked genuine updates (``lax.while_loop`` for the
  γ-searches), and aggregation defenses (FedAvg, median, trimmed-mean,
  Krum, ShieldFL, ScionFL, FLTrust, GMM filter, FLTracer, hypernetwork
  personalization) are pure functions from (stacked params, sizes) to a
  global pytree.

Public API mirrors the reference's surface (config.yaml schema, model
registry keyed by class name, CLI launchers) while replacing its transport
and execution model wholesale.
"""

__version__ = "0.1.0"

from attackfl_tpu.config import Config, load_config  # noqa: F401
from attackfl_tpu.registry import get_model, register_model, MODEL_REGISTRY  # noqa: F401
