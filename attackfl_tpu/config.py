"""Typed configuration for attackfl_tpu.

Parses the same ``config.yaml`` schema as the reference testbed
(reference: config.yaml:1-38, read at server.py:55-89 and client.py:42-48)
into frozen dataclasses, and extends it with sections the reference put on
the client CLI (attacker specs, reference: client.py:19-38) or did not have
at all (TPU mesh layout).

The ``rabbit:`` section is accepted and ignored — there is no broker in
this framework; transport is an in-process sharded array axis.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Sequence

import yaml

from attackfl_tpu.faults.plan import FaultSpec, faults_from_config

# Server aggregation modes, matching the reference's dispatch strings
# (reference: server.py:287-494).  "fltracer" was dead code there
# (server.py:395-435) but is live here.
AGGREGATION_MODES = (
    "fedavg",
    "hyper",
    "FLTrust",
    "trimmed_mean",
    "shieldfl",
    "gmm",
    "krum",
    "median",
    "scionfl",
    "fltracer",
    # byzantine_tolerance_aggregation (Utils.py:228-248) — also dead in the
    # reference (imported at server.py:25, never dispatched), live here.
    "byzantine",
)

ATTACK_MODES = ("Random", "Min-Max", "Min-Sum", "Opt-Fang", "LIE")

# The clean-baseline sentinel on the matrix attack axis (ISSUE 17): an
# attacker cohort that never fires.  Not in ATTACK_MODES — the five real
# attacks stay the default sweep axis — but AttackSpec accepts it, so a
# `none` cell keeps the SAME cohort geometry (the configured attackers
# are still excluded from the genuine leak pool) while every client
# trains genuinely every round.  That makes attack damage a paired
# measurement: `none` vs attacked cells differ ONLY in the attack.
NONE_ATTACK = "none"

# Hard ceiling on the pipelined executor's in-flight round queue: beyond
# this, each extra slot only adds device-state residency (one full state
# pytree per slot when checkpointing) without host latency left to hide.
MAX_PIPELINE_DEPTH = 32

DATA_NAMES = ("ICU", "HAR", "CIFAR10")


@dataclass(frozen=True)
class HyperDetectionConfig:
    """Embedding anomaly defense knobs (reference: config.yaml:6-11)."""

    enable: bool = False
    cosine_search: int = 10
    n_components: int = 3
    eps: float = 0.007
    min_samples: int = 3
    # Round index (1-based) from which detection starts firing.  The
    # reference hardcodes 18 (server.py:513,524); configurable here.
    start_round: int = 18


def parse_profile_rounds(spec: str) -> tuple[int, int] | None:
    """Parse a ``--profile-rounds A:B`` window ("A" alone means A:A).
    Returns (start, stop) inclusive 1-based round numbers, or None for the
    empty spec.  Raises ValueError on malformed input."""
    if not spec:
        return None
    start_text, sep, stop_text = spec.partition(":")
    try:
        start = int(start_text)
        stop = int(stop_text) if sep else start
    except ValueError:
        raise ValueError(
            f"profile_rounds must be 'A:B' (integers), got {spec!r}") from None
    if not 1 <= start <= stop:
        raise ValueError(
            f"profile_rounds needs 1 <= A <= B, got {spec!r}")
    return start, stop


@dataclass(frozen=True)
class TelemetryConfig:
    """Observability knobs (attackfl_tpu/telemetry): structured JSONL
    events + Chrome-trace spans + counters + the live run monitor.

    ``enabled`` gates ALL file output (events.jsonl / trace.json) AND the
    monitor; off, the engine uses null objects and pays no per-round I/O.
    ``sample_every`` thins per-round event records for very long runs
    (failed rounds and the compile round are always recorded).  Empty paths
    default to ``<log_path>/events.jsonl`` and ``<log_path>/trace.json``
    (``events.<process_index>.jsonl`` / ``trace.<process_index>.json``
    under a multi-host mesh); the ``ATTACKFL_TELEMETRY_DIR`` env var (test
    harness) overrides the base directory.

    ``monitor`` starts the live health endpoint + stall watchdog
    (telemetry/monitor.py; process 0 only) on ``monitor_port`` (0 =
    ephemeral; a busy fixed port falls back to ephemeral with a warning —
    the actual URL is printed at run start).  The watchdog declares a stall when
    no round completes within ``stall_factor ×`` the rolling-median round
    time; before the FIRST round completes (compiles — and the round-5
    init-wedge class) the threshold is ``stall_grace_seconds``.
    ``profile_rounds`` ("A:B") wraps those rounds in
    ``jax.profiler.start_trace/stop_trace`` writing device traces under
    ``<telemetry base>/profile``.

    ``numerics`` enables the in-graph numerics engine (ops/metrics +
    telemetry/numerics): per-round device-side metric rows (update-norm
    distributions per cohort, attack separation, weight drift, non-finite
    provenance, histograms) accumulated in a device ring buffer of
    ``numerics_window`` rows and drained up to that many rounds late as
    schema-v3 ``metric`` events — sync-free on the fused/pipelined paths,
    one batched transfer per window on the synchronous path.  Metrics
    never touch the params math (bit-identical global params on vs off).

    ``ledger`` (ISSUE 7, default on) appends one distilled record per run
    to the persistent cross-run ledger (attackfl_tpu/ledger) at
    ``ledger_dir`` (default ``<telemetry base>/ledger``; the
    ``ATTACKFL_LEDGER_DIR`` env var overrides both) — pure event-log
    post-processing at ``_finish_run``, zero new host syncs, queryable
    with ``attackfl-tpu ledger list|show|compare|regress``.
    """

    enabled: bool = True
    sample_every: int = 1
    events_path: str = ""
    trace_path: str = ""
    monitor: bool = False
    monitor_port: int = 8780
    stall_factor: float = 10.0
    stall_grace_seconds: float = 900.0
    profile_rounds: str = ""
    # hotspot observatory (ISSUE 19): the structured profiling window
    # the capture half of attackfl_tpu/profiler drives — same 'A:B'
    # format as profile_rounds (which it supersedes when both are set).
    # Each window closes with a schema-v14 `hotspot` event carrying the
    # mined op-level attribution; fail-open when the profiler backend
    # is unavailable.
    hotspots: str = ""
    numerics: bool = False
    numerics_window: int = 16
    ledger: bool = True
    ledger_dir: str = ""
    # cost observatory (ISSUE 11, default on): guarded
    # cost_analysis/memory_analysis snapshots at the AOT-compile seams,
    # emitted as schema-v9 `program_profile` events and folded into the
    # ledger record (attackfl_tpu/costmodel).  Purely observational —
    # params are bit-identical on vs off; the only cost is one extra
    # AOT compile of the synchronous-path programs (a persistent-cache
    # hit when compile_cache_dir is set; the fused/pipelined/matrix
    # executors profile the executable they dispatch anyway, for free).
    costmodel: bool = True

    def __post_init__(self):
        if self.sample_every < 1:
            raise ValueError(
                f"telemetry.sample_every must be >= 1, got {self.sample_every}")
        if not 0 <= self.monitor_port <= 65535:
            raise ValueError(
                f"telemetry.monitor_port must be a port, got {self.monitor_port}")
        if self.stall_factor <= 1.0:
            raise ValueError(
                "telemetry.stall_factor must be > 1 (a factor of the median "
                f"round time), got {self.stall_factor}")
        if self.stall_grace_seconds <= 0:
            raise ValueError(
                f"telemetry.stall_grace_seconds must be > 0, got "
                f"{self.stall_grace_seconds}")
        parse_profile_rounds(self.profile_rounds)  # validate format
        parse_profile_rounds(self.hotspots)  # same 'A:B' grammar
        if not 2 <= self.numerics_window <= 65536:
            raise ValueError(
                "telemetry.numerics_window must be in [2, 65536] (ring rows "
                f"= max drain lateness in rounds), got {self.numerics_window}")


@dataclass(frozen=True)
class ServiceConfig:
    """Run-service daemon knobs (``attackfl-tpu serve`` — ISSUE 8).

    ``spool_dir`` holds the durable job queue, the service event log, the
    shared cross-run ledger and one working directory per job (telemetry
    + checkpoints) — everything the daemon needs to recover after a kill
    -9 lives under it.  ``port`` is the control plane's HTTP port (0 =
    ephemeral; the ACTUAL port is printed at startup and published in
    ``<spool>/service.json``).  ``max_workers`` bounds concurrent runs
    (they share the persistent compile cache and the device pool);
    ``queue_depth`` bounds queued+running jobs — submission beyond it is
    an EXPLICIT rejection (HTTP 429 + a ``job`` event), never a silent
    drop.  A crashed worker is restarted with exponential backoff (base
    ``worker_backoff`` seconds, doubling, capped at
    ``worker_backoff_cap``) up to ``worker_retries`` restarts, then the
    job is marked failed without taking down the service.
    ``run_monitors`` gives every job its own live monitor on an
    ephemeral port (stall watchdog + per-run /metrics; the service-level
    /healthz aggregates their states).  ``drain_grace_seconds`` bounds
    how long a SIGTERM drain waits for in-flight rounds before the
    daemon exits anyway (the queue replay recovers whatever was cut
    short).

    Scheduler knobs (ISSUE 15 — the preemptive multi-tenant scheduler,
    default ON; ``scheduler=False`` restores the oldest-first loop):
    ``sched_aging_rate`` is effective-priority points per waiting second
    (the starvation bound scales as 1/rate); ``sched_min_runtime``
    protects fresh runs from preemption thrash; ``sched_shed_horizon``
    > 0 sheds submissions whose predicted backlog exceeds it (429 with
    a priced retry-after; 0 = never shed); ``sched_breaker_attempts``
    is the per-job circuit-breaker threshold on persisted crash
    attempts; ``sched_default_cost`` prices jobs the cost model cannot
    (cold ledger, malformed profile).
    """

    spool_dir: str = ""
    port: int = 8781
    host: str = "0.0.0.0"
    max_workers: int = 1
    queue_depth: int = 16
    worker_retries: int = 2
    worker_backoff: float = 0.5
    worker_backoff_cap: float = 30.0
    run_monitors: bool = True
    drain_grace_seconds: float = 120.0
    scheduler: bool = True
    sched_aging_rate: float = 1.0
    sched_min_runtime: float = 2.0
    sched_shed_horizon: float = 0.0
    sched_breaker_attempts: int = 5
    sched_default_cost: float = 30.0

    def __post_init__(self):
        if not 0 <= self.port <= 65535:
            raise ValueError(
                f"service.port must be a port, got {self.port}")
        if self.max_workers < 1:
            raise ValueError(
                f"service.max_workers must be >= 1, got {self.max_workers}")
        if self.queue_depth < 1:
            raise ValueError(
                f"service.queue_depth must be >= 1, got {self.queue_depth}")
        if self.worker_retries < 0:
            raise ValueError(
                f"service.worker_retries must be >= 0, got "
                f"{self.worker_retries}")
        if self.worker_backoff <= 0 or self.worker_backoff_cap <= 0:
            raise ValueError(
                "service.worker_backoff and worker_backoff_cap must be > 0, "
                f"got {self.worker_backoff} / {self.worker_backoff_cap}")
        if self.drain_grace_seconds <= 0:
            raise ValueError(
                f"service.drain_grace_seconds must be > 0, got "
                f"{self.drain_grace_seconds}")
        if self.sched_aging_rate <= 0:
            raise ValueError(
                "service.sched_aging_rate must be > 0 (aging is the "
                f"starvation-freedom mechanism), got {self.sched_aging_rate}")
        if self.sched_min_runtime < 0:
            raise ValueError(
                f"service.sched_min_runtime must be >= 0, got "
                f"{self.sched_min_runtime}")
        if self.sched_shed_horizon < 0:
            raise ValueError(
                "service.sched_shed_horizon must be >= 0 (0 disables "
                f"shedding), got {self.sched_shed_horizon}")
        if self.sched_breaker_attempts < 1:
            raise ValueError(
                f"service.sched_breaker_attempts must be >= 1, got "
                f"{self.sched_breaker_attempts}")
        if self.sched_default_cost <= 0:
            raise ValueError(
                f"service.sched_default_cost must be > 0, got "
                f"{self.sched_default_cost}")


@dataclass(frozen=True)
class AttackSpec:
    """One group of attacker clients.

    The reference configures attackers per client process via CLI flags
    (client.py:19-38); in-process simulation declares them in config or
    through the ``client.py`` parity launcher.
    """

    mode: str = "LIE"
    num_clients: int = 0
    # Explicit client indices; if empty, the *last* ``num_clients`` indices
    # are attackers.
    client_ids: tuple[int, ...] = ()
    # First training round (1-based) at which the attack fires
    # (reference: RpcClient.py:100 `training_round >= attack_round`).
    attack_round: int = 1
    # Positional args, matching reference semantics: Random -> perturbation
    # sigma (default 1e6, Utils.py:52); LIE -> z scaling factor (0.74,
    # Utils.py:207); gamma-search attacks take (gamma0, tau) = (50, 1).
    args: tuple[float, ...] = ()

    def __post_init__(self):
        if self.mode not in ATTACK_MODES and self.mode != NONE_ATTACK:
            raise ValueError(
                f"Unknown attack mode {self.mode!r}; choose from "
                f"{ATTACK_MODES} (or {NONE_ATTACK!r} for a clean-baseline "
                "cohort that never fires)")
        # normalize args to floats HERE so every producer (YAML, CLI,
        # matrix grids) yields identical specs — and identical config
        # fingerprints — for e.g. `args: [50, 1]` vs `args: [50.0, 1.0]`
        object.__setattr__(
            self, "args", tuple(float(x) for x in self.args))
        object.__setattr__(self, "client_ids", tuple(self.client_ids))


@dataclass(frozen=True)
class MeshConfig:
    """TPU device-mesh layout for the client axis.

    ``num_devices=0`` means "use every visible device".  The single mesh
    axis is named ``clients``: stacked per-client params/opt-state/batches
    are sharded along it, aggregation reductions become ICI collectives.
    """

    num_devices: int = 0
    axis_name: str = "clients"
    # Compute dtype for local training matmuls (params stay f32).
    compute_dtype: str = "float32"


@dataclass(frozen=True)
class Config:
    # --- server section (reference: config.yaml:2-22) ---
    num_round: int = 30
    total_clients: int = 3
    mode: str = "fedavg"
    model: str = "TransformerModel"
    data_name: str = "ICU"
    load_parameters: bool = False
    # Reference fidelity quirk (server.py:578-586): with parameters.load
    # True the reference re-reads {model}.pth before EVERY broadcast of a
    # non-hyper round.  It also rewrites that file after every successful
    # round (server.py:550-553), so the save→re-read round-trip is how the
    # aggregate reaches clients — and after a FAILED round the re-read
    # restores the last saved params.  Default False keeps this
    # framework's load-once-resume semantics; opt in to replicate the
    # per-broadcast re-read (pair with per-round checkpoint saving for the
    # full reference cycle; missing file = no-op, like the reference's
    # os.path.exists gate).
    reload_parameters_per_round: bool = False
    validation: bool = True
    # Validation cadence: evaluate every k-th broadcast (1 = every round,
    # the reference cadence).  Skipped rounds have no validation gate —
    # they pass/fail on training alone.  Keyed on the broadcast clock so
    # the synchronous, pipelined and fused paths agree on which rounds
    # validate (the clock advances identically on all three).
    validation_every: int = 1
    # Async validation: round N's params are evaluated while round N+1
    # trains; results fold into telemetry (a ``validation`` event) and the
    # round's history entry when they land.  The validation verdict no
    # longer gates round acceptance — an opt-in semantic change (the
    # reference blocks every round on the gate, server.py:539-547).
    validation_async: bool = False
    # Depth-k software-pipelined round executor (Simulator.run): round N's
    # success flag resolves on the host while round N+1's programs are
    # already dispatched; a failed round keeps the previous params through
    # the same accept-select the fused scan uses.  Off by default — the
    # synchronous path stays the parity reference.
    pipeline: bool = False
    # Pipeline depth k: how many rounds may be in flight beyond the one
    # being resolved (ISSUE 10).  1 = the historical depth-1 overlap;
    # 0 = dispatch-then-resolve with no overlap (the demoted mode, useful
    # for bench floors); "auto" = pick k from the ledger's measured
    # host_resolution_latency / round_device_time ratio for this config's
    # fingerprint, clamped by numerics_window and the checkpoint cadence
    # (see Simulator.resolve_pipeline_depth).  Every depth runs the SAME
    # single-round jitted program — params are bit-identical to the
    # synchronous path at any k (tests/test_pipeline.py).
    pipeline_depth: int | str = 1
    # Background checkpoint persistence (utils/checkpoint
    # AsyncCheckpointWriter): the device->host gather stays on the round
    # loop, serialization + file write + fsync move to a writer thread
    # with last-write-wins coalescing, a drain-on-close guarantee and a
    # supervisor that restarts a dead writer thread.
    checkpoint_async: bool = False
    # Resume from the checkpoint directory's manifest.json (ISSUE 6): the
    # newest VALID entry is restored (torn/truncated entries detected by
    # content hash and skipped with fallback to the previous good one),
    # a `resume` event records the boundary, and round numbering
    # continues from the checkpointed round (exactly-once accounting).
    # `load_parameters` keeps the legacy single-file reload.
    resume: bool = False
    # Manifest retention: how many round-stamped checkpoint entries stay
    # on disk (utils/checkpoint.CheckpointManager).  More entries = more
    # torn-file fallback depth at ~one state size each.
    checkpoint_keep: int = 3
    # Graceful executor degradation (ISSUE 6): the pipelined executor
    # demotes to depth-0 (resolve-before-dispatch) after this many
    # consecutive device-side rollbacks ...
    pipeline_demote_after: int = 3
    # ... and re-promotes to depth-1 after this many consecutive clean
    # rounds.  Both transitions emit `degrade` events and flip the live
    # monitor's degraded state.
    pipeline_repromote_after: int = 5
    num_data_range: tuple[int, int] = (12000, 15000)
    genuine_rate: float = 0.5
    random_seed: int = 1
    # Dataset seed, when it must be decoupled from the simulation seed
    # (ISSUE 9): the scenario matrix sweeps `random_seed` as its per-cell
    # axis while every cell shares ONE synthetic dataset — cell configs
    # pin `data_seed` to the sweep's base seed so a standalone replay of
    # a cell sees the same data the sweep did.  None (the default) keeps
    # the historical coupling: the dataset is seeded by `random_seed`.
    data_seed: int | None = None
    hyper_detection: HyperDetectionConfig = field(default_factory=HyperDetectionConfig)
    # Hypernetwork class for mode 'hyper': the generic spec-derived
    # "HyperNetwork" (reference server.py:800) or the CNNModel-specialized
    # "CNNHyper" (the commented-out alternative, server.py:801).
    hyper_class: str = "HyperNetwork"
    # Spectral normalization on hypernetwork trunk+head kernels
    # (reference: spec_norm ctor flag, src/Model.py:252,310; always False
    # where instantiated, server.py:800).
    hyper_spec_norm: bool = False
    # How the hypernetwork consumes the round's client updates:
    # "sequential" replicates the reference's per-client loop through one
    # shared Adam state (server.py:644-670) — an O(C) serial chain of
    # vjp+Adam steps, order-faithful but the predicted bottleneck at
    # 100-1000 clients (SURVEY.md §7).  "batched" vmaps the per-client
    # vjp grads, averages them over active clients, and takes ONE Adam
    # step per round — a different (minibatch-style) trajectory with the
    # same fixed-point structure, fully parallel on the MXU.
    hyper_update_mode: str = "sequential"
    # Straggler/dropout fault injection (SURVEY.md §5): each round every
    # client independently fails to report with this probability.  A
    # dropped client contributes no update that round: size-weighted
    # aggregators exclude it exactly (its round size is 0), geometric
    # aggregators (median/krum/trimmed-mean/shieldfl) operate over
    # reporters only (masked variants), in hyper mode its hnet step is
    # skipped, and its last
    # REPORTED update stays (stale) in the genuine-leak pool.  The
    # reference has no dropout handling at all — its round barrier waits
    # forever on a silent client (server.py:271-272); here a round where
    # EVERY client drops fails and retries like any failed round.
    client_dropout_rate: float = 0.0
    # Label-skew partitioning: "iid" replicates the reference (every client
    # samples uniformly from the shared set, RpcClient.py:166); "dirichlet"
    # gives a non-IID label split with concentration ``dirichlet_alpha``.
    partition: str = "iid"
    dirichlet_alpha: float = 0.5

    # --- learning section (reference: config.yaml:31-37) ---
    epochs: int = 5
    lr: float = 0.004
    hyper_lr: float = 0.001
    momentum: float = 0.5  # accepted for schema parity; Adam ignores it
    batch_size: int = 128
    clip_grad_norm: float = 1.0

    # --- attackers ---
    attacks: tuple[AttackSpec, ...] = ()

    # --- fault injection (ISSUE 6) ---
    # Deterministic scheduled failures (YAML `faults:` section / CLI
    # `--inject-faults`): NaN storms + forced-dropout cohorts compiled
    # into the jitted round program, checkpoint write errors / torn
    # files / writer-thread death / monitor stalls injected at the host
    # seams (attackfl_tpu/faults).  Empty = no injection anywhere.
    faults: tuple[FaultSpec, ...] = ()

    # --- infra ---
    mesh: MeshConfig = field(default_factory=MeshConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    # run-service daemon knobs (`attackfl-tpu serve` reads these as its
    # defaults; a plain `run` never consults them)
    service: ServiceConfig = field(default_factory=ServiceConfig)
    log_path: str = "."
    checkpoint_dir: str = "."
    # JAX persistent compilation cache directory: compiled XLA programs
    # survive process restarts, so repeat runs skip the multi-minute
    # first-dispatch compile entirely.  Empty = disabled.  The
    # ``ATTACKFL_COMPILE_CACHE`` env var overrides this (bench/CI harness).
    compile_cache_dir: str = ""
    # Krum's assumed-malicious count f.  The reference computes
    # f = int(n * genuine_rate) from a field hardcoded to 0.0
    # (server.py:109,384) so effectively f=0; we default to 0 for parity but
    # let users set the real byzantine count.
    krum_f: int = 0
    trim_ratio: float = 0.1  # trimmed-mean (Utils.py:267)
    # cosine-vs-anchor keep threshold for mode "byzantine" (Utils.py:228)
    byzantine_threshold: float = 0.9
    # PRNG implementation for simulation keys.  "rbg" (hardware random-bit
    # generator) makes per-batch dropout-mask generation ~4x cheaper on TPU
    # than counter-based "threefry"; streams differ between impls but both
    # are deterministic per seed (the reference's torch/python rng streams
    # are incomparable anyway — parity is metric-level, SURVEY.md §7).
    prng_impl: str = "rbg"
    # Unroll factor for the local-training minibatch lax.scan.  >1 lets XLA
    # fuse across consecutive optimizer steps (~10% faster rounds at 4) at
    # the cost of proportionally longer compiles; 1 = cheapest compile.
    scan_unroll: int = 1
    # Local-training backend: "xla" (vmapped lax.scan autodiff path, any
    # model) or "pallas" (ops/fused_step hand-fused TPU mega-kernel:
    # forward+backward+clip+Adam as one kernel per minibatch grid step;
    # TransformerModel on ICU only).
    local_backend: str = "xla"
    # Synthetic dataset sizes (reference blobs are absent,
    # .MISSING_LARGE_BLOBS): train/test sample counts.
    train_size: int = 20000
    test_size: int = 4000

    def __post_init__(self):
        if self.prng_impl == "threefry":  # accept the colloquial name
            object.__setattr__(self, "prng_impl", "threefry2x32")
        if self.prng_impl not in ("rbg", "unsafe_rbg", "threefry2x32"):
            raise ValueError(
                f"Unknown prng_impl {self.prng_impl!r}; choose rbg, "
                "unsafe_rbg or threefry2x32"
            )
        if self.scan_unroll < 1:
            raise ValueError(f"scan_unroll must be >= 1, got {self.scan_unroll}")
        if self.validation_every < 1:
            raise ValueError(
                f"validation_every must be >= 1 (1 = every round; disable "
                f"validation with validation: false), got {self.validation_every}"
            )
        if self.checkpoint_keep < 1:
            raise ValueError(
                f"checkpoint_keep must be >= 1 (manifest retention depth), "
                f"got {self.checkpoint_keep}")
        if isinstance(self.pipeline_depth, str):
            depth_text = self.pipeline_depth.strip().lower()
            if depth_text != "auto":
                try:
                    object.__setattr__(self, "pipeline_depth",
                                       int(depth_text))
                except ValueError:
                    raise ValueError(
                        f"pipeline_depth must be an integer or 'auto', got "
                        f"{self.pipeline_depth!r}") from None
            else:
                object.__setattr__(self, "pipeline_depth", "auto")
        if isinstance(self.pipeline_depth, int) and not (
                0 <= self.pipeline_depth <= MAX_PIPELINE_DEPTH):
            raise ValueError(
                f"pipeline_depth must be in [0, {MAX_PIPELINE_DEPTH}] or "
                f"'auto', got {self.pipeline_depth}")
        if self.pipeline_demote_after < 1 or self.pipeline_repromote_after < 1:
            raise ValueError(
                "pipeline_demote_after and pipeline_repromote_after must be "
                f">= 1, got {self.pipeline_demote_after} / "
                f"{self.pipeline_repromote_after}")
        for spec in self.faults:
            for cid in spec.clients:
                if not 0 <= cid < self.total_clients:
                    raise ValueError(
                        f"fault {spec.kind}@{spec.round}: client {cid} out "
                        f"of range [0, {self.total_clients})")
        if self.reload_parameters_per_round and not self.load_parameters:
            raise ValueError(
                "reload_parameters_per_round replicates the reference's "
                "per-broadcast re-read, which is gated on parameters.load "
                "(server.py:580) — set load_parameters=True as well"
            )
        if self.mesh.compute_dtype not in ("float32", "bfloat16", "float16"):
            raise ValueError(
                f"Unknown compute-dtype {self.mesh.compute_dtype!r}; choose "
                "float32, bfloat16 or float16"
            )
        if self.local_backend not in ("xla", "pallas"):
            raise ValueError(
                f"Unknown local_backend {self.local_backend!r}; choose xla or pallas"
            )
        if self.local_backend == "pallas" and (
            self.model != "TransformerModel" or self.data_name != "ICU"
        ):
            raise ValueError(
                "local_backend 'pallas' implements the flagship "
                "TransformerModel-on-ICU step only; use local_backend 'xla'"
            )
        if self.local_backend == "pallas" and self.mesh.compute_dtype != "float32":
            raise ValueError(
                "local_backend 'pallas' computes in float32 (the fused "
                "kernel is hardwired f32); compute-dtype applies to the "
                "xla backend only"
            )
        if self.local_backend == "pallas" and self.mode == "hyper":
            raise ValueError(
                "local_backend 'pallas' fuses the plain local-training step; "
                "hyper mode trains against per-client generated weights and "
                "runs on the xla backend only"
            )
        if self.mode not in AGGREGATION_MODES:
            raise ValueError(f"Unknown server mode {self.mode!r}; choose from {AGGREGATION_MODES}")
        if self.data_name not in DATA_NAMES:
            raise ValueError(f"Unknown data name {self.data_name!r}; choose from {DATA_NAMES}")
        lo, hi = self.num_data_range
        if not (0 < lo <= hi):
            raise ValueError(f"Bad num-data-range {self.num_data_range}")
        if not (0.0 <= self.client_dropout_rate < 1.0):
            raise ValueError(
                f"client_dropout_rate must be in [0, 1), got "
                f"{self.client_dropout_rate} (1.0 would drop every client "
                "every round; the reference analog is a barrier deadlock)"
            )
        if self.hyper_update_mode not in ("sequential", "batched"):
            raise ValueError(
                f"Unknown hyper_update_mode {self.hyper_update_mode!r}; "
                "choose 'sequential' (reference-faithful) or 'batched'"
            )
        if self.hyper_class not in ("HyperNetwork", "CNNHyper"):
            raise ValueError(
                f"Unknown hyper_class {self.hyper_class!r}; choose "
                "HyperNetwork or CNNHyper"
            )
        if self.hyper_class == "CNNHyper" and self.mode == "hyper" and self.model != "CNNModel":
            raise ValueError(
                "hyper_class 'CNNHyper' is hand-specialized to CNNModel "
                f"(src/Model.py:309-416); got model {self.model!r}"
            )
        if self.mode == "hyper" and self.validation and self.data_name == "HAR":
            # hyper validation exists only for ICU/CIFAR10
            # (reference: Validation.test_hyper, src/Validation.py:138-145)
            raise ValueError(
                "mode 'hyper' with validation has no HAR evaluator; use "
                "data-name ICU/CIFAR10 or disable validation"
            )

    # ---- attacker geometry -------------------------------------------------
    def attacker_assignment(self) -> dict[int, AttackSpec]:
        """Map client index -> attack spec.  Non-attackers are absent."""
        assignment: dict[int, AttackSpec] = {}
        next_free = self.total_clients
        for spec in self.attacks:
            ids: Sequence[int]
            if spec.client_ids:
                ids = spec.client_ids
            else:
                next_free -= spec.num_clients
                ids = range(next_free, next_free + spec.num_clients)
            for cid in ids:
                if not 0 <= cid < self.total_clients:
                    raise ValueError(f"Attacker id {cid} out of range [0, {self.total_clients})")
                if cid in assignment:
                    raise ValueError(f"Client {cid} claimed by two attack specs")
                assignment[cid] = spec
        return assignment

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw)


def audit_config(**overrides: Any) -> Config:
    """Representative CPU-sized config for the static program auditor
    (attackfl_tpu/analysis/program_audit) and the retrace guard.

    Small enough to trace/lower in seconds on one CPU device, yet it
    exercises the full round program: an active LIE attacker group (attack
    + cohort-mask ops in-graph), validation (the eval program folds into
    the fused/pipelined bodies) and the default fedavg aggregation.
    Telemetry is disabled — auditing must not write event files or spin up
    monitors — and logs/checkpoints go to a throwaway temp dir so running
    ``attackfl-tpu audit`` never litters the working tree.  Keyword
    overrides replace any field (e.g. ``mode="hyper"`` to audit the
    hypernetwork programs).
    """
    import tempfile

    scratch = tempfile.mkdtemp(prefix="attackfl_audit_")
    base: dict[str, Any] = dict(
        num_round=3, total_clients=4, mode="fedavg", model="CNNModel",
        data_name="ICU", num_data_range=(48, 64), epochs=1, batch_size=32,
        train_size=256, test_size=128,
        attacks=(AttackSpec(mode="LIE", num_clients=1, attack_round=2),),
        telemetry=TelemetryConfig(enabled=False),
        log_path=scratch, checkpoint_dir=scratch,
    )
    base.update(overrides)
    return Config(**base)


def _get(d: dict, key: str, default: Any) -> Any:
    return d.get(key, default) if isinstance(d, dict) else default


def config_from_dict(raw: dict) -> Config:
    """Build a Config from a dict using the reference YAML key names."""
    server = _get(raw, "server", {})
    learning = _get(raw, "learning", {})
    hd = _get(server, "hyper-detection", {})
    dist = _get(server, "data-distribution", {})
    ndr = _get(dist, "num-data-range", [12000, 15000])
    mesh = _get(raw, "tpu", {})
    tele = _get(raw, "telemetry", {})
    svc = _get(raw, "service", {})

    attacks = []
    for a in _get(raw, "attack-clients", []) or []:
        attacks.append(
            AttackSpec(
                mode=_get(a, "mode", "LIE"),
                num_clients=int(_get(a, "num-clients", 0)),
                client_ids=tuple(_get(a, "client-ids", []) or []),
                attack_round=int(_get(a, "attack-round", 1)),
                args=tuple(float(x) for x in (_get(a, "args", []) or [])),
            )
        )

    defaults = Config()
    return Config(
        num_round=int(_get(server, "num-round", defaults.num_round)),
        total_clients=int(_get(server, "clients", defaults.total_clients)),
        mode=str(_get(server, "mode", defaults.mode)),
        model=str(_get(server, "model", defaults.model)),
        data_name=str(_get(server, "data-name", defaults.data_name)),
        load_parameters=bool(_get(_get(server, "parameters", {}), "load", False)),
        reload_parameters_per_round=bool(_get(
            _get(server, "parameters", {}), "reload-per-round",
            defaults.reload_parameters_per_round)),
        validation=bool(_get(server, "validation", True)),
        validation_every=int(_get(server, "validation-every",
                                  defaults.validation_every)),
        validation_async=bool(_get(server, "validation-async",
                                   defaults.validation_async)),
        pipeline=bool(_get(server, "pipeline", defaults.pipeline)),
        pipeline_depth=_get(server, "pipeline-depth",
                            defaults.pipeline_depth),
        checkpoint_async=bool(_get(server, "checkpoint-async",
                                   defaults.checkpoint_async)),
        resume=bool(_get(server, "resume", defaults.resume)),
        checkpoint_keep=int(_get(server, "checkpoint-keep",
                                 defaults.checkpoint_keep)),
        pipeline_demote_after=int(_get(server, "pipeline-demote-after",
                                       defaults.pipeline_demote_after)),
        pipeline_repromote_after=int(_get(
            server, "pipeline-repromote-after",
            defaults.pipeline_repromote_after)),
        num_data_range=(int(ndr[0]), int(ndr[1])),
        genuine_rate=float(_get(server, "genuine-rate", defaults.genuine_rate)),
        random_seed=int(_get(server, "random-seed", defaults.random_seed) or 0),
        data_seed=(int(_get(server, "data-seed", 0))
                   if _get(server, "data-seed", None) is not None else None),
        hyper_detection=HyperDetectionConfig(
            enable=bool(_get(hd, "enable", False)),
            cosine_search=int(_get(hd, "cosine-search", 10)),
            n_components=int(_get(hd, "n_components", 3)),
            eps=float(_get(hd, "eps", 0.007)),
            min_samples=int(_get(hd, "min_samples", 3)),
            start_round=int(_get(hd, "start-round", 18)),
        ),
        client_dropout_rate=float(_get(server, "client-dropout-rate",
                                       defaults.client_dropout_rate)),
        hyper_class=str(_get(server, "hyper-class", defaults.hyper_class)),
        hyper_spec_norm=bool(_get(server, "hyper-spec-norm", defaults.hyper_spec_norm)),
        hyper_update_mode=str(_get(server, "hyper-update-mode",
                                   defaults.hyper_update_mode)),
        partition=str(_get(server, "partition", defaults.partition)),
        dirichlet_alpha=float(_get(server, "dirichlet-alpha", defaults.dirichlet_alpha)),
        epochs=int(_get(learning, "epoch", defaults.epochs)),
        lr=float(_get(learning, "learning-rate", defaults.lr)),
        hyper_lr=float(_get(learning, "hyper-lr", defaults.hyper_lr)),
        momentum=float(_get(learning, "momentum", defaults.momentum)),
        batch_size=int(_get(learning, "batch-size", defaults.batch_size)),
        clip_grad_norm=float(_get(learning, "clip-grad-norm", defaults.clip_grad_norm)),
        attacks=tuple(attacks),
        faults=faults_from_config(_get(raw, "faults", []) or []),
        mesh=MeshConfig(
            num_devices=int(_get(mesh, "num-devices", 0)),
            axis_name=str(_get(mesh, "axis-name", "clients")),
            compute_dtype=str(_get(mesh, "compute-dtype", "float32")),
        ),
        telemetry=TelemetryConfig(
            enabled=bool(_get(tele, "enabled", True)),
            sample_every=int(_get(tele, "sample-every", 1)),
            events_path=str(_get(tele, "events-path", "")),
            trace_path=str(_get(tele, "trace-path", "")),
            monitor=bool(_get(tele, "monitor", False)),
            monitor_port=int(_get(tele, "monitor-port", 8780)),
            stall_factor=float(_get(tele, "stall-factor", 10.0)),
            stall_grace_seconds=float(
                _get(tele, "stall-grace-seconds", 900.0)),
            profile_rounds=str(_get(tele, "profile-rounds", "")),
            hotspots=str(_get(tele, "hotspots", "")),
            numerics=bool(_get(tele, "numerics", False)),
            numerics_window=int(_get(tele, "numerics-window", 16)),
            ledger=bool(_get(tele, "ledger", True)),
            ledger_dir=str(_get(tele, "ledger-dir", "")),
            costmodel=bool(_get(tele, "costmodel", True)),
        ),
        service=ServiceConfig(
            spool_dir=str(_get(svc, "spool-dir", "")),
            port=int(_get(svc, "port", 8781)),
            host=str(_get(svc, "host", "0.0.0.0")),
            max_workers=int(_get(svc, "max-workers", 1)),
            queue_depth=int(_get(svc, "queue-depth", 16)),
            worker_retries=int(_get(svc, "worker-retries", 2)),
            worker_backoff=float(_get(svc, "worker-backoff", 0.5)),
            worker_backoff_cap=float(_get(svc, "worker-backoff-cap", 30.0)),
            run_monitors=bool(_get(svc, "run-monitors", True)),
            drain_grace_seconds=float(
                _get(svc, "drain-grace-seconds", 120.0)),
        ),
        log_path=str(_get(raw, "log_path", ".")),
        checkpoint_dir=str(_get(raw, "checkpoint-dir", _get(raw, "log_path", "."))),
        compile_cache_dir=str(_get(raw, "compile-cache-dir",
                                   defaults.compile_cache_dir)),
        local_backend=str(_get(mesh, "local-backend", defaults.local_backend)),
        krum_f=int(_get(server, "krum-f", defaults.krum_f)),
        trim_ratio=float(_get(server, "trim-ratio", defaults.trim_ratio)),
        byzantine_threshold=float(
            _get(server, "byzantine-threshold", defaults.byzantine_threshold)),
        train_size=int(_get(server, "train-size", defaults.train_size)),
        test_size=int(_get(server, "test-size", defaults.test_size)),
    )


def load_config(path: str) -> Config:
    with open(path, "r") as fh:
        raw = yaml.safe_load(fh) or {}
    return config_from_dict(raw)
