"""The federated round as one jitted program.

The reference's round is a distributed protocol: broadcast START over AMQP,
N processes train, UPDATE messages accumulate at a barrier, then the server
aggregates (server.py:205-275 → process_consumer server.py:277-567).  Here
the same semantics compile to a single XLA program over the stacked client
axis:

    sample data → vmap(local_update) → overwrite attacker rows with
    attack(prev-round genuine leak) → collect new genuine set → aggregate.

Key fidelity points:
* Attackers do NOT train in attack rounds: their update is computed from
  the globally broadcast params + the genuine models leaked from the
  *previous* round (the server accumulates genuine UPDATEs each round and
  ships a sample inside the next START — server.py:259-268,596-616;
  clients attack instead of training at RpcClient.py:100-104).  Before any
  genuine set exists (round 1) attackers train genuinely.
* Each attacker receives its own leak sample of size
  max(int(genuine_rate·G), 1) drawn without replacement (server.py:599-600).
* Attack activation is per-broadcast (the client counts STARTs,
  RpcClient.py:72), so retried rounds advance the attack clock.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from attackfl_tpu.config import NONE_ATTACK, Config
from attackfl_tpu.data.partition import apply_client_dropout, sample_round_indices
from attackfl_tpu.faults.inject import apply_nan_storm, build_client_fault_fn
from attackfl_tpu.ops import aggregators, attacks
from attackfl_tpu.ops import pytree as pt
from attackfl_tpu.training.local import (
    build_local_update, build_root_update, resolve_compute_dtype,
)

Batch = dict[str, jnp.ndarray]

# Memory budget (elements, not bytes) for the per-attacker leak gather.
# Each attacker materializes its own (leak_k, P) leaked-tree sample; a
# plain vmap over attackers allocates (n_attackers, leak_k, P) AT ONCE —
# 3.8e9 floats (15+ GB) at the 1000-client north star (200 attackers x
# 400 leaked x 48k params), which would OOM a 16 GB TPU chip and was
# OOM-killed at 130 GB RSS on CPU (XLA temporaries multiply it).
ATTACK_GATHER_BUDGET = int(2e8)  # ~800 MB f32 peak per chunk


def map_attackers(attack_one: Callable, xs: Any, n_attackers: int,
                  leak_k: int, params_template: Any) -> Any:
    """Evaluate the per-attacker closure over stacked inputs ``xs`` with
    bounded peak memory: plain vmap while the full (n_attackers, leak_k, P)
    gather fits ``ATTACK_GATHER_BUDGET``, otherwise sequential chunks of
    vmapped attackers — identical results, bounded temporaries.

    Chunked by hand rather than ``lax.map(batch_size=...)``: jax
    0.4.37's remainder handling traces a ZERO-SIZE vmap when the batch
    size divides the length exactly, and rbg typed keys cannot trace
    ``random.choice`` over an empty key batch (IndexError) — exactly the
    shape the reference-scale rbg configs hit when the budget chunk
    lands on a divisor of the attacker count."""
    p_total = sum(x.size for x in jax.tree.leaves(params_template))
    chunk = max(1, ATTACK_GATHER_BUDGET // max(leak_k * p_total, 1))
    if chunk >= n_attackers:
        return jax.vmap(attack_one)(xs)
    rem = n_attackers % chunk
    head_n = n_attackers - rem
    head = jax.tree.map(
        lambda x: x[:head_n].reshape((head_n // chunk, chunk)
                                     + x.shape[1:]), xs)
    out = jax.lax.map(jax.vmap(attack_one), head)
    out = jax.tree.map(
        lambda x: x.reshape((head_n,) + x.shape[2:]), out)
    if rem:
        tail = jax.vmap(attack_one)(jax.tree.map(lambda x: x[head_n:], xs))
        out = jax.tree.map(lambda a, b: jnp.concatenate([a, b], axis=0),
                           out, tail)
    return out


@dataclass(frozen=True)
class AttackGroup:
    """Static attacker geometry for one attack spec."""

    mode: str
    indices: tuple[int, ...]
    attack_round: int
    args: tuple[float, ...]


def build_attack_groups(cfg: Config) -> tuple[list[AttackGroup], list[int]]:
    """Resolve config attack specs into (groups, genuine client indices)."""
    assignment = cfg.attacker_assignment()
    groups: dict[int, AttackGroup] = {}
    by_spec: dict[int, list[int]] = {}
    specs: dict[int, Any] = {}
    for cid, spec in assignment.items():
        key = id(spec)
        by_spec.setdefault(key, []).append(cid)
        specs[key] = spec
    group_list = [
        AttackGroup(
            mode=specs[k].mode,
            indices=tuple(sorted(ids)),
            attack_round=specs[k].attack_round,
            args=tuple(specs[k].args),
        )
        for k, ids in by_spec.items()
    ]
    genuine = sorted(set(range(cfg.total_clients)) - set(assignment))
    return group_list, genuine


def build_cohort_masks(
    total_clients: int, groups: Sequence[AttackGroup]
) -> tuple[np.ndarray, np.ndarray]:
    """(genuine_mask, attacker_mask) host bool arrays over client indices —
    the static cohort geometry shared by the engine's defense bookkeeping
    and the numerics layout (ops/metrics.build_layout).  A configured
    attacker is "malicious" for cohort statistics even on rounds before
    its attack fires (cohort membership is static; per-round activation is
    what ``active_attacker_indices`` reports)."""
    attacker = np.zeros(total_clients, dtype=bool)
    for grp in groups:
        attacker[list(grp.indices)] = True
    return ~attacker, attacker


def describe_attack_groups(groups: Sequence[AttackGroup]) -> list[dict[str, Any]]:
    """JSON-ready attacker geometry for the telemetry run header."""
    return [
        {
            "mode": g.mode,
            "num_clients": len(g.indices),
            "indices": list(g.indices),
            "attack_round": g.attack_round,
            "args": list(g.args),
        }
        for g in groups
    ]


def active_attack_modes(groups: Sequence[AttackGroup], broadcast_number: int,
                        have_genuine: bool) -> list[str]:
    """Attack modes firing at this broadcast — the host-side mirror of the
    per-group ``active`` gate inside round_step (attackers need a leaked
    genuine set, so nothing fires before one exists)."""
    if not have_genuine:
        return []
    return sorted({g.mode for g in groups
                   if broadcast_number >= g.attack_round
                   and g.mode != NONE_ATTACK})


def active_attacker_indices(groups: Sequence[AttackGroup],
                            broadcast_number: int,
                            have_genuine: bool) -> list[int]:
    """Client indices that actually attack at this broadcast — the
    forensic ground truth (a configured attacker that has not fired yet
    trained genuinely, so counting it as a positive would miscredit the
    defense)."""
    if not have_genuine:
        return []
    return sorted({cid for g in groups if broadcast_number >= g.attack_round
                   and g.mode != NONE_ATTACK for cid in g.indices})


def build_round_step(
    model,
    cfg: Config,
    train_data: Batch,
    attack_groups: Sequence[AttackGroup],
    genuine_idx: Sequence[int],
    client_pools: jnp.ndarray | None = None,
    constrain: Callable | None = None,
    mesh=None,
    use_shard_map: bool = False,
) -> Callable:
    """Build ``round_step(global_params, prev_genuine, have_genuine, rng,
    broadcast_number) -> (stacked, sizes, new_genuine, ok, mean_loss)``.

    ``constrain`` (from parallel.mesh.make_constrain) pins stacked
    per-client tensors to the client mesh axis inside jit, sharding the
    vmapped local-training compute across devices.  ``use_shard_map``
    (with a mesh) maps the local-training half explicitly over
    device-local client shards instead of leaving the split to the GSPMD
    partitioner (parallel/shard — the engine gates it on
    ``supports_shard_map``).

    ``prev_genuine`` is the stacked tree of the G genuine clients' previous
    updates; ``have_genuine`` is False until one round has completed.
    The result is mode-agnostic: aggregation is a separate jitted function
    so host-side defenses (GMM / FLTracer) can filter in between.
    """
    num_clients = cfg.total_clients
    lo, hi = cfg.num_data_range
    pool = next(iter(train_data.values())).shape[0]
    num_genuine = len(genuine_idx)
    leak_k = max(int(cfg.genuine_rate * num_genuine), 1)
    genuine_arr = jnp.asarray(genuine_idx, dtype=jnp.int32)

    if cfg.local_backend == "pallas":
        from attackfl_tpu.ops import fused_step
        from attackfl_tpu.utils.logging import print_with_color

        from attackfl_tpu.parallel.mesh import is_tpu_backend

        # NOT a literal 'backend == "tpu"' check: the axon tunnel's
        # platform name is "axon", and that literal comparison silently
        # forced interpret mode on the real chip (rounds 1-3 never ran
        # the compiled kernel because of it).
        interpret = not is_tpu_backend()
        if interpret:
            print_with_color(
                "[pallas] no TPU backend: running the fused kernel in "
                "INTERPRET mode (slow, dropout forced off) — a correctness "
                "path, not a fast path; use local_backend 'xla' off-TPU.",
                "yellow")
        # dropout rates mirror TransformerModel: block/attention 0.1
        # (models/icu.py TransformerBlock call), head = model.dropout_rate
        batched_update = fused_step.build_fused_local_update(
            train_data, epochs=cfg.epochs, batch_size=cfg.batch_size,
            lr=cfg.lr, clip_grad_norm=cfg.clip_grad_norm,
            dropout=(0.1, 0.1, float(getattr(model, "dropout_rate", 0.3))),
            interpret=interpret,
        )
        if mesh is not None:
            # perf lever x scale lever: run the kernel per-device on its
            # client shard.  The grid already chunks clients; shard_map
            # splits the leading axis so each device's Pallas program sees
            # C/n_dev clients (params replicated, per-client rows sharded).
            # check stays off: the pallas_call's ShapeDtypeStructs carry
            # no replication info the checker could see through.
            from attackfl_tpu.parallel.shard import shard_local_update

            batched_update = shard_local_update(
                batched_update, mesh, cfg.mesh.axis_name)
    else:
        local_update = build_local_update(
            model, cfg.data_name, train_data,
            epochs=cfg.epochs, batch_size=cfg.batch_size,
            lr=cfg.lr, clip_grad_norm=cfg.clip_grad_norm,
            scan_unroll=cfg.scan_unroll,
            compute_dtype=resolve_compute_dtype(cfg.mesh.compute_dtype),
        )
        batched_update = jax.vmap(local_update, in_axes=(None, 0, 0, 0))
        if mesh is not None and use_shard_map:
            # mesh-native local epochs (ISSUE 12): each device runs the
            # vmapped trainer on its own client shard — a collective-free
            # C/n_dev-client program whose while-loops never see a sharded
            # operand.  Gated on supports_shard_map at the engine (rbg
            # hardware keys draw batch-shape-dependent bits; see
            # parallel/shard module doc).
            from attackfl_tpu.parallel.shard import shard_local_update

            batched_update = shard_local_update(
                batched_update, mesh, cfg.mesh.axis_name)
    constrain = constrain or (lambda tree: tree)

    drop_rate = cfg.client_dropout_rate
    # plan-driven deterministic faults, compiled into the program (ISSUE
    # 6): a forced-dropout cohort mask and a NaN storm keyed on the
    # broadcast clock — None without a plan, so fault-free programs carry
    # zero injection ops
    forced_drop_fn = build_client_fault_fn(cfg.faults, num_clients, "dropout")
    nan_storm_fn = build_client_fault_fn(cfg.faults, num_clients, "nan_storm")

    def round_step(global_params, prev_genuine, have_genuine, rng, broadcast_number):
        if drop_rate > 0:
            k_data, k_train, k_attack, k_drop = jax.random.split(rng, 4)
        else:
            k_data, k_train, k_attack = jax.random.split(rng, 3)
        idx, mask, sizes = sample_round_indices(
            k_data, num_clients, pool, lo, hi, client_pools
        )
        if drop_rate > 0:
            sizes, mask, kept = apply_client_dropout(k_drop, sizes, mask, drop_rate)
        else:
            kept = jnp.ones((num_clients,), bool)
        if forced_drop_fn is not None:
            # scheduled straggler cohort: exactly the probabilistic-dropout
            # semantics (size 0, all batches masked), at a chosen round
            kept = kept & ~forced_drop_fn(broadcast_number)
            sizes = sizes * kept
            mask = mask & kept[:, None]
        idx, mask = constrain(idx), constrain(mask)
        train_keys = constrain(jax.random.split(k_train, num_clients))
        stacked, ok, losses = batched_update(global_params, train_keys, idx, mask)
        stacked = constrain(stacked)

        for gi, grp in enumerate(attack_groups):
            if grp.mode == NONE_ATTACK:
                # clean-baseline cohort (ISSUE 17): the group keeps its
                # static geometry (excluded from the genuine leak pool
                # above) but contributes ZERO ops — the compiled program
                # is the benign program, so a `none` matrix cell is
                # bit-identical to a standalone run of the same config.
                # Skipping BEFORE the per-group key fold keeps the other
                # groups' keys untouched (each folds its own gi).
                continue
            n_attackers = len(grp.indices)
            keys = jax.random.split(jax.random.fold_in(k_attack, gi), n_attackers)
            active = (broadcast_number >= grp.attack_round) & have_genuine
            grp_arr = jnp.asarray(grp.indices)
            # a dropped attacker never reports, so its row stays the no-op
            active_rows = active & kept[grp_arr]

            def attack_one(key):
                k_leak, k_noise = jax.random.split(key)
                leak = jax.random.choice(
                    k_leak, num_genuine, (min(leak_k, num_genuine),), replace=False
                )
                leaked = pt.tree_take(prev_genuine, leak)
                return attacks.apply_attack(
                    grp.mode, global_params, leaked, k_noise, grp.args
                )

            attacked = map_attackers(attack_one, keys, n_attackers,
                                     min(leak_k, num_genuine), global_params)

            def scatter(s, a):
                sel = active_rows.reshape((-1,) + (1,) * (a.ndim - 1))
                return s.at[grp_arr].set(jnp.where(sel, a, s[grp_arr]))

            stacked = jax.tree.map(scatter, stacked, attacked)
            # attackers that attacked did not train; their NaN status resets
            ok = ok.at[grp_arr].set(jnp.where(active_rows, True, ok[grp_arr]))

        if nan_storm_fn is not None:
            # injected AFTER the attack scatter so a stormed attacker row
            # is stormed too: the failure rides the existing ok-flag path
            # (train_ok below fails the round, the leak-pool select keeps
            # the previous pool, the executor retries/rolls back)
            stacked, ok = apply_nan_storm(
                nan_storm_fn(broadcast_number), stacked, ok)

        # a round where every client drops has no updates at all — fail it
        # (the reference analog is a barrier deadlock, server.py:271-272)
        train_ok = jnp.all(ok) & jnp.any(kept)
        fresh = pt.tree_take(stacked, genuine_arr)
        # The genuine-leak pool only absorbs rounds whose training was
        # clean: the reference gates accumulation on the per-client result
        # flag (server.py:245,260-268).  Selecting INSIDE the program (vs
        # on host) keeps the returned tree correct on failed rounds too, so
        # callers may treat ``prev_genuine`` as consumed (donation-safe).
        if drop_rate > 0:
            # Dropped genuine clients never report, so their last REPORTED
            # update stays in the leak pool (stale) — the reference
            # accumulates only clients that sent an UPDATE
            # (server.py:259-268).  Until a client has reported once
            # (~have_genuine: the pool rows are still init placeholders)
            # its fresh no-op row is used instead.
            sel = train_ok & (kept[genuine_arr] | ~have_genuine)
        else:
            sel = jnp.broadcast_to(train_ok, (num_genuine,))
        new_genuine = jax.tree.map(
            lambda n, p: jnp.where(
                sel.reshape((-1,) + (1,) * (n.ndim - 1)), n, p),
            fresh, prev_genuine,
        )
        if mesh is not None:
            # canonical output sharding: the leak pool REPLICATES (every
            # attacker gathers arbitrary rows from it next round, and a
            # declared placement keeps round 2's input sharding equal to
            # round 1's — without this the jit re-specializes once per
            # new input sharding, which the retrace guard flags)
            from jax.sharding import NamedSharding, PartitionSpec

            rep = NamedSharding(mesh, PartitionSpec())
            new_genuine = jax.tree.map(
                lambda x: jax.lax.with_sharding_constraint(x, rep),
                new_genuine)
        keptf = kept.astype(losses.dtype)
        mean_loss = jnp.sum(losses * keptf) / jnp.maximum(jnp.sum(keptf), 1.0)
        return stacked, sizes, new_genuine, train_ok, mean_loss

    # host-side program metadata for the telemetry run header (never read
    # inside the traced function)
    round_step.telemetry_info = {
        "program": "plain_round_step",
        "local_backend": cfg.local_backend,
        "clients": num_clients,
        "leak_k": leak_k,
        "attack_groups": len(attack_groups),
        "dropout_rate": drop_rate,
        "device_faults": sum(1 for s in cfg.faults
                             if s.kind in ("nan_storm", "dropout")),
    }
    return round_step


def build_aggregator(
    model,
    cfg: Config,
    test_data: Batch | None,
    mesh=None,
) -> Callable:
    """Build ``aggregate(global_params, stacked, sizes, weights_mask, rng)
    -> new_global`` for the configured mode.

    ``weights_mask`` (C,) soft-excludes clients (host-side defense filters,
    inactive clients); all-ones means everyone participates.
    For "gmm" the reference averages survivors UNWEIGHTED
    (avg_selected_parameters, server.py:777-797); every other weighted mode
    uses sizes.

    With ``mesh`` the aggregation/defense chain becomes in-program
    collectives over the sharded client axis (ISSUE 12):
    ``parallel.shard.shard_aggregator`` wraps the same-signature function
    with psum partial sums or an all_gather, per the defense's needs —
    FLTrust's root-trust pass runs replicated outside the mapped region
    and only its combine shards.  The caller gates on
    ``supports_shard_map``.
    """
    mode = cfg.mode
    if mesh is not None:
        from attackfl_tpu.parallel.shard import shard_aggregator

        ax = cfg.mesh.axis_name
        if mode == "FLTrust":
            if test_data is None:
                raise ValueError("FLTrust requires test data for root training")
            root = {k: jnp.asarray(v[:200]) for k, v in test_data.items()}
            root_update = build_root_update(
                model, cfg.data_name, root,
                epochs=cfg.epochs, batch_size=100, lr=cfg.lr,
                clip_grad_norm=cfg.clip_grad_norm,
            )
            combine = shard_aggregator(None, "FLTrust", mesh, ax)

            def aggregate(global_params, stacked, sizes, weights_mask, rng):
                # the root pass reads only replicated operands (global
                # params + rng) — every device computes the identical
                # trajectory, no collective needed
                root_params = root_update(global_params, rng)
                root_delta = jax.tree.map(
                    lambda a, b: a - b, root_params, global_params)
                deltas = jax.tree.map(
                    lambda s, g: s - g[None], stacked, global_params)
                return combine(global_params, deltas, root_delta, rng)
        else:
            plain = build_aggregator(model, cfg, test_data, mesh=None)
            aggregate = shard_aggregator(plain, mode, mesh, ax)
        aggregate.telemetry_info = {"program": f"aggregate[{mode}]",
                                    "sharded": True}
        return aggregate
    # Geometric modes ignore client weights by construction, but under
    # straggler injection a dropped client's row equals the unchanged
    # broadcast params — an implicit "no change" vote biasing robust
    # aggregation toward the previous global (ADVICE r3 #2).  When
    # dropout is configured, pass the participation mask so these modes
    # operate over reporters only (real-straggler semantics); without
    # dropout keep the exact static-shape paths.
    geo_mask = cfg.client_dropout_rate > 0.0

    if mode == "fedavg" or mode == "fltracer":
        def aggregate(global_params, stacked, sizes, weights_mask, rng):
            return aggregators.fedavg(stacked, sizes.astype(jnp.float32) * weights_mask)
    elif mode == "gmm":
        def aggregate(global_params, stacked, sizes, weights_mask, rng):
            return aggregators.mean_aggregation(stacked, weights_mask)
    elif mode == "median":
        def aggregate(global_params, stacked, sizes, weights_mask, rng):
            return aggregators.median_aggregation(
                stacked, weights_mask if geo_mask else None)
    elif mode == "trimmed_mean":
        def aggregate(global_params, stacked, sizes, weights_mask, rng):
            return aggregators.trimmed_mean(
                stacked, cfg.trim_ratio, weights_mask if geo_mask else None)
    elif mode == "krum":
        def aggregate(global_params, stacked, sizes, weights_mask, rng):
            return aggregators.krum(
                stacked, cfg.krum_f, weights_mask if geo_mask else None)
    elif mode == "shieldfl":
        def aggregate(global_params, stacked, sizes, weights_mask, rng):
            return aggregators.shieldfl(
                stacked, mask=weights_mask if geo_mask else None)
    elif mode == "scionfl":
        def aggregate(global_params, stacked, sizes, weights_mask, rng):
            return aggregators.scionfl(stacked, sizes.astype(jnp.float32) * weights_mask, rng)
    elif mode == "byzantine":
        def aggregate(global_params, stacked, sizes, weights_mask, rng):
            return aggregators.byzantine_tolerance(
                stacked, cfg.byzantine_threshold,
                weights_mask if geo_mask else None)
    elif mode == "FLTrust":
        if test_data is None:
            raise ValueError("FLTrust requires test data for root training")
        # Root set: first 200 test samples, batch 100, unshuffled
        # (server.py:290-293).
        root = {k: jnp.asarray(v[:200]) for k, v in test_data.items()}
        root_update = build_root_update(
            model, cfg.data_name, root,
            epochs=cfg.epochs, batch_size=100, lr=cfg.lr,
            clip_grad_norm=cfg.clip_grad_norm,
        )

        def aggregate(global_params, stacked, sizes, weights_mask, rng):
            root_params = root_update(global_params, rng)
            root_delta = jax.tree.map(lambda a, b: a - b, root_params, global_params)
            deltas = jax.tree.map(lambda s, g: s - g[None], stacked, global_params)
            return aggregators.fltrust_combine(global_params, deltas, root_delta)
    else:
        raise ValueError(f"Server mode '{mode}' is not valid.")

    aggregate.telemetry_info = {"program": f"aggregate[{mode}]",
                                "geo_mask": geo_mask}
    return aggregate


def build_defense_branches(
    model,
    cfg: Config,
    test_data: Batch | None,
    modes: Sequence[str],
) -> list[Callable]:
    """Uniform-signature aggregate branches for the scenario matrix's
    ``lax.switch`` defense dispatch (ISSUE 9): one
    ``(global_params, stacked, sizes, weights_mask, rng) -> new_global``
    per mode, each built by :func:`build_aggregator` under the base
    config with only the mode swapped — the same defense knobs
    (krum_f, trim_ratio, byzantine_threshold) every standalone run of
    that mode reads, so a switched branch and a standalone aggregate are
    the same program."""
    return [build_aggregator(model, cfg.replace(mode=mode), test_data)
            for mode in modes]


def build_attribution_fn(
    model,
    cfg: Config,
    test_data: Batch | None,
) -> Callable | None:
    """Build the forensic-attribution program for the configured defense:
    ``attribution(global_params, stacked, sizes, weights_mask, rng) ->
    (keep, scores)`` where ``keep`` is the (C,) bool per-client decision
    and ``scores`` the (C,) float evidence behind it.

    This mirrors :func:`build_aggregator`'s signature and, for the
    stochastic/score-based defenses, recomputes the SAME decision the
    aggregate applied (same mask semantics, same rng for ScionFL's
    quantization, same root batch for FLTrust) — it is the defense's
    verdict made observable, not a second defense.  Element-wise defenses
    (trimmed-mean / median) have no native per-client decision; their
    ``keep`` is derived from the per-client survival fraction — the share
    of parameter coordinates inside the kept window — flagged when below
    half the nominal share (a client whose coordinates are trimmed at
    twice the background rate is being systematically rejected).

    Returns None for modes with no defense decision (fedavg) and for
    host-side-filter modes (gmm / fltracer), where the engine already
    holds the keep mask and emits it directly.
    """
    mode = cfg.mode
    n = cfg.total_clients
    geo_mask = cfg.client_dropout_rate > 0.0

    if mode == "krum":
        def attribution(global_params, stacked, sizes, weights_mask, rng):
            sel = aggregators.krum_select(
                stacked, cfg.krum_f, weights_mask if geo_mask else None)
            keep = jnp.zeros((n,), bool).at[sel].set(True)
            return keep, keep.astype(jnp.float32)
    elif mode in ("trimmed_mean", "median"):
        ratio = cfg.trim_ratio

        def attribution(global_params, stacked, sizes, weights_mask, rng):
            flat = pt.tree_ravel_stacked(stacked)  # (C, P)
            mask = (weights_mask if geo_mask
                    else jnp.ones((n,), flat.dtype))
            valid = mask > 0
            v = jnp.sum(mask).astype(jnp.int32)
            if mode == "median":
                lo = (v - 1) // 2  # torch-parity lower middle
                hi = lo + 1
            else:
                kd = jnp.floor(v * ratio).astype(jnp.int32)
                lo, hi = kd, v - kd
            # rank of each client per coordinate (masked rows sort last,
            # matching the aggregator's +inf sentinel)
            order = jnp.argsort(
                jnp.where(valid[:, None], flat, jnp.inf), axis=0)
            ranks = jnp.argsort(order, axis=0)
            surviving = ((ranks >= lo) & (ranks < hi)).astype(jnp.float32)
            frac = jnp.mean(surviving, axis=1)
            nominal = (hi - lo).astype(jnp.float32) / jnp.maximum(v, 1)
            keep = (frac >= 0.5 * nominal) & valid
            return keep, frac
    elif mode == "shieldfl":
        def attribution(global_params, stacked, sizes, weights_mask, rng):
            mask = weights_mask if geo_mask else None
            weights = aggregators.shieldfl_weights(stacked, mask=mask)
            valid = (weights_mask > 0 if geo_mask
                     else jnp.ones((n,), bool))
            mean_w = jnp.sum(weights * valid) / jnp.maximum(
                jnp.sum(valid), 1)
            # ShieldFL's weights are continuous; "removed" = weighted at
            # less than half an average share of the aggregate
            keep = (weights >= 0.5 * mean_w) & valid
            return keep, weights
    elif mode == "scionfl":
        def attribution(global_params, stacked, sizes, weights_mask, rng):
            weights = aggregators.scionfl_weights(
                stacked, sizes.astype(jnp.float32) * weights_mask, rng)
            return weights > 0, weights
    elif mode == "byzantine":
        def attribution(global_params, stacked, sizes, weights_mask, rng):
            keep = aggregators.byzantine_keep(
                stacked, cfg.byzantine_threshold,
                weights_mask if geo_mask else None)
            return keep > 0, keep
    elif mode == "FLTrust":
        if test_data is None:
            return None
        # identical root batch/optimizer to build_aggregator's FLTrust
        # branch; the shared rng reproduces the same root trajectory
        root = {k: jnp.asarray(v[:200]) for k, v in test_data.items()}
        root_update = build_root_update(
            model, cfg.data_name, root,
            epochs=cfg.epochs, batch_size=100, lr=cfg.lr,
            clip_grad_norm=cfg.clip_grad_norm,
        )

        def attribution(global_params, stacked, sizes, weights_mask, rng):
            root_params = root_update(global_params, rng)
            root_delta = jax.tree.map(
                lambda a, b: a - b, root_params, global_params)
            deltas = jax.tree.map(
                lambda s, g: s - g[None], stacked, global_params)
            trust = aggregators.fltrust_trust(deltas, root_delta)
            return trust > 0, trust
    else:
        return None

    attribution.telemetry_info = {"program": f"attribution[{mode}]"}
    return attribution
