"""Hypernetwork ("hyper") server mode: pFedHN-style personalized FL.

The server owns a hypernetwork mapping client index -> full target-model
parameters.  Broadcast is generation (``hnet(i)``), aggregation is
hypernetwork training: for each client,
``delta_theta = hnet(i) − client_params`` and the hnet gradient is the VJP
of the generator applied to that cotangent — the reference computes exactly
this with ``torch.autograd.grad(outputs=weights, inputs=hnet.params,
grad_outputs=delta_theta)`` (server.py:654-659); in JAX it is literally
``jax.vjp``.  The per-client updates are sequential through one shared
Adam state (server.py:165,644-670) and are replicated here as a
``lax.scan`` carrying (hnet_params, opt_state) — order-faithful.

Client removal (hyper-detection) is handled with an ``active_mask`` so
shapes stay static: inactive clients still flow through the vmapped
trainer but their hnet contribution, genuine-leak eligibility and
validation rows are masked out.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import optax

from attackfl_tpu.config import Config
from attackfl_tpu.data.partition import apply_client_dropout, sample_round_indices
from attackfl_tpu.faults.inject import apply_nan_storm, build_client_fault_fn
from attackfl_tpu.ops import attacks
from attackfl_tpu.ops import pytree as pt
from attackfl_tpu.training.local import build_local_update, resolve_compute_dtype
from attackfl_tpu.training.round import AttackGroup, map_attackers

Batch = dict[str, jnp.ndarray]


def make_hyper_optimizer(cfg: Config) -> optax.GradientTransformation:
    """Adam(hyper_lr) behind the configured grad clip
    (server.py:165,667-668)."""
    tx = []
    if cfg.clip_grad_norm and cfg.clip_grad_norm > 0:
        tx.append(optax.clip_by_global_norm(cfg.clip_grad_norm))
    tx.append(optax.adam(cfg.hyper_lr, b1=0.9, b2=0.999, eps=1e-8))
    return optax.chain(*tx)


def build_hyper_round(
    model,
    cfg: Config,
    train_data: Batch,
    attack_groups: Sequence[AttackGroup],
    genuine_idx: Sequence[int],
    hnet_apply: Callable,
    client_pools: jnp.ndarray | None = None,
    constrain: Callable | None = None,
) -> Callable:
    """Build the client-side phase of a hyper round:

    ``round_step(hnet_params, prev_genuine, have_genuine, active_mask, rng,
    broadcast_number) -> (stacked_params, sizes, new_genuine, ok, loss)``

    Personalized params are generated per client, locally trained under
    vmap, and attacker rows are replaced by attacks computed from their own
    broadcast weights + the previous round's leaked genuine updates —
    mirroring that hyper-mode clients attack from hnet-generated weights
    (RpcClient.py:80-104).
    """
    num_clients = cfg.total_clients
    lo, hi = cfg.num_data_range
    pool = next(iter(train_data.values())).shape[0]
    num_genuine = len(genuine_idx)
    leak_k = max(int(cfg.genuine_rate * num_genuine), 1)
    genuine_arr = jnp.asarray(genuine_idx, dtype=jnp.int32)

    local_update = build_local_update(
        model, cfg.data_name, train_data,
        epochs=cfg.epochs, batch_size=cfg.batch_size,
        lr=cfg.lr, clip_grad_norm=cfg.clip_grad_norm,
        scan_unroll=cfg.scan_unroll,
        compute_dtype=resolve_compute_dtype(cfg.mesh.compute_dtype),
    )

    constrain = constrain or (lambda tree: tree)

    def generate_all(hnet_params):
        """hnet(i) for every client: stacked personalized params +
        embeddings (broadcast phase, server.py:588-590)."""
        return jax.vmap(lambda i: hnet_apply(hnet_params, i))(
            jnp.arange(num_clients)
        )

    drop_rate = cfg.client_dropout_rate
    # plan-driven deterministic faults (ISSUE 6) — see training/round.py
    forced_drop_fn = build_client_fault_fn(cfg.faults, num_clients, "dropout")
    nan_storm_fn = build_client_fault_fn(cfg.faults, num_clients, "nan_storm")

    def round_step(hnet_params, prev_genuine, have_genuine, active_mask, rng, broadcast_number):
        broadcast_params, _emb = generate_all(hnet_params)
        broadcast_params = constrain(broadcast_params)
        if drop_rate > 0:
            k_data, k_train, k_attack, k_drop = jax.random.split(rng, 4)
        else:
            k_data, k_train, k_attack = jax.random.split(rng, 3)
        idx, mask, sizes = sample_round_indices(
            k_data, num_clients, pool, lo, hi, client_pools
        )
        if drop_rate > 0:
            # straggler injection — the caller additionally skips dropped
            # clients' hnet steps (engine passes active_mask * (sizes > 0))
            sizes, mask, kept = apply_client_dropout(k_drop, sizes, mask, drop_rate)
        else:
            kept = jnp.ones((num_clients,), bool)
        if forced_drop_fn is not None:
            # scheduled straggler cohort at a chosen broadcast (ISSUE 6)
            kept = kept & ~forced_drop_fn(broadcast_number)
            sizes = sizes * kept
            mask = mask & kept[:, None]
        idx, mask = constrain(idx), constrain(mask)
        train_keys = constrain(jax.random.split(k_train, num_clients))
        stacked, ok, losses = jax.vmap(local_update, in_axes=(0, 0, 0, 0))(
            broadcast_params, train_keys, idx, mask
        )
        stacked = constrain(stacked)

        # Genuine-leak eligibility: only active genuine clients may be
        # leaked.  The sample size is static but the active pool can shrink
        # below it (detector removals), so when the detector is enabled
        # sampling is WITH replacement over the eligibility distribution —
        # duplicates only slightly sharpen the attack statistics, while
        # without-replacement would be forced to pick zero-probability
        # (removed) clients.  With the detector off the pool is fixed and
        # sampling is without replacement, matching the reference's
        # random.sample (server.py:599).  If no genuine client is active at
        # all, attacks are disabled entirely (the reference's
        # empty-leak-list case, RpcClient.py:100).
        active_genuine = active_mask[genuine_arr].astype(jnp.float32)
        n_active_genuine = jnp.sum(active_genuine)
        any_active_genuine = n_active_genuine > 0
        leak_p = active_genuine / jnp.maximum(n_active_genuine, 1.0)

        for gi, grp in enumerate(attack_groups):
            n_attackers = len(grp.indices)
            keys = jax.random.split(jax.random.fold_in(k_attack, gi), n_attackers)
            active = (
                (broadcast_number >= grp.attack_round)
                & have_genuine
                & any_active_genuine
            )
            grp_arr = jnp.asarray(grp.indices)
            # a dropped attacker never reports (training/round.py)
            active_rows = active & kept[grp_arr]
            own_params = pt.tree_take(broadcast_params, grp_arr)

            def attack_one(key, own):
                k_leak, k_noise = jax.random.split(key)
                leak = jax.random.choice(
                    k_leak, num_genuine, (min(leak_k, num_genuine),),
                    replace=cfg.hyper_detection.enable, p=leak_p,
                )
                leaked = pt.tree_take(prev_genuine, leak)
                return attacks.apply_attack(grp.mode, own, leaked, k_noise, grp.args)

            # memory-bounded over attackers (see round.map_attackers: the
            # per-attacker leak gather OOMs at north-star scale if vmapped
            # all at once)
            attacked = map_attackers(
                lambda ko: attack_one(*ko), (keys, own_params),
                n_attackers, min(leak_k, num_genuine),
                jax.tree.map(lambda x: x[0], own_params))

            def scatter(s, a):
                sel = active_rows.reshape((-1,) + (1,) * (a.ndim - 1))
                return s.at[grp_arr].set(jnp.where(sel, a, s[grp_arr]))

            stacked = jax.tree.map(scatter, stacked, attacked)
            ok = ok.at[grp_arr].set(jnp.where(active_rows, True, ok[grp_arr]))

        if nan_storm_fn is not None:
            # after the attack scatter; rides the ok-flag path (ISSUE 6)
            stacked, ok = apply_nan_storm(
                nan_storm_fn(broadcast_number), stacked, ok)

        ok = jnp.all(ok | ~active_mask.astype(bool))
        participating = active_mask * kept.astype(active_mask.dtype)
        ok = ok & (jnp.sum(participating) > 0)
        fresh = pt.tree_take(stacked, genuine_arr)
        # ok-gated leak-pool select inside the program (donation-safe
        # contract — see training/round.py round_step): a failed round's
        # returned tree already keeps the previous pool.
        if drop_rate > 0:
            # dropped genuine clients keep their last REPORTED update in
            # the leak pool (see training/round.py round_step)
            sel = ok & (kept[genuine_arr] | ~have_genuine)
        else:
            sel = jnp.broadcast_to(ok, (num_genuine,))
        new_genuine = jax.tree.map(
            lambda n, p: jnp.where(
                sel.reshape((-1,) + (1,) * (n.ndim - 1)), n, p),
            fresh, prev_genuine,
        )
        loss = jnp.sum(losses * participating) / jnp.maximum(jnp.sum(participating), 1.0)
        return stacked, sizes, new_genuine, ok, loss

    # host-side program metadata for the telemetry run header (never read
    # inside the traced function)
    round_step.telemetry_info = {
        "program": "hyper_round_step",
        "clients": num_clients,
        "leak_k": leak_k,
        "attack_groups": len(attack_groups),
        "dropout_rate": drop_rate,
        "detector": bool(cfg.hyper_detection.enable),
    }
    return round_step, generate_all


def build_hyper_update(
    cfg: Config,
    hnet_apply: Callable,
    num_clients: int,
) -> tuple[Callable, optax.GradientTransformation]:
    """Build the server-side hypernetwork training step:

    ``hyper_update(hnet_params, opt_state, stacked_client_params,
    active_mask) -> (hnet_params, opt_state)``

    Two variants, selected by ``cfg.hyper_update_mode``:

    - ``sequential`` (default): scan over clients through the shared Adam
      state — the faithful re-expression of the reference's per-client
      loop (server.py:644-670).  Inactive clients are skipped by keeping
      the carry unchanged (masked select).  O(C) serial vjp+Adam chain.
    - ``batched``: vmap all per-client vjp grads, average over active
      clients, ONE Adam step per round.  Fully parallel (the C vjps batch
      onto the MXU and shard over the client mesh axis), but a different
      trajectory: minibatch-style gradient averaging instead of C
      sequential Adam steps — accuracy equivalence is asserted at
      convergence level, not per-step (tests/test_hyper_batched.py).
      Memory: materializes C hnet-grad trees; at very large C prefer
      sharding over the client axis (the engine's mesh does this).
    """
    tx = make_hyper_optimizer(cfg)

    if cfg.hyper_update_mode == "batched":
        def hyper_update(hnet_params, opt_state, stacked_client_params, active_mask):
            def client_grad(i, client_params):
                weights, vjp_fn = jax.vjp(lambda p: hnet_apply(p, i)[0],
                                          hnet_params)
                delta_theta = jax.tree.map(lambda w, c: w - c, weights,
                                           client_params)
                (grads,) = vjp_fn(delta_theta)
                return grads

            grads = jax.vmap(client_grad)(jnp.arange(num_clients),
                                          stacked_client_params)
            mean_grads = pt.tree_weighted_mean(grads, active_mask)
            updates, new_opt = tx.update(mean_grads, opt_state, hnet_params)
            new_hp = optax.apply_updates(hnet_params, updates)
            # all-inactive round (every client dropped/removed): no step
            any_active = jnp.sum(active_mask) > 0
            sel = lambda n, o: jnp.where(any_active, n, o)  # noqa: E731
            return (jax.tree.map(sel, new_hp, hnet_params),
                    jax.tree.map(sel, new_opt, opt_state))

        hyper_update.telemetry_info = {"program": "hyper_update[batched]",
                                       "clients": num_clients}
        return hyper_update, tx

    def hyper_update(hnet_params, opt_state, stacked_client_params, active_mask):
        def body(carry, xs):
            hp, opt_s = carry
            i, active = xs
            client_params = pt.tree_take(stacked_client_params, i)
            weights, vjp_fn = jax.vjp(lambda p: hnet_apply(p, i)[0], hp)
            delta_theta = jax.tree.map(lambda w, c: w - c, weights, client_params)
            (grads,) = vjp_fn(delta_theta)
            updates, new_opt_s = tx.update(grads, opt_s, hp)
            new_hp = optax.apply_updates(hp, updates)
            hp = jax.tree.map(lambda n, o: jnp.where(active, n, o), new_hp, hp)
            opt_s = jax.tree.map(lambda n, o: jnp.where(active, n, o), new_opt_s, opt_s)
            return (hp, opt_s), None

        xs = (jnp.arange(num_clients), active_mask.astype(bool))
        (hnet_params, opt_state), _ = jax.lax.scan(body, (hnet_params, opt_state), xs)
        return hnet_params, opt_state

    hyper_update.telemetry_info = {"program": "hyper_update[sequential]",
                                   "clients": num_clients}
    return hyper_update, tx
