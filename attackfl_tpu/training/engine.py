"""The Simulator: in-process federation engine.

This replaces the reference's entire server/client process topology
(server.py Server class + N client.py processes + RabbitMQ): registration,
broadcast, the UPDATE barrier (server.py:271-272), aggregation dispatch
(server.py:286-494), genuine-model leaking (server.py:596-616), validation
gating, checkpointing and the round-retry loop (server.py:539-567) — all
driven from one Python loop around jitted round programs.

Round/retry semantics parity: a failed round (client NaN or failed
validation) is retried without decrementing the remaining-round counter
(server.py:546-563); the attack clock advances per *broadcast*, matching
the client-side ``training_round`` counter (RpcClient.py:72).  Unlike the
reference (which retries forever), retries are capped.
"""

from __future__ import annotations

import dataclasses
import math
import os
import statistics
import time
import uuid
import warnings
from collections import deque
from typing import Any, Callable

# Donation here is for EARLY FREE (the runtime may release a donated
# buffer after its last in-program use, cutting peak HBM), not only for
# in-place aliasing; XLA warns whenever a donated buffer has no
# same-shaped output to alias (e.g. the (C, P) stacked deltas donated
# into an aggregation that outputs (P,)).  That is the expected case, not
# a bug — misuse (reuse after donation) raises RuntimeError instead.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

import jax
import jax.numpy as jnp
import numpy as np

from attackfl_tpu.config import Config, parse_profile_rounds
from attackfl_tpu.costmodel.capture import compiled_profile
from attackfl_tpu.data.partition import dirichlet_label_partition
from attackfl_tpu.data.synthetic import get_dataset
from attackfl_tpu.eval.validation import Validation
from attackfl_tpu.models.hyper import make_cnn_hyper, make_hypernetwork
from attackfl_tpu.ops import defenses
from attackfl_tpu.ops import pytree as pt
from attackfl_tpu.profiler.capture import HotspotCapture
from attackfl_tpu.parallel.mesh import (
    broadcast_bytes, broadcast_string, gather_to_host, is_multiprocess,
    make_client_mesh, make_constrain, replicate_to_mesh,
)
from attackfl_tpu.registry import get_model
from attackfl_tpu.telemetry import Logger, RoundTimer, Telemetry, print_with_color
from attackfl_tpu.telemetry.xla import (
    ENV_COMPILE_CACHE, compile_cache_stats, enable_compile_cache,
    memory_analysis_bytes,
)
from attackfl_tpu.training.hyper import build_hyper_round, build_hyper_update, make_hyper_optimizer
from attackfl_tpu.ops import metrics as num_metrics
from attackfl_tpu.telemetry.numerics import NumericsDrainer
from attackfl_tpu.training.round import (
    active_attack_modes, active_attacker_indices, build_aggregator,
    build_attack_groups, build_attribution_fn, build_cohort_masks,
    build_round_step, describe_attack_groups,
)
from attackfl_tpu.utils import checkpoint as ckpt

MAX_ROUND_RETRIES = 20
# run_fast dispatch granularity: one compiled scan of this many rounds
# (compile time scales with scan length; 16 bounds the first-dispatch
# compile while amortizing per-dispatch overhead over 16 rounds)
DEFAULT_SCAN_CHUNK = 16
# `pipeline_depth: auto` ceiling (ISSUE 10): past ~8 in-flight rounds
# each extra slot only adds device-state residency without host
# resolution latency left to hide on any measured workload.
AUTO_DEPTH_CAP = 8


def auto_depth_from_records(records, fingerprint: str, window: int = 5
                            ) -> tuple[int | None, dict[str, Any]]:
    """Measured auto-tune inputs -> proposed pipeline depth (pre-clamp).

    The cross-run ledger (ISSUE 7) records the inputs on every run:
    ``round_device_time`` (D — device seconds per round) and
    ``host_resolution_latency`` (H — host seconds per round spent
    resolving verdicts), plus the per-round FOREGROUND checkpoint
    seconds from ``time_attribution`` (synchronous per-round
    checkpointing blocks the resolve path — exactly the host latency a
    deeper queue hides; the async writer's ``checkpoint_overlapped_s``
    is already hidden and excluded).  The pending queue needs enough
    in-flight rounds to cover that host work with device compute, so the
    pick is ``k = ceil((H + ckpt_fg) / D)`` (floored at 1).  Medians
    over the newest ``window`` fingerprint-matching records keep one
    noisy run from steering the pick — ``pipeline_depth`` is
    fingerprint-VOLATILE (utils/fingerprint), so runs at any depth feed
    the same pool.  Returns ``(k, info)``; ``(None, info)`` when no
    matching record carries the inputs."""
    peers: list[tuple[float, float]] = []

    # plain JSON numbers out of ledger records — no device values here,
    # so no float(...) materialization (the host-sync rule's territory)
    def number(value) -> float | None:
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            return value + 0.0
        return None

    for record in records:
        if record.get("fingerprint") != fingerprint:
            continue
        device = number(record.get("round_device_time"))
        host = number(record.get("host_resolution_latency"))
        if device is None or device <= 0 or host is None or host < 0:
            continue
        rounds = number(record.get("rounds"))
        ckpt_fg = number(
            (record.get("time_attribution") or {}).get("checkpoint_s"))
        if ckpt_fg is not None and rounds and rounds > 0:
            host += ckpt_fg / rounds
        peers.append((device, host))
    if not peers:
        return None, {"reason": "no_ledger_peers"}
    peers = peers[-window:]
    device = statistics.median([d for d, _ in peers])
    host = statistics.median([h for _, h in peers])
    ratio = host / device
    return max(1, math.ceil(ratio)), {
        "round_device_time": round(device, 6),
        "host_latency_per_round": round(host, 6),
        "ratio": round(ratio, 4),
        "peers": len(peers),
    }


def sample_inputs(data_name: str):
    """Minimal input structure for model.init per dataset."""
    if data_name == "ICU":
        return (jnp.zeros((1, 7)), jnp.zeros((1, 16)))
    if data_name == "HAR":
        return (jnp.zeros((1, 561)),)
    if data_name == "CIFAR10":
        return (jnp.zeros((1, 32, 32, 3)),)
    raise ValueError(f"Data name '{data_name}' is not valid.")


class Simulator:
    """End-to-end federated simulation for one Config."""

    def __init__(
        self,
        cfg: Config,
        train_data: dict[str, np.ndarray] | None = None,
        test_data: dict[str, np.ndarray] | None = None,
        logger: Logger | None = None,
        use_mesh: bool = False,
        mesh=None,
        telemetry: Telemetry | None = None,
        mesh_strategy: str | None = None,
    ):
        self.cfg = cfg
        self.logger = logger or Logger(f"{cfg.log_path}/app.log")
        self.model = get_model(cfg.model)

        # ---- persistent compilation cache -------------------------------
        # Enabled before any program is built so every jit below can hit
        # it.  Process-wide (jax config); env var wins over config so the
        # bench/CI harness can redirect without touching configs.
        self._compile_cache_dir = (
            os.environ.get(ENV_COMPILE_CACHE) or cfg.compile_cache_dir or None)
        if self._compile_cache_dir:
            enable_compile_cache(self._compile_cache_dir)
        self._cache_stats_start = compile_cache_stats()

        # data_seed decouples the dataset from the simulation seed (ISSUE
        # 9): matrix cell configs sweep random_seed while sharing the
        # sweep's one dataset
        data_seed = (cfg.data_seed if cfg.data_seed is not None
                     else cfg.random_seed)
        train_np = train_data if train_data is not None else get_dataset(
            cfg.data_name, "train", cfg.train_size, data_seed
        )
        test_np = test_data if test_data is not None else get_dataset(
            cfg.data_name, "test", cfg.test_size, data_seed
        )
        self.train_data = {k: jnp.asarray(v) for k, v in train_np.items()}
        self.test_np = test_np

        self.attack_groups, self.genuine_idx = build_attack_groups(cfg)
        self.genuine_mask, self.attacker_mask = build_cohort_masks(
            cfg.total_clients, self.attack_groups)

        self.client_pools = None
        if cfg.partition == "dirichlet":
            pools = dirichlet_label_partition(
                np.asarray(train_np["label"]), cfg.total_clients,
                cfg.dirichlet_alpha, seed=cfg.random_seed,
            )
            self.client_pools = jnp.asarray(pools)

        # ---- mesh / sharding -------------------------------------------
        self.mesh = mesh
        if use_mesh and mesh is None:
            self.mesh = make_client_mesh(cfg.mesh.num_devices, cfg.mesh.axis_name)
        if self.mesh is not None and cfg.total_clients % self.mesh.size != 0:
            if is_multiprocess(self.mesh):
                # dropping to mesh=None here would silently run N identical
                # full simulations, one per host — refuse instead
                raise ValueError(
                    f"{cfg.total_clients} clients must divide the "
                    f"{self.mesh.size}-device multi-host mesh"
                )
            print_with_color(
                f"[mesh] {cfg.total_clients} clients not divisible by "
                f"{self.mesh.size} devices; running replicated.", "yellow")
            self.mesh = None
        # Multi-host (DCN) mesh: every process runs this same Simulator
        # SPMD (parallel/mesh.distributed_init).  Host-side code must not
        # materialize sharded arrays; checkpoints gather to process 0
        # (_save_checkpoint) and resume via process-0 byte broadcast
        # (load_or_init_state).
        self.multiprocess = is_multiprocess(self.mesh)
        if self.multiprocess and cfg.mode in ("gmm", "fltracer"):
            raise ValueError(
                f"mode '{cfg.mode}' filters on host with sklearn-style "
                "stats and needs the full client matrix locally; run it "
                "single-process (the matrices are tiny — SURVEY.md §7)"
            )
        if self.multiprocess and cfg.resume:
            raise ValueError(
                "--resume restores from the host-local manifest.json and is "
                "single-process; multi-host resume goes through "
                "load_parameters (process-0 byte broadcast)"
            )
        if self.multiprocess and cfg.reload_parameters_per_round:
            raise ValueError(
                "reload_parameters_per_round re-reads a host-local file "
                "each round; under DCN every process would race its own "
                "copy — run it single-process (the reference it replicates "
                "is single-server, server.py:578-586)"
            )
        # Mesh execution strategy (ISSUE 12): "shard_map" maps the
        # training half over device-local client shards and turns the
        # aggregation/defense chain into in-program collectives
        # (parallel/shard); "gspmd" keeps the partitioned single program
        # (sharding constraints only).  Auto picks shard_map exactly when
        # the PRNG is bit-stable under re-batching (threefry) and the
        # mode is plain — rbg hardware keys draw batch-shape-dependent
        # bits, so a device-local client block would diverge from the
        # single-program trajectory (parallel/shard.supports_shard_map).
        self.mesh_strategy: str | None = None
        if self.mesh is not None:
            from attackfl_tpu.parallel.shard import supports_shard_map

            if mesh_strategy is None:
                self.mesh_strategy = ("shard_map" if supports_shard_map(cfg)
                                      else "gspmd")
            else:
                if mesh_strategy not in ("shard_map", "gspmd"):
                    raise ValueError(
                        f"unknown mesh_strategy {mesh_strategy!r}; choose "
                        "'shard_map' or 'gspmd'")
                if mesh_strategy == "shard_map" and not supports_shard_map(cfg):
                    raise ValueError(
                        "mesh_strategy 'shard_map' needs prng_impl "
                        "threefry2x32 on a plain (non-hyper) mode: rbg "
                        "hardware keys draw batch-shape-dependent bits, so "
                        "device-local client blocks cannot reproduce the "
                        "single-program trajectory (parallel/shard)")
                self.mesh_strategy = mesh_strategy
        self._use_shard_map = self.mesh_strategy == "shard_map"
        constrain = make_constrain(self.mesh, cfg.mesh.axis_name)

        # ---- telemetry --------------------------------------------------
        # Under a multi-host mesh every process runs this Simulator SPMD
        # and EVERY process writes its own events.<process_index>.jsonl /
        # trace.<process_index>.json, all keyed by process 0's run_id
        # (broadcast below — a collective, symmetric because every process
        # constructs the same Simulators in the same order).  `metrics
        # --merge` interleaves the files for cross-host skew analysis.
        if telemetry is not None:
            self.telemetry = telemetry
        elif self.multiprocess:
            tcfg = getattr(cfg, "telemetry", None)
            if tcfg is not None and tcfg.enabled:
                run_id = (uuid.uuid4().hex[:12]
                          if jax.process_index() == 0 else None)
                self.telemetry = Telemetry.from_config(
                    cfg, process_index=jax.process_index(),
                    run_id=broadcast_string(run_id))
            else:
                self.telemetry = Telemetry.disabled()
        else:
            self.telemetry = Telemetry.from_config(cfg)
        self._header_emitted = False
        # extra run_header fields a wrapping executor wants recorded —
        # the scenario matrix (ISSUE 9) stamps its fallback cells' runs
        # with the sweep's `sweep_id` + the cell key (schema v7 optional
        # run_header fields), so cell artifacts join their sweep
        self.header_extra: dict[str, Any] = {}
        # why the last run stopped early, when the stop hook said so —
        # hooks may return a truthy REASON string ("drain", "preempt",
        # "cancel"); recorded on run_end so a preempted run's log says
        # which seam cut it short (ISSUE 15)
        self._stop_reason: str | None = None
        # in-graph numerics (ISSUE 4): decided before the round programs
        # are jitted because it changes their donation policy (below)
        self._numerics_on = bool(self.telemetry.enabled
                                 and cfg.telemetry.numerics)
        self._nan_counter: Callable | None = None
        # AOT-compiled fused chunk programs, keyed by (scan length,
        # donate) (False = AOT failed for this key; fall back to the lazy
        # jit path)
        self._fused_exe_cache: dict[tuple, Any] = {}
        # cost observatory (ISSUE 11): guarded cost/memory-analysis
        # snapshots of every compiled program, emitted as schema-v9
        # `program_profile` events keyed by program name + config
        # fingerprint.  The fused/pipelined AOT seams profile the
        # executable they dispatch (free); the synchronous path AOT-
        # compiles its programs once per run for the snapshot
        # (_capture_sync_profiles).  Observational only — params are
        # bit-identical on vs off.  ATTACKFL_COSTMODEL=0 is the harness
        # kill switch (the tier-1 suite constructs hundreds of
        # Simulators whose sync-capture compiles would eat the time
        # budget; production runs keep the config default = on).
        self._costmodel_on = bool(
            self.telemetry.enabled and cfg.telemetry.costmodel
            and os.environ.get("ATTACKFL_COSTMODEL", "1") != "0")
        self._program_profiles: dict[str, dict[str, Any]] = {}
        self._sync_profiles_captured = False

        # ---- live monitor (health endpoint + stall watchdog) ------------
        # Config-gated; process 0 only — one health endpoint per run, and
        # the watchdog's heartbeat is the SPMD round loop every process
        # shares anyway.  Never constructed with telemetry disabled (the
        # null-object zero-overhead path).
        self.monitor = None
        if (self.telemetry.enabled and cfg.telemetry.monitor
                and (not self.multiprocess or jax.process_index() == 0)):
            from attackfl_tpu.telemetry.monitor import RunMonitor

            self.monitor = RunMonitor(
                self.telemetry,
                port=cfg.telemetry.monitor_port,
                stall_factor=cfg.telemetry.stall_factor,
                stall_grace_seconds=cfg.telemetry.stall_grace_seconds,
            )
        # hotspot observatory (ISSUE 19): the structured jax.profiler
        # window (--hotspots A:B, superseding the legacy
        # --profile-rounds spec) — fail-open capture at the dispatch
        # seams, each closed window mined into a schema-v14 `hotspot`
        # event (attackfl_tpu/profiler).  Device traces land under
        # <telemetry base>/profile as before.
        self._hotspots = HotspotCapture(
            self.telemetry,
            parse_profile_rounds(cfg.telemetry.hotspots
                                 or cfg.telemetry.profile_rounds),
            monitor=self.monitor)

        # ---- cross-run ledger (ISSUE 7) ---------------------------------
        # One distilled record per run, appended at _finish_run by pure
        # event-log post-processing (zero new host syncs).  Process 0 only
        # under DCN — workers' per-process event files merge through
        # `metrics --merge`, not the ledger.  The store's startup orphan
        # sweep rides the same counter as the checkpoint layer's.
        self._ledger = None
        self._ledger_events_offset = 0
        self._ledger_trace_offset = 0
        self._header_record: dict[str, Any] | None = None
        if (self.telemetry.enabled and cfg.telemetry.ledger
                and (not self.multiprocess or jax.process_index() == 0)):
            from attackfl_tpu.ledger.store import (
                LedgerStore, resolve_ledger_dir,
            )

            self._ledger = LedgerStore(resolve_ledger_dir(
                cfg.telemetry.ledger_dir or None, base=self.telemetry.base_dir))
            if self._ledger.swept_orphans:
                self.telemetry.counters.inc(
                    "orphan_tmp_swept", len(self._ledger.swept_orphans))
            if self.monitor is not None:
                self.monitor.set_ledger(self._ledger)
            try:
                self._ledger_events_offset = os.path.getsize(
                    self.telemetry.events.path)
            except OSError:
                self._ledger_events_offset = 0

        # ---- validation -------------------------------------------------
        self.validation = None
        if cfg.validation:
            self.validation = Validation(self.model, cfg.data_name, test_np,
                                         self.logger, telemetry=self.telemetry)

        # ---- mode-specific programs ------------------------------------
        self.is_hyper = cfg.mode == "hyper"
        # donation policy resolved ONCE (donation_spec) so the jit calls
        # below, the audit hook (audit_programs) and the static analyzers
        # (attackfl_tpu/analysis) all read the same source of truth
        donation = self.donation_spec()
        if self.is_hyper:
            init_rng = jax.random.key(cfg.random_seed, impl=cfg.prng_impl)
            template = self.model.init(init_rng, *sample_inputs(cfg.data_name))["params"]
            self.target_template = template
            make_hnet = (make_cnn_hyper if cfg.hyper_class == "CNNHyper"
                         else make_hypernetwork)
            self.hnet, self.hnet_apply = make_hnet(
                template, cfg.total_clients, embedding_dim=8, hidden_dim=100,
                spec_norm=cfg.hyper_spec_norm, n_hidden=2,
            )
            round_step, generate_all = build_hyper_round(
                self.model, cfg, self.train_data, self.attack_groups,
                self.genuine_idx, self.hnet_apply, self.client_pools, constrain,
            )
            self.round_step = jax.jit(round_step)
            self._round_step_raw = round_step
            self.generate_all = jax.jit(generate_all)
            self._generate_all_raw = generate_all
            hyper_update, self.hyper_tx = build_hyper_update(
                cfg, self.hnet_apply, cfg.total_clients
            )
            # donate the stacked client-params tree: the hnet step is its
            # last consumer each round, so its HBM copy is recycled in
            # place instead of living alongside the update's temporaries.
            # With in-graph numerics on, the numerics step reads `stacked`
            # AFTER this dispatch on the synchronous path, so donation is
            # off there (values are unchanged either way — donation is an
            # aliasing hint, never arithmetic); the pipelined/fused paths
            # keep full donation because their numerics live inside the
            # same program.
            self.hyper_update = jax.jit(
                hyper_update, donate_argnums=donation["hyper_update"])
            self._hyper_update_raw = hyper_update
            self.detector = None
            if cfg.hyper_detection.enable:
                hd = cfg.hyper_detection
                self.detector = defenses.HyperDetector(
                    cfg.total_clients, hd.cosine_search, hd.n_components,
                    hd.eps, hd.min_samples, hd.start_round,
                    save_path=f"{cfg.log_path}/all_embeddings.npy",
                )
        else:
            round_step = build_round_step(
                self.model, cfg, self.train_data, self.attack_groups,
                self.genuine_idx, self.client_pools, constrain, mesh=self.mesh,
                use_shard_map=self._use_shard_map,
            )
            self.round_step = jax.jit(round_step)
            self._round_step_raw = round_step
            aggregate = build_aggregator(
                self.model, cfg, test_np,
                mesh=self.mesh if self._use_shard_map else None)
            # donate the stacked client deltas — the (C, P)-scale buffer.
            # Aggregation is dispatched after every other consumer (the
            # host defenses and the attribution program read it first), so
            # XLA reuses its HBM for the reduction instead of holding a
            # second copy.  Do NOT pass the same stacked tree to anything
            # after self.aggregate.  Exception: with in-graph numerics on,
            # the numerics step is dispatched after aggregation and reads
            # `stacked`, so donation is off on this synchronous-path
            # program (an aliasing hint only — the aggregated values are
            # bit-identical either way; fused/pipelined paths keep
            # donation since their numerics are inside the same program).
            self.aggregate = jax.jit(
                aggregate, donate_argnums=donation["aggregate"])
            self._aggregate_raw = aggregate

        # ---- defense forensics ------------------------------------------
        # Per-round attribution (ground-truth attackers vs. the defense's
        # kept/removed set) — only meaningful with attackers configured,
        # and only worth the extra jitted program when events are recorded.
        # gmm/fltracer filter on host; the engine emits their masks
        # directly (see _run_plain_round).
        self._attribution = None
        if (not self.is_hyper and self.telemetry.enabled
                and self.attack_groups
                and cfg.mode not in ("gmm", "fltracer")):
            attribution = build_attribution_fn(self.model, cfg, test_np)
            if attribution is not None:
                self._attribution = jax.jit(attribution)

        # ---- in-graph numerics engine (ISSUE 4) --------------------------
        # Device-side metric rows (ops/metrics) accumulated in a ring
        # buffer carried in the round state; the drainer
        # (telemetry/numerics) resolves them up to `numerics_window` rounds
        # late — piggybacking on the fused/pipelined paths' existing late
        # materialization, one batched transfer per window on the
        # synchronous path.  The step consumes no rng and never feeds the
        # params math: global params are bit-identical on vs off.
        self._numerics = None
        self._numerics_drainer = None
        self._numerics_step = None
        self._numerics_step_raw = None
        if self._numerics_on:
            if self.is_hyper:
                template = self.target_template
            else:
                # leaf structure only — eval_shape never runs the init
                template = jax.eval_shape(
                    lambda key: self.model.init(
                        key, *sample_inputs(cfg.data_name))["params"],
                    jax.random.key(cfg.random_seed, impl=cfg.prng_impl))
            layout = num_metrics.build_layout(
                template, bool(self.attack_groups))
            self._numerics = num_metrics.Numerics(
                layout, self.genuine_mask, self.attacker_mask,
                window=cfg.telemetry.numerics_window)
            self._numerics_drainer = NumericsDrainer(
                layout, self.telemetry, cfg.telemetry.numerics_window,
                on_gauges=(self.monitor.update_numerics
                           if self.monitor is not None else None))
            numerics = self._numerics
            if self.is_hyper:
                gen_raw = self._generate_all_raw

                def numerics_step(num_state, old_ref, new_ref, stacked,
                                  sizes, loss, ok, broadcast):
                    # client updates are measured against the params the
                    # hnet GENERATED for them this broadcast; inside the
                    # fused/pipelined program XLA CSEs this with
                    # round_step's own generate_all call
                    base = gen_raw(old_ref)[0]
                    return numerics.step(num_state, base, old_ref, new_ref,
                                         stacked, sizes, loss, ok, broadcast)
            else:
                def numerics_step(num_state, old_ref, new_ref, stacked,
                                  sizes, loss, ok, broadcast):
                    # old_ref's leaves broadcast across the client axis
                    return numerics.step(num_state, old_ref, old_ref,
                                         new_ref, stacked, sizes, loss, ok,
                                         broadcast)
            self._numerics_step_raw = numerics_step
            self._numerics_step = jax.jit(numerics_step)

        self._ravel_stacked = jax.jit(pt.tree_ravel_stacked)
        # State-donation safety latch (ISSUE 6): donating the carry of a
        # run that started from a RESTORED checkpoint state corrupts
        # memory on jax 0.4.37 when the fused/pipelined executable is a
        # persistent-compile-cache hit (observed on CPU as NaN rounds or
        # a hard segfault on the second dispatch; reproduced on the
        # pre-ISSUE-6 tree with load_parameters resume + run_scan).
        # Donation is an optimization hint, never semantics — a resumed
        # run trades one state copy per dispatch for correctness.
        self._state_donation_ok = True
        # fused chunk programs, keyed by (scan length, donate)
        self._fused_cache: dict[tuple, Callable] = {}
        # pipelined single-round programs, keyed by (include_eval, donate)
        # — ONE program serves every pipeline depth (the depth is pure
        # host-side queue discipline), so depth changes never retrace
        self._pipeline_cache: dict[tuple, Callable] = {}
        self._pipeline_exe_cache: dict[tuple, Any] = {}
        # resolved pipeline depth (ISSUE 10): set by
        # resolve_pipeline_depth before the run header goes out so the
        # header (and through it the ledger record) carries both the
        # configured value ("auto" included) and the concrete k
        self._depth_resolved: int | None = None
        self._depth_info: dict[str, Any] | None = None
        # reload_parameters_per_round: (mtime_ns, size) -> cached params so
        # an unchanged checkpoint file costs a stat, not a deserialize
        self._reload_cache: tuple[tuple[int, int], Any] | None = None
        # validation_async: (history entry, round, in-flight device dict)
        self._inflight_validations: list[tuple[dict, int, dict]] = []

        # ---- fault-tolerant persistence (ISSUE 6) ------------------------
        # Plan-driven host-side fault injector (None without a plan); the
        # device-side half was already compiled into round_step above
        # (training/round.py reads cfg.faults at build time).
        self._fault_injector = None
        if cfg.faults:
            from attackfl_tpu.faults.inject import HostFaultInjector

            self._fault_injector = HostFaultInjector(cfg.faults, self.telemetry)
        # Orphaned temp files from killed/failed writes are swept before
        # any new checkpoint activity (satellite: they used to accumulate
        # forever).  Process 0 only under DCN — workers never write here.
        if not self.multiprocess or jax.process_index() == 0:
            swept = ckpt.sweep_orphans(cfg.checkpoint_dir)
            if swept:
                self.telemetry.counters.inc("orphan_tmp_swept", len(swept))
                print_with_color(
                    f"[checkpoint] swept {len(swept)} orphaned temp "
                    f"file(s) from {cfg.checkpoint_dir or '.'}", "yellow")
        # Durable manifest-tracked checkpoints: every save lands as a
        # round-stamped entry + the legacy alias, recorded in
        # manifest.json (round, config fingerprint, run_id, content hash)
        # with last-k retention, bounded retry-with-backoff and torn-file
        # fallback at load (utils/checkpoint.CheckpointManager).
        self._ckpt_manager = ckpt.CheckpointManager(
            ckpt.checkpoint_path(cfg),
            fingerprint=ckpt.config_fingerprint(cfg),
            run_id=self.telemetry.events.run_id,
            keep=cfg.checkpoint_keep,
            telemetry=self.telemetry,
            injector=self._fault_injector,
            fresh=not (cfg.resume or cfg.load_parameters),
        )
        self._resume_info: dict[str, Any] | None = None
        # checkpoint_async: background serialize+write+fsync thread; the
        # device->host gather stays on the round loop (_save_checkpoint).
        # The manager is the write_fn (manifest + retries + fail-open);
        # a dead thread is restarted by the writer's supervisor, counted
        # and surfaced as a `fault` recovery event.
        self._ckpt_writer = None
        if cfg.checkpoint_async:
            self._ckpt_writer = ckpt.AsyncCheckpointWriter(
                write_fn=self._ckpt_manager.write,
                on_restart=self._on_writer_restart)

    # ------------------------------------------------------------------
    # audit hooks (attackfl_tpu/analysis — ISSUE 5)
    # ------------------------------------------------------------------

    def donation_spec(self) -> dict[str, tuple[int, ...]]:
        """The engine's buffer-donation policy, stated in ONE place.

        Keys are round-program names, values the ``donate_argnums`` their
        ``jax.jit`` calls are built with (``__init__`` / ``_fused_chunk``
        / ``_pipeline_step_fn`` all read this, so the declared policy and
        the compiled programs cannot drift).  The jaxpr/HLO auditor
        (:mod:`attackfl_tpu.analysis.program_audit`) lowers each program
        and checks the declared donation against the aliasing XLA actually
        established.  Synchronous-path donation of the stacked client tree
        is OFF when in-graph numerics is enabled — the numerics step is
        dispatched after aggregation and still reads ``stacked``
        (see the jit call sites for the full rationale)."""
        spec: dict[str, tuple[int, ...]] = {"round_step": ()}
        if self.is_hyper:
            spec["generate_all"] = ()
            spec["hyper_update"] = () if self._numerics_on else (2,)
        else:
            spec["aggregate"] = () if self._numerics_on else (1,)
        spec["fused_chunk"] = (0,)
        # applied only when checkpointing is off (the caller keeps no
        # reference to the pre-round state) — see _run_pipelined
        spec["pipeline_step"] = (0,)
        return spec

    def audit_programs(self, state: dict[str, Any] | None = None
                       ) -> list[dict[str, Any]]:
        """Every jitted round program with concrete example arguments, for
        the static program auditor: ``{name, executor, raw, jit, args,
        donate}`` per program.  ``raw`` is the traceable Python callable
        (``jax.make_jaxpr``-ready), ``jit`` its jitted counterpart
        (``.lower()``-ready), ``donate`` the donation policy from
        :meth:`donation_spec`.  Nothing is executed — large operands are
        ``ShapeDtypeStruct``s where possible."""
        state = self._canonical_device_state(self._ensure_numerics_state(
            state if state is not None else self.init_state()))
        spec = self.donation_spec()
        _, k_round, k_agg = jax.random.split(state["rng"], 3)
        b = jnp.asarray(1)
        programs: list[dict[str, Any]] = []
        if self.is_hyper:
            args = (state["hnet_params"], state["prev_genuine"],
                    state["have_genuine"], jnp.asarray(state["active_mask"]),
                    k_round, b)
            stacked, sizes, *_ = jax.eval_shape(self._round_step_raw, *args)
            programs.append(dict(
                name="round_step", executor="sync",
                raw=self._round_step_raw, jit=self.round_step, args=args,
                donate=spec["round_step"]))
            programs.append(dict(
                name="hyper_update", executor="sync",
                raw=self._hyper_update_raw, jit=self.hyper_update,
                args=(state["hnet_params"], state["hyper_opt_state"],
                      stacked, jnp.asarray(state["active_mask"])),
                donate=spec["hyper_update"]))
        else:
            args = (state["global_params"], state["prev_genuine"],
                    state["have_genuine"], k_round, b)
            stacked, sizes, *_ = jax.eval_shape(self._round_step_raw, *args)
            wmask = jnp.ones((self.cfg.total_clients,), jnp.float32)
            programs.append(dict(
                name="round_step", executor="sync",
                raw=self._round_step_raw, jit=self.round_step, args=args,
                donate=spec["round_step"]))
            programs.append(dict(
                name="aggregate", executor="sync",
                raw=self._aggregate_raw, jit=self.aggregate,
                args=(state["global_params"], stacked, sizes, wmask, k_agg),
                donate=spec["aggregate"]))
        if self.supports_fused():
            body = self._build_fused_body()

            def chunk2(s):
                return jax.lax.scan(body, s, None, length=2)

            programs.append(dict(
                name="fused_chunk[2]", executor="fused",
                raw=chunk2, jit=self._fused_chunk(2), args=(state,),
                donate=spec["fused_chunk"]))
            include_eval = self.validation is not None
            body_pipeline = self._build_fused_body(include_eval=include_eval)

            def step(s):
                return body_pipeline(s, None)

            programs.append(dict(
                name=f"pipeline_step[eval={include_eval}]",
                executor="pipelined", raw=step,
                jit=self._pipeline_step_fn(include_eval, donate=True),
                args=(state,), donate=spec["pipeline_step"]))
        return programs

    def damage_objective(self, state: dict[str, Any] | None = None
                         ) -> list[dict[str, Any]]:
        """Scalar post-defense damage objectives for the transform-safety
        auditor (ISSUE 20): ``{name, executor, objective, args, donate}``
        per executor path.  Each ``objective(perturb, ...) -> scalar``
        measures how far the defended aggregate moves under an additive
        perturbation of the attackers' stacked deltas (sync) or of the
        leaked-genuine pool the attack templates read (fused) — the thing
        a learned adversary would ascend.  ``jax.grad`` of these is what
        grad_audit traces/lowers; nothing here executes.  Donating the
        perturbation (argnum 0) is aliasable 1:1: the gradient output has
        the perturbation's exact tree."""
        if self.is_hyper:
            raise NotImplementedError(
                "hyper mode has no attack-perturbation damage objective "
                "(no per-client aggregate to perturb)")
        state = self._canonical_device_state(self._ensure_numerics_state(
            state if state is not None else self.init_state()))
        _, k_round, k_agg = jax.random.split(state["rng"], 3)
        b = jnp.asarray(1)
        args = (state["global_params"], state["prev_genuine"],
                state["have_genuine"], k_round, b)
        stacked_sd, *_ = jax.eval_shape(self._round_step_raw, *args)
        attacker_sel = jnp.asarray(self.attacker_mask, jnp.float32)
        wmask = jnp.ones((self.cfg.total_clients,), jnp.float32)
        round_step_raw = self._round_step_raw
        aggregate_raw = self._aggregate_raw

        def sync_damage(perturb, global_params, prev_genuine,
                        have_genuine, rng, broadcast_number, agg_rng):
            stacked, sizes, _, _, _ = round_step_raw(
                global_params, prev_genuine, have_genuine, rng,
                broadcast_number)
            stacked = jax.tree.map(
                lambda s, p: s + p * attacker_sel.reshape(
                    (-1,) + (1,) * (s.ndim - 1)),
                stacked, perturb)
            new_global = aggregate_raw(global_params, stacked, sizes,
                                       wmask * (sizes > 0), agg_rng)
            sq = jax.tree.map(lambda n, g: jnp.sum((n - g) ** 2),
                              new_global, global_params)
            return jax.tree.reduce(lambda a, c: a + c, sq)

        perturb = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), stacked_sd)
        entries: list[dict[str, Any]] = [dict(
            name="sync_damage", executor="sync", objective=sync_damage,
            args=(perturb,) + args + (k_agg,), donate=(0,))]
        if self.supports_fused():
            body = self._build_fused_body()

            def fused_damage(pool_perturb, scan_state):
                s = dict(scan_state)
                s["prev_genuine"] = jax.tree.map(
                    lambda a, p: a + p, s["prev_genuine"], pool_perturb)
                out, _ = jax.lax.scan(body, s, None, length=2)
                sq = jax.tree.map(lambda n, g: jnp.sum((n - g) ** 2),
                                  out["global_params"],
                                  scan_state["global_params"])
                return jax.tree.reduce(lambda a, c: a + c, sq)

            pool = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype),
                state["prev_genuine"])
            entries.append(dict(
                name="fused_damage[2]", executor="fused",
                objective=fused_damage, args=(pool, state), donate=(0,)))
        return entries

    # ------------------------------------------------------------------
    # cost observatory (attackfl_tpu/costmodel — ISSUE 11)
    # ------------------------------------------------------------------

    def sync_profile_programs(self, state: dict[str, Any] | None = None
                              ) -> list[tuple[str, Any, tuple]]:
        """The synchronous path's jitted round programs with example
        arguments, ``(name, jit_fn, args)`` each — the cost observatory's
        sync capture set and the ``cost estimate`` CLI's no-peer profiling
        hook.  Mirrors :meth:`audit_programs`'s argument construction
        (large operands via ``eval_shape``); nothing is executed."""
        state = self._canonical_device_state(self._ensure_numerics_state(
            state if state is not None else self.init_state()))
        _, k_round, k_agg = jax.random.split(state["rng"], 3)
        b = jnp.asarray(1)
        programs: list[tuple[str, Any, tuple]] = []
        if self.is_hyper:
            args = (state["hnet_params"], state["prev_genuine"],
                    state["have_genuine"], jnp.asarray(state["active_mask"]),
                    k_round, b)
            stacked, sizes, *_ = jax.eval_shape(self._round_step_raw, *args)
            programs.append(("round_step", self.round_step, args))
            programs.append(("hyper_update", self.hyper_update,
                             (state["hnet_params"], state["hyper_opt_state"],
                              stacked, jnp.asarray(state["active_mask"]))))
        else:
            args = (state["global_params"], state["prev_genuine"],
                    state["have_genuine"], k_round, b)
            stacked, sizes, *_ = jax.eval_shape(self._round_step_raw, *args)
            wmask = jnp.ones((self.cfg.total_clients,), jnp.float32)
            programs.append(("round_step", self.round_step, args))
            programs.append(("aggregate", self.aggregate,
                             (state["global_params"], stacked, sizes, wmask,
                              k_agg)))
        return programs

    def _emit_program_profile(self, name: str, compiled: Any,
                              rounds_per_dispatch: int = 1) -> None:
        """Snapshot one compiled program's guarded cost/memory analysis
        as a ``program_profile`` event (schema v9) and feed the live
        monitor's cost gauges.  A backend with no stats degrades to a
        partial profile or silence — never an error."""
        if not self._costmodel_on:
            return
        profile = compiled_profile(compiled)
        if profile is None:
            return
        profile["rounds_per_dispatch"] = int(rounds_per_dispatch)
        profile["device_kind"] = str(jax.devices()[0].device_kind)
        self._program_profiles[name] = profile
        self.telemetry.events.emit(
            "program_profile", program=name,
            fingerprint=self._ckpt_manager.fingerprint, **profile)
        if self.monitor is not None:
            self.monitor.set_cost_model(dict(self._program_profiles))

    def _capture_sync_profiles(self, state: dict[str, Any]) -> None:
        """AOT-compile the synchronous path's round programs ONCE per
        Simulator for their cost profiles (the fused/pipelined/matrix
        executors profile the executable they dispatch, so only the
        lazy-jit sync path needs this extra compile — a persistent-cache
        hit when ``compile_cache_dir`` is set).  Compile time is recorded
        under the usual ``compile`` spans/events, so the ledger's
        attribution stays honest.  Skipped under a mesh, like the AOT
        executors (AOT pins shardings)."""
        if (not self._costmodel_on or self._sync_profiles_captured
                or self.mesh is not None):
            return
        self._sync_profiles_captured = True
        tel = self.telemetry
        for name, fn, args in self.sync_profile_programs(state):
            t0 = time.perf_counter()
            try:
                with tel.tracer.span("compile", program=name):
                    compiled = fn.lower(*args).compile()
            except Exception as e:  # noqa: BLE001 — capture is best-effort
                tel.events.emit("compile", program=name,
                                seconds=round(time.perf_counter() - t0, 6),
                                error=f"{type(e).__name__}: {e}"[:300])
                continue
            tel.events.emit("compile", program=name,
                            seconds=round(time.perf_counter() - t0, 6))
            self._emit_program_profile(name, compiled)

    # ------------------------------------------------------------------
    # state
    # ------------------------------------------------------------------

    def init_state(self, seed: int | None = None) -> dict[str, Any]:
        """Fresh simulation state (the reference's fresh-init path,
        server.py:160-162)."""
        state = self._init_host_state(seed)
        if self.multiprocess:
            # committed-to-local-device arrays can't feed a program over a
            # multi-process mesh: replicate them globally (every process
            # computed identical values from the shared seed)
            state = replicate_to_mesh(state, self.mesh)
        return self._ensure_numerics_state(state)

    def _place_on_mesh(self, state: dict[str, Any]) -> dict[str, Any]:
        """Canonical mesh placement of a run-entry state (ISSUE 12):
        replicate every leaf onto the mesh so the FIRST dispatch compiles
        the same input shardings every later round produces (the round
        programs' state outputs are replicated by construction — the
        shard_map aggregate's ``out_specs=P()``, the round_step leak-pool
        constraint).  Without this, round 1 runs on host-placed arrays
        and round 2 re-specializes the jit for the device shardings —
        one wasted multi-second compile per program on real silicon, and
        a retrace-guard violation here.  Multiprocess states are already
        replicated (init/resume paths).  ``replicate_local`` copies per
        device — the fused/pipelined paths DONATE this state, and
        ``replicate_to_mesh``'s callback-built shards alias one host
        buffer (donating those corrupts memory on jax 0.4.37)."""
        if self.mesh is None or self.multiprocess:
            return state
        from attackfl_tpu.parallel.mesh import replicate_local

        return replicate_local(state, self.mesh)

    def _ensure_numerics_state(self, state: dict[str, Any]) -> dict[str, Any]:
        """Attach the numerics ring to a state that lacks one (fresh init,
        checkpoint resume, or a state built before numerics was enabled).
        The ring is observability state: it is NOT part of checkpoints
        (_save_checkpoint strips it; _init_host_state — the resume
        template — never carries it), so resume stays structure-compatible
        across numerics on/off and a resumed run simply starts a fresh
        ring."""
        if self._numerics is not None and "numerics" not in state:
            state = dict(state, numerics=self._numerics.init_state())
        return state

    def _init_host_state(self, seed: int | None = None) -> dict[str, Any]:
        """Host-local fresh state (pre-replication) — also the structural
        template multi-host resume deserializes checkpoint bytes against."""
        seed = self.cfg.random_seed if seed is None else seed
        # typed key: carries prng_impl (rbg by default — hardware RNG makes
        # dropout-mask generation ~4x cheaper on TPU than threefry)
        rng = jax.random.key(seed, impl=self.cfg.prng_impl)
        k_model, k_state = jax.random.split(rng)
        num_genuine = len(self.genuine_idx)

        if self.is_hyper:
            hnet_params = self.hnet.init(k_model, jnp.asarray(0))["params"]
            opt_state = make_hyper_optimizer(self.cfg).init(hnet_params)
            template = self.target_template
            prev_genuine = pt.tree_broadcast(
                jax.tree.map(jnp.zeros_like, template), num_genuine
            )
            state = {
                "hnet_params": hnet_params,
                "hyper_opt_state": opt_state,
                "prev_genuine": prev_genuine,
                "have_genuine": np.asarray(False),
                "active_mask": np.ones(self.cfg.total_clients, np.float32),
                "rng": k_state,
                "completed_rounds": np.asarray(0),
                "broadcasts": np.asarray(0),
            }
        else:
            params = self.model.init(k_model, *sample_inputs(self.cfg.data_name))["params"]
            prev_genuine = pt.tree_broadcast(
                jax.tree.map(jnp.zeros_like, params), num_genuine
            )
            state = {
                "global_params": params,
                "prev_genuine": prev_genuine,
                "have_genuine": np.asarray(False),
                "rng": k_state,
                "completed_rounds": np.asarray(0),
                "broadcasts": np.asarray(0),
            }
        return state

    def _load_resume_state(self) -> dict[str, Any] | None:
        """``--resume``: restore the newest VALID manifest entry
        (torn/truncated entries are detected by content hash and fall
        back to the previous good one), stash the ``resume`` event
        payload for :meth:`_emit_run_header`, and return the state —
        or None when nothing valid exists (the run starts fresh, loudly).
        """
        result = self._ckpt_manager.load_latest(self._init_host_state())
        rejected = [{"file": entry.get("file"), "round": entry.get("round"),
                     "reason": reason[:200]}
                    for entry, reason in result.rejected]
        if rejected:
            self.telemetry.counters.inc("checkpoint_fallbacks", len(rejected))
            for item in rejected:
                print_with_color(
                    f"[resume] rejected checkpoint {item['file']}: "
                    f"{item['reason']}", "yellow")
        if result.state is None:
            print_with_color(
                "[resume] no valid checkpoint entry found under "
                f"{self._ckpt_manager.directory!r}; starting fresh", "yellow")
            self._resume_info = None
            return None
        entry = result.entry or {}
        manifest = result.manifest or {}
        fingerprint_match = (
            manifest.get("fingerprint") == self._ckpt_manager.fingerprint
            if manifest.get("fingerprint") else None)
        if fingerprint_match is False:
            print_with_color(
                "[resume] config fingerprint mismatch: this checkpoint was "
                "written under a different experiment config — resuming "
                "anyway because the state structure matched, but verify "
                "your config", "red")
        state = result.state
        round_no = int(state["completed_rounds"])
        self._resume_info = {
            "round": round_no,
            "broadcast": int(state["broadcasts"]),
            "path": os.path.join(self._ckpt_manager.directory,
                                 str(entry.get("file", ""))),
            "source_run_id": manifest.get("run_id", ""),
            "fingerprint_match": fingerprint_match,
            "rejected": rejected,
        }
        print_with_color(
            f"[resume] continuing from round {round_no} "
            f"({entry.get('file')})", "yellow")
        self._state_donation_ok = False  # restored state: donation off
        return self._ensure_numerics_state(state)

    def load_or_init_state(self) -> dict[str, Any]:
        """Resume from checkpoint when configured
        (reference: server.py:144-163,578-586).

        ``cfg.resume`` restores through the checkpoint manifest (newest
        valid entry, torn-file fallback, ``resume`` telemetry event with
        exactly-once round accounting: the resumed run's round numbers
        continue from the checkpoint instead of restarting at 1).
        ``cfg.load_parameters`` keeps the legacy single-file reload.

        Multi-host: process 0's checkpoint bytes are broadcast so every
        process restores IDENTICAL state (host-local files may differ or
        be absent on workers), then re-replicated onto the DCN mesh."""
        if self.cfg.resume:
            state = self._load_resume_state()
            if state is not None:
                return state
            return self.init_state()
        if self.cfg.load_parameters and self.multiprocess:
            path = ckpt.checkpoint_path(self.cfg)
            data = None
            if jax.process_index() == 0 and os.path.exists(path):
                with open(path, "rb") as fh:
                    data = fh.read()
            data = broadcast_bytes(data)
            if data is None:
                return self.init_state()
            host = ckpt.load_state_bytes(data, self._init_host_state(), path)
            print_with_color(
                f"Load state from checkpoint (process-0 broadcast): {path}",
                "yellow")
            self._state_donation_ok = False  # restored state: donation off
            return self._ensure_numerics_state(
                replicate_to_mesh(host, self.mesh))
        state = self.init_state()
        if self.cfg.load_parameters:
            path = ckpt.checkpoint_path(self.cfg)
            # checkpoints never hold the numerics ring — load against a
            # ring-less template, then re-attach this run's fresh ring
            template = {k: v for k, v in state.items() if k != "numerics"}
            try:
                loaded = ckpt.load_state(path, template)
                if "numerics" in state:
                    loaded["numerics"] = state["numerics"]
                state = loaded
                self._state_donation_ok = False  # restored: donation off
                print_with_color(f"Load state from checkpoint: {path}", "yellow")
            except FileNotFoundError:
                pass
        return state

    def _consult_stop(self, stop, completed_rounds) -> bool:
        """One stop-hook consultation, shared by every executor: any
        truthy verdict stops the run, and a STRING verdict is kept as
        the stop reason for run_end (the run service's hooks return
        "drain" / "preempt" / "cancel" so the event log names the seam
        that cut the run short)."""
        if stop is None:
            return False
        verdict = stop(int(completed_rounds))
        if not verdict:
            return False
        self._stop_reason = (verdict if isinstance(verdict, str)
                             else "stopped")
        return True

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------

    def _emit_run_header(self) -> None:
        """First event of a run: config + backend/device info + the static
        program/attacker geometry (host-known values only)."""
        tel = self.telemetry
        if self._header_emitted or not tel.enabled:
            return
        self._header_emitted = True
        # bind the live monitor BEFORE the header goes out so the header
        # can record the ACTUAL port (`monitor-port: 0` binds ephemeral —
        # parallel tests and multi-tenant services never collide, and
        # tooling reading the run directory still finds the endpoint)
        if self.monitor is not None:
            self._start_monitor()
        programs = {}
        for name, fn in (("round_step", getattr(self, "_round_step_raw", None)),
                         ("aggregate", getattr(self, "_aggregate_raw", None)),
                         ("hyper_update", getattr(self, "_hyper_update_raw", None))):
            info = getattr(fn, "telemetry_info", None)
            if info:
                programs[name] = info
        if self._numerics is not None:
            programs["numerics"] = {
                "program": "numerics_step",
                "slots": self._numerics.layout.size,
                "window": self._numerics.window,
                "metrics": list(self._numerics.layout.names),
                "leaf_names": list(self._numerics.layout.leaf_names),
            }
        # schema v5 provenance: the cross-run ledger joins runs on these
        # (a perf delta is only actionable when the code + toolchain that
        # produced each side is known)
        from attackfl_tpu.ledger.record import git_revision
        try:
            import jaxlib

            jaxlib_version = getattr(jaxlib, "__version__", "")
        except ImportError:  # pragma: no cover — jax always ships jaxlib
            jaxlib_version = ""
        self._header_record = tel.events.emit(
            "run_header",
            backend=jax.default_backend(),
            num_devices=len(jax.devices()),
            mesh_devices=self.mesh.size if self.mesh is not None else 0,
            # schema v10: how the mesh executes (shard_map = mesh-native
            # collectives, gspmd = partitioned single program); absent on
            # non-mesh runs
            **({"mesh_strategy": self.mesh_strategy}
               if self.mesh_strategy is not None else {}),
            multiprocess=self.multiprocess,
            mode=self.cfg.mode,
            model=self.cfg.model,
            data_name=self.cfg.data_name,
            total_clients=self.cfg.total_clients,
            attacks=describe_attack_groups(self.attack_groups),
            programs=programs,
            jax_version=jax.__version__,
            jaxlib_version=jaxlib_version,
            platform=jax.devices()[0].platform,
            git_rev=git_revision(),
            compile_cache_dir=self._compile_cache_dir or "",
            fault_plan=[spec.describe() for spec in self.cfg.faults],
            config=dataclasses.asdict(self.cfg),
            # schema v6: the monitor's ACTUAL bound port (ephemeral under
            # `monitor-port: 0`), absent when no monitor runs
            **({"monitor_port": int(self.monitor.port)}
               if self.monitor is not None and self.monitor.port is not None
               else {}),
            # schema v8: the pipelined executor's resolved depth + the
            # configured value ("auto" included) — resolved BEFORE the
            # header goes out (run() orders it so), absent on
            # non-pipelined runs
            **({"pipeline_depth": int(self._depth_resolved),
                "pipeline_depth_configured": str(self.cfg.pipeline_depth)}
               if self._depth_resolved is not None else {}),
            # schema v7: sweep_id/cell when this run is a matrix cell
            **self.header_extra,
        )
        if self._resume_info is not None:
            # exactly-once round accounting: the resumed run declares the
            # boundary it continues from (its own round events then start
            # at round+1 — no round number is ever recorded twice within
            # a run, and cross-run tooling can join on this event)
            tel.events.emit("resume", **self._resume_info)
            self._resume_info = None

    def _emit_attribution(self, metrics, global_params, stacked, sizes,
                          weights_mask, broadcast_number: int,
                          have_genuine: bool, defense_mask, rng,
                          timer) -> None:
        """Record the defense's per-round verdict against ground truth
        (the ``attribution`` event — telemetry/forensics.py computes
        TPR/FPR from these).  ``defense_mask`` is the host-side filter
        decision (gmm/fltracer); score-based defenses recompute theirs via
        the jitted attribution program (same rng/mask as the aggregate).
        Per-round path only: a fused chunk is one opaque dispatch.
        """
        tel = self.telemetry
        if not (tel.enabled and self.attack_groups):
            return
        if self._attribution is None and defense_mask is None:
            return
        with timer.phase("attribution"):
            if self._attribution is not None:
                keep, scores = self._attribution(
                    global_params, stacked, sizes, weights_mask, rng)
            else:
                keep = scores = defense_mask
            if self.multiprocess:
                # (C,)-sized outputs, but possibly DCN-sharded — gather is
                # a collective every process runs (symmetric SPMD path)
                keep, scores, sizes = gather_to_host((keep, scores, sizes))
            keep = np.asarray(keep).astype(bool)
            scores = np.asarray(scores, dtype=np.float64)
            reporting = np.asarray(sizes) > 0
        active = active_attacker_indices(
            self.attack_groups, broadcast_number, have_genuine)
        attackers = [int(i) for i in active if reporting[i]]
        kept = [int(i) for i in np.flatnonzero(reporting & keep)]
        removed = [int(i) for i in np.flatnonzero(reporting & ~keep)]
        metrics["defense_removed"] = len(removed)
        tel.events.emit(
            "attribution",
            round=metrics["round"],
            broadcast=broadcast_number,
            mode=self.cfg.mode,
            attackers=attackers,
            kept=kept,
            removed=removed,
            non_reporting=[int(i) for i in np.flatnonzero(~reporting)],
            scores={str(i): round(float(s), 6)
                    for i, s in enumerate(scores)},
        )

    def _count_nan_clients(self, stacked) -> int:
        """How many clients' stacked updates contain non-finite values —
        computed on the failure path only (one jitted reduction)."""
        if self._nan_counter is None:
            def count(tree):
                flat = pt.tree_ravel_stacked(tree)
                return jnp.sum(~jnp.all(jnp.isfinite(flat), axis=1))

            self._nan_counter = jax.jit(count)
        return int(self._nan_counter(stacked))

    def _finish_run(self, history: list[dict[str, Any]], t_start: float,
                    state: dict[str, Any] | None = None) -> None:
        """Terminal work of a run()/run_fast() call: resolve in-flight
        async validations, drain any un-emitted numerics ring rows (the
        synchronous path batches them — ``state`` carries the ring), drain
        the background checkpoint writer (the final state is durably on
        disk before the call returns), then the counters snapshot,
        compile-cache stats, a run_end record, and the Chrome trace
        file.

        Runs on EVERY exit path — the run methods call it from a
        ``finally`` block, so a crashing round still drains the async
        checkpoint writer (the last durable checkpoint survives the
        crash) and still leaves a closed, usable event record.  A drain
        error is re-raised only after the telemetry record is written."""
        self._resolve_inflight_validations()
        if self._numerics_drainer is not None and state is not None:
            self._numerics_drainer.drain(state.get("numerics"))
        drain_error: BaseException | None = None
        if self._ckpt_writer is not None:
            try:
                self._ckpt_writer.drain()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                drain_error = e
        try:
            self._emit_run_end(history, t_start)
            # cross-run ledger record (ISSUE 7): distilled AFTER run_end is
            # on disk so the derivation sees the complete run — and inside
            # the same try/finally chain, so a crashing round still
            # records its partial run
            self._append_ledger_record()
        finally:
            if drain_error is not None:
                raise drain_error

    def _emit_run_end(self, history: list[dict[str, Any]],
                      t_start: float) -> None:
        tel = self.telemetry
        if not tel.enabled:
            return
        self._maybe_stop_profile(force=True)
        if self.monitor is not None:
            self.monitor.run_ended()
        if self._compile_cache_dir:
            stats = compile_cache_stats()
            start, self._cache_stats_start = self._cache_stats_start, stats
            tel.events.emit(
                "compile",
                program="persistent_cache",
                seconds=round(stats["backend_compile_seconds"]
                              - start.get("backend_compile_seconds", 0.0), 6),
                cache_dir=self._compile_cache_dir,
                cache_hits=int(stats["cache_hits"] - start.get("cache_hits", 0)),
                cache_misses=int(stats["cache_misses"]
                                 - start.get("cache_misses", 0)),
                cache_retrieval_seconds=round(
                    stats["cache_retrieval_seconds"]
                    - start.get("cache_retrieval_seconds", 0.0), 6),
            )
        tel.events.emit("counters", counters=tel.counters.snapshot())
        tel.events.emit(
            "run_end",
            rounds=len(history),
            ok_rounds=sum(1 for h in history if h.get("ok")),
            seconds=round(time.perf_counter() - t_start, 6),
            # extra-by-design field: which seam stopped the run early
            # ("drain" / "preempt" / "cancel"), absent on full runs
            **({"stop_reason": self._stop_reason}
               if self._stop_reason else {}),
        )
        self._stop_reason = None
        tel.flush()

    def _append_ledger_record(self) -> None:
        """Distill THIS run's slice of events.jsonl into one cross-run
        ledger record and append it (attackfl_tpu/ledger — ISSUE 7).

        Pure post-processing: the event log is line-buffered, so by the
        time ``_emit_run_end`` has flushed, everything the derivation
        needs is on disk; the byte offset taken at construction / after
        the previous run isolates each ``run()`` call's slice when one
        Simulator runs several times (bench reps).  The host-side trace
        spans (already in memory) provide the device/host wall-time
        attribution.  Best-effort by design — a full ledger disk must
        never fail the run that produced the science."""
        if self._ledger is None or not self.telemetry.enabled:
            return
        try:
            import json as _json

            from attackfl_tpu.ledger.record import derive_record

            path = self.telemetry.events.path
            # this run's slice: everything emitted since the previous
            # ledger append (events.jsonl accumulates across run() calls)
            offset = self._ledger_events_offset
            with open(path, "rb") as fh:
                fh.seek(offset)
                tail = fh.read().decode("utf-8", errors="replace")
                self._ledger_events_offset = fh.tell()
            slice_events = []
            for line in tail.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    record = _json.loads(line)
                except ValueError:
                    continue
                if isinstance(record, dict):
                    slice_events.append(record)
            if (self._header_record is not None
                    and not any(e.get("kind") == "run_header"
                                for e in slice_events)):
                slice_events.insert(0, self._header_record)
            # the tracer accumulates spans across run() calls too: slice
            # off the spans already attributed to previous records
            trace_events = getattr(self.telemetry.tracer, "_events", None)
            if trace_events is not None:
                trace_tail = trace_events[self._ledger_trace_offset:]
                self._ledger_trace_offset = len(trace_events)
                trace_events = trace_tail
            # the existing corpus feeds the hotspot observatory's
            # measured-vs-predicted join (this run isn't appended yet,
            # so no self-exclusion is needed)
            try:
                corpus = self._ledger.records()
            except Exception:  # noqa: BLE001 — the join is optional
                corpus = None
            record = derive_record(
                slice_events, trace_events=trace_events,
                fingerprint=self._ckpt_manager.fingerprint,
                ledger_records=corpus)
            if record is None:
                return
            rid = self._ledger.append(record)
            self.telemetry.counters.inc("ledger_records_appended")
            self.telemetry.events.emit(
                "ledger", record_id=rid, ledger_path=self._ledger.path)
        except Exception as e:  # noqa: BLE001 — observability, fail open
            self.telemetry.counters.inc("ledger_append_failures")
            print_with_color(
                f"[ledger] append failed (run unaffected): "
                f"{type(e).__name__}: {e}", "yellow")

    def _resolve_inflight_validations(self) -> None:
        """Materialize async-validation results (``validation_async``) and
        fold them into telemetry + the round's history entry when they
        land.  The verdict never gates the round in async mode."""
        while self._inflight_validations:
            entry, round_no, out = self._inflight_validations.pop(0)
            val_ok, val_metrics = self.validation.resolve_async(
                out, record=False)
            entry.update(val_metrics)
            entry["validation_ok"] = val_ok
            if not val_ok:
                self.telemetry.counters.inc("validation_failures")
            self.telemetry.events.emit(
                "validation", ok=val_ok, round=round_no,
                data_name=self.validation.data_name, background=True,
                **val_metrics)

    def _start_monitor(self) -> None:
        """Bind the health endpoint (idempotent) and arm the watchdog for
        this run."""
        if self.monitor is None:
            return
        first = self.monitor.port is None
        if self.mesh is not None:
            self.monitor.set_mesh(self.mesh.size, self.mesh_strategy)
        self.monitor.start().run_started()
        if first:
            print_with_color(
                f"[monitor] http://localhost:{self.monitor.port} "
                "(/healthz /metrics /last-round — poll with "
                "`attackfl-tpu watch`)", "cyan")

    def _maybe_start_profile(self, first_round: int,
                             last_round: int | None = None,
                             program: str = "sync") -> None:
        """Open the profiling window when the upcoming round(s)
        [first_round, last_round] overlap --hotspots/--profile-rounds.
        Fused chunks pass their whole round range (the chunk is one
        dispatch; profiling starts at its boundary).  Delegates to the
        hotspot observatory's fail-open capture (attackfl_tpu/profiler);
        ``program`` names the dispatch seam on the ``hotspot`` event."""
        self._hotspots.maybe_start(first_round, last_round,
                                   program=program)

    def _maybe_stop_profile(self, completed_rounds: int = 0,
                            force: bool = False) -> None:
        self._hotspots.maybe_stop(completed_rounds, force=force)

    def close(self) -> None:
        """Release observability + persistence resources (monitor thread,
        checkpoint writer, event file).  Safe to call twice; the Simulator
        itself stays usable for pure compute after close (telemetry
        becomes flush-less no-ops; a closed checkpoint writer falls back
        to synchronous saves)."""
        if self.monitor is not None:
            self.monitor.stop()
        if self._ckpt_writer is not None:
            self._ckpt_writer.close()
            self._ckpt_writer = None
        self.telemetry.close()

    # ------------------------------------------------------------------
    # one round
    # ------------------------------------------------------------------

    def _on_writer_restart(self, restarts: int) -> None:
        """The async-writer supervisor revived a dead thread: count it
        and record the recovery (a dead writer used to silently stop
        persisting until close() deadlocked)."""
        self.telemetry.counters.inc("checkpoint_writer_restarts")
        self.telemetry.events.emit("fault", fault="writer_death",
                                   action="recovered", restarts=restarts)

    def _note_round_faults(self, round_no: int, broadcast: int) -> None:
        """Host-side bookkeeping once a round resolves: record the plan's
        device-side injections for this broadcast (the injection itself
        ran inside the jitted program) and fire any armed monitor stall."""
        injector = self._fault_injector
        if injector is None:
            return
        injector.note_round_resolved(broadcast)
        injector.maybe_stall_monitor(round_no, self.monitor)

    def _save_checkpoint(self, state: dict[str, Any]) -> None:
        """Persist ``state`` (reference cadence: every successful round,
        server.py:549-553).  Multi-host: gather the DCN-sharded tree to
        host (one all-gather over DCN) and let process 0 alone write the
        file — every process participates in the gather collective.

        All writes flow through the :class:`CheckpointManager`: a
        round-stamped durable entry + the legacy alias + the manifest
        record, with bounded retry-with-backoff and fail-open on a dead
        disk (the run outlives its persistence).  With
        ``cfg.checkpoint_async`` the device->host gather stays here (on
        the round loop) but serialization, the file write and the fsync
        move to the supervised background writer: submit is O(gather)
        and rapid rounds coalesce to the newest state (last-write-wins).
        """
        path = ckpt.checkpoint_path(self.cfg)
        writer = self._ckpt_writer
        round_no = int(state["completed_rounds"])
        meta = {"round": round_no, "broadcast": int(state["broadcasts"])}
        if self._fault_injector is not None:
            self._fault_injector.maybe_kill_writer(round_no, writer)
        with self.telemetry.tracer.span("checkpoint", background=writer is not None):
            # the numerics ring is observability state, excluded from
            # checkpoints (resume compatibility across numerics on/off;
            # a resumed run starts a fresh ring)
            target = {k: v for k, v in state.items() if k != "numerics"}
            write_here = True
            if self.multiprocess:
                target = gather_to_host(state)
                write_here = jax.process_index() == 0
            if write_here:
                if writer is not None:
                    writer.submit(path, ckpt.host_state(target), meta=meta)
                    self.telemetry.counters.inc("checkpoint_submits")
                else:
                    self._ckpt_manager.write(path, ckpt.host_state(target),
                                             meta)
        self.telemetry.events.emit("checkpoint", path=path, round=round_no,
                                   background=writer is not None)

    def run_round(self, state: dict[str, Any]) -> tuple[dict[str, Any], dict[str, Any]]:
        """Execute one broadcast->train->attack->aggregate->validate round.

        Returns (new_state, metrics).  On failure (``metrics["ok"]`` False)
        the returned state keeps the previous global/hyper params but
        advances the rng, broadcast clock and genuine-leak cache — matching
        the reference's retry path (server.py:546-567).
        """
        cfg = self.cfg
        self._emit_run_header()
        # async validations dispatched last round resolve here, AFTER the
        # device has had the inter-round host window to evaluate them
        self._resolve_inflight_validations()
        t0 = time.perf_counter()
        if cfg.reload_parameters_per_round and not self.is_hyper:
            # reference fidelity (server.py:578-586): with parameters.load,
            # every non-hyper broadcast re-reads the checkpoint file.  The
            # reference also REWRITES that file after every successful
            # round (server.py:550-553), so there the round-trip is how the
            # aggregate reaches clients — replicate it with per-round
            # checkpoint saving on (run(save_checkpoints=True)); with
            # saving off this pins training to the file's params instead.
            # A missing file is a no-op (os.path.exists gate).  The
            # re-read is mtime/size-cached: an unchanged file costs one
            # stat instead of a full msgpack deserialize on the critical
            # path (the async checkpoint writer rewrites it off-thread, so
            # the cache also absorbs the submit-to-write latency window).
            path = ckpt.checkpoint_path(cfg)
            try:
                st = os.stat(path)
                key = (st.st_mtime_ns, st.st_size)
                if (self._reload_cache is not None
                        and self._reload_cache[0] == key):
                    params = self._reload_cache[1]
                    self.telemetry.counters.inc("reload_cache_hits")
                else:
                    params = ckpt.load_state(
                        path, {k: v for k, v in state.items()
                               if k != "numerics"})["global_params"]
                    self._reload_cache = (key, params)
                    self.telemetry.counters.inc("reload_cache_misses")
                state = dict(state, global_params=params)
            except FileNotFoundError:
                pass
        rng, k_round, k_agg = jax.random.split(state["rng"], 3)
        broadcast_number = int(state["broadcasts"]) + 1
        metrics: dict[str, Any] = {"round": int(state["completed_rounds"]) + 1,
                                   "broadcast": broadcast_number}

        with self.telemetry.tracer.span("round", round=metrics["round"],
                                        broadcast=broadcast_number):
            if self.is_hyper:
                new_state, metrics = self._run_hyper_round(
                    state, rng, k_round, broadcast_number, metrics
                )
            else:
                new_state, metrics = self._run_plain_round(
                    state, rng, k_round, k_agg, broadcast_number, metrics
                )
        metrics["seconds"] = time.perf_counter() - t0
        self.telemetry.events.round_event(metrics)
        return new_state, metrics

    def _validation_due(self, broadcast_number: int) -> bool:
        """Validation cadence (``validation_every``), keyed on the
        broadcast clock so the synchronous, pipelined and fused paths
        validate the same rounds."""
        return (self.validation is not None
                and broadcast_number % self.cfg.validation_every == 0)

    def _run_plain_round(self, state, rng, k_round, k_agg, broadcast_number, metrics):
        cfg = self.cfg
        tel = self.telemetry
        timer = RoundTimer(tracer=tel.tracer)
        if self.attack_groups:
            metrics["attacks_active"] = active_attack_modes(
                self.attack_groups, broadcast_number,
                bool(state["have_genuine"]))
        with timer.phase("train"):
            stacked, sizes, new_genuine, ok, loss = self.round_step(
                state["global_params"], state["prev_genuine"],
                jnp.asarray(bool(state["have_genuine"])), k_round,
                jnp.asarray(broadcast_number),
            )
            ok = train_ok = bool(ok)  # blocks on the dispatched program
        metrics["train_loss"] = float(loss)
        if not train_ok:
            tel.counters.inc("nan_train_rounds")
            if tel.enabled:
                nan_clients = self._count_nan_clients(stacked)
                metrics["nan_clients"] = nan_clients
                tel.counters.inc("nan_clients_detected", nan_clients)

        weights_mask = jnp.ones((cfg.total_clients,), jnp.float32)
        defense_mask = None  # host-side filter decision (gmm/fltracer)
        if ok and cfg.mode == "gmm":
            with timer.phase("defense"):
                # ravel dispatched ON DEVICE (jitted tree_ravel_stacked);
                # ONE host transfer of the concatenated (C, P) matrix —
                # the defense_transfer_bytes counter makes its cost
                # visible in `metrics`
                flat = np.asarray(self._ravel_stacked(stacked))
                tel.counters.inc("defense_transfer_bytes", flat.nbytes)
                keep = defenses.gmm_filter(flat, self.attacker_mask, seed=cfg.random_seed)
            metrics["gmm_kept"] = int(keep.sum())
            tel.counters.inc("anomalies_removed", cfg.total_clients - int(keep.sum()))
            if not keep.any():
                ok = False  # round fails when no client survives (server.py:369-372)
            defense_mask = np.asarray(keep, bool)
            weights_mask = jnp.asarray(keep, jnp.float32)
        elif ok and cfg.mode == "fltracer":
            with timer.phase("defense"):
                # single device->host copy, same contract as the gmm branch
                flat = np.asarray(self._ravel_stacked(stacked))
                tel.counters.inc("defense_transfer_bytes", flat.nbytes)
                anomalies = defenses.fltracer_anomalies(flat)
            metrics["fltracer_anomalies"] = anomalies.tolist()
            tel.counters.inc("anomalies_removed", len(anomalies))
            mask = np.ones(cfg.total_clients, np.float32)
            mask[anomalies] = 0.0
            if not mask.any():
                ok = False
            defense_mask = mask > 0
            weights_mask = jnp.asarray(mask)

        # defense filter ∩ reporting clients: with dropout on, the defense
        # can keep only dropped (size-0) clients — then no weight remains
        # and a weighted average would be 0/0; fail the round instead
        weights_mask = weights_mask * (sizes > 0)
        if ok and not bool(jnp.any(weights_mask > 0)):
            ok = False

        if ok:
            self._emit_attribution(
                metrics, state["global_params"], stacked, sizes,
                weights_mask, broadcast_number,
                bool(state["have_genuine"]), defense_mask, k_agg, timer)

        new_global = state["global_params"]
        if ok:
            with timer.phase("aggregate"):
                # self.aggregate DONATES stacked (its last consumer)
                new_global = self.aggregate(
                    state["global_params"], stacked, sizes, weights_mask, k_agg
                )
                jax.block_until_ready(new_global)
            if self._validation_due(broadcast_number):
                if cfg.validation_async:
                    # dispatch only; the result lands one round later
                    # (telemetry `validation` event + this entry's dict)
                    # and does NOT gate this round's acceptance
                    self._inflight_validations.append(
                        (metrics, metrics["round"],
                         self.validation.test_async(new_global)))
                else:
                    with timer.phase("validate"):
                        val_ok, val_metrics = self.validation.test(new_global)
                    metrics.update(val_metrics)
                    ok = ok and val_ok

        metrics["ok"] = ok
        metrics["phases"] = timer.durations
        new_state = dict(state)
        new_state["rng"] = rng
        new_state["broadcasts"] = np.asarray(broadcast_number)
        # The genuine-leak cache only absorbs rounds whose *training* was
        # clean (the ok-gated select now lives INSIDE round_step —
        # training/round.py), so a NaN round never contaminates the leak
        # pool.  Validation-failed rounds DO leak (the reference
        # re-broadcasts the already-accumulated list, server.py:596-616).
        new_state["prev_genuine"] = new_genuine
        if train_ok:
            new_state["have_genuine"] = np.asarray(True)
        if ok:
            new_state["global_params"] = new_global
            new_state["completed_rounds"] = np.asarray(int(state["completed_rounds"]) + 1)
        if self._numerics is not None:
            with timer.phase("numerics"):
                # dispatch-only: the row lands in the device ring (stacked
                # is still alive — aggregation does not donate it with
                # numerics on); `accepted` mirrors the fused body's accept
                # select, so a failed round records zero drift
                accepted = new_global if ok else state["global_params"]
                new_state["numerics"], _ = self._numerics_step(
                    state["numerics"], state["global_params"], accepted,
                    stacked, sizes, loss, jnp.asarray(ok),
                    jnp.asarray(broadcast_number))
            self._numerics_drainer.note_round(
                metrics["round"], broadcast_number)
            self._numerics_drainer.maybe_drain(new_state["numerics"])
        return new_state, metrics

    def _run_hyper_round(self, state, rng, k_round, broadcast_number, metrics):
        cfg = self.cfg
        tel = self.telemetry
        timer = RoundTimer(tracer=tel.tracer)
        if self.attack_groups:
            metrics["attacks_active"] = active_attack_modes(
                self.attack_groups, broadcast_number,
                bool(state["have_genuine"]))
        active_mask = jnp.asarray(state["active_mask"])
        with timer.phase("train"):
            stacked, sizes, new_genuine, ok, loss = self.round_step(
                state["hnet_params"], state["prev_genuine"],
                jnp.asarray(bool(state["have_genuine"])), active_mask, k_round,
                jnp.asarray(broadcast_number),
            )
            ok = train_ok = bool(ok)
        metrics["train_loss"] = float(loss)
        if not train_ok:
            tel.counters.inc("nan_train_rounds")
            if tel.enabled:
                nan_clients = self._count_nan_clients(stacked)
                metrics["nan_clients"] = nan_clients
                tel.counters.inc("nan_clients_detected", nan_clients)

        # snapshot for detection rollback (reference: server.py:296-298)
        prev_hnet = state["hnet_params"] if self.detector is not None else None
        prev_opt = state["hyper_opt_state"] if self.detector is not None else None

        hnet_params, opt_state = state["hnet_params"], state["hyper_opt_state"]
        new_active = np.asarray(state["active_mask"]).copy()
        if ok:
            with timer.phase("hyper_update"):
                hnet_params, opt_state = self.hyper_update(
                    # dropped clients (size 0) skip their hnet step;
                    # self.hyper_update DONATES stacked (last consumer) —
                    # unless numerics is on, which reads it afterwards
                    hnet_params, opt_state, stacked, active_mask * (sizes > 0)
                )
                jax.block_until_ready(hnet_params)

            gen_params = None
            if self.detector is not None:
                with timer.phase("detect"):
                    gen_params, embeddings = self.generate_all(hnet_params)
                    selected = [int(i) for i in np.flatnonzero(new_active > 0)]
                    emb_np = np.asarray(embeddings)[selected]
                    removals = self.detector.observe(broadcast_number, selected, emb_np)
                if tel.enabled:
                    # per-client anomaly signal: embedding L2 norms of this
                    # round's selected clients (host-side, already gathered)
                    metrics["embedding_norms"] = {
                        cid: round(float(n), 6) for cid, n in
                        zip(selected, np.linalg.norm(emb_np, axis=1))
                    }
                if removals:
                    print_with_color(f"Removing anomalies {removals}, rolling back", "yellow")
                    metrics["removed_clients"] = removals
                    tel.counters.inc("anomalies_removed", len(removals))
                    tel.events.emit("rollback", removed=list(removals),
                                    broadcast=broadcast_number)
                    for cid in removals:
                        new_active[cid] = 0.0
                    hnet_params, opt_state = prev_hnet, prev_opt
                    gen_params = None  # rollback invalidates the generation
                if tel.enabled and self.attack_groups:
                    # hyper-detection forensics (folds the detector into
                    # `metrics --forensics`): ground-truth attackers among
                    # this round's still-active clients vs the detector's
                    # removal verdict, scored by embedding L2 norm.  A
                    # round with no removals is still a (negative) verdict
                    # — it gives TPR/FPR their denominators.
                    active = set(active_attacker_indices(
                        self.attack_groups, broadcast_number,
                        bool(state["have_genuine"])))
                    removed_set = set(int(c) for c in removals)
                    kept = [c for c in selected if c not in removed_set]
                    metrics["defense_removed"] = len(removed_set)
                    tel.events.emit(
                        "attribution",
                        round=metrics["round"], broadcast=broadcast_number,
                        mode=cfg.mode, source="hyper_detection",
                        attackers=[c for c in selected if c in active],
                        kept=kept, removed=sorted(removed_set),
                        non_reporting=[c for c in range(cfg.total_clients)
                                       if c not in set(selected)],
                        scores={str(c): round(float(n), 6) for c, n in
                                zip(selected,
                                    np.linalg.norm(emb_np, axis=1))},
                    )

            if self._validation_due(broadcast_number):
                if gen_params is None:
                    gen_params, _ = self.generate_all(hnet_params)
                active_ids = jnp.asarray(np.flatnonzero(new_active > 0))
                taken = pt.tree_take(gen_params, active_ids)
                if cfg.validation_async:
                    # dispatch only; lands one round later and does not
                    # gate acceptance (see _run_plain_round)
                    self._inflight_validations.append(
                        (metrics, metrics["round"],
                         self.validation.test_hyper_async(taken)))
                else:
                    with timer.phase("validate"):
                        val_ok, val_metrics = self.validation.test_hyper(taken)
                    metrics.update(val_metrics)
                    ok = ok and val_ok

        metrics["ok"] = ok
        metrics["phases"] = timer.durations
        new_state = dict(state)
        new_state["rng"] = rng
        new_state["broadcasts"] = np.asarray(broadcast_number)
        # ok-gated leak-pool select lives inside round_step (hyper.py)
        new_state["prev_genuine"] = new_genuine
        if train_ok:
            new_state["have_genuine"] = np.asarray(True)
        new_state["active_mask"] = new_active
        if ok:
            new_state["hnet_params"] = hnet_params
            new_state["hyper_opt_state"] = opt_state
            new_state["completed_rounds"] = np.asarray(int(state["completed_rounds"]) + 1)
        if self._numerics is not None:
            with timer.phase("numerics"):
                # `hnet_params` already reflects rollback (drift 0 on a
                # rolled-back round); a failed round keeps the old params
                accepted = hnet_params if ok else state["hnet_params"]
                new_state["numerics"], _ = self._numerics_step(
                    state["numerics"], state["hnet_params"], accepted,
                    stacked, sizes, loss, jnp.asarray(ok),
                    jnp.asarray(broadcast_number))
            self._numerics_drainer.note_round(
                metrics["round"], broadcast_number)
            self._numerics_drainer.maybe_drain(new_state["numerics"])
        return new_state, metrics

    # ------------------------------------------------------------------
    # fused multi-round fast path
    # ------------------------------------------------------------------

    def supports_fused(self) -> bool:
        """True when the whole round (train → attack → aggregate → validate)
        is expressible as one XLA program, i.e. no host-side per-round work.

        GMM / FLTracer filter with sklearn between training and aggregation,
        and hyper-detection runs DBSCAN + rollback on host — those modes
        stay on the per-round path.
        """
        if self.cfg.mode in ("gmm", "fltracer"):
            return False
        if self.is_hyper and self.detector is not None:
            return False
        if self.cfg.reload_parameters_per_round and not self.is_hyper:
            # re-reads a file on host before every broadcast (hyper mode
            # never reloads — reference gate server.py:580 — so it keeps
            # the fused path)
            return False
        return True

    def _build_fused_body(self, include_eval: bool = True) -> Callable:
        """One broadcast as a ``lax.scan`` body over the simulation state.

        Collapses the reference's whole distributed round protocol — START
        broadcast, N client trainings, UPDATE barrier, aggregation,
        validation gate, accept-or-retry (server.py:205-567) — into a single
        scan step: a failed round (NaN training or failed validation) keeps
        the old params via ``where`` instead of a host-side retry branch.

        ``include_eval=False`` builds the body without the validation
        program (the pipelined executor's validation_async mode, which
        dispatches evaluation outside the acceptance chain).  With
        ``cfg.validation_every > 1`` the inlined evaluation is wrapped in
        a ``lax.cond`` keyed on the broadcast clock: skipped rounds pay no
        eval FLOPs, report NaN metrics and carry no validation gate — the
        same cadence the per-round paths apply on host.
        """
        cfg = self.cfg
        eval_fn = None
        if include_eval and self.validation is not None:
            eval_fn = (self.validation.eval_hyper_fn if self.is_hyper
                       else self.validation.eval_fn)
        val_every = max(int(cfg.validation_every), 1)
        # in-graph numerics: the row is computed INSIDE this same program
        # (reductions fuse into the round; no extra dispatch), written to
        # the ring carried in the state AND surfaced through the metrics
        # output, which the scan stacks / the pipelined resolve
        # materializes one round late
        numerics_step = self._numerics_step_raw

        def gated_eval(b, make_ev):
            """Run ``make_ev`` when this broadcast is due for validation;
            otherwise skip the compute entirely (NaN metrics, ok=True)."""
            if val_every == 1:
                return make_ev(None)
            struct = jax.eval_shape(make_ev, None)

            def skip(_):
                return {
                    k: (jnp.ones(s.shape, s.dtype) if k == "ok"
                        else jnp.full(s.shape, jnp.nan, s.dtype))
                    for k, s in struct.items()
                }

            return jax.lax.cond(b % val_every == 0, make_ev, skip, None)

        def accept(flag, new, old):
            return jax.tree.map(lambda n, o: jnp.where(flag, n, o), new, old)

        if self.is_hyper:
            round_step = self._round_step_raw
            hyper_update = self._hyper_update_raw
            generate_all = self._generate_all_raw

            def body(state, _):
                # split(3) matches run_round's pattern so both paths walk
                # the same rng trajectory (k_agg is unused in hyper mode)
                rng, k_round, _k_agg = jax.random.split(state["rng"], 3)
                b = state["broadcasts"] + 1
                active_mask = jnp.asarray(state["active_mask"])
                stacked, sizes, new_gen, train_ok, loss = round_step(
                    state["hnet_params"], state["prev_genuine"],
                    state["have_genuine"], active_mask, k_round, b,
                )
                new_hp, new_opt = hyper_update(
                    state["hnet_params"], state["hyper_opt_state"],
                    # dropped clients (size 0) skip their hnet step — the
                    # reference iterates only reporting clients
                    stacked, active_mask * (sizes > 0),
                )
                ok = train_ok
                metrics = {"train_loss": loss}
                if eval_fn is not None:
                    ev = gated_eval(
                        b, lambda _: eval_fn(
                            stacked_params=generate_all(new_hp)[0]))
                    ok = ok & ev.pop("ok")
                    # run_round skips validation entirely when training
                    # failed; the scan body can't skip, so mask the metrics
                    # of train-failed rounds to NaN for history parity
                    metrics.update(
                        {k: jnp.where(train_ok, v, jnp.nan) for k, v in ev.items()}
                    )
                new_state = {
                    "hnet_params": accept(ok, new_hp, state["hnet_params"]),
                    "hyper_opt_state": accept(ok, new_opt, state["hyper_opt_state"]),
                    # round_step selects the leak pool internally (ok-gated)
                    "prev_genuine": new_gen,
                    "have_genuine": state["have_genuine"] | train_ok,
                    "active_mask": active_mask,
                    "rng": rng,
                    "completed_rounds": state["completed_rounds"] + ok.astype(jnp.int32),
                    "broadcasts": b,
                }
                if numerics_step is not None:
                    new_state["numerics"], metrics["numerics_row"] = \
                        numerics_step(
                            state["numerics"], state["hnet_params"],
                            new_state["hnet_params"], stacked, sizes, loss,
                            ok, b)
                metrics["ok"] = ok
                return new_state, metrics

        else:
            round_step = self._round_step_raw
            aggregate = self._aggregate_raw
            wmask = jnp.ones((cfg.total_clients,), jnp.float32)

            def body(state, _):
                rng, k_round, k_agg = jax.random.split(state["rng"], 3)
                b = state["broadcasts"] + 1
                stacked, sizes, new_gen, train_ok, loss = round_step(
                    state["global_params"], state["prev_genuine"],
                    state["have_genuine"], k_round, b,
                )
                round_mask = wmask * (sizes > 0)
                new_global = aggregate(
                    state["global_params"], stacked, sizes, round_mask, k_agg
                )
                # run_round's empty-reporters guard (engine.py run_round:
                # "no clients reported"): with dropout an all-dropped round
                # would feed an all-zero mask into the masked geometric
                # aggregators (v=0 → inf/NaN global) — fail the round so
                # `accept` keeps the previous params instead
                ok = train_ok & jnp.any(round_mask > 0)
                metrics = {"train_loss": loss}
                if eval_fn is not None:
                    ev = gated_eval(b, lambda _: eval_fn(params=new_global))
                    ok = ok & ev.pop("ok")
                    # mask train-failed rounds' val metrics (see hyper body)
                    metrics.update(
                        {k: jnp.where(train_ok, v, jnp.nan) for k, v in ev.items()}
                    )
                new_state = {
                    "global_params": accept(ok, new_global, state["global_params"]),
                    # round_step selects the leak pool internally (ok-gated)
                    "prev_genuine": new_gen,
                    "have_genuine": state["have_genuine"] | train_ok,
                    "rng": rng,
                    "completed_rounds": state["completed_rounds"] + ok.astype(jnp.int32),
                    "broadcasts": b,
                }
                if numerics_step is not None:
                    # measured against the ACCEPTED params (a failed
                    # round's drift is 0), matching the sync path
                    new_state["numerics"], metrics["numerics_row"] = \
                        numerics_step(
                            state["numerics"], state["global_params"],
                            new_state["global_params"], stacked, sizes,
                            loss, ok, b)
                metrics["ok"] = ok
                return new_state, metrics

        return body

    def _fused_chunk(self, length: int, donate: bool = True) -> Callable:
        key = (length, donate)
        fn = self._fused_cache.get(key)
        if fn is None:
            self.telemetry.counters.inc("round_program_cache_misses")
            body = self._build_fused_body()

            def chunk(state):
                return jax.lax.scan(body, state, None, length=length)

            fn = jax.jit(chunk,
                         donate_argnums=(self.donation_spec()["fused_chunk"]
                                         if donate else ()))
            self._fused_cache[key] = fn
        else:
            self.telemetry.counters.inc("round_program_cache_hits")
        return fn

    def _fused_executable(self, key: tuple, fn: Callable, state) -> Any:
        """AOT-compile the fused chunk under a telemetry compile span
        (explicit compile-vs-dispatch split + guarded memory stats).

        Only used when telemetry is on and no mesh is involved (AOT
        executables pin input shardings; the lazy jit path re-shards
        freely).  Returns the executable, or False when AOT failed — the
        caller then falls back to the jitted ``fn`` permanently."""
        exe = self._fused_exe_cache.get(key)
        if exe is None:
            length = key[0]
            tel = self.telemetry
            label = f"fused_scan[{length}]"
            t0 = time.perf_counter()
            try:
                with tel.tracer.span("compile", program=label):
                    exe = fn.lower(state).compile()
            except Exception as e:  # noqa: BLE001 — AOT is best-effort
                exe = False
                tel.events.emit("compile", program=label,
                                seconds=round(time.perf_counter() - t0, 6),
                                error=f"{type(e).__name__}: {e}"[:300])
            else:
                event = {"program": label,
                         "seconds": round(time.perf_counter() - t0, 6),
                         "scan_length": length}
                memory = memory_analysis_bytes(exe)
                if memory:
                    event["memory_bytes"] = memory
                tel.events.emit("compile", **event)
                # cost observatory: the chunk program IS `length` rounds
                # per dispatch — profiled from the executable we dispatch
                self._emit_program_profile(label, exe,
                                           rounds_per_dispatch=length)
            self._fused_exe_cache[key] = exe
        return exe

    def _canonical_device_state(self, state: dict[str, Any]) -> dict[str, Any]:
        """Cast host-typed counters/flags so the fused carry has stable
        dtypes across scan iterations."""
        out = dict(state)
        out["completed_rounds"] = jnp.asarray(state["completed_rounds"], jnp.int32)
        out["broadcasts"] = jnp.asarray(state["broadcasts"], jnp.int32)
        out["have_genuine"] = jnp.asarray(bool(state["have_genuine"]))
        if "active_mask" in out:
            out["active_mask"] = jnp.asarray(state["active_mask"], jnp.float32)
        if self._numerics is not None:
            if "numerics" not in out:
                out["numerics"] = self._numerics.init_state()
            else:
                num = dict(out["numerics"])
                num["buffer"] = jnp.asarray(num["buffer"], jnp.float32)
                num["cursor"] = jnp.asarray(num["cursor"], jnp.int32)
                num["prev_loss"] = jnp.asarray(num["prev_loss"], jnp.float32)
                out["numerics"] = num
        else:
            # a state built under a numerics-enabled Simulator fed to a
            # numerics-off one: the fused body would drop the key from the
            # scan carry (structure mismatch) — drop it up front instead
            out.pop("numerics", None)
        # mesh runs: canonical replicated placement AFTER the casts above
        # (a cast re-materializes the leaf on the default device, which
        # would undo an earlier placement) — see _place_on_mesh
        return self._place_on_mesh(out)

    def run_scan(
        self, state: dict[str, Any], num_broadcasts: int
    ) -> tuple[dict[str, Any], dict[str, Any]]:
        """Run ``num_broadcasts`` rounds as ONE jitted ``lax.scan`` dispatch.

        Returns (new_state, metrics) where each metrics value is a
        ``(num_broadcasts,)`` array.  Failed rounds keep the previous
        params (the retry clock still advances, matching run_round).  The
        input state is donated — do not reuse it after this call.
        """
        if not self.supports_fused():
            raise ValueError(
                f"mode '{self.cfg.mode}' (hyper-detection={self.is_hyper and self.detector is not None}) "
                "needs host-side per-round work; use run_round/run instead"
            )
        if "active_mask" in state and not np.all(np.asarray(state["active_mask"]) > 0):
            # the fused hyper body validates ALL clients' personalized
            # outputs; with removed clients (a state resumed from a
            # hyper-detection run) that would pool rolled-back clients into
            # the AUC — the per-round path filters them (tree_take over
            # active ids, _run_hyper_round)
            raise ValueError(
                "state has inactive clients (resumed from a hyper-detection "
                "run?); use run_round/run for active-mask-aware validation"
            )
        # restored-state runs keep donation off (see the donation
        # safety latch in __init__)
        donate = self._state_donation_ok
        fn = self._fused_chunk(num_broadcasts, donate=donate)
        state = self._canonical_device_state(state)
        if self.telemetry.enabled and self.mesh is None:
            exe = self._fused_executable((num_broadcasts, donate), fn, state)
            if exe is not False:
                return exe(state)
        return fn(state)

    def run_fast(
        self,
        num_rounds: int | None = None,
        state: dict[str, Any] | None = None,
        chunk_size: int | None = None,
        save_checkpoints: bool = True,
        verbose: bool = True,
        progress: dict[str, Any] | None = None,
        stop: Callable[[int], bool] | None = None,
    ) -> tuple[dict[str, Any], list[dict[str, Any]]]:
        """Like :meth:`run` but on the fused scan path: one device dispatch
        per chunk instead of several per round.  Checkpoints land per chunk
        rather than per round (the reference checkpoints per round,
        server.py:549-553 — set ``chunk_size=1`` for that cadence).

        ``progress``, if given, is updated in place after every chunk with
        ``ok_rounds`` and ``interim_rounds_per_sec_incl_compile`` so a
        watchdog (bench --deadline) can report best-so-far throughput if a
        later dispatch wedges.

        ``stop`` (see :meth:`run`) is consulted between CHUNKS — the
        chunk is one opaque device dispatch, so that is the finest
        graceful-drain granularity this path has.

        Unlike :meth:`run`, the passed-in ``state``'s buffers are DONATED to
        the device program — do not reuse it after this call.
        """
        cfg = self.cfg
        tel = self.telemetry
        num_rounds = num_rounds if num_rounds is not None else cfg.num_round
        state = self._ensure_numerics_state(
            state if state is not None else self.load_or_init_state())
        self._emit_run_header()
        history: list[dict[str, Any]] = []
        consecutive_failures = 0  # run()'s retry counter semantics
        first_dispatch = True
        # exactly-once round accounting: a resumed run's round numbers
        # continue from the checkpoint instead of restarting at 1
        round_offset = int(state["completed_rounds"])
        t_start = time.perf_counter()

        self._start_monitor()
        try:
            while int(state["completed_rounds"]) < num_rounds:
                if self._consult_stop(stop, state["completed_rounds"]):
                    break
                remaining = num_rounds - int(state["completed_rounds"])
                # Chunk sizing doubles as a compile-cache policy: the first
                # dispatch compiles one bounded-length scan (a 100-round run
                # must not compile a length-100 program — compile time grows
                # with scan length), repeat full chunks hit the jit cache, and
                # retry tails use length-1 scans (one extra compile total)
                # instead of a fresh fused program per shrinking remainder.
                cap = chunk_size if chunk_size else DEFAULT_SCAN_CHUNK
                if chunk_size:
                    n = min(chunk_size, remaining)
                elif first_dispatch or remaining >= cap:
                    n = min(cap, remaining)
                else:
                    n = 1
                first_dispatch = False
                # compile happens on this chunk length's first dispatch —
                # either AOT inside run_scan (telemetry on) or lazily at the
                # jitted call (telemetry off); flag the chunk either way so
                # the metrics CLI can split steady vs incl-compile rates
                donate_key = (n, self._state_donation_ok)
                includes_compile = (donate_key not in self._fused_cache
                                    and donate_key not in self._fused_exe_cache)
                done_before = int(state["completed_rounds"])
                self._maybe_start_profile(done_before + 1, done_before + n,
                                          program="fused")
                t0 = time.perf_counter()
                with tel.tracer.span("chunk", chunk_len=n):
                    state, metrics = self.run_scan(state, n)
                    # dispatch is ASYNC (CPU backend included): without
                    # blocking, `elapsed` measures enqueue time (~10 ms) while
                    # the actual rounds run inside the np.asarray sync below,
                    # making chunk_seconds fiction.  Block inside the timed
                    # section.
                    jax.block_until_ready(metrics)
                elapsed = time.perf_counter() - t0
                tel.events.emit("chunk", chunk_len=n, seconds=round(elapsed, 6),
                                includes_compile=includes_compile)
                host = {k: np.asarray(v) for k, v in metrics.items()}
                # the scan stacked one numerics row per round — already host
                # numpy via the per-chunk materialization above (no new sync)
                numerics_rows = host.pop("numerics_row", None)
                broadcasts_after = int(state["broadcasts"])
                for i in range(n):
                    entry = {k: (bool(v[i]) if k == "ok" else float(v[i]))
                             for k, v in host.items()}
                    # A fused chunk is ONE device dispatch: per-round wall time
                    # is not observable inside it, so report the genuine chunk
                    # measurement instead of a synthetic per-round average
                    # (run()'s per-entry "seconds" IS genuine, engine.py:286).
                    entry["chunk_seconds"] = elapsed
                    entry["chunk_len"] = n
                    # attempt index, offset by the resume point
                    entry["round"] = round_offset + len(history) + 1
                    entry["broadcast"] = broadcasts_after - n + i + 1
                    if numerics_rows is not None:
                        self._numerics_drainer.push_host_row(
                            entry["round"], entry["broadcast"],
                            numerics_rows[i])
                    history.append(entry)
                    tel.events.round_event(entry)
                    self._note_round_faults(entry["round"], entry["broadcast"])
                    if self.monitor is not None:
                        # heartbeat cadence: the chunk is one dispatch, so the
                        # amortized per-round time feeds the stall median
                        self.monitor.record_round(entry, duration=elapsed / n)
                    if entry["ok"]:
                        consecutive_failures = 0
                    else:
                        consecutive_failures += 1
                        tel.counters.inc("rounds_failed")
                self._maybe_stop_profile(int(state["completed_rounds"]))
                if consecutive_failures > MAX_ROUND_RETRIES:
                    raise RuntimeError(
                        f"round failed {consecutive_failures} times in a row; "
                        "aborting (the reference would retry forever, "
                        "server.py:546-556)"
                    )
                if progress is not None:
                    ok_so_far = sum(1 for h in history if h["ok"])
                    progress["ok_rounds"] = ok_so_far
                    progress["interim_rounds_per_sec_incl_compile"] = round(
                        ok_so_far / (time.perf_counter() - t_start), 4)
                if save_checkpoints:
                    self._save_checkpoint(state)
                if verbose:
                    done = int(state["completed_rounds"])
                    last = history[-1]
                    keys = [k for k in ("roc_auc", "accuracy", "nll", "train_loss") if k in last]
                    msg = " ".join(f"{k}={last[k]:.4f}" for k in keys)
                    print_with_color(
                        f"[fast] {done}/{num_rounds} rounds, chunk of {n} in "
                        f"{elapsed:.2f}s ({elapsed / n:.3f}s/round) {msg}", "green")
        finally:
            # every exit path — including a crashing round — drains the
            # async checkpoint writer (the last durable checkpoint
            # survives) and closes the telemetry record
            self._finish_run(history, t_start, state)
        return state, history

    # ------------------------------------------------------------------
    # pipelined per-round path
    # ------------------------------------------------------------------

    def resolve_pipeline_depth(self, save_checkpoints: bool = True) -> int:
        """Resolve ``cfg.pipeline_depth`` to a concrete k for this run.

        An explicit integer is used as-is (range-checked by the config).
        ``"auto"`` reads the cross-run ledger's measured
        ``round_device_time`` / ``host_resolution_latency`` for this
        config's fingerprint (:func:`auto_depth_from_records` — the depth
        knob itself is fingerprint-volatile, so runs at any depth feed
        the same measurement pool) and picks ``k = ceil(H/D)``, clamped
        by:

        * :data:`AUTO_DEPTH_CAP` — past it each in-flight slot only adds
          device-state residency;
        * ``telemetry.numerics_window`` when in-graph numerics is on —
          numerics rows resolve k rounds late, and the reporting window
          the drainer guarantees is sized to the ring;
        * the checkpoint cadence: per-round SYNCHRONOUS checkpointing
          (``save_checkpoints`` without the async writer) serializes a
          state gather + write + fsync into every resolve, so auto never
          picks past 2 there — deeper queues just pile behind the fsync.

        No ledger measurement yet -> depth 1 (today's behavior), loudly.
        The result and its derivation are stashed for the run header
        (``pipeline_depth`` / ``pipeline_depth_configured``, schema v8).
        """
        configured = self.cfg.pipeline_depth
        if isinstance(configured, int):
            self._depth_resolved = configured
            self._depth_info = {"source": "config", "depth": configured}
            return configured
        info: dict[str, Any] = {"source": "auto"}
        k: int | None = None
        try:
            if self._ledger is not None:
                records, _ = self._ledger.load()
            else:
                from attackfl_tpu.ledger.store import (
                    LedgerStore, resolve_ledger_dir,
                )

                directory = resolve_ledger_dir(
                    self.cfg.telemetry.ledger_dir or None,
                    base=getattr(self.telemetry, "base_dir", None))
                # never CREATE a ledger dir just to discover it is empty
                records = (LedgerStore(directory).load()[0]
                           if os.path.isdir(directory) else [])
            k, measured = auto_depth_from_records(
                records, self._ckpt_manager.fingerprint)
            info.update(measured)
        except Exception as e:  # noqa: BLE001 — auto must never fail the run
            info["error"] = f"{type(e).__name__}: {e}"[:200]
        if k is None:
            k = 1
            print_with_color(
                "[pipeline] depth auto: no ledger measurement for this "
                "config yet — defaulting to depth-1 (a run with "
                "telemetry.ledger on feeds the auto-tuner)", "yellow")
        cap = AUTO_DEPTH_CAP
        if self._numerics_on:
            cap = min(cap, self.cfg.telemetry.numerics_window)
        if save_checkpoints and not self.cfg.checkpoint_async:
            cap = min(cap, 2)
        if k > cap:
            info["clamped_from"] = k
            k = cap
        info["depth"] = k
        self._depth_resolved = k
        self._depth_info = info
        if "ratio" in info:
            print_with_color(
                f"[pipeline] depth auto -> {k} (measured host/device ratio "
                f"{info['ratio']} over {info['peers']} ledger record(s)"
                + (f", clamped from {info['clamped_from']}"
                   if "clamped_from" in info else "") + ")", "cyan")
        return k

    def _pipeline_step_fn(self, include_eval: bool, donate: bool) -> Callable:
        """One round as ONE jitted program (the fused scan body, unrolled
        to a single step).  ``donate`` recycles the input state's buffers
        in place — only legal when the caller keeps no reference to the
        pre-round state (i.e. checkpointing is off; a checkpointed round
        must gather the state the next dispatch would otherwise consume).
        """
        key = (include_eval, donate)
        fn = self._pipeline_cache.get(key)
        if fn is None:
            body = self._build_fused_body(include_eval=include_eval)

            def step(state):
                return body(state, None)

            fn = jax.jit(
                step,
                donate_argnums=(self.donation_spec()["pipeline_step"]
                                if donate else ()))
            self._pipeline_cache[key] = fn
        return fn

    def _pipeline_executable(self, key: tuple, fn: Callable, state) -> Any:
        """AOT-compile the pipeline step under a telemetry compile span
        (same rationale and fallback contract as _fused_executable)."""
        exe = self._pipeline_exe_cache.get(key)
        if exe is None:
            tel = self.telemetry
            label = f"pipeline_step[eval={key[0]}]"
            t0 = time.perf_counter()
            try:
                with tel.tracer.span("compile", program=label):
                    exe = fn.lower(state).compile()
            except Exception as e:  # noqa: BLE001 — AOT is best-effort
                exe = False
                tel.events.emit("compile", program=label,
                                seconds=round(time.perf_counter() - t0, 6),
                                error=f"{type(e).__name__}: {e}"[:300])
            else:
                event = {"program": label,
                         "seconds": round(time.perf_counter() - t0, 6)}
                memory = memory_analysis_bytes(exe)
                if memory:
                    event["memory_bytes"] = memory
                tel.events.emit("compile", **event)
                # cost observatory: one round per dispatch
                self._emit_program_profile(label, exe)
            self._pipeline_exe_cache[key] = exe
        return exe

    def _resolve_pipeline_round(self, pending: dict[str, Any],
                                round_no: int) -> dict[str, Any]:
        """Materialize one pipelined round's metrics — the ONLY host sync
        of the pipelined path, and it happens while the NEXT round's
        program is already in flight on the device.  The numerics row
        (in-graph metrics) rides this same sync: draining it adds zero
        transfers to the pipelined path."""
        host = {k: np.asarray(v) for k, v in pending["metrics"].items()}
        numerics_row = host.pop("numerics_row", None)
        entry: dict[str, Any] = {
            k: (bool(v) if k == "ok" else float(v)) for k, v in host.items()}
        entry["round"] = round_no
        entry["broadcast"] = pending["broadcast"]
        entry["pipelined"] = True
        if numerics_row is not None:
            self._numerics_drainer.push_host_row(
                round_no, pending["broadcast"], numerics_row)
        if pending["val"] is not None:
            # async validation for this round was dispatched alongside the
            # round program; by resolve time it has had a full round of
            # device time — fold it in (no acceptance gate, by contract)
            self._inflight_validations.append(
                (entry, round_no, pending["val"]))
            self._resolve_inflight_validations()
        return entry

    def _run_pipelined(
        self,
        num_rounds: int,
        state: dict[str, Any],
        save_checkpoints: bool,
        verbose: bool,
        stop: Callable[[int], bool] | None = None,
        depth: int | None = None,
    ) -> tuple[dict[str, Any], list[dict[str, Any]]]:
        """Depth-k software-pipelined round loop (``cfg.pipeline`` +
        ``cfg.pipeline_depth`` — ISSUE 10 generalizes the depth-1 loop).

        Every round is dispatched as the SAME single jitted step program
        (train -> attack -> aggregate -> validate -> device-side
        accept-select), and up to ``depth`` rounds stay in flight beyond
        the oldest unresolved one: the host resolves success flags up to
        k rounds late, in dispatch order, while the device keeps
        computing.  Because acceptance IS the in-program ``where``
        select, a rollback at any queue slot needs no host intervention —
        the rounds dispatched after it already trained against the
        rolled-back (last accepted) state, exactly like the synchronous
        retry path — so the queue never has to be flushed and params stay
        bit-identical to sync at every depth (tests/test_pipeline.py).
        ``depth`` 0 = dispatch-then-resolve with no overlap (the demoted
        mode); None resolves it from the config (``"auto"`` reads the
        ledger — :meth:`resolve_pipeline_depth`).

        With checkpointing off the step DONATES the state pytree (do not
        reuse a passed-in ``state`` afterwards — same contract as
        run_fast); with checkpointing on every queue slot pins its
        round's state until resolution, and the resolved round's state is
        handed to the async writer (or written synchronously without
        ``cfg.checkpoint_async``).

        **Graceful degradation** (ISSUE 6): after
        ``cfg.pipeline_demote_after`` consecutive device-side rollbacks —
        e.g. a NaN storm filling ALL k in-flight slots — the executor
        DEMOTES to depth-0: no new dispatches until the queue drains,
        then each round resolves before the next dispatches, so a failure
        storm stops paying for wasted in-flight rounds and the host sees
        every verdict immediately.  After
        ``cfg.pipeline_repromote_after`` consecutive clean rounds it
        re-promotes to the CONFIGURED depth, not 1.  Both transitions
        emit ``degrade`` events (carrying the depth they leave the
        executor at), flip the live monitor's degraded flag (/healthz
        ``status: degraded``) and its ``attackfl_pipeline_depth`` gauge —
        and never retrace: every depth, demoted included, dispatches the
        one cached step program.  Because demotion only changes WHEN the
        host resolves (never what the device computes), final params stay
        bit-identical to the never-demoted and fully-synchronous runs.
        """
        cfg = self.cfg
        tel = self.telemetry
        if depth is None:
            depth = self.resolve_pipeline_depth(save_checkpoints)
        history: list[dict[str, Any]] = []
        t_start = time.perf_counter()
        self._start_monitor()
        state = self._canonical_device_state(state)
        # the loop's only unconditional syncs: the resume point, once
        completed = int(state["completed_rounds"])
        broadcast = int(state["broadcasts"])
        include_eval = self.validation is not None and not cfg.validation_async
        # donation also stays off for restored-state runs (see the
        # donation safety latch in __init__)
        donate = not save_checkpoints and self._state_donation_ok
        step = self._pipeline_step_fn(include_eval, donate)
        # FIFO of unresolved rounds, dispatch order; holds at most
        # overlap()+1 slots (the one about to resolve + the in-flight k)
        queue: deque[dict[str, Any]] = deque()
        consecutive_failures = 0
        degraded = False
        clean_streak = 0
        last_resolve = time.perf_counter()
        if self.monitor is not None:
            self.monitor.set_pipeline_depth(depth)

        def overlap() -> int:
            """Rounds allowed in flight beyond the resolving one: the
            configured depth, or 0 while demoted."""
            return 0 if degraded else depth

        stopping = False
        try:
            while completed < num_rounds or queue:
                # graceful-drain seam: once the hook says stop, dispatch
                # no new rounds; in-flight ones still resolve (and
                # checkpoint) below, then the loop exits quiesced
                stopping = stopping or self._consult_stop(stop, completed)
                if stopping and not queue:
                    break
                want_more = (completed + len(queue) < num_rounds
                             and not stopping)
                if want_more and len(queue) <= overlap():
                    broadcast += 1
                    target_round = completed + len(queue) + 1
                    self._maybe_start_profile(target_round,
                                              program="pipelined")
                    with tel.tracer.span("dispatch", round=target_round,
                                         broadcast=broadcast):
                        if tel.enabled and self.mesh is None:
                            exe = self._pipeline_executable(
                                (include_eval, donate), step, state)
                        else:
                            exe = False
                        new_state, metrics = (
                            exe(state) if exe is not False else step(state))
                    val = None
                    if (self.validation is not None and cfg.validation_async
                            and broadcast % cfg.validation_every == 0):
                        if self.is_hyper:
                            gen_params, _ = self.generate_all(
                                new_state["hnet_params"])
                            val = self.validation.test_hyper_async(gen_params)
                        else:
                            val = self.validation.test_async(
                                new_state["global_params"])
                    queue.append({
                        "metrics": metrics,
                        "broadcast": broadcast,
                        "val": val,
                        # kept ONLY for checkpointing; with donation on,
                        # the next dispatch consumes these buffers
                        "state": new_state if save_checkpoints else None,
                    })
                    state = new_state
                    want_more = (completed + len(queue) < num_rounds
                                 and not stopping)
                # resolve the oldest slot once the queue is past its
                # overlap budget, or while draining (stop hook / tail)
                if queue and (len(queue) > overlap() or not want_more):
                    pending = queue.popleft()
                    round_no = completed + 1
                    with tel.tracer.span("resolve", round=round_no):
                        entry = self._resolve_pipeline_round(pending, round_no)
                    now = time.perf_counter()
                    entry["seconds"] = now - last_resolve
                    last_resolve = now
                    if degraded:
                        entry["degraded"] = True
                    history.append(entry)
                    tel.events.round_event(entry)
                    self._note_round_faults(round_no, pending["broadcast"])
                    if self.monitor is not None:
                        self.monitor.record_round(entry)
                    if entry["ok"]:
                        completed += 1
                        consecutive_failures = 0
                        if save_checkpoints:
                            self._save_checkpoint(pending["state"])
                        if degraded:
                            clean_streak += 1
                            if clean_streak >= cfg.pipeline_repromote_after:
                                degraded = False
                                clean_streak = 0
                                tel.counters.inc("executor_repromotions")
                                tel.events.emit(
                                    "degrade", state="repromoted",
                                    round=round_no, depth=depth,
                                    clean_rounds=cfg.pipeline_repromote_after)
                                if self.monitor is not None:
                                    self.monitor.set_degraded(None)
                                    self.monitor.set_pipeline_depth(depth)
                                print_with_color(
                                    f"[pipeline] re-promoted to "
                                    f"depth-{depth} after "
                                    f"{cfg.pipeline_repromote_after} "
                                    "clean rounds", "cyan")
                        if verbose:
                            keys = [k for k in ("roc_auc", "accuracy", "nll",
                                                "train_loss")
                                    if k in entry and entry[k] == entry[k]]
                            msg = " ".join(f"{k}={entry[k]:.4f}" for k in keys)
                            print_with_color(
                                f"[pipeline] round {round_no} resolved in "
                                f"{entry['seconds']:.2f}s {msg}", "green")
                    else:
                        consecutive_failures += 1
                        clean_streak = 0
                        tel.counters.inc("rounds_failed")
                        tel.counters.inc("rounds_retried")
                        tel.events.emit("retry", round=round_no,
                                        retries=consecutive_failures)
                        print_with_color("Training failed!", "yellow")
                        self.logger.log_warning(
                            f"Round {round_no} failed "
                            f"(retry {consecutive_failures})")
                        if (not degraded and consecutive_failures
                                >= cfg.pipeline_demote_after):
                            degraded = True
                            clean_streak = 0
                            info = {
                                "round": round_no,
                                "consecutive_failures": consecutive_failures,
                                "depth": 0,
                                "configured_depth": depth,
                                "in_flight": len(queue),
                            }
                            tel.counters.inc("executor_demotions")
                            tel.events.emit("degrade", state="demoted", **info)
                            if self.monitor is not None:
                                self.monitor.set_degraded(info)
                                self.monitor.set_pipeline_depth(0)
                            print_with_color(
                                f"[pipeline] {consecutive_failures} "
                                "consecutive rollbacks — demoting from "
                                f"depth-{depth} to synchronous (depth-0) "
                                "resolution", "yellow")
                        if consecutive_failures > MAX_ROUND_RETRIES:
                            raise RuntimeError(
                                f"Round {round_no} failed "
                                f"{consecutive_failures} times; aborting (the "
                                "reference would retry forever, "
                                "server.py:546-556)")
                    self._maybe_stop_profile(completed)
        finally:
            if self.monitor is not None and degraded:
                self.monitor.set_degraded(None)
            # drains the async writer + closes the telemetry record on
            # exception paths too (satellite: the last durable checkpoint
            # must survive a crashing round)
            self._finish_run(history, t_start, state)
        return state, history

    # ------------------------------------------------------------------
    # full run
    # ------------------------------------------------------------------

    def run(
        self,
        num_rounds: int | None = None,
        state: dict[str, Any] | None = None,
        save_checkpoints: bool = True,
        verbose: bool = True,
        pipeline: bool | None = None,
        stop: Callable[[int], bool] | None = None,
    ) -> tuple[dict[str, Any], list[dict[str, Any]]]:
        """Run until ``num_rounds`` rounds complete (reference main loop,
        server.py:559-567).

        ``pipeline`` (default: ``cfg.pipeline``) routes through the
        depth-k software-pipelined executor (:meth:`_run_pipelined`,
        k = ``cfg.pipeline_depth``, ``"auto"`` tuned from the ledger) —
        same final params and per-round ``ok`` sequence as the synchronous
        path, with up to k rounds dispatched before round N's flag is
        materialized.  Host-side-defense modes (gmm / fltracer,
        hyper-detection, reload-per-round) fall back to the synchronous
        loop with a warning.

        ``stop``, if given, is consulted between rounds with the current
        completed-round count: returning True ends the run at the next
        round boundary — the in-flight round finishes, its checkpoint is
        saved, and ``_finish_run`` drains as usual.  This is the run
        service's graceful-drain seam (SIGTERM → finish the round →
        checkpoint → requeue) and its ``worker_death`` injection point
        (the hook may raise; the exception takes the normal crash path
        through the ``finally`` drains)."""
        cfg = self.cfg
        num_rounds = num_rounds if num_rounds is not None else cfg.num_round
        state = self._place_on_mesh(self._ensure_numerics_state(
            state if state is not None else self.load_or_init_state()))
        use_pipeline = cfg.pipeline if pipeline is None else pipeline
        depth = None
        if use_pipeline and self.supports_fused():
            # resolved BEFORE the run header goes out, so the header (and
            # the ledger record derived from it) carries the concrete k
            depth = self.resolve_pipeline_depth(save_checkpoints)
        self._emit_run_header()
        if use_pipeline:
            if self.supports_fused():
                return self._run_pipelined(num_rounds, state,
                                           save_checkpoints, verbose,
                                           stop=stop, depth=depth)
            print_with_color(
                f"[pipeline] mode '{cfg.mode}' needs host-side per-round "
                "work; falling back to the synchronous path.", "yellow")
        # cost observatory: the sync loop dispatches lazily-jitted
        # programs, so their profiles need one explicit AOT pass (the
        # fused/pipelined executors profile at their existing AOT seams)
        self._capture_sync_profiles(state)
        history: list[dict[str, Any]] = []
        retries = 0
        t_start = time.perf_counter()
        self.logger.log_info("### Application start ###")

        self._start_monitor()
        try:
            while int(state["completed_rounds"]) < num_rounds:
                if self._consult_stop(stop, state["completed_rounds"]):
                    break
                round_no = int(state["completed_rounds"]) + 1
                if verbose:
                    print_with_color(f"Start training round {round_no}", "yellow")
                self._maybe_start_profile(round_no, program="sync")
                state, metrics = self.run_round(state)
                history.append(metrics)
                self._note_round_faults(round_no, metrics["broadcast"])
                if self.monitor is not None:
                    self.monitor.record_round(metrics)
                self._maybe_stop_profile(int(state["completed_rounds"]))
                if metrics["ok"]:
                    retries = 0
                    if save_checkpoints:
                        self._save_checkpoint(state)
                    if verbose:
                        keys = [k for k in ("roc_auc", "accuracy", "nll", "train_loss") if k in metrics]
                        msg = " ".join(f"{k}={metrics[k]:.4f}" for k in keys)
                        phases = metrics.get("phases") or {}
                        if phases:
                            msg += " [" + ", ".join(
                                f"{k}={v * 1e3:.0f}ms" for k, v in phases.items()) + "]"
                        print_with_color(
                            f"Round {round_no} done in {metrics['seconds']:.2f}s {msg}", "green")
                else:
                    retries += 1
                    self.telemetry.counters.inc("rounds_failed")
                    self.telemetry.counters.inc("rounds_retried")
                    self.telemetry.events.emit("retry", round=round_no,
                                               retries=retries)
                    print_with_color("Training failed!", "yellow")
                    self.logger.log_warning(f"Round {round_no} failed (retry {retries})")
                    if retries > MAX_ROUND_RETRIES:
                        raise RuntimeError(
                            f"Round {round_no} failed {retries} times; aborting "
                            "(the reference would retry forever, server.py:546-556)"
                        )
        finally:
            # every exit path — including a crashing round — drains the
            # async checkpoint writer and closes the telemetry record
            self._finish_run(history, t_start, state)
        return state, history
