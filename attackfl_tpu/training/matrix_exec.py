"""MatrixRun: the scenario-matrix executor (ISSUE 9 tentpole).

Runs a full (attack × defense × seed) grid as ONE compiled device
program per chunk: the device cells (vmap-stable defenses + FLTrust —
see :mod:`attackfl_tpu.matrix.grid` for the classification) share one
jitted ``lax.scan`` over the batched matrix body, while host-side
defenses (gmm / fltracer) and the structure-incompatible hyper mode
fall back to per-cell child Simulators — gmm/fltracer per-cell
SYNCHRONOUS with a warning (exactly like the pipelined executor's
fallback today), hyper per-cell on its own compiled fused program.

Executor contract, mirrored from ``run_fast``:

* **bit-identity** — every cell's final params equal a standalone
  ``Simulator.run`` / ``run_fast`` of its
  :func:`~attackfl_tpu.matrix.grid.cell_config`, byte for byte
  (tests/test_matrix.py).  A cell that reaches its round target is
  FROZEN in-program (``jnp.where`` select over the whole cell state) so
  straggler cells retrying failed rounds never advance finished ones.
* **crash safety** — the batched grid state is checkpointed per chunk
  through the same :class:`~attackfl_tpu.utils.checkpoint.
  CheckpointManager` the engine uses (round-stamped entries, manifest,
  torn-entry fallback); fallback cells checkpoint through their own
  child Simulators.  ``resume=True`` restores the newest valid entry
  and re-runs fallback cells with ``resume`` (completed cells reload
  their final state and run zero rounds), so a killed sweep resumes
  byte-identical.  Restored sweeps keep state donation OFF (the jax
  0.4.37 latch, same as the engine).
* **observability** — schema-v7 ``matrix`` events (started / chunk /
  fallback / cell_done / cell_aborted / resumed / interrupted /
  completed), per-cell numerics rows riding the chunk's existing
  materialization (zero new syncs), and k×45 per-cell ledger records
  sharing a ``sweep_id`` (:mod:`attackfl_tpu.matrix.records`).
* **quarantine, not collapse** — a cell that exceeds the per-cell retry
  budget (a NaN-poisoned trajectory that can never recover — the
  standalone run would ABORT there) is quarantined: it stops counting
  toward sweep progress and its abort is recorded, while the other
  cells' science completes.

Host-sync policy: the ONLY device->host materialization is
``MatrixRun._resolve_chunk`` (allowlisted, like ``Simulator.run_fast``);
everything under :mod:`attackfl_tpu.matrix` stays traced-only with NO
allowlist.
"""

from __future__ import annotations

import dataclasses
import os
import time
import uuid
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from attackfl_tpu.config import Config, parse_profile_rounds
from attackfl_tpu.costmodel.capture import compiled_profile
from attackfl_tpu.data.synthetic import get_dataset
from attackfl_tpu.eval.validation import Validation
from attackfl_tpu.matrix.grid import (
    Cell, GridSpec, cell_config, defense_group, expand_cells,
)
from attackfl_tpu.matrix.program import build_cell_body, build_matrix_body
from attackfl_tpu.matrix.records import cell_event_summaries, sweep_records
from attackfl_tpu.ops import metrics as num_metrics
from attackfl_tpu.profiler.capture import HotspotCapture
from attackfl_tpu.ops import pytree as pt
from attackfl_tpu.registry import get_model
from attackfl_tpu.telemetry import Telemetry, print_with_color
from attackfl_tpu.telemetry.numerics import NumericsDrainer
from attackfl_tpu.training.round import (
    build_attack_groups, build_cohort_masks, build_defense_branches,
    build_round_step,
)
from attackfl_tpu.utils import checkpoint as ckpt
from attackfl_tpu.utils.fingerprint import config_fingerprint

MAX_CELL_RETRIES = 20  # per-cell consecutive-failure abort, like run_fast

MATRIX_STATE_FILE = "matrix.msgpack"


class _CellTelemetry:
    """Per-cell facade over the sweep telemetry: every emitted event is
    stamped with the cell key (the numerics drainers emit through this,
    so their ``metric`` events are per-cell attributable)."""

    def __init__(self, telemetry, cell_key: str):
        self._tel = telemetry
        self.counters = telemetry.counters
        self.events = self
        self._cell = cell_key

    def emit(self, kind: str, **fields: Any):
        return self._tel.events.emit(kind, cell=self._cell, **fields)


class MatrixRun:
    """One sweep: a base workload Config + a GridSpec."""

    def __init__(self, cfg: Config, grid: GridSpec,
                 sweep_id: str | None = None,
                 telemetry: Telemetry | None = None,
                 use_mesh: bool = False,
                 mesh=None):
        grid.validate_base(cfg)
        self.cfg = cfg
        self.grid = grid
        # ---- CELL-axis mesh (ISSUE 12) ---------------------------------
        # Cells are embarrassingly parallel: the grid state's leading
        # axis shards across the device mesh (placement at init/resume +
        # an in-program constraint per chunk), so a 45-cell sweep scales
        # near-linearly with devices.  No divisibility requirement — the
        # partitioner pads uneven cell counts.  Per-cell results stay
        # bit-identical: partitioning splits the vmapped cell batch, it
        # never re-associates any within-cell reduction (the sweep's
        # threefry requirement already guarantees bit-stable keys).
        self.mesh = mesh
        if use_mesh and mesh is None:
            from attackfl_tpu.parallel.mesh import make_client_mesh

            self.mesh = make_client_mesh(cfg.mesh.num_devices,
                                         cfg.mesh.axis_name)
        self._cell_constrain = None
        if self.mesh is not None:
            from attackfl_tpu.parallel.mesh import make_constrain

            self._cell_constrain = make_constrain(
                self.mesh, cfg.mesh.axis_name)
        self.sweep_id = sweep_id or uuid.uuid4().hex[:12]
        self.cells = expand_cells(grid)
        self.device_cells = [c for c in self.cells
                             if c.group in ("batched", "mapped")]
        self.fallback_cells = [c for c in self.cells
                               if c.group in ("host", "special")]
        self.telemetry = (telemetry if telemetry is not None
                          else Telemetry.from_config(cfg))
        self.model = get_model(cfg.model)

        data_seed = (cfg.data_seed if cfg.data_seed is not None
                     else cfg.random_seed)
        train_np = get_dataset(cfg.data_name, "train", cfg.train_size,
                               data_seed)
        self.test_np = get_dataset(cfg.data_name, "test", cfg.test_size,
                                   data_seed)
        self.train_data = {k: jnp.asarray(v) for k, v in train_np.items()}

        # ---- shared programs -------------------------------------------
        # branch order = the grid's batched defenses in grid order; the
        # per-cell defense_idx arrays index into this ONE list
        self.branch_modes = tuple(d for d in grid.defenses
                                  if defense_group(d) == "batched")
        branches = build_defense_branches(
            self.model, cfg, self.test_np, self.branch_modes)

        eval_fn = None
        self.validation = None
        if cfg.validation:
            self.validation = Validation(
                self.model, cfg.data_name, self.test_np, telemetry=None)
            eval_fn = self.validation.eval_fn

        # cohort geometry is shared (GridSpec pins the attacker count)
        probe_cfg = cfg.replace(attacks=(grid.attacks[0],))
        probe_groups, self.genuine_idx = build_attack_groups(probe_cfg)
        self.genuine_mask, self.attacker_mask = build_cohort_masks(
            cfg.total_clients, probe_groups)
        self.num_genuine = len(self.genuine_idx)

        # ---- in-graph numerics (per-cell rings) ------------------------
        self._numerics = None
        self._numerics_step_raw = None
        self._numerics_on = bool(self.telemetry.enabled
                                 and cfg.telemetry.numerics)
        if self._numerics_on:
            template = jax.eval_shape(
                lambda key: self.model.init(
                    key, *_sample_inputs(cfg.data_name))["params"],
                jax.random.key(cfg.random_seed, impl=cfg.prng_impl))
            layout = num_metrics.build_layout(template, True)
            self._numerics = num_metrics.Numerics(
                layout, self.genuine_mask, self.attacker_mask,
                window=cfg.telemetry.numerics_window)
            numerics = self._numerics

            def numerics_step(num_state, old_ref, new_ref, stacked, sizes,
                              loss, ok, broadcast):
                return numerics.step(num_state, old_ref, old_ref, new_ref,
                                     stacked, sizes, loss, ok, broadcast)

            self._numerics_step_raw = numerics_step

        # ---- compile groups (attack-major, deterministic order) --------
        # group name -> {"body", "kind", "defense_idx", "cells"}
        self.groups: dict[str, dict[str, Any]] = {}
        for attack in grid.attacks:
            acfg = cfg.replace(attacks=(attack,))
            agroups, _ = build_attack_groups(acfg)
            round_step = build_round_step(
                self.model, acfg, self.train_data, agroups,
                self.genuine_idx, None, None, mesh=None)
            batched = [c for c in self.device_cells
                       if c.attack == attack and c.group == "batched"]
            mapped = [c for c in self.device_cells
                      if c.attack == attack and c.group == "mapped"]
            if batched:
                self.groups[f"{attack.mode}:batched"] = {
                    "kind": "batched",
                    "cells": batched,
                    "defense_idx": jnp.asarray(
                        [self.branch_modes.index(c.defense)
                         for c in batched], jnp.int32),
                    "body": self._frozen(build_cell_body(
                        round_step, branches, cfg.total_clients, eval_fn,
                        cfg.validation_every, self._numerics_step_raw)),
                }
            if mapped:
                # FLTrust: single static branch, sequential lax.map slices
                fl_branch = build_defense_branches(
                    self.model, cfg, self.test_np, (mapped[0].defense,))
                self.groups[f"{attack.mode}:mapped"] = {
                    "kind": "mapped",
                    "cells": mapped,
                    "defense_idx": None,
                    "body": self._frozen(build_cell_body(
                        round_step, fl_branch, cfg.total_clients, eval_fn,
                        cfg.validation_every, self._numerics_step_raw)),
                }
        # ---- cell-axis padding for the mesh ----------------------------
        # jax 0.4.37 requires the sharded axis to divide the mesh, so
        # each BATCHED group's cell axis is padded with clones of its
        # first cell up to the next multiple of the device count: the
        # pad rows ride the same vmapped program (bounded waste, ~(n_dev
        # - 1) cells worst case) and are invisible to resolve/progress/
        # final-params, which all iterate the REAL cell list.  Mapped
        # (lax.map) groups stay replicated — their slices run
        # sequentially, so sharding them buys nothing.
        for name, group in self.groups.items():
            pad = 0
            if (self.mesh is not None and group["kind"] == "batched"):
                pad = (-len(group["cells"])) % self.mesh.size
                if pad and group["defense_idx"] is not None:
                    group["defense_idx"] = jnp.concatenate(
                        [group["defense_idx"],
                         jnp.repeat(group["defense_idx"][:1], pad)])
            group["pad"] = pad
        self._matrix_body = build_matrix_body(self.groups)
        # jitted chunk programs keyed by (scan length, donate) — the
        # attribute NAME matches the engine's so the retrace guard
        # (analysis/retrace.jitted_programs) picks the cache up as-is
        self._fused_cache: dict[tuple, Callable] = {}
        # AOT-compiled chunk executables (engine._fused_executable's
        # pattern: compile under a telemetry span, profile the executable
        # we dispatch — the cost observatory's matrix seam, ISSUE 11;
        # False = AOT failed, fall back to the lazy jit path)
        self._matrix_exe_cache: dict[tuple, Any] = {}
        # ATTACKFL_COSTMODEL=0 = the harness kill switch (see engine)
        self._costmodel_on = bool(
            self.telemetry.enabled and cfg.telemetry.costmodel
            and os.environ.get("ATTACKFL_COSTMODEL", "1") != "0")
        self._program_profiles: dict[str, dict[str, Any]] = {}

        # ---- persistence ------------------------------------------------
        # restored sweeps keep donation OFF (jax 0.4.37 latch — see the
        # engine's _state_donation_ok note)
        self._state_donation_ok = True
        self._resumed = False
        # set by run(): True when a stop hook cut the sweep short (the
        # service's requeue signal — byte-identical resume picks it up)
        self.interrupted = False
        # which seam cut it short ("drain"/"preempt"/"cancel"), when the
        # stop hook returned a reason string (ISSUE 15)
        self.stop_reason: str | None = None
        # extra run_header fields a wrapping service wants recorded —
        # the scheduler stamps sweeps with sched_priority/preemptions/
        # wait (schema v11), mirroring the engine's header_extra seam
        self.header_extra: dict[str, Any] = {}
        # quarantined cells: exceeded the per-cell retry budget (e.g. a
        # NaN-poisoned trajectory that can never recover) — they stop
        # counting toward sweep progress and their records say so, but
        # one toxic cell never kills the other 44 cells' science
        self._aborted: set[str] = set()
        os.makedirs(cfg.checkpoint_dir or ".", exist_ok=True)
        self._ckpt_manager = ckpt.CheckpointManager(
            os.path.join(cfg.checkpoint_dir or ".", MATRIX_STATE_FILE),
            fingerprint=self.sweep_fingerprint(),
            run_id=self.telemetry.events.run_id,
            keep=cfg.checkpoint_keep,
            telemetry=self.telemetry,
            fresh=not cfg.resume,
        )

        # ---- cross-run ledger (per-cell records) ------------------------
        self._ledger = None
        if self.telemetry.enabled and cfg.telemetry.ledger:
            from attackfl_tpu.ledger.store import (
                LedgerStore, resolve_ledger_dir,
            )

            self._ledger = LedgerStore(resolve_ledger_dir(
                cfg.telemetry.ledger_dir or None,
                base=self.telemetry.base_dir))

        # per-cell numerics drainers, lazily built at first resolve
        self._drainers: dict[str, NumericsDrainer] = {}

        # hotspot observatory (ISSUE 19): the matrix seam gets its own
        # profiling window — the sweep's chunk dispatch is exactly the
        # program the warm-batched 0.61x question is about
        self._hotspots = HotspotCapture(
            self.telemetry,
            parse_profile_rounds(cfg.telemetry.hotspots
                                 or cfg.telemetry.profile_rounds))

    # ------------------------------------------------------------------
    # identity
    # ------------------------------------------------------------------

    def sweep_fingerprint(self) -> str:
        """Checkpoint/resume identity: the base config fingerprint plus
        the grid geometry (a resumed sweep must be the SAME sweep)."""
        import hashlib

        blob = (config_fingerprint(self.cfg) + "|"
                + repr(self.grid.describe()))
        return "matrix-" + hashlib.sha256(blob.encode()).hexdigest()[:12]

    # ------------------------------------------------------------------
    # state
    # ------------------------------------------------------------------

    def _cell_host_state(self, seed: int) -> dict[str, Any]:
        """One cell's fresh state — the engine's ``_init_host_state``
        (plain branch), field for field, so cell init == standalone
        init."""
        rng = jax.random.key(seed, impl=self.cfg.prng_impl)
        k_model, k_state = jax.random.split(rng)
        params = self.model.init(
            k_model, *_sample_inputs(self.cfg.data_name))["params"]
        prev_genuine = pt.tree_broadcast(
            jax.tree.map(jnp.zeros_like, params), self.num_genuine)
        state = {
            "global_params": params,
            "prev_genuine": prev_genuine,
            "have_genuine": jnp.asarray(False),
            "rng": k_state,
            "completed_rounds": jnp.asarray(0, jnp.int32),
            "broadcasts": jnp.asarray(0, jnp.int32),
        }
        if self._numerics is not None:
            state["numerics"] = self._numerics.init_state()
        return state

    def init_state(self) -> dict[str, Any]:
        """The grid state: per compile group, every cell's state stacked
        on the leading axis (cell init happens UNBATCHED, so slice 0 of
        the stack is byte-equal to the standalone init).  Under a mesh,
        batched groups carry ``pad`` clone rows of their first cell so
        the cell axis divides the device count (see ``__init__``)."""
        out: dict[str, Any] = {}
        for name, group in self.groups.items():
            per_cell = [self._cell_host_state(c.seed)
                        for c in group["cells"]]
            per_cell += [per_cell[0]] * group.get("pad", 0)
            out[name] = jax.tree.map(
                lambda *leaves: jnp.stack(leaves), *per_cell)
        return out

    def _strip_numerics(self, state: dict[str, Any]) -> dict[str, Any]:
        return {name: {k: v for k, v in sub.items() if k != "numerics"}
                for name, sub in state.items()}

    def _ensure_numerics(self, state: dict[str, Any]) -> dict[str, Any]:
        if self._numerics is None:
            return state
        out = {}
        for name, sub in state.items():
            if "numerics" not in sub:
                # padded cell count under a mesh — match the state's own
                # leading axis, not the real-cell list
                n = int(sub["completed_rounds"].shape[0])
                ring = self._numerics.init_state()
                sub = dict(sub, numerics=jax.tree.map(
                    lambda leaf: jnp.stack([leaf] * n), ring))
            out[name] = sub
        return out

    def load_or_init_state(self) -> dict[str, Any]:
        """Fresh grid state, or — under ``cfg.resume`` — the newest
        hash-valid checkpoint entry (torn entries fall back, exactly the
        engine's resume semantics), with donation latched off."""
        if not self.cfg.resume:
            return self.init_state()
        template = self._strip_numerics(self.init_state())
        result = self._ckpt_manager.load_latest(template)
        if result.state is None:
            print_with_color(
                "[matrix] no valid sweep checkpoint; starting fresh",
                "yellow")
            return self.init_state()
        for entry, reason in result.rejected:
            self.telemetry.counters.inc("checkpoint_fallbacks")
            print_with_color(
                f"[matrix] rejected checkpoint {entry.get('file')}: "
                f"{reason[:120]}", "yellow")
        self._state_donation_ok = False
        self._resumed = True
        self.telemetry.events.emit(
            "matrix", sweep_id=self.sweep_id, action="resumed",
            round=int(result.entry.get("round", 0))
            if result.entry else 0)
        return self._ensure_numerics(result.state)

    # ------------------------------------------------------------------
    # programs
    # ------------------------------------------------------------------

    def _frozen(self, body: Callable) -> Callable:
        """Freeze a cell once it reaches the sweep's round target: the
        whole cell state rides a ``where`` select, so straggler cells
        (retrying failed rounds) never advance finished ones past their
        standalone-final state."""
        target = jnp.asarray(self.grid.rounds, jnp.int32)

        def frozen(state, defense_idx):
            done = state["completed_rounds"] >= target
            new_state, metrics = body(state, defense_idx)
            kept = jax.tree.map(
                lambda new, old: jnp.where(done, old, new),
                new_state, state)
            metrics["active"] = ~done
            return kept, metrics

        return frozen

    def _matrix_chunk(self, length: int, donate: bool) -> Callable:
        key = (length, donate)
        fn = self._fused_cache.get(key)
        if fn is None:
            self.telemetry.counters.inc("round_program_cache_misses")
            body = self._matrix_body
            constrain = self._cell_constrain

            batched = {name for name, g in self.groups.items()
                       if g["kind"] == "batched"}

            def chunk(state):
                if constrain is not None:
                    # pin the batched groups' (padded) cell axis to the
                    # mesh at scan entry so the carry stays sharded
                    # across the chunk (the constrain is key-data-aware
                    # — see parallel/mesh.make_constrain); mapped groups
                    # run sequentially and stay replicated
                    state = {name: (constrain(sub) if name in batched
                                    else sub)
                             for name, sub in state.items()}
                return jax.lax.scan(body, state, None, length=length)

            fn = jax.jit(chunk, donate_argnums=(0,) if donate else ())
            self._fused_cache[key] = fn
        else:
            self.telemetry.counters.inc("round_program_cache_hits")
        return fn

    def _matrix_executable(self, key: tuple, fn: Callable, state) -> Any:
        """AOT-compile the grid chunk under a telemetry compile span
        (same contract as the engine's ``_fused_executable``: best-effort,
        False = permanent fallback to the lazy jit path) and snapshot its
        cost profile — the executable IS what run() dispatches, so the
        profile costs no extra compile."""
        exe = self._matrix_exe_cache.get(key)
        if exe is None:
            length = key[0]
            tel = self.telemetry
            label = f"matrix_chunk[{length}]"
            t0 = time.perf_counter()
            try:
                with tel.tracer.span("compile", program=label):
                    exe = fn.lower(state).compile()
            except Exception as e:  # noqa: BLE001 — AOT is best-effort
                exe = False
                tel.events.emit("compile", program=label,
                                seconds=round(time.perf_counter() - t0, 6),
                                error=f"{type(e).__name__}: {e}"[:300])
            else:
                tel.events.emit(
                    "compile", program=label,
                    seconds=round(time.perf_counter() - t0, 6),
                    scan_length=length)
                self._emit_program_profile(label, exe,
                                           rounds_per_dispatch=length)
            self._matrix_exe_cache[key] = exe
        return exe

    def _emit_program_profile(self, name: str, compiled: Any,
                              rounds_per_dispatch: int = 1) -> None:
        """Schema-v9 ``program_profile`` for the grid program, keyed by
        the SWEEP fingerprint (the grid program's identity) and carrying
        the device-cell count — one dispatch covers every cell."""
        if not self._costmodel_on:
            return
        profile = compiled_profile(compiled)
        if profile is None:
            return
        profile["rounds_per_dispatch"] = int(rounds_per_dispatch)
        profile["cells"] = len(self.device_cells)
        profile["device_kind"] = str(jax.devices()[0].device_kind)
        self._program_profiles[name] = profile
        self.telemetry.events.emit(
            "program_profile", program=name,
            fingerprint=self.sweep_fingerprint(), **profile)

    # ------------------------------------------------------------------
    # audit hooks (attackfl_tpu/analysis)
    # ------------------------------------------------------------------

    def audit_programs(self, state: dict[str, Any] | None = None
                       ) -> list[dict[str, Any]]:
        """The batched grid program for the jaxpr/HLO auditor — same
        contract as ``Simulator.audit_programs``.  Under a mesh the
        audited step includes the cell-axis constraint exactly as
        ``_matrix_chunk`` dispatches it."""
        state = self._ensure_numerics(
            state if state is not None else self.init_state())
        constrain = self._cell_constrain
        batched = {name for name, g in self.groups.items()
                   if g["kind"] == "batched"}

        def step(s):
            if constrain is not None:
                s = {name: (constrain(sub) if name in batched else sub)
                     for name, sub in s.items()}
            return self._matrix_body(s, None)

        return [dict(
            name=f"matrix_step[{len(self.device_cells)} cells]",
            executor="matrix", raw=step,
            jit=jax.jit(step, donate_argnums=(0,)), args=(state,),
            donate=(0,))]

    # ------------------------------------------------------------------
    # run
    # ------------------------------------------------------------------

    def _emit_header(self) -> None:
        tel = self.telemetry
        if not tel.enabled:
            return
        tel.events.emit(
            "run_header",
            backend=jax.default_backend(),
            num_devices=len(jax.devices()),
            mesh_devices=self.mesh.size if self.mesh is not None else 0,
            mode="matrix",
            model=self.cfg.model,
            data_name=self.cfg.data_name,
            total_clients=self.cfg.total_clients,
            jax_version=jax.__version__,
            platform=jax.devices()[0].platform,
            sweep_id=self.sweep_id,
            grid=self.grid.describe(),
            config=dataclasses.asdict(self.cfg),
            # schema v11: scheduler metadata when the service runs us
            **self.header_extra,
        )

    def _resolve_chunk(self, metrics: Any, length: int,
                       histories: dict[str, list[dict[str, Any]]],
                       consecutive: dict[str, int]) -> None:
        """THE sweep's audited device->host materialization: one batched
        copy of the chunk's metrics covers every cell × round in the
        dispatch (per-cell numerics rows ride it — zero extra syncs).
        Frozen-cell rounds (``active`` False) and quarantined cells are
        skipped: the former already hold their standalone-final state,
        the latter stopped being science."""
        host = {name: {k: np.asarray(v) for k, v in group.items()}
                for name, group in metrics.items()}
        for name, group in self.groups.items():
            data = host[name]
            numerics_rows = data.pop("numerics_row", None)
            for j, cell in enumerate(group["cells"]):
                if cell.key in self._aborted:
                    continue
                history = histories.setdefault(cell.key, [])
                for i in range(length):
                    if not bool(data["active"][i, j]):
                        continue
                    entry = {
                        k: (bool(v[i, j]) if k in ("ok", "active")
                            else float(v[i, j]))
                        for k, v in data.items()}
                    entry.pop("active", None)
                    entry["round"] = len(history) + 1
                    entry["cell"] = cell.key
                    history.append(entry)
                    if entry["ok"]:
                        consecutive[cell.key] = 0
                    else:
                        consecutive[cell.key] = \
                            consecutive.get(cell.key, 0) + 1
                        self.telemetry.counters.inc("rounds_failed")
                    if numerics_rows is not None:
                        self._drainer_for(cell).push_host_row(
                            entry["round"], entry["round"],
                            numerics_rows[i, j])

    def _drainer_for(self, cell: Cell) -> NumericsDrainer:
        drainer = self._drainers.get(cell.key)
        if drainer is None:
            drainer = NumericsDrainer(
                self._numerics.layout,
                _CellTelemetry(self.telemetry, cell.key),
                self.cfg.telemetry.numerics_window)
            self._drainers[cell.key] = drainer
        return drainer

    def _min_completed(self, state: dict[str, Any]) -> int:
        """The sweep's progress gate: the minimum completed-round count
        over the LIVE device cells (quarantined cells are excluded — a
        cell that can never succeed must not wedge the other 44)."""
        values = [int(v) for name in self.groups
                  for cell, v in zip(
                      self.groups[name]["cells"],
                      np.asarray(state[name]["completed_rounds"]))
                  if cell.key not in self._aborted]
        return min(values) if values else self.grid.rounds

    def _save_checkpoint(self, state: dict[str, Any],
                         completed: int) -> None:
        target = self._strip_numerics(state)
        if self.mesh is not None:
            # gather-at-checkpoint (ISSUE 12): the cell-sharded grid
            # state funnels through the same seam the engine uses for
            # DCN meshes — single-process sharded arrays materialize via
            # host_state's np conversion; a multi-process mesh needs the
            # explicit all-gather so every host serializes the SAME bytes
            from attackfl_tpu.parallel.mesh import (
                gather_to_host, is_multiprocess,
            )

            if is_multiprocess(self.mesh):
                target = gather_to_host(target)
        self._ckpt_manager.write(
            os.path.join(self.cfg.checkpoint_dir or ".", MATRIX_STATE_FILE),
            ckpt.host_state(target),
            {"round": completed, "broadcast": completed})

    def run(self, stop: Callable[[int], bool] | None = None,
            save_checkpoints: bool = True, verbose: bool = True
            ) -> tuple[dict[str, Any], dict[str, list[dict[str, Any]]]]:
        """Run the sweep to completion (or a graceful ``stop``).

        Returns ``(final_params, histories)``: per cell key, the final
        global params tree and the per-round history.  ``stop`` is
        consulted between chunks and between fallback cells — the
        service's drain seam."""
        cfg = self.cfg
        tel = self.telemetry
        t_start = time.perf_counter()
        self._emit_header()
        tel.events.emit("matrix", sweep_id=self.sweep_id, action="started",
                        grid=self.grid.describe(),
                        device_cells=len(self.device_cells),
                        fallback_cells=len(self.fallback_cells),
                        resumed=self._resumed)
        state = self.load_or_init_state()
        if self.mesh is not None:
            # place the batched groups' cell axis over the mesh up front
            # — the resume path hands back host arrays, and letting the
            # first dispatch re-shard would hide a full-state transfer
            # in the first chunk's timing
            from attackfl_tpu.parallel.mesh import shard_stacked

            state = {name: (shard_stacked(sub, self.mesh,
                                          self.cfg.mesh.axis_name)
                            if self.groups[name]["kind"] == "batched"
                            else sub)
                     for name, sub in state.items()}
        histories: dict[str, list[dict[str, Any]]] = {}
        consecutive: dict[str, int] = {}
        interrupted = False
        first_dispatch = True
        completed = self._min_completed(state) if self.groups else 0

        try:
            while self.groups and completed < self.grid.rounds:
                if self._consult_stop(stop, completed):
                    interrupted = True
                    break
                remaining = self.grid.rounds - completed
                cap = self.grid.chunk
                if first_dispatch or remaining >= cap:
                    n = min(cap, remaining)
                else:
                    n = 1  # retry tails reuse one length-1 program
                first_dispatch = False
                donate = self._state_donation_ok
                includes_compile = (
                    (n, donate) not in self._fused_cache
                    and (n, donate) not in self._matrix_exe_cache)
                t0 = time.perf_counter()
                # hotspot window around the chunk dispatch (the chunk is
                # one device program; profiling starts at its boundary)
                self._hotspots.maybe_start(completed + 1, completed + n,
                                           program="matrix")
                with tel.tracer.span("chunk", chunk_len=n, matrix=True):
                    fn = self._matrix_chunk(n, donate)
                    # AOT seam (cost observatory): dispatch the profiled
                    # executable when telemetry is on, exactly like
                    # run_fast — the lazy jit path stays the fallback.
                    # Skipped under a mesh (AOT pins input shardings;
                    # the lazy path re-shards freely — engine.run_scan's
                    # rule).
                    exe = (self._matrix_executable((n, donate), fn, state)
                           if tel.enabled and self.mesh is None else False)
                    state, metrics = (exe(state) if exe is not False
                                      else fn(state))
                    # the np.asarray inside _resolve_chunk IS the block:
                    # dispatch is async, so timing must enclose the
                    # materialization (run_fast's lesson)
                    self._resolve_chunk(metrics, n, histories, consecutive)
                elapsed = time.perf_counter() - t0
                completed = self._min_completed(state)
                self._hotspots.maybe_stop(completed)
                tel.events.emit(
                    "matrix", sweep_id=self.sweep_id, action="chunk",
                    chunk_len=n, seconds=round(elapsed, 6),
                    includes_compile=includes_compile,
                    min_completed=completed)
                for key, failures in list(consecutive.items()):
                    if failures > MAX_CELL_RETRIES and \
                            key not in self._aborted:
                        # quarantine, don't kill: the standalone run
                        # would abort HERE (run_fast's retry cap) — the
                        # sweep records that verdict per cell and keeps
                        # the other cells' science alive
                        self._aborted.add(key)
                        tel.counters.inc("matrix_cells_aborted")
                        tel.events.emit(
                            "matrix", sweep_id=self.sweep_id,
                            action="cell_aborted", cell=key,
                            consecutive_failures=failures)
                        print_with_color(
                            f"[matrix] cell {key} failed {failures} "
                            "rounds in a row — quarantined (the "
                            "standalone run would abort here); the "
                            "sweep continues", "red")
                completed = self._min_completed(state)
                if save_checkpoints:
                    self._save_checkpoint(state, completed)
                if verbose:
                    print_with_color(
                        f"[matrix] {completed}/{self.grid.rounds} rounds "
                        f"x {len(self.device_cells)} device cells, chunk "
                        f"of {n} in {elapsed:.2f}s", "green")

            final_params = self._slice_final_params(state)

            if not interrupted:
                interrupted = self._run_fallback_cells(
                    final_params, histories, stop)
        finally:
            self.interrupted = interrupted
            self._finish(histories, t_start, interrupted)
        return final_params, histories

    def _slice_final_params(self, state: dict[str, Any]
                            ) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for name, group in self.groups.items():
            stacked = state[name]["global_params"]
            for j, cell in enumerate(group["cells"]):
                out[cell.key] = jax.tree.map(lambda leaf: leaf[j], stacked)
        return out

    # ------------------------------------------------------------------
    # fallback cells (host defenses / hyper)
    # ------------------------------------------------------------------

    def _cell_dir(self, cell: Cell) -> str:
        return os.path.join(self.cfg.checkpoint_dir or ".", "cells",
                            cell.key)

    def _fallback_config(self, cell: Cell) -> Config:
        cell_dir = self._cell_dir(cell)
        telemetry = dataclasses.replace(
            self.cfg.telemetry,
            events_path=os.path.join(cell_dir, "events.jsonl"),
            trace_path=os.path.join(cell_dir, "trace.json"),
            monitor=False,
            # one ledger record per cell comes from the SWEEP's
            # distillation — the child must not double-append
            ledger=False,
        )
        return cell_config(self.cfg, cell, rounds=self.grid.rounds,
                           log_path=cell_dir, checkpoint_dir=cell_dir,
                           telemetry=telemetry,
                           resume=self._resumed)

    def _run_fallback_cells(self, final_params: dict[str, Any],
                            histories: dict[str, list[dict[str, Any]]],
                            stop: Callable[[int], bool] | None) -> bool:
        """Per-cell fallback runs.  Returns True when stopped early."""
        from attackfl_tpu.training.engine import Simulator

        for cell in self.fallback_cells:
            if self._consult_stop(stop, self.grid.rounds):
                return True
            os.makedirs(self._cell_dir(cell), exist_ok=True)
            if cell.group == "host":
                print_with_color(
                    f"[matrix] defense '{cell.defense}' filters on host — "
                    f"cell {cell.key} falls back to a per-cell "
                    "synchronous run", "yellow")
            self.telemetry.events.emit(
                "matrix", sweep_id=self.sweep_id, action="fallback",
                cell=cell.key, group=cell.group)
            sim = Simulator(self._fallback_config(cell))
            sim.header_extra = {"sweep_id": self.sweep_id,
                                "cell": cell.key, **self.header_extra}
            try:
                if sim.supports_fused():
                    # per-cell specialization: the cell's own compiled
                    # fused program (hyper without detection)
                    state, history = sim.run_fast(verbose=False, stop=stop)
                else:
                    state, history = sim.run(verbose=False, stop=stop)
            finally:
                sim.close()
            key = ("hnet_params" if "hnet_params" in state
                   else "global_params")
            final_params[cell.key] = state[key]
            for entry in history:
                entry["cell"] = cell.key
            histories[cell.key] = history
            self.telemetry.events.emit(
                "matrix", sweep_id=self.sweep_id, action="cell_done",
                cell=cell.key,
                rounds=len(history),
                ok_rounds=sum(1 for h in history if h.get("ok")))
            if int(state["completed_rounds"]) < self.grid.rounds:
                # the stop hook cut this cell short mid-run; re-consult
                # it to capture the reason (the hook is a level check —
                # drain/preempt/cancel events stay set once raised)
                self._consult_stop(stop, int(state["completed_rounds"]))
                return True
        return False

    # ------------------------------------------------------------------
    # terminal work
    # ------------------------------------------------------------------

    def _consult_stop(self, stop, completed) -> bool:
        """One stop-hook consultation (the engine's rule): any truthy
        verdict stops the sweep at this chunk/cell boundary, and a
        STRING verdict is kept as :attr:`stop_reason` so the sweep's
        ``interrupted`` event names the seam (drain/preempt/cancel)."""
        if stop is None:
            return False
        verdict = stop(int(completed))
        if not verdict:
            return False
        self.stop_reason = (verdict if isinstance(verdict, str)
                            else "stopped")
        return True

    def _finish(self, histories: dict[str, list[dict[str, Any]]],
                t_start: float, interrupted: bool) -> None:
        tel = self.telemetry
        wall = time.perf_counter() - t_start
        self._hotspots.maybe_stop(force=True)
        records = self._distill_records(histories, wall)
        self._append_ledger_records(records)
        if tel.enabled:
            tel.events.emit(
                "matrix", sweep_id=self.sweep_id,
                action="interrupted" if interrupted else "completed",
                cells_done=len(histories), seconds=round(wall, 6),
                **({"stop_reason": self.stop_reason}
                   if interrupted and self.stop_reason else {}))
            self._emit_science(records)
            tel.events.emit("counters", counters=tel.counters.snapshot())
            total = sum(len(h) for h in histories.values())
            tel.events.emit(
                "run_end", rounds=total,
                ok_rounds=sum(1 for h in histories.values()
                              for e in h if e.get("ok")),
                seconds=round(wall, 6))
            tel.flush()

    def _mine_cell_summaries(self) -> dict[str, dict[str, Any]]:
        """Per-cell forensics/numerics blocks mined from the sweep's own
        telemetry (ISSUE 17): batched cells' drainer events sit
        cell-stamped in the sweep spool (``_CellTelemetry``); each
        fallback cell ran against its OWN spool under ``cells/<key>/``,
        whose events carry no stamp — assigned here at read time."""
        from attackfl_tpu.telemetry.summary import load_events

        events: list[dict[str, Any]] = []
        spool = self.telemetry.events.path
        if spool and os.path.exists(spool):
            self.telemetry.events.flush()
            events.extend(load_events(spool))
        for cell in self.fallback_cells:
            path = os.path.join(self._cell_dir(cell), "events.jsonl")
            if not os.path.exists(path):
                continue
            for event in load_events(path):
                event.setdefault("cell", cell.key)
                events.append(event)
        return cell_event_summaries(events)

    def _distill_records(self, histories: dict[str, list[dict[str, Any]]],
                         wall: float) -> list[dict[str, Any]]:
        """The sweep's per-cell ledger records (also the science event's
        input).  Fail-open: distillation is observability."""
        if not histories:
            return []
        try:
            return sweep_records(
                sweep_id=self.sweep_id, cells=self.cells,
                histories=histories, base_cfg=self.cfg,
                rounds=self.grid.rounds,
                run_id=self.telemetry.events.run_id,
                ts=time.time(), wall_s=wall, resumed=self._resumed,
                provenance={"jax_version": jax.__version__,
                            "backend": jax.default_backend(),
                            "mesh_devices": (self.mesh.size
                                             if self.mesh is not None
                                             else 0)},
                programs=dict(self._program_profiles) or None,
                event_summaries=self._mine_cell_summaries())
        except Exception as e:  # noqa: BLE001 — observability, fail open
            self.telemetry.counters.inc("ledger_append_failures")
            print_with_color(
                f"[matrix] record distillation failed (sweep "
                f"unaffected): {type(e).__name__}: {e}", "yellow")
            return []

    def _append_ledger_records(self,
                               records: list[dict[str, Any]]) -> None:
        if self._ledger is None or not records:
            return
        try:
            for record in records:
                self._ledger.append(record)
            self.telemetry.counters.inc("ledger_records_appended",
                                        len(records))
        except Exception as e:  # noqa: BLE001 — observability, fail open
            self.telemetry.counters.inc("ledger_append_failures")
            print_with_color(
                f"[matrix] ledger append failed (sweep unaffected): "
                f"{type(e).__name__}: {e}", "yellow")

    def _emit_science(self, records: list[dict[str, Any]]) -> None:
        """Sweep-level ``science`` event (schema v13): the defense
        leaderboard the scoreboard CLI would compute, stamped into the
        spool so the ranking travels with the sweep's artifacts (and the
        service daemon's ``/science`` route can serve it).  Fail-open —
        ranking must never fail the sweep."""
        try:
            from attackfl_tpu.science.outcomes import (
                BASELINE_ATTACK, outcome_rows,
            )
            from attackfl_tpu.science.rank import leaderboard

            rows = outcome_rows(records, sweep_id=self.sweep_id)
            if not rows:
                return
            board = leaderboard(rows, sweep_id=self.sweep_id, n_boot=200)
            fields: dict[str, Any] = {
                "cells": board["cells"], "attacks": board["attacks"],
                "defenses": board["defenses"], "seeds": board["seeds"],
                "baseline": BASELINE_ATTACK,
                "leaderboard": [
                    {"defense": e["defense"], "rank": e["rank"],
                     "damage_mean": e["damage_mean"],
                     "damage_worst": e["damage_worst"],
                     "quality_mean": e["quality_mean"],
                     "seed_spread": e["seed_spread"]}
                    for e in board["leaderboard"]],
            }
            if board.get("quality_key"):
                fields["quality_key"] = board["quality_key"]
            self.telemetry.events.emit(
                "science", sweep_id=self.sweep_id, **fields)
        except Exception as e:  # noqa: BLE001 — observability, fail open
            self.telemetry.counters.inc("science_emit_failures")
            print_with_color(
                f"[matrix] science summary failed (sweep unaffected): "
                f"{type(e).__name__}: {e}", "yellow")

    def close(self) -> None:
        self.telemetry.close()


def _sample_inputs(data_name: str):
    from attackfl_tpu.training.engine import sample_inputs

    return sample_inputs(data_name)
