"""Per-client local training as a pure JAX function.

The reference's client hot loop — E epochs of minibatch Adam per client
process (client.py:66-131, driven from RpcClient.genuine_training,
src/RpcClient.py:147-172) — becomes one pure function
``local_update(params, rng, idx, mask)`` compiled once and ``vmap``-ed over
the stacked client axis.  Epoch and batch loops are ``lax.scan``s; batches
are fixed-shape gathers from the device-resident dataset, so N clients'
training runs as one fused batched-matmul program on the MXU.

Divergences from the reference (intentional fixes, SURVEY.md §2 quirks):
* gradient clipping is applied to *real* gradients via optax; the reference
  calls clip_grad_norm_ before backward() so it clipped zeros
  (client.py:104-106);
* batches of size 1 are handled by masking instead of being skipped
  (client.py:86-87) — no BatchNorm anywhere, so size-1 batches are safe;
* the NaN tripwire (client.py:100-102) is a carried boolean instead of an
  early return (single round outcome is identical: the round is rejected).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax

Batch = dict[str, jnp.ndarray]


def resolve_compute_dtype(name: str):
    """cfg.mesh.compute_dtype -> dtype for :func:`make_loss_fn` (None =
    full f32, i.e. no mixed-precision casting)."""
    return jnp.dtype(name).type if name != "float32" else None


def make_loss_fn(model, data_name: str, compute_dtype=None) -> Callable:
    """Per-batch masked mean loss.

    ICU -> BCE on sigmoid outputs (client.py:77), HAR -> softmax CE on
    logits (client.py:117), CIFAR10 -> NLL on log-prob outputs (the
    validation contract, src/Validation.py:76).

    ``compute_dtype`` (e.g. jnp.bfloat16) runs the model forward/backward
    in that dtype — parameters are cast on the way into ``model.apply``
    and the loss is reduced in float32, so the f32 master params, Adam
    state and loss tripwire are unchanged (mixed-precision: the MXU eats
    bf16 natively; cfg.mesh.compute_dtype).
    """

    def cast_in(params, batch):
        if compute_dtype is None:
            return params, batch
        c = lambda x: (x.astype(compute_dtype)
                       if jnp.issubdtype(x.dtype, jnp.floating) else x)
        return jax.tree.map(c, params), {k: c(v) for k, v in batch.items()}

    if data_name == "ICU":

        def loss_fn(params, batch: Batch, mask, rng):
            params, batch = cast_in(params, batch)
            probs = model.apply(
                {"params": params}, batch["vitals"], batch["labs"], train=True,
                rngs={"dropout": rng},
            )[:, 0].astype(jnp.float32)
            probs = jnp.clip(probs, 1e-7, 1.0 - 1e-7)
            y = batch["label"].astype(jnp.float32)
            per = -(y * jnp.log(probs) + (1.0 - y) * jnp.log(1.0 - probs))
            return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    elif data_name == "HAR":

        def loss_fn(params, batch: Batch, mask, rng):
            params, batch = cast_in(params, batch)
            logits = model.apply(
                {"params": params}, batch["x"], train=True, rngs={"dropout": rng}
            ).astype(jnp.float32)
            per = optax.softmax_cross_entropy_with_integer_labels(logits, batch["label"])
            return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    elif data_name == "CIFAR10":

        def loss_fn(params, batch: Batch, mask, rng):
            params, batch = cast_in(params, batch)
            logp = model.apply(
                {"params": params}, batch["x"], train=True, rngs={"dropout": rng}
            ).astype(jnp.float32)
            per = -jnp.take_along_axis(logp, batch["label"][:, None], axis=1)[:, 0]
            return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    else:
        raise ValueError(f"Data name '{data_name}' is not valid.")

    return loss_fn


def make_optimizer(lr: float, clip_grad_norm: float) -> optax.GradientTransformation:
    """Adam with torch-default hyperparameters (client.py:78,116) behind an
    optional global-norm clip (config.yaml:37)."""
    tx = []
    if clip_grad_norm and clip_grad_norm > 0:
        tx.append(optax.clip_by_global_norm(clip_grad_norm))
    tx.append(optax.adam(lr, b1=0.9, b2=0.999, eps=1e-8))
    return optax.chain(*tx)


def build_local_update(
    model,
    data_name: str,
    dataset: Batch,
    *,
    epochs: int,
    batch_size: int,
    lr: float,
    clip_grad_norm: float,
    scan_unroll: int = 1,
    compute_dtype=None,
) -> Callable:
    """Build ``local_update(params, rng, idx, mask) -> (params, ok, loss)``.

    ``idx`` (hi,) are padded sample indices into ``dataset``; ``mask`` (hi,)
    marks which are real.  The optimizer is created fresh per call,
    mirroring the per-round Adam construction in the reference
    (client.py:78).  vmap over the leading client axis with
    ``in_axes=(0 or None, 0, 0, 0)``.
    """
    loss_fn = make_loss_fn(model, data_name, compute_dtype)
    tx = make_optimizer(lr, clip_grad_norm)
    grad_fn = jax.value_and_grad(loss_fn)

    def local_update(params: Any, rng: jax.Array, idx: jnp.ndarray, mask: jnp.ndarray):
        hi = idx.shape[0]
        num_batches = -(-hi // batch_size)
        pad = num_batches * batch_size - hi
        opt_state = tx.init(params)

        def epoch_step(carry, ek):
            params, opt_state, ok = carry
            k_perm, k_drop = jax.random.split(ek)
            perm = jax.random.permutation(k_perm, hi)
            bidx = jnp.pad(idx[perm], (0, pad)).reshape(num_batches, batch_size)
            bmask = jnp.pad(mask[perm], (0, pad)).reshape(num_batches, batch_size)
            dropout_keys = jax.random.split(k_drop, num_batches)

            def batch_step(carry, xs):
                params, opt_state, ok = carry
                bi, bm, dk = xs
                batch = {k: v[bi] for k, v in dataset.items()}
                loss, grads = grad_fn(params, batch, bm.astype(jnp.float32), dk)
                ok = ok & jnp.isfinite(loss)
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state, ok), loss

            (params, opt_state, ok), losses = jax.lax.scan(
                batch_step, (params, opt_state, ok), (bidx, bmask, dropout_keys),
                unroll=scan_unroll,
            )
            return (params, opt_state, ok), jnp.mean(losses)

        ok0 = jnp.asarray(True)
        (params, _, ok), epoch_losses = jax.lax.scan(
            epoch_step, (params, opt_state, ok0), jax.random.split(rng, epochs)
        )
        return params, ok, epoch_losses[-1]

    return local_update


def build_root_update(
    model,
    data_name: str,
    root_data: Batch,
    *,
    epochs: int,
    batch_size: int,
    lr: float,
    clip_grad_norm: float,
) -> Callable:
    """FLTrust server-side root training (reference: server.py:290-293,711
    — the server runs the same ``train_on_device`` on the first 200 test
    samples, batch 100, unshuffled).  Returns ``root_update(params, rng) ->
    params`` over the full fixed root set."""
    n = next(iter(root_data.values())).shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    mask = jnp.ones((n,), dtype=bool)
    inner = build_local_update(
        model, data_name, root_data,
        epochs=epochs, batch_size=batch_size, lr=lr, clip_grad_norm=clip_grad_norm,
    )

    def root_update(params, rng):
        new_params, _ok, _loss = inner(params, rng, idx, mask)
        return new_params

    return root_update
