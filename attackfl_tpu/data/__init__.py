from attackfl_tpu.data.synthetic import make_dataset  # noqa: F401
from attackfl_tpu.data.partition import (  # noqa: F401
    sample_round_indices,
    dirichlet_label_partition,
)
