"""Per-round client data assignment.

The reference's data distribution is *quantity skew over a shared pool*:
every round, every client independently draws ``num_data ~ U[lo, hi]``
fresh samples from the full shared train set (src/RpcClient.py:97,166-169).
Under jit/vmap all shapes must be static, so this becomes: every client
gets a padded index matrix of shape (hi,) plus a validity mask — gathers
stay fixed-shape, the weighted aggregation uses the true sizes.

Additionally a Dirichlet non-IID *label* partition is provided (BASELINE
config 3 requires a non-IID split the reference does not implement).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def sample_round_indices(
    rng: jax.Array,
    num_clients: int,
    pool_size: int,
    lo: int,
    hi: int,
    client_pools: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Draw per-client padded sample indices for one round.

    Returns ``(indices (C, hi) int32, mask (C, hi) bool, sizes (C,) int32)``.
    ``sizes[c] ~ U[lo, hi]`` inclusive, matching the reference's
    ``random.randrange(lo, hi + 1)`` (src/RpcClient.py:97).  Indices are
    drawn uniformly *with replacement* from the pool — the reference uses
    ``random.sample`` (without replacement); with pool sizes ≫ num_data the
    difference is statistically negligible and with-replacement keeps the
    sampler O(hi) and shape-static on device.

    If ``client_pools`` (C, pool_size) is given (non-IID partition), each
    row holds the client's own permitted indices (padded by repetition) and
    sampling gathers from that row instead of the global range.
    """
    k_size, k_idx = jax.random.split(rng)
    sizes = jax.random.randint(k_size, (num_clients,), lo, hi + 1)
    if client_pools is not None:
        slot = jax.random.randint(k_idx, (num_clients, hi), 0, client_pools.shape[1])
        idx = jnp.take_along_axis(client_pools, slot, axis=1)
    else:
        idx = jax.random.randint(k_idx, (num_clients, hi), 0, pool_size)
    mask = jnp.arange(hi)[None, :] < sizes[:, None]
    return idx.astype(jnp.int32), mask, sizes.astype(jnp.int32)


def apply_client_dropout(
    k_drop: jax.Array,
    sizes: jnp.ndarray,
    mask: jnp.ndarray,
    rate: float,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Straggler injection (cfg.client_dropout_rate), shared by the plain
    and hyper round builders: each client independently drops with
    probability ``rate``; a dropped client gets zero samples (all-masked
    batches → exact local-update no-op) and round size 0 (exact exclusion
    from size-weighted aggregation).  Returns ``(sizes, mask, kept)``."""
    kept = jax.random.bernoulli(k_drop, 1.0 - rate, sizes.shape)
    return sizes * kept, mask & kept[:, None], kept


def dirichlet_label_partition(
    labels: np.ndarray,
    num_clients: int,
    alpha: float,
    seed: int = 0,
) -> np.ndarray:
    """Non-IID label split: per-class Dirichlet(alpha) proportions over
    clients (the standard Hsu et al. 2019 protocol).

    Returns an int32 matrix (num_clients, pool) where row c lists the
    sample indices client c may draw from, padded by repetition to equal
    length so it can live on device as one array.
    """
    rng = np.random.default_rng(seed)
    labels = np.asarray(labels).astype(np.int64)
    classes = np.unique(labels)
    client_indices: list[list[int]] = [[] for _ in range(num_clients)]
    for cls in classes:
        cls_idx = np.flatnonzero(labels == cls)
        rng.shuffle(cls_idx)
        props = rng.dirichlet(np.full(num_clients, alpha))
        cuts = (np.cumsum(props) * len(cls_idx)).astype(int)[:-1]
        for c, part in enumerate(np.split(cls_idx, cuts)):
            client_indices[c].extend(part.tolist())
    # Guarantee non-empty pools, then pad by repetition to a rectangle.
    for c in range(num_clients):
        if not client_indices[c]:
            client_indices[c].append(int(rng.integers(len(labels))))
    width = max(len(ci) for ci in client_indices)
    out = np.zeros((num_clients, width), dtype=np.int32)
    for c, ci in enumerate(client_indices):
        reps = -(-width // len(ci))
        out[c] = np.tile(np.asarray(ci, dtype=np.int32), reps)[:width]
    return out
