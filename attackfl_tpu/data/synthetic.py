"""Datasets.

The reference trains on gzip-pickled pandas/torch datasets whose blobs are
absent from its repo (reference: .MISSING_LARGE_BLOBS; loaders at
src/RpcClient.py:155-164 and src/Validation.py:32-48).  This module
provides (a) deterministic synthetic generators with the same shapes and
label semantics so every config is runnable end-to-end, and (b) a loader
for the reference's gzip-pickle format when real blobs exist.

Arrays are plain dict-of-ndarray "struct of arrays" — no Dataset objects,
no per-item __getitem__: batches are gathers on device.

Shapes:
  ICU:     vitals (N, 7) float32, labs (N, 16) float32, label (N,) {0,1}
           (dual-branch models, src/Model.py:95; ~mortality base rate .25)
  HAR:     x (N, 561) float32, label (N,) in 0..5 (src/Model.py:435-453)
  CIFAR10: x (N, 32, 32, 3) float32 normalized to [-1, 1], label (N,) 0..9
           (torchvision normalize (0.5,0.5,0.5), Validation.py:39-42)
"""

from __future__ import annotations

import gzip
import os
import pickle
from typing import Any

import numpy as np

Batch = dict[str, np.ndarray]


def _icu(rng: np.random.Generator, n: int) -> Batch:
    """Synthetic ICU cohort: labels depend on a sparse linear risk score of
    vitals+labs through a logistic link, so models can reach AUC >> 0.5."""
    vitals = rng.normal(0.0, 1.0, size=(n, 7)).astype(np.float32)
    labs = rng.normal(0.0, 1.0, size=(n, 16)).astype(np.float32)
    # fixed ground-truth weights (same for every call at a given seed policy)
    w_rng = np.random.default_rng(7)
    wv = w_rng.normal(0, 1, size=(7,))
    wl = w_rng.normal(0, 1, size=(16,))
    score = vitals @ wv + labs @ wl
    prob = 1.0 / (1.0 + np.exp(-(score - 1.0)))  # ~25% positive rate
    label = (rng.uniform(size=n) < prob).astype(np.float32)
    # sprinkle the reference's mask value into vitals (missing measurements;
    # RNNModel zeroes them, src/Model.py:98,122)
    mask = rng.uniform(size=vitals.shape) < 0.05
    vitals = np.where(mask, np.float32(-2.0), vitals)
    return {"vitals": vitals, "labs": labs, "label": label}


def _har(rng: np.random.Generator, n: int) -> Batch:
    """Synthetic HAR: 6 activity classes, each a distinct smooth template
    over 561 pseudo-features plus noise."""
    t = np.linspace(0.0, 6.0 * np.pi, 561)
    templates = np.stack(
        [np.sin((k + 1) * 0.5 * t + k) * (1.0 + 0.1 * k) for k in range(6)]
    ).astype(np.float32)  # (6, 561)
    label = rng.integers(0, 6, size=n)
    x = templates[label] + rng.normal(0, 0.5, size=(n, 561)).astype(np.float32)
    return {"x": x.astype(np.float32), "label": label.astype(np.int32)}


def _cifar10(rng: np.random.Generator, n: int) -> Batch:
    """Synthetic CIFAR-10 stand-in: class-conditional colored blobs."""
    label = rng.integers(0, 10, size=n)
    base = np.random.default_rng(11).uniform(-0.6, 0.6, size=(10, 1, 1, 3)).astype(np.float32)
    x = base[label] + rng.normal(0, 0.3, size=(n, 32, 32, 3)).astype(np.float32)
    return {"x": np.clip(x, -1, 1).astype(np.float32), "label": label.astype(np.int32)}


_GENERATORS = {"ICU": _icu, "HAR": _har, "CIFAR10": _cifar10}


def make_dataset(data_name: str, n: int, seed: int = 0) -> Batch:
    if data_name not in _GENERATORS:
        raise ValueError(f"Data name '{data_name}' is not valid.")
    return _GENERATORS[data_name](np.random.default_rng(seed), n)


def load_reference_pickle(path: str) -> Batch:
    """Load a reference-format gzip-pickled dataset if present
    (``train_dataset.pkl.gz`` / ``data/icu_har_*.pkl.gz``,
    src/RpcClient.py:157-162).  The pickle holds a torch Dataset; we
    convert to the struct-of-arrays layout."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with gzip.open(path, "rb") as fh:
        ds: Any = pickle.load(fh)
    first = ds[0]
    if isinstance(first, (tuple, list)) and len(first) == 3:  # ICU (vitals, labs, label)
        cols = list(zip(*(ds[i] for i in range(len(ds)))))
        return {
            "vitals": np.stack([np.asarray(v) for v in cols[0]]).astype(np.float32),
            "labs": np.stack([np.asarray(v) for v in cols[1]]).astype(np.float32),
            "label": np.asarray(cols[2], dtype=np.float32),
        }
    if isinstance(first, (tuple, list)) and len(first) == 2:  # HAR (x, label)
        cols = list(zip(*(ds[i] for i in range(len(ds)))))
        x = np.stack([np.asarray(v) for v in cols[0]]).astype(np.float32)
        if x.ndim == 3 and x.shape[1] == 1:
            x = x[:, 0, :]
        return {"x": x, "label": np.asarray(cols[1], dtype=np.int32)}
    raise ValueError(f"Unrecognized reference dataset format in {path}")


def load_cifar10_batches(root: str, split: str) -> Batch:
    """Load CIFAR-10 from the standard ``cifar-10-batches-py`` layout the
    reference pulls via torchvision (root './data', src/Validation.py:38-44):
    train = data_batch_1..5, test = test_batch, each a pickle dict with
    ``data`` (N, 3072) uint8 row-major CHW and ``labels``.  Pixels are
    normalized exactly like the reference's transform —
    ToTensor (/255) then Normalize(0.5, 0.5) => [-1, 1] — and returned
    NHWC for the Flax ResNet."""
    batch_dir = os.path.join(root, "cifar-10-batches-py")
    names = ([f"data_batch_{i}" for i in range(1, 6)] if split == "train"
             else ["test_batch"])
    xs, ys = [], []
    for name in names:
        with open(os.path.join(batch_dir, name), "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        xs.append(np.asarray(d[b"data"], dtype=np.uint8))
        ys.append(np.asarray(d[b"labels"], dtype=np.int32))
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    x = (x.astype(np.float32) / 255.0 - 0.5) / 0.5
    return {"x": x, "label": np.concatenate(ys)}


def get_dataset(data_name: str, split: str, size: int, seed: int) -> Batch:
    """Reference-compatible entry point: try the reference's on-disk
    dataset paths first (same working-directory contract as the reference,
    src/RpcClient.py:155-164 / src/Validation.py:32-44), fall back to
    synthetic data."""
    paths = {
        ("ICU", "train"): "train_dataset.pkl.gz",
        ("ICU", "test"): "data/test_dataset.pkl.gz",
        ("HAR", "train"): "data/icu_har_train_ds.pkl.gz",
        ("HAR", "test"): "data/icu_har_test_ds.pkl.gz",
    }
    path = paths.get((data_name, split))
    if path and os.path.exists(path):
        return load_reference_pickle(path)
    if data_name == "CIFAR10" and os.path.exists(
        os.path.join("data", "cifar-10-batches-py")
    ):
        return load_cifar10_batches("data", split)
    # seeds: train/test splits must be disjoint
    return make_dataset(data_name, size, seed=seed + (0 if split == "train" else 10_000))
