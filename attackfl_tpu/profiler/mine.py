"""Chrome-trace mining: device traces -> op-level time attribution.

The jax-free half of the hotspot observatory.  ``jax.profiler`` writes
TensorBoard-layout artifacts under ``<telemetry dir>/profile/plugins/
profile/<timestamp>/<host>.trace.json.gz``; this module parses them with
stdlib gzip+json only, so the miner (and every test driving it) never
imports jax.

**What a trace looks like** (jax 0.4.37, all backends): ``traceEvents``
carries ``ph: "M"`` metadata naming processes/threads and ``ph: "X"``
duration events.  Device-op events are the ``X`` events whose ``args``
carry ``hlo_op`` — on CPU they live on the ``tf_XLATfrtCpuClient``
thread, on TPU on the device lanes — and ``args.hlo_module`` names the
compiled program (``jit_round_step`` etc.), which gives per-program
grouping for free.  Timestamps/durations are microseconds.

**Attribution**: per (program, op) — total time (Σ dur), self time
(Σ dur minus nested children, the fusion-vs-constituents split), share
of the window's attributed self time, and a category rollup
(matmul / elementwise / reduction / collective / copy / other).

**Dispatch-gap diagnosis**: merge every device-op interval into one
busy union; the gaps between consecutive busy stretches are time the
device sat idle waiting for the host to dispatch.  The gap histogram
(log-spaced buckets) plus ``host_bound_fraction`` = idle/span classify
each window device-bound vs host/dispatch-bound — exactly the
instrument the ROADMAP's warm-sweep 0.61x question needs.

**Books-close invariant** (the fleet ledger's discipline)::

    Σ op self-time <= device busy (per-lane interval union)
                   <= window wall x lanes

Torn / truncated / empty traces are COUNTED (status ``torn`` /
``empty``) and surfaced in every report — never silently dropped.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import Any

# Idle fraction of the window span past which a window is classified
# host/dispatch-bound rather than device-bound.
HOST_BOUND_THRESHOLD = 0.5
DEFAULT_TOP_K = 5
# Gap-histogram bucket upper edges (microseconds, log-spaced); the last
# bucket is open-ended (+inf).
GAP_BUCKETS_US = (10.0, 100.0, 1_000.0, 10_000.0, 100_000.0)
# Absolute float slop for the books-close comparisons (trace timestamps
# are microsecond floats; summing thousands of them wobbles).
_EPS_US = 1.0

TRACE_SUFFIX = ".trace.json.gz"


def _num(value: Any) -> float | None:
    """Bool-safe numeric coercion (``+ 0.0``, the costmodel idiom — the
    host-sync lint audits this module with NO allowlist, so ``float()``
    never appears here)."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    if value != value:  # NaN
        return None
    return value + 0.0


# ---------------------------------------------------------------------------
# op categories
# ---------------------------------------------------------------------------

# Whole-name substrings checked FIRST (collective names are hyphenated
# multi-token, so token sets would misfile all-reduce under reduction).
_COLLECTIVE_MARKS = ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective", "ppermute",
                     "partition-id", "replica-id")
_MATMUL_TOKENS = frozenset({"dot", "convolution", "conv", "einsum",
                            "gemm", "cublas"})
_REDUCTION_TOKENS = frozenset({"reduce", "sort", "topk", "argmax",
                               "argmin", "cumsum", "cumprod"})
_ELEMENTWISE_TOKENS = frozenset({
    "add", "subtract", "multiply", "divide", "exp", "expm1", "log",
    "log1p", "tanh", "maximum", "minimum", "max", "min", "select",
    "compare", "rsqrt", "sqrt", "power", "abs", "negate", "sign",
    "clamp", "floor", "ceil", "round", "sigmoid", "logistic", "erf",
    "xor", "shift", "remainder", "atan2", "sin", "cos", "map"})
_COPY_TOKENS = frozenset({
    "copy", "transpose", "reshape", "bitcast", "concatenate", "slice",
    "gather", "scatter", "dynamic", "update", "pad", "iota", "convert",
    "tuple", "parameter", "constant", "broadcast", "rng", "bitcast",
    "get", "while", "conditional", "call", "custom"})


def _base_name(name: str) -> str:
    """``broadcast_divide_fusion.3`` -> ``broadcast_divide_fusion``
    (strip the trailing ``.N`` HLO instruction counter only)."""
    head, dot, tail = name.rpartition(".")
    if dot and tail.isdigit():
        return head
    return name


def op_category(name: str) -> str:
    """Map one HLO op/fusion name to its roofline category.  Fusions
    keep their constituents' names (``broadcast_divide_fusion``), so
    classification is token-based with a fixed priority: collective >
    matmul > reduction > elementwise > copy > other."""
    base = _base_name(str(name)).lower()
    if any(mark in base for mark in _COLLECTIVE_MARKS):
        return "collective"
    tokens = set(base.replace("-", "_").split("_"))
    if tokens & _MATMUL_TOKENS:
        return "matmul"
    if tokens & _REDUCTION_TOKENS:
        return "reduction"
    if tokens & _ELEMENTWISE_TOKENS:
        return "elementwise"
    if tokens & _COPY_TOKENS:
        return "copy"
    return "other"


# ---------------------------------------------------------------------------
# trace loading
# ---------------------------------------------------------------------------

def load_trace_events(path: str) -> tuple[list[dict[str, Any]], str]:
    """One trace file -> (traceEvents, status).  ``status`` is ``ok``,
    ``empty`` (valid JSON, no events) or ``torn`` (truncated gzip,
    invalid JSON, unreadable file) — torn inputs return loudly, never
    raise."""
    try:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as fh:
            raw = fh.read()
        doc = json.loads(raw.decode("utf-8"))
    except (OSError, EOFError, ValueError, UnicodeDecodeError):
        # gzip.BadGzipFile is an OSError; json errors are ValueError
        return [], "torn"
    events = doc.get("traceEvents") if isinstance(doc, dict) else None
    if not isinstance(events, list):
        return [], "torn"
    rows = [e for e in events if isinstance(e, dict)]
    return rows, ("ok" if rows else "empty")


def _device_ops(events: list[dict[str, Any]]
                ) -> list[tuple[float, float, str, str, tuple]]:
    """The device-op events: ``ph == "X"`` with ``args.hlo_op`` —
    robust across backends (thread names differ; the HLO annotation
    does not).  Returns (ts, dur, program, op_name, lane) rows."""
    rows: list[tuple[float, float, str, str, tuple]] = []
    for event in events:
        if event.get("ph") != "X":
            continue
        args = event.get("args")
        if not isinstance(args, dict) or "hlo_op" not in args:
            continue
        ts = _num(event.get("ts"))
        dur = _num(event.get("dur"))
        if ts is None or dur is None or dur < 0:
            continue
        program = str(args.get("hlo_module") or "<unknown>")
        name = str(args.get("hlo_op") or event.get("name") or "<op>")
        lane = (event.get("pid"), event.get("tid"))
        rows.append((ts, dur, program, name, lane))
    return rows


# ---------------------------------------------------------------------------
# interval math
# ---------------------------------------------------------------------------

def _merge_intervals(intervals: list[tuple[float, float]]
                     ) -> list[tuple[float, float]]:
    if not intervals:
        return []
    merged: list[list[float]] = []
    for start, end in sorted(intervals):
        if merged and start <= merged[-1][1]:
            if end > merged[-1][1]:
                merged[-1][1] = end
        else:
            merged.append([start, end])
    return [(s, e) for s, e in merged]


def _self_durations(items: list[tuple[float, float]]) -> list[float]:
    """Per-event self time for one lane's (ts, dur) rows: dur minus the
    time covered by nested children (a fusion's span contains its
    constituents' spans on the same lane).  Items need not be sorted."""
    order = sorted(range(len(items)),
                   key=lambda i: (items[i][0], -items[i][1]))
    child_total = [0.0] * len(items)
    stack: list[int] = []  # indices of open (enclosing) events
    for i in order:
        ts, dur = items[i]
        end = ts + dur
        while stack and items[stack[-1]][0] + items[stack[-1]][1] \
                <= ts + 1e-9:
            stack.pop()
        if stack:
            # nested: this event's whole duration is the immediate
            # parent's child time (grandparents already count the parent)
            child_total[stack[-1]] += dur
        stack.append(i)
    return [max(items[i][1] - child_total[i], 0.0)
            for i in range(len(items))]


def _gap_histogram(union: list[tuple[float, float]]
                   ) -> tuple[list[dict[str, Any]], float]:
    """Gaps between consecutive busy stretches -> (histogram rows,
    total gap time).  Buckets are upper-edge labeled, last one +inf."""
    counts = [0] * (len(GAP_BUCKETS_US) + 1)
    total = 0.0
    for (_, prev_end), (next_start, _) in zip(union, union[1:]):
        gap = next_start - prev_end
        if gap <= 0:
            continue
        total += gap
        for b, edge in enumerate(GAP_BUCKETS_US):
            if gap <= edge:
                counts[b] += 1
                break
        else:
            counts[-1] += 1
    rows = [{"le_us": edge, "count": counts[b]}
            for b, edge in enumerate(GAP_BUCKETS_US)]
    rows.append({"le_us": None, "count": counts[-1]})
    return rows, total


# ---------------------------------------------------------------------------
# single-trace mining
# ---------------------------------------------------------------------------

def mine_trace(path: str, top_k: int = DEFAULT_TOP_K) -> dict[str, Any]:
    """One ``*.trace.json.gz`` -> the window's attribution report (see
    module doc for the fields).  Torn/empty traces come back with that
    status and zeroed attribution — counted by the caller, never
    dropped."""
    events, status = load_trace_events(path)
    ops = _device_ops(events) if status == "ok" else []
    if status == "ok" and not ops:
        status = "empty"
    report: dict[str, Any] = {
        "trace": path, "status": status, "lanes": 0,
        "wall_us": 0.0, "device_busy_us": 0.0, "op_self_us": 0.0,
        "host_bound_fraction": None, "classification": None,
        "gap_histogram": [], "ops": [], "top_ops": [],
        "categories": {}, "programs": {},
        "books": {"op_self_us": 0.0, "device_busy_us": 0.0,
                  "wall_us": 0.0, "lanes": 0, "close": status == "ok"},
    }
    if not ops:
        return report

    # per-lane rows for self time + busy union
    lanes: dict[tuple, list[int]] = {}
    for i, row in enumerate(ops):
        lanes.setdefault(row[4], []).append(i)
    self_us = [0.0] * len(ops)
    busy = 0.0
    for indices in lanes.values():
        items = [(ops[i][0], ops[i][1]) for i in indices]
        for i, self_dur in zip(indices, _self_durations(items)):
            self_us[i] = self_dur
        for start, end in _merge_intervals(
                [(ts, ts + dur) for ts, dur in items]):
            busy += end - start

    span_start = min(ts for ts, _, _, _, _ in ops)
    span_end = max(ts + dur for ts, dur, _, _, _ in ops)
    wall = max(span_end - span_start, 0.0)

    # dispatch-gap diagnosis over the cross-lane union: idle time is
    # host/dispatch time the device spent waiting
    union = _merge_intervals(
        [(ts, ts + dur) for ts, dur, _, _, _ in ops])
    histogram, gap_total = _gap_histogram(union)
    host_fraction = (gap_total / wall) if wall > 0 else 0.0

    # per-(program, op) attribution
    table: dict[tuple[str, str], dict[str, Any]] = {}
    for i, (_, dur, program, name, _) in enumerate(ops):
        key = (program, _base_name(name))
        row = table.setdefault(key, {
            "name": key[1], "program": program,
            "category": op_category(name),
            "count": 0, "total_us": 0.0, "self_us": 0.0})
        row["count"] += 1
        row["total_us"] += dur
        row["self_us"] += self_us[i]
    total_self = sum(row["self_us"] for row in table.values())
    rows = sorted(table.values(),
                  key=lambda r: (-r["self_us"], r["name"]))
    for row in rows:
        row["total_us"] = round(row["total_us"], 3)
        row["self_us"] = round(row["self_us"], 3)
        row["share"] = round(row["self_us"] / total_self, 4) \
            if total_self > 0 else 0.0

    categories: dict[str, dict[str, Any]] = {}
    for row in rows:
        bucket = categories.setdefault(
            row["category"], {"self_us": 0.0, "ops": 0})
        bucket["self_us"] = round(bucket["self_us"] + row["self_us"], 3)
        bucket["ops"] += 1
    for bucket in categories.values():
        bucket["share"] = round(bucket["self_us"] / total_self, 4) \
            if total_self > 0 else 0.0

    programs: dict[str, dict[str, Any]] = {}
    for row in rows:
        prog = programs.setdefault(
            row["program"], {"self_us": 0.0, "ops": 0, "top_op": None})
        prog["self_us"] = round(prog["self_us"] + row["self_us"], 3)
        prog["ops"] += 1
        if prog["top_op"] is None:  # rows arrive self-time sorted
            prog["top_op"] = row["name"]

    books_close = (total_self <= busy + _EPS_US
                   and busy <= wall * len(lanes) + _EPS_US)
    report.update({
        "lanes": len(lanes),
        "wall_us": round(wall, 3),
        "device_busy_us": round(busy, 3),
        "op_self_us": round(total_self, 3),
        "host_bound_fraction": round(host_fraction, 4),
        "classification": ("host_bound"
                           if host_fraction > HOST_BOUND_THRESHOLD
                           else "device_bound"),
        "gap_histogram": histogram,
        "ops": rows,
        "top_ops": rows[:max(int(top_k), 1)],
        "categories": categories,
        "programs": programs,
        "books": {"op_self_us": round(total_self, 3),
                  "device_busy_us": round(busy, 3),
                  "wall_us": round(wall, 3), "lanes": len(lanes),
                  "close": books_close},
    })
    return report


# ---------------------------------------------------------------------------
# directory mining (a run's whole profile/ tree)
# ---------------------------------------------------------------------------

def find_traces(profile_dir: str) -> list[str]:
    """Every ``*.trace.json.gz`` under ``profile_dir`` (the TensorBoard
    layout nests them two levels down), sorted for determinism."""
    found: list[str] = []
    for root, _, files in os.walk(profile_dir):
        for name in files:
            if name.endswith(TRACE_SUFFIX):
                found.append(os.path.join(root, name))
    return sorted(found)


def mine_profile_dir(profile_dir: str,
                     top_k: int = DEFAULT_TOP_K) -> dict[str, Any]:
    """Aggregate attribution over every trace window under a profile
    directory.  Torn/empty windows are counted in the header and listed
    in ``windows`` with their status — the books-close verdict is the
    conjunction over the OK windows only (a torn window has no books to
    close, but it is never hidden)."""
    paths = find_traces(profile_dir)
    windows = [mine_trace(path, top_k=top_k) for path in paths]
    ok = [w for w in windows if w["status"] == "ok"]
    torn = sum(1 for w in windows if w["status"] == "torn")
    empty = sum(1 for w in windows if w["status"] == "empty")

    table: dict[tuple[str, str], dict[str, Any]] = {}
    categories: dict[str, dict[str, Any]] = {}
    programs: dict[str, dict[str, Any]] = {}
    hist_counts: dict[Any, int] = {}
    wall = busy = total_self = 0.0
    gap_weight = 0.0
    for window in ok:
        wall += window["wall_us"]
        busy += window["device_busy_us"]
        total_self += window["op_self_us"]
        fraction = window["host_bound_fraction"] or 0.0
        gap_weight += fraction * window["wall_us"]
        for row in window["ops"]:
            key = (row["program"], row["name"])
            agg = table.setdefault(key, {
                "name": row["name"], "program": row["program"],
                "category": row["category"], "count": 0,
                "total_us": 0.0, "self_us": 0.0})
            agg["count"] += row["count"]
            agg["total_us"] = round(agg["total_us"] + row["total_us"], 3)
            agg["self_us"] = round(agg["self_us"] + row["self_us"], 3)
        for bucket in window["gap_histogram"]:
            hist_counts[bucket["le_us"]] = (
                hist_counts.get(bucket["le_us"], 0) + bucket["count"])

    rows = sorted(table.values(),
                  key=lambda r: (-r["self_us"], r["name"]))
    for row in rows:
        row["share"] = round(row["self_us"] / total_self, 4) \
            if total_self > 0 else 0.0
        bucket = categories.setdefault(
            row["category"], {"self_us": 0.0, "ops": 0})
        bucket["self_us"] = round(bucket["self_us"] + row["self_us"], 3)
        bucket["ops"] += 1
        prog = programs.setdefault(
            row["program"], {"self_us": 0.0, "ops": 0, "top_op": None})
        prog["self_us"] = round(prog["self_us"] + row["self_us"], 3)
        prog["ops"] += 1
        if prog["top_op"] is None:
            prog["top_op"] = row["name"]
    for bucket in categories.values():
        bucket["share"] = round(bucket["self_us"] / total_self, 4) \
            if total_self > 0 else 0.0

    host_fraction = (gap_weight / wall) if wall > 0 else None
    histogram = [{"le_us": edge, "count": hist_counts.get(edge, 0)}
                 for edge in (*GAP_BUCKETS_US, None)] if ok else []
    books_close = bool(ok) and all(w["books"]["close"] for w in ok)
    status = "ok" if ok else ("torn" if torn else
                              ("empty" if windows else "no_traces"))
    return {
        "dir": profile_dir,
        "traces": len(windows), "ok": len(ok),
        "torn": torn, "empty": empty,
        "status": status,
        "wall_us": round(wall, 3),
        "device_busy_us": round(busy, 3),
        "op_self_us": round(total_self, 3),
        "host_bound_fraction": (round(host_fraction, 4)
                                if host_fraction is not None else None),
        "classification": (
            ("host_bound" if host_fraction > HOST_BOUND_THRESHOLD
             else "device_bound") if host_fraction is not None else None),
        "gap_histogram": histogram,
        "ops": rows,
        "top_ops": rows[:max(int(top_k), 1)],
        "categories": categories,
        "programs": programs,
        "books": {"op_self_us": round(total_self, 3),
                  "device_busy_us": round(busy, 3),
                  "wall_us": round(wall, 3),
                  "close": books_close},
        "windows": [{"trace": os.path.basename(w["trace"]),
                     "status": w["status"], "wall_us": w["wall_us"],
                     "device_busy_us": w["device_busy_us"],
                     "host_bound_fraction": w["host_bound_fraction"],
                     "classification": w["classification"],
                     "books_close": w["books"]["close"]}
                    for w in windows],
    }


def compact_summary(report: dict[str, Any],
                    top_k: int = DEFAULT_TOP_K) -> dict[str, Any]:
    """The window fields a ``hotspot`` event (and the ledger block)
    carries: top-K ops, category shares, the diagnosis, the books."""
    out: dict[str, Any] = {
        "wall_us": report.get("wall_us"),
        "device_busy_us": report.get("device_busy_us"),
        "op_self_us": report.get("op_self_us"),
        "books_close": bool((report.get("books") or {}).get("close")),
        "top_ops": [
            {"name": row["name"], "program": row["program"],
             "category": row["category"], "self_us": row["self_us"],
             "share": row["share"]}
            for row in (report.get("top_ops") or [])[:top_k]],
        "category_shares": {
            name: bucket.get("share")
            for name, bucket in (report.get("categories") or {}).items()},
    }
    if report.get("host_bound_fraction") is not None:
        out["host_bound_fraction"] = report["host_bound_fraction"]
        out["classification"] = report.get("classification")
    if report.get("lanes"):
        out["lanes"] = report["lanes"]
    return out


# ---------------------------------------------------------------------------
# event-stream distillation (the ledger join's input)
# ---------------------------------------------------------------------------

def hotspots_from_events(events: list[dict[str, Any]]
                         ) -> dict[str, Any] | None:
    """One run's ``hotspot`` events -> the compact ledger block, or
    None when the run profiled nothing.  Window statuses are counted
    (unavailable/torn windows are part of the record), attribution is
    merged across OK windows, and the measured per-round device time —
    the number the cost-observatory join prices against — is
    Σ busy / Σ window rounds."""
    rows = [e for e in events if e.get("kind") == "hotspot"]
    if not rows:
        return None
    status_counts: dict[str, int] = {}
    for event in rows:
        status = str(event.get("status") or "unknown")
        status_counts[status] = status_counts.get(status, 0) + 1
    ok = [e for e in rows if e.get("status") == "ok"]

    wall = busy = gap_weight = 0.0
    rounds = 0
    ops: dict[tuple[str, str], dict[str, Any]] = {}
    cat_weight: dict[str, float] = {}
    books_close = bool(ok)
    for event in ok:
        w = _num(event.get("wall_us")) or 0.0
        b = _num(event.get("device_busy_us")) or 0.0
        wall += w
        busy += b
        fraction = _num(event.get("host_bound_fraction"))
        if fraction is not None:
            gap_weight += fraction * w
        first = event.get("round_first")
        last = event.get("round_last")
        if isinstance(first, int) and isinstance(last, int) \
                and not isinstance(first, bool) \
                and not isinstance(last, bool) and last >= first:
            rounds += last - first + 1
        if event.get("books_close") is False:
            books_close = False
        for row in event.get("top_ops") or []:
            if not isinstance(row, dict):
                continue
            key = (str(row.get("program") or ""),
                   str(row.get("name") or ""))
            agg = ops.setdefault(key, {
                "name": key[1], "program": key[0],
                "category": row.get("category"), "self_us": 0.0})
            agg["self_us"] = round(
                agg["self_us"] + (_num(row.get("self_us")) or 0.0), 3)
        shares = event.get("category_shares")
        if isinstance(shares, dict) and w > 0:
            for name, share in shares.items():
                value = _num(share)
                if value is not None:
                    cat_weight[str(name)] = (
                        cat_weight.get(str(name), 0.0) + value * w)

    top = sorted(ops.values(), key=lambda r: (-r["self_us"], r["name"]))
    top_total = sum(r["self_us"] for r in top)
    for row in top:
        row["share"] = round(row["self_us"] / top_total, 4) \
            if top_total > 0 else 0.0
    host_fraction = (gap_weight / wall) if wall > 0 else None
    block: dict[str, Any] = {
        "windows": len(rows),
        "status_counts": status_counts,
        "host_bound_fraction": (round(host_fraction, 4)
                                if host_fraction is not None else None),
        "classification": (
            ("host_bound" if host_fraction > HOST_BOUND_THRESHOLD
             else "device_bound") if host_fraction is not None else None),
        "device_busy_us": round(busy, 3),
        "wall_us": round(wall, 3),
        "books_close": books_close,
        "top_ops": top[:DEFAULT_TOP_K],
        "category_shares": {
            name: round(weight / wall, 4)
            for name, weight in sorted(cat_weight.items())} if wall > 0
        else {},
        "profiled_rounds": rounds,
        "measured_round_device_s": (
            round(busy / 1e6 / rounds, 6) if rounds > 0 and busy > 0
            else None),
    }
    return block
