"""Hotspot observatory (ISSUE 19): the sixth observability layer.

Three halves, one pipeline:

* **capture** (:mod:`attackfl_tpu.profiler.capture`, the only jax-using
  module here): structured ``jax.profiler`` windows at every executor's
  dispatch seam (sync / fused / pipelined / matrix), hardening the PR-2
  ``--profile-rounds`` path — fail-open when the profiler backend is
  unavailable, each window closed with a schema-v14 ``hotspot`` event
  carrying the trace artifact path, the window's rounds, the program
  name and the mined compact summary;
* **mine** (:mod:`attackfl_tpu.profiler.mine`, jax-free stdlib
  gzip+json): Chrome-trace ``*.trace.json.gz`` files -> per-op /
  per-fusion device-time attribution grouped by program (top-K op
  table, per-category rollup, dispatch-gap diagnosis with a measured
  host-bound fraction), under the books-close invariant
  Σ op self-time <= device busy <= wall x lanes — torn/partial traces
  counted loudly, never silently dropped;
* **join** (:mod:`attackfl_tpu.ledger.record` + ``hotspots diff``):
  measured per-program device time reconciled against the cost
  observatory's predictions (``hotspot_prediction_error_factor``, the
  symmetric max(p/a, a/p) convention from costmodel/estimate.py), the
  compact ``hotspots`` block folded into ledger records, and
  noise-floored ``ledger regress`` gates on host-bound-fraction rise
  and top-op share drift.

CLI: ``attackfl-tpu hotspots [show|diff] [--json]``
(:mod:`attackfl_tpu.profiler.cli`).
"""

from attackfl_tpu.profiler.mine import (  # noqa: F401
    HOST_BOUND_THRESHOLD,
    hotspots_from_events,
    mine_profile_dir,
    mine_trace,
    op_category,
)
