"""``attackfl-tpu hotspots``: mine profiler traces, render, diff, gate.

Jax-free (stdlib + :mod:`attackfl_tpu.profiler.mine` only — safe on any
box that merely holds the trace artifacts):

* ``show [DIR]`` — mine every ``*.trace.json.gz`` under DIR (a
  ``profile/`` tree, or a telemetry dir containing one; default ``.``)
  and render the attribution report: top-K op table, category rollup,
  dispatch-gap histogram, host-bound classification, books-close
  verdict.  Exit 0 on a usable, books-closing report; 1 when no window
  mined OK or the books fail; 2 on usage errors.
* ``diff A B`` — mine two directories and gate the drift with the
  ledger's thresholds (absolute host-bound-fraction rise,
  absolute top-op share drift on ops named in both tables).  Exit 0
  within thresholds (diff-vs-self always passes), 1 on drift, 2 on
  usage/unminable inputs.

Both take ``--json`` for the machine-readable report.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any

from attackfl_tpu.profiler.mine import (
    DEFAULT_TOP_K,
    HOST_BOUND_THRESHOLD,
    mine_profile_dir,
)

# gate defaults shared with `ledger regress` (compare.DEFAULT_THRESHOLDS
# — duplicated as literals so this module imports nothing jax-adjacent)
DEFAULT_HOSTBOUND_RISE = 0.15
DEFAULT_SHARE_DRIFT = 0.15


def _resolve_dir(path: str) -> str:
    """A telemetry dir containing ``profile/`` resolves to it; a profile
    tree (or anything else) is mined as-is."""
    nested = os.path.join(path, "profile")
    return nested if os.path.isdir(nested) else path


def _fmt(value: Any, nd: int = 4) -> str:
    if isinstance(value, bool):
        return str(value)
    if isinstance(value, float):
        return f"{value:.{nd}g}"
    return "-" if value is None else str(value)


def _render(report: dict[str, Any], top_k: int) -> str:
    lines = [
        f"profile dir: {report['dir']}",
        f"traces: {report['traces']} "
        f"(ok={report['ok']} torn={report['torn']} "
        f"empty={report['empty']})",
    ]
    if report["status"] != "ok":
        lines.append(f"status: {report['status']} — nothing to attribute")
        return "\n".join(lines)
    books = report["books"]
    lines += [
        f"wall: {_fmt(report['wall_us'], 6)}us  "
        f"device busy: {_fmt(report['device_busy_us'], 6)}us  "
        f"op self: {_fmt(report['op_self_us'], 6)}us",
        f"books close: {books['close']} "
        "(op self <= busy <= wall x lanes)",
        f"host-bound fraction: {_fmt(report['host_bound_fraction'])} "
        f"-> {report['classification']} "
        f"(threshold {HOST_BOUND_THRESHOLD})",
    ]
    lines.append(f"{'op':<40}{'category':<13}{'self us':>12}"
                 f"{'share':>8}{'n':>6}  program")
    for row in report["ops"][:top_k]:
        lines.append(
            f"{row['name'][:39]:<40}{row['category']:<13}"
            f"{row['self_us']:>12.1f}{row['share']:>8.3f}"
            f"{row['count']:>6}  {row['program']}")
    lines.append("categories: " + "  ".join(
        f"{name}={_fmt(bucket['share'])}"
        for name, bucket in sorted(
            report["categories"].items(),
            key=lambda kv: -kv[1]["self_us"])))
    if report["gap_histogram"]:
        cells = []
        for bucket in report["gap_histogram"]:
            label = ("inf" if bucket["le_us"] is None
                     else f"{bucket['le_us']:g}us")
            cells.append(f"<={label}:{bucket['count']}")
        lines.append("dispatch gaps: " + "  ".join(cells))
    for window in report["windows"]:
        if window["status"] != "ok":
            lines.append(
                f"window {window['trace']}: {window['status']} "
                "(counted, not attributed)")
    return "\n".join(lines)


def _cmd_show(args: list[str]) -> int:
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    top_k = DEFAULT_TOP_K
    if "--top" in args:
        at = args.index("--top")
        if at + 1 >= len(args):
            print("--top needs a value", file=sys.stderr)
            return 2
        try:
            top_k = int(args[at + 1])
        except ValueError:
            print(f"--top needs an integer, got {args[at + 1]!r}",
                  file=sys.stderr)
            return 2
        del args[at:at + 2]
    if len(args) > 1:
        print("usage: attackfl-tpu hotspots show [DIR] [--json] [--top K]",
              file=sys.stderr)
        return 2
    path = _resolve_dir(args[0] if args else ".")
    report = mine_profile_dir(path, top_k=top_k)
    if as_json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(_render(report, top_k))
    if report["status"] != "ok" or not report["books"]["close"]:
        return 1
    return 0


def _shares(report: dict[str, Any]) -> dict[str, float]:
    return {row["name"]: row["share"] for row in report["top_ops"]}


def _cmd_diff(args: list[str]) -> int:
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    hostbound_rise = DEFAULT_HOSTBOUND_RISE
    share_drift = DEFAULT_SHARE_DRIFT
    for flag in ("--hostbound-rise", "--share-drift"):
        if flag in args:
            at = args.index(flag)
            if at + 1 >= len(args):
                print(f"{flag} needs a value", file=sys.stderr)
                return 2
            try:
                value = json.loads(args[at + 1])
            except ValueError:
                value = None
            if isinstance(value, bool) or \
                    not isinstance(value, (int, float)):
                print(f"{flag} needs a number, got {args[at + 1]!r}",
                      file=sys.stderr)
                return 2
            if flag == "--hostbound-rise":
                hostbound_rise = value + 0.0
            else:
                share_drift = value + 0.0
            del args[at:at + 2]
    if len(args) != 2:
        print("usage: attackfl-tpu hotspots diff A B [--json]\n"
              "  [--hostbound-rise X] [--share-drift X]",
              file=sys.stderr)
        return 2
    old = mine_profile_dir(_resolve_dir(args[0]))
    new = mine_profile_dir(_resolve_dir(args[1]))
    if old["status"] != "ok" or new["status"] != "ok":
        print(f"cannot diff: {args[0]} status={old['status']}, "
              f"{args[1]} status={new['status']}", file=sys.stderr)
        return 2
    old_hb = old["host_bound_fraction"] or 0.0
    new_hb = new["host_bound_fraction"] or 0.0
    violations: list[dict[str, Any]] = []
    if (new_hb - old_hb) > hostbound_rise:
        violations.append({
            "check": "host_bound_fraction",
            "old": old_hb, "new": new_hb,
            "rise": round(new_hb - old_hb, 4),
            "threshold": hostbound_rise})
    old_shares, new_shares = _shares(old), _shares(new)
    drifts = {}
    for name in sorted(set(old_shares) & set(new_shares)):
        drift = round(new_shares[name] - old_shares[name], 4)
        drifts[name] = {"old": old_shares[name],
                        "new": new_shares[name], "drift": drift}
        if abs(drift) > share_drift:
            violations.append({
                "check": f"op_share:{name}",
                "old": old_shares[name], "new": new_shares[name],
                "drift": drift, "threshold": share_drift})
    result = {
        "ok": not violations,
        "violations": violations,
        "host_bound_fraction": {"old": old_hb, "new": new_hb},
        "op_shares": drifts,
        "old_dir": old["dir"], "new_dir": new["dir"],
    }
    if as_json:
        print(json.dumps(result, indent=1, sort_keys=True))
    else:
        print(f"hostbound: {_fmt(old_hb)} -> {_fmt(new_hb)} "
              f"(rise threshold {hostbound_rise})")
        for name, row in drifts.items():
            print(f"  {name}: share {_fmt(row['old'])} -> "
                  f"{_fmt(row['new'])} (drift {_fmt(row['drift'])})")
        if violations:
            for violation in violations:
                moved = violation.get("rise", violation.get("drift"))
                print(f"DRIFT {violation['check']}: {_fmt(moved)} "
                      f"past {_fmt(violation['threshold'])}")
        else:
            print("ok: within thresholds")
    return 0 if not violations else 1


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args or args[0] in ("-h", "--help"):
        print(__doc__, end="")
        return 0 if args else 2
    command = args[0]
    if command == "show":
        return _cmd_show(args[1:])
    if command == "diff":
        return _cmd_diff(args[1:])
    print(f"unknown hotspots subcommand {command!r} "
          "(expected show|diff)", file=sys.stderr)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
