"""Structured profiling windows at the executors' dispatch seams.

The jax-facing half of the hotspot observatory: :class:`HotspotCapture`
wraps the PR-2 ``--profile-rounds`` machinery (``jax.profiler``
start/stop around a 1-based inclusive round window) and hardens it:

* **fail-open** — a missing/unwritable profile directory or a raising
  ``jax.profiler.start_trace`` degrades to a schema-v14 ``hotspot``
  event with ``status: unavailable`` plus a counter; the run itself is
  never affected, and the window is spent so a broken backend is asked
  exactly once, not every round;
* **structured close** — each window that does open is stopped at the
  seam, its new ``*.trace.json.gz`` artifact located and mined inline
  (:mod:`attackfl_tpu.profiler.mine` — stdlib-only, microseconds of
  work), and emitted as one ``hotspot`` event per artifact carrying the
  trace path, the window rounds, the dispatch program name
  (sync / fused / pipelined / matrix) and the compact attribution
  summary (top ops, category shares, host-bound fraction, books);
* **live surfacing** — the summary is pushed to the run monitor when
  one is attached (``/hotspots`` route + the
  ``attackfl_host_bound_fraction`` gauge).

Legacy ``profile`` start/stop/start_failed events keep flowing for the
old tooling; the ``hotspot`` record is the new, mined contract.
"""

from __future__ import annotations

import os
from typing import Any

from attackfl_tpu.profiler.mine import (
    compact_summary,
    find_traces,
    mine_trace,
)
from attackfl_tpu.telemetry.console import print_with_color


def _short(error: BaseException) -> str:
    return f"{type(error).__name__}: {error}"[:300]


class HotspotCapture:
    """One profiling window per run, opened/closed at dispatch seams.

    ``window`` is the parsed ``(first, last)`` inclusive round range
    (from ``telemetry.hotspots`` or, compatibly, ``profile_rounds``) or
    None for no profiling.  The engine's ``_maybe_start_profile`` /
    ``_maybe_stop_profile`` delegate here 1:1.
    """

    def __init__(self, telemetry: Any,
                 window: tuple[int, int] | None,
                 monitor: Any = None) -> None:
        self.telemetry = telemetry
        self.window = window if telemetry.enabled else None
        self.monitor = monitor
        self._active = False
        self._program = ""
        self._first = 0
        self._last = 0
        self._path = ""
        self._seen: frozenset[str] = frozenset()

    @property
    def profiling(self) -> bool:
        return self._active

    # -- open ----------------------------------------------------------

    def maybe_start(self, first_round: int,
                    last_round: int | None = None,
                    program: str = "sync") -> None:
        """Open the trace when [first_round, last_round] overlaps the
        window.  Fused chunks pass their whole round range (the chunk is
        one dispatch; profiling starts at its boundary).  ``program``
        names the dispatch seam for the window's ``hotspot`` event."""
        if self.window is None or self._active:
            return
        start, stop = self.window
        last_round = first_round if last_round is None else last_round
        if last_round < start or first_round > stop:
            return
        path = os.path.join(self.telemetry.base_dir or ".", "profile")
        # Preflight the artifact directory BEFORE asking the backend —
        # an unwritable disk degrades the window, never the run.
        try:
            os.makedirs(path, exist_ok=True)
            probe = os.path.join(path, ".hotspot_writable")
            with open(probe, "w"):
                pass
            os.remove(probe)
        except OSError as e:
            self._degrade(path, first_round, last_round, program,
                          f"profile dir unwritable ({_short(e)})")
            return
        self._seen = frozenset(find_traces(path))
        try:
            import jax  # deferred: mine/CLI paths never pay this

            jax.profiler.start_trace(path)
        except Exception as e:  # noqa: BLE001 — profiling is best-effort
            self._degrade(path, first_round, last_round, program,
                          f"start_trace failed ({_short(e)})")
            return
        self._active = True
        self._program = program
        self._first = first_round
        self._last = max(last_round, first_round)
        self._path = path
        self.telemetry.events.emit("profile", action="start", path=path,
                                   round=first_round)

    def _degrade(self, path: str, first: int, last: int, program: str,
                 reason: str) -> None:
        """Fail-open: one loud unavailable record + counter, window
        spent (no retry storm), run untouched."""
        self.telemetry.events.emit(
            "profile", action="start_failed", path=path, error=reason)
        self.telemetry.events.emit(
            "hotspot", status="unavailable", program=program,
            round_first=first, round_last=max(last, first), reason=reason)
        self.telemetry.counters.inc("hotspot_windows_unavailable")
        print_with_color(
            f"[hotspots] window unavailable: {reason}", "yellow")
        self.window = None

    # -- close ---------------------------------------------------------

    def maybe_stop(self, completed_rounds: int = 0,
                   force: bool = False) -> None:
        """Close the trace once the window's last round completed (or on
        ``force`` at run end), mine the artifact(s) and emit one
        ``hotspot`` event per trace file."""
        if not self._active:
            return
        if not force and completed_rounds < self.window[1]:
            return
        self._active = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            reason = f"stop_trace failed ({_short(e)})"
            self.telemetry.events.emit(
                "profile", action="stop_failed", error=_short(e))
            self.telemetry.events.emit(
                "hotspot", status="unavailable", program=self._program,
                round_first=self._first, round_last=self._last,
                reason=reason)
            self.telemetry.counters.inc("hotspot_windows_unavailable")
            return
        self.telemetry.events.emit("profile", action="stop",
                                   round=completed_rounds)
        # the trace stayed open until here: the window's true coverage
        # runs to the last completed round (the sync seam starts with a
        # single round number but profiles through the window's end)
        if completed_rounds > self._last:
            self._last = int(completed_rounds)
        try:
            self._emit_window()
        except Exception as e:  # noqa: BLE001 — mining must not kill a run
            self.telemetry.events.emit(
                "hotspot", status="torn", program=self._program,
                round_first=self._first, round_last=self._last,
                reason=f"mining failed ({_short(e)})")
            self.telemetry.counters.inc("hotspot_windows_torn")

    def _emit_window(self) -> None:
        new = [p for p in find_traces(self._path)
               if p not in self._seen]
        if not new:
            # the backend stopped cleanly but wrote nothing — counted,
            # not hidden
            self.telemetry.events.emit(
                "hotspot", status="empty", program=self._program,
                round_first=self._first, round_last=self._last,
                reason="no trace artifact written")
            self.telemetry.counters.inc("hotspot_windows_empty")
            return
        base = self.telemetry.base_dir or "."
        for path in new:
            report = mine_trace(path)
            status = report["status"]
            summary = compact_summary(report)
            self.telemetry.events.emit(
                "hotspot", status=status, program=self._program,
                round_first=self._first, round_last=self._last,
                trace=os.path.relpath(path, base), **summary)
            self.telemetry.counters.inc(f"hotspot_windows_{status}")
            if status == "ok":
                fraction = report.get("host_bound_fraction")
                top = summary["top_ops"][0]["name"] \
                    if summary["top_ops"] else "-"
                print_with_color(
                    f"[hotspots] {self._program} rounds "
                    f"{self._first}-{self._last}: top={top} "
                    f"hostbound={fraction} "
                    f"({report.get('classification')})", "cyan")
                if self.monitor is not None:
                    set_hotspots = getattr(self.monitor, "set_hotspots",
                                           None)
                    if set_hotspots is not None:
                        set_hotspots({
                            "program": self._program,
                            "round_first": self._first,
                            "round_last": self._last,
                            **summary})
