"""CLI launchers with the reference's process UX, minus the broker.

The reference is started as ``python server.py`` (waits for N clients on
RabbitMQ) plus N ``python client.py [--attack ...]`` processes
(README.md:91-143).  Here the simulation is in-process, but the same
workflow is preserved through a file-based rendezvous: each ``client.py``
invocation writes a registration (client id + attack flags) into
``.registrations/`` and exits; ``server.py`` collects registrations until
``server.clients`` are present (its registration wait, server.py:231) and
then runs the whole federation on the TPU.  ``server.py --no-wait`` skips
the rendezvous and reads attackers from the config's ``attack-clients``
section instead.

``main`` is the ``attackfl-tpu`` umbrella entry point
(``python -m attackfl_tpu`` / the repo-root ``attackfl-tpu`` script):

* ``attackfl-tpu run [--config ...] [--rounds N]`` — run the federation
  with attackers from the config (no rendezvous), telemetry on by default;
* ``attackfl-tpu server`` / ``attackfl-tpu client`` — the rendezvous pair;
* ``attackfl-tpu metrics <dir>`` — summarize a run's ``events.jsonl``
  (``--merge`` for cross-host skew, ``--forensics`` for defense TPR/FPR);
* ``attackfl-tpu watch`` — poll a live run's monitor endpoint
  (``--monitor`` on run/server) and print each new round as it lands;
* ``attackfl-tpu ledger`` — the persistent cross-run store:
  list/show/compare records, ``regress`` = the CI gate, ``import`` =
  backfill committed BENCH_*.json artifacts;
* ``attackfl-tpu serve`` — the resilient run service (ISSUE 8): a
  persistent daemon with a durable job queue, supervised workers,
  admission control and crash recovery;
* ``attackfl-tpu job`` — the jax-free service client
  (submit/list/status/cancel/wait over HTTP).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
import uuid

from attackfl_tpu.config import AttackSpec, Config, load_config
from attackfl_tpu.telemetry import print_with_color

REG_DIR = ".registrations"


def _registration_dir(base: str) -> str:
    path = os.path.join(base, REG_DIR)
    os.makedirs(path, exist_ok=True)
    return path


def client_main(argv=None) -> None:
    """Reference client flags (client.py:19-38) -> registration file."""
    parser = argparse.ArgumentParser(description="attackfl_tpu client launcher")
    parser.add_argument("--config", type=str, default="config.yaml")
    parser.add_argument("--device", type=str, required=False, help="accepted for parity; unused")
    # accepts bare `--attack` and the reference's `--attack True` form
    # (client.py:21 uses argparse type=bool, which would treat ANY string,
    # even "False", as truthy — parse the text instead)
    parser.add_argument(
        "--attack", nargs="?", const=True, default=False,
        type=lambda s: str(s).strip().lower() in ("true", "1", "yes"),
    )
    parser.add_argument("--attack_mode", type=str,
                        choices=["Random", "Min-Max", "Min-Sum", "Opt-Fang", "LIE"])
    parser.add_argument("--attack_round", type=int)
    parser.add_argument("--attack_args", type=float, nargs="+")
    args = parser.parse_args(argv)

    if args.attack and not args.attack_mode:
        print("Error: --attack_mode is required when --attack is True.")
        sys.exit(1)
    if args.attack and not args.attack_round:
        print("Error: --attack_round is required when --attack is True.")
        sys.exit(1)

    client_id = str(uuid.uuid4())
    reg = {
        "client_id": client_id,
        "attack": bool(args.attack),
        "attack_mode": args.attack_mode,
        "attack_round": args.attack_round,
        "attack_args": args.attack_args or [],
    }
    reg_dir = _registration_dir(os.path.dirname(os.path.abspath(args.config)))
    path = os.path.join(reg_dir, f"{client_id}.json")
    tmp = path + ".tmp"  # atomic publish: the server polls this directory
    with open(tmp, "w") as fh:
        json.dump(reg, fh)
    os.replace(tmp, path)
    print_with_color("[>>>] Client sending registration message to server...", "red")
    print(f"Client ID: {client_id}")
    print(f"Attack: {reg['attack']}, Mode: {reg['attack_mode']}")


def _collect_registrations(cfg: Config, base: str, timeout: float = 600.0) -> list[dict]:
    reg_dir = _registration_dir(base)
    print_with_color(f"Server is waiting for {cfg.total_clients} clients.", "green")
    deadline = time.time() + timeout
    while True:
        regs = []
        for name in sorted(os.listdir(reg_dir)):
            if name.endswith(".json"):
                try:
                    with open(os.path.join(reg_dir, name)) as fh:
                        regs.append(json.load(fh))
                except (json.JSONDecodeError, OSError):
                    continue  # mid-write or vanished; retry next poll
        if len(regs) >= cfg.total_clients:
            for name in os.listdir(reg_dir):  # queue hygiene, cf. delete_old_queues
                os.unlink(os.path.join(reg_dir, name))
            return regs[: cfg.total_clients]
        if time.time() > deadline:
            raise TimeoutError(
                f"only {len(regs)}/{cfg.total_clients} clients registered"
            )
        time.sleep(0.5)


def _attacks_from_registrations(regs: list[dict]) -> tuple[AttackSpec, ...]:
    specs = []
    for i, reg in enumerate(regs):
        if reg.get("attack"):
            specs.append(AttackSpec(
                mode=reg["attack_mode"],
                client_ids=(i,),
                attack_round=int(reg["attack_round"] or 1),
                args=tuple(reg.get("attack_args") or []),
            ))
    return tuple(specs)


def server_main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="Federated learning framework with controller."
    )
    parser.add_argument("--config", type=str, default="config.yaml")
    parser.add_argument("--device", type=str, required=False,
                        help="jax platform override (tpu/cpu); default = auto")
    parser.add_argument("--no-wait", action="store_true",
                        help="skip client rendezvous; attackers come from config")
    parser.add_argument("--rounds", type=int, default=None, help="override num-round")
    # --- round-pipeline / persistence overrides (config: server: section) ---
    parser.add_argument("--pipeline", action="store_true",
                        help="pipelined round executor: later rounds "
                             "dispatch before round N's success flag "
                             "materializes (server.pipeline)")
    parser.add_argument("--pipeline-depth", type=str, default=None,
                        metavar="K",
                        help="pipeline depth: K rounds in flight beyond "
                             "the one being resolved (0 = no overlap, "
                             "'auto' = tune from the ledger's measured "
                             "host/device ratio for this config; "
                             "server.pipeline-depth).  Implies --pipeline")
    parser.add_argument("--checkpoint-async", action="store_true",
                        help="background checkpoint writer: serialize + "
                             "write + fsync off the round loop "
                             "(server.checkpoint-async)")
    parser.add_argument("--resume", action="store_true",
                        help="continue from the checkpoint directory's "
                             "manifest.json: newest valid entry wins, "
                             "torn/truncated entries fall back to the "
                             "previous good one, round numbering continues "
                             "(server.resume)")
    parser.add_argument("--inject-faults", type=str, default=None,
                        metavar="PLAN",
                        help="deterministic fault plan, e.g. "
                             "'nan_storm@3:clients=0,1;ckpt_write_error@2:"
                             "count=2;writer_death@4;monitor_stall@5' "
                             "(kinds: nan_storm dropout ckpt_write_error "
                             "ckpt_torn writer_death monitor_stall; "
                             "config `faults:` section takes the same "
                             "entries as mappings)")
    parser.add_argument("--validation-every", type=int, default=None,
                        metavar="K",
                        help="validate every K-th broadcast "
                             "(server.validation-every; default 1)")
    parser.add_argument("--validation-async", action="store_true",
                        help="validate round N while round N+1 trains; "
                             "results land in telemetry, no acceptance "
                             "gate (server.validation-async)")
    parser.add_argument("--compile-cache", type=str, default=None,
                        metavar="DIR",
                        help="JAX persistent compilation cache directory "
                             "(compile-cache-dir; ATTACKFL_COMPILE_CACHE "
                             "env var also works)")
    # --- observability overrides (config: telemetry: section) ---
    parser.add_argument("--monitor", action="store_true",
                        help="serve /healthz /metrics /last-round + stall "
                             "watchdog (telemetry.monitor)")
    parser.add_argument("--monitor-port", type=int, default=None,
                        help="monitor port (0 = ephemeral, printed at start)")
    parser.add_argument("--profile-rounds", type=str, default=None,
                        metavar="A:B",
                        help="wrap rounds A..B in jax.profiler device "
                             "tracing (output: <telemetry dir>/profile)")
    parser.add_argument("--hotspots", type=str, default=None,
                        metavar="A:B",
                        help="hotspot observatory window (supersedes "
                             "--profile-rounds): profile rounds A..B at "
                             "the dispatch seam and mine the trace into "
                             "a schema-v14 `hotspot` event (op-level "
                             "attribution + dispatch-gap diagnosis; "
                             "render with `attackfl-tpu hotspots show`)")
    parser.add_argument("--numerics", action="store_true",
                        help="in-graph numerics engine: device-side "
                             "per-round metric rows (update-norm "
                             "distributions, attack separation, drift, "
                             "non-finite provenance) drained late as "
                             "schema-v3 metric events "
                             "(telemetry.numerics; report with "
                             "`attackfl-tpu metrics --numerics`)")
    # --- multi-host (DCN) scale-out: one process per host, same command
    # with a distinct --process-id (parallel/mesh.distributed_init) ---
    parser.add_argument("--coordinator", type=str, default=None,
                        help="host:port of process 0; enables jax.distributed")
    parser.add_argument("--num-processes", type=int, default=1)
    parser.add_argument("--process-id", type=int, default=0)
    args = parser.parse_args(argv)

    if args.device:
        import jax
        device = args.device
        if device == "tpu":
            # "tpu" is the user-facing name (reference CLI parity:
            # /root/reference/server.py:38), but a TPU plugin may register
            # under another platform name — this image's tunnel registers
            # as "axon", and forcing jax_platforms="tpu" would fail
            # backend init on exactly the hardware the flag targets.
            from attackfl_tpu.parallel.mesh import resolve_tpu_platform
            device = resolve_tpu_platform()
        jax.config.update("jax_platforms", device)

    if args.coordinator:
        if not args.no_wait:
            # the file rendezvous is host-local; with N hosts the attacker
            # assignment must come from the shared config so every process
            # builds the identical SPMD program
            print("Error: --coordinator requires --no-wait "
                  "(declare attackers in config's attack-clients).")
            sys.exit(1)
        from attackfl_tpu.parallel.mesh import distributed_init
        distributed_init(args.coordinator, args.num_processes, args.process_id)

    cfg = load_config(args.config)
    overrides = {}
    if args.monitor:
        overrides["monitor"] = True
    if args.monitor_port is not None:
        overrides["monitor"] = True
        overrides["monitor_port"] = args.monitor_port
    if args.profile_rounds is not None:
        overrides["profile_rounds"] = args.profile_rounds
    if args.hotspots is not None:
        overrides["hotspots"] = args.hotspots
    if args.numerics:
        overrides["numerics"] = True
    if overrides:
        cfg = cfg.replace(
            telemetry=dataclasses.replace(cfg.telemetry, **overrides))
    perf_overrides = {}
    if args.pipeline:
        perf_overrides["pipeline"] = True
    if args.pipeline_depth is not None:
        # a depth without --pipeline implies the pipelined executor; the
        # Config normalizes/validates the value ("auto" or 0..max)
        perf_overrides["pipeline"] = True
        perf_overrides["pipeline_depth"] = args.pipeline_depth
    if args.checkpoint_async:
        perf_overrides["checkpoint_async"] = True
    if args.resume:
        perf_overrides["resume"] = True
    if args.inject_faults is not None:
        from attackfl_tpu.faults.plan import parse_fault_plan

        perf_overrides["faults"] = parse_fault_plan(args.inject_faults)
    if args.validation_every is not None:
        perf_overrides["validation_every"] = args.validation_every
    if args.validation_async:
        perf_overrides["validation_async"] = True
    if args.compile_cache is not None:
        perf_overrides["compile_cache_dir"] = args.compile_cache
    if perf_overrides:
        cfg = cfg.replace(**perf_overrides)
    base = os.path.dirname(os.path.abspath(args.config))

    if not args.no_wait:
        regs = _collect_registrations(cfg, base)
        print_with_color("All clients are connected. Sending notifications.", "green")
        cfg = cfg.replace(attacks=_attacks_from_registrations(regs))

    from attackfl_tpu.training.engine import Simulator

    sim = Simulator(cfg, use_mesh=True)
    try:
        state, history = sim.run(num_rounds=args.rounds)
    finally:
        if sim.telemetry.enabled:
            print_with_color(
                f"Telemetry: {sim.telemetry.events.path} "
                f"(summarize with `attackfl-tpu metrics`), trace: "
                f"{sim.telemetry.tracer.path} (open in https://ui.perfetto.dev)",
                "cyan")
        sim.close()
    ok_rounds = sum(1 for h in history if h["ok"])
    print_with_color(f"Finished: {ok_rounds} successful rounds.", "green")


def run_main(argv=None) -> None:
    """``attackfl-tpu run``: the no-rendezvous launcher (attackers come
    from the config's ``attack-clients`` section)."""
    args = list(sys.argv[1:] if argv is None else argv)
    server_main(["--no-wait", *args])


def metrics_main(argv=None) -> int:
    """``attackfl-tpu metrics``: summarize a run's events.jsonl
    (``--merge`` for multi-host skew, ``--forensics`` for defense
    TPR/FPR)."""
    from attackfl_tpu.telemetry.summary import main as summary_main

    return summary_main(list(sys.argv[1:] if argv is None else argv))


def _http_get_json(url: str, timeout: float = 5.0):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read().decode() or "{}")


def _http_get_text(url: str, timeout: float = 5.0) -> tuple[int, str]:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def _parse_prom(text: str) -> dict:
    """Minimal Prometheus text-exposition parser: ``{name{labels} ->
    float}`` with the raw label string kept as part of the key (enough
    to read back the gauges our own ``metrics_text`` writes)."""
    gauges: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            gauges[name] = float(value)
        except ValueError:
            continue
    return gauges


def _watch_backoff(failures: int, interval: float, cap: float = 60.0) -> float:
    """Capped exponential backoff for unreachable monitors: the normal
    poll period for the first miss, doubling per consecutive miss, never
    above ``cap``.  A service restart (seconds of connection-refused)
    costs a few quick retries instead of a crash or a minute-long gap."""
    return min(interval * (2 ** max(failures - 1, 0)), cap)


def _watch_schedule(base: str, args) -> int:
    """``attackfl-tpu watch --schedule``: poll a run service's
    ``/schedule`` endpoint (ISSUE 15) — one line per poll with queue
    depth / predicted backlog / totals, plus a per-job table whenever
    the queue composition changes.  Unreachable services get the same
    capped-backoff forgiveness as the monitor poller."""
    import http.client
    import urllib.error

    failures = 0
    last_shape: tuple | None = None
    while True:
        try:
            _, snap = _http_get_json(base + "/schedule")
        except urllib.error.HTTPError as e:
            print(f"[watch] /schedule -> http {e.code} "
                  "(scheduler disabled?)", file=sys.stderr)
            return 2
        except (urllib.error.URLError, http.client.HTTPException, OSError,
                ValueError) as e:
            failures += 1
            delay = _watch_backoff(failures, args.interval,
                                   args.max_backoff)
            print(f"[watch] {base} unreachable: {e} "
                  f"(retry {failures} in {delay:.1f}s)", file=sys.stderr)
            if args.once:
                return 2
            time.sleep(delay)
            continue
        failures = 0
        jobs = snap.get("jobs") or []
        print(f"[watch] sched queue={snap.get('queue_depth')} "
              f"backlog={snap.get('backlog_seconds', 0):.1f}s "
              f"max_wait={snap.get('max_wait_seconds', 0):.1f}s "
              f"preempted={snap.get('preempted_total')} "
              f"shed={snap.get('shed_total')} "
              f"broken={snap.get('circuit_broken_total')}", flush=True)
        shape = tuple((j.get("job_id"), j.get("state")) for j in jobs)
        if jobs and shape != last_shape:
            last_shape = shape
            for job in jobs:
                print(f"[watch]   {job.get('job_id')} "
                      f"{job.get('state'):<7} {job.get('priority'):<6} "
                      f"eff={job.get('effective_priority')} "
                      f"rem~{job.get('predicted_remaining_seconds')}s "
                      f"preempts={job.get('preemptions')} "
                      f"wait={job.get('wait_seconds')}s", flush=True)
        if args.once:
            return 0
        time.sleep(args.interval)


def _watch_fleet(base: str, args) -> int:
    """``attackfl-tpu watch --fleet``: poll a run service's Prometheus
    ``/metrics`` endpoint (ISSUE 16) and render the scheduler + SLO
    gauges one line per poll — queue depth, running jobs, per-priority
    p95 waits, preemption/shed rates.  Same capped-backoff forgiveness
    as every other watcher."""
    import http.client
    import urllib.error

    failures = 0
    while True:
        try:
            _, text = _http_get_text(base + "/metrics")
        except urllib.error.HTTPError as e:
            print(f"[watch] /metrics -> http {e.code}", file=sys.stderr)
            return 2
        except (urllib.error.URLError, http.client.HTTPException, OSError,
                ValueError) as e:
            failures += 1
            delay = _watch_backoff(failures, args.interval,
                                   args.max_backoff)
            print(f"[watch] {base} unreachable: {e} "
                  f"(retry {failures} in {delay:.1f}s)", file=sys.stderr)
            if args.once:
                return 2
            time.sleep(delay)
            continue
        failures = 0
        gauges = _parse_prom(text)

        def g(name: str, default: float = 0.0) -> float:
            return gauges.get(name, default)

        line = (f"[watch] fleet queue={g('attackfl_sched_queue_depth'):.0f} "
                f"running={g('attackfl_sched_running_jobs'):.0f} "
                f"backlog={g('attackfl_sched_backlog_seconds'):.1f}s "
                f"preempted={g('attackfl_sched_preempted_total'):.0f} "
                f"shed={g('attackfl_sched_shed_total'):.0f}")
        slo_parts = []
        for name, value in sorted(gauges.items()):
            if name.startswith("attackfl_slo_queue_wait_p95_seconds{"):
                prio = name.split('priority="', 1)[-1].rstrip('"}')
                slo_parts.append(f"p95[{prio}]={value:.1f}s")
        if "attackfl_slo_preemption_rate" in gauges:
            slo_parts.append(
                f"preempt-rate={gauges['attackfl_slo_preemption_rate']}")
        if "attackfl_slo_shed_rate" in gauges:
            slo_parts.append(
                f"shed-rate={gauges['attackfl_slo_shed_rate']}")
        margin = gauges.get("attackfl_slo_starvation_bound_margin_seconds")
        if margin is not None:
            slo_parts.append(f"starv-margin={margin:.1f}s")
        if slo_parts:
            line += "  slo: " + " ".join(slo_parts)
        print(line, flush=True)
        if args.once:
            return 0
        time.sleep(args.interval)


def watch_main(argv=None) -> int:
    """``attackfl-tpu watch``: thin poller of a live run's monitor
    endpoint (``--monitor`` on run/server) — prints each new round as it
    completes and shouts when ``/healthz`` flips to stalled.  This
    replaces the retired ``scripts/tpu_watch.sh`` loop: liveness now comes
    from the run itself, not from out-of-process probe jobs.

    Connection-refused / connection-reset (a run-service restart, a
    monitor rebinding) is survived with capped exponential backoff — the
    poller retries forever rather than crashing mid-watch."""
    import http.client
    import urllib.error

    parser = argparse.ArgumentParser(
        prog="attackfl-tpu watch",
        description="Poll a running simulation's monitor endpoint.")
    parser.add_argument("url", nargs="?", default="http://127.0.0.1:8780",
                        help="monitor base URL (printed at run start)")
    parser.add_argument("--interval", type=float, default=5.0,
                        help="poll period in seconds (default 5)")
    parser.add_argument("--max-backoff", type=float, default=60.0,
                        help="cap for the unreachable-retry backoff "
                             "(default 60s)")
    parser.add_argument("--once", action="store_true",
                        help="single poll: exit 0 healthy, 1 stalled, "
                             "2 unreachable")
    parser.add_argument("--schedule", action="store_true",
                        help="watch a run SERVICE's /schedule endpoint "
                             "instead: queue depth, backlog vs horizon, "
                             "per-job effective priorities and "
                             "preemption/wait accounting")
    parser.add_argument("--fleet", action="store_true",
                        help="watch a run SERVICE's Prometheus /metrics "
                             "endpoint instead: scheduler gauges + the "
                             "fleet SLO gauges (per-priority p95 queue "
                             "wait, preemption/shed rates, starvation "
                             "margin)")
    args = parser.parse_args(argv)
    base = args.url.rstrip("/")
    if args.schedule:
        return _watch_schedule(base, args)
    if args.fleet:
        return _watch_fleet(base, args)

    seen_round = object()
    stalled = False
    degraded = False
    failures = 0
    while True:
        try:
            code, health = _http_get_json(base + "/healthz")
        except urllib.error.HTTPError as e:
            code, health = e.code, {"status": f"http {e.code}"}
        except (urllib.error.URLError, http.client.HTTPException, OSError,
                ValueError) as e:
            # connection refused/reset — the service is restarting or the
            # monitor is rebinding; back off (capped) and keep polling
            failures += 1
            delay = _watch_backoff(failures, args.interval,
                                   args.max_backoff)
            print(f"[watch] {base} unreachable: {e} "
                  f"(retry {failures} in {delay:.1f}s)", file=sys.stderr)
            if args.once:
                return 2
            time.sleep(delay)
            continue
        failures = 0
        try:
            _, last = _http_get_json(base + "/last-round")
        except Exception:  # noqa: BLE001 — health is the primary signal
            last = {}
        # cost observatory (ISSUE 11): live roofline estimate from
        # /programs — printed next to each round so a collapsing
        # utilization is visible as it happens, not post-mortem
        try:
            _, cost = _http_get_json(base + "/programs")
        except Exception:  # noqa: BLE001 — optional endpoint
            cost = {}
        utilization = cost.get("utilization") or {}
        # hotspot observatory (ISSUE 19): latest mined window from
        # /hotspots — hostbound= on the round line makes a dispatch-
        # bound drift visible live
        try:
            _, hot = _http_get_json(base + "/hotspots")
        except Exception:  # noqa: BLE001 — optional endpoint
            hot = {}
        hot_windows = hot.get("windows") or {}
        if code == 503:
            if not stalled:
                print_with_color(f"[watch] STALL detected: {health}", "red")
            stalled = True
        else:
            stalled = False
        # degraded ≠ stalled ≠ healthy: the pipelined executor demoted to
        # depth-0 after consecutive rollbacks — progressing, but flagged
        depth = last.get("pipeline_depth")
        depth_text = (f" (depth {depth}"
                      + (f", configured {health['configured_depth']}"
                         if isinstance(health.get("configured_depth"), int)
                         else "") + ")") \
            if isinstance(depth, int) else ""
        if health.get("status") == "degraded":
            if not degraded:
                print_with_color(
                    f"[watch] executor DEGRADED{depth_text}: {health}",
                    "yellow")
            degraded = True
        elif degraded and code != 503:
            print_with_color(
                f"[watch] executor re-promoted (healthy{depth_text})",
                "cyan")
            degraded = False
        rnd = last.get("round")
        if last and rnd != seen_round:
            seen_round = rnd
            keys = [k for k in ("roc_auc", "accuracy", "nll", "train_loss")
                    if isinstance(last.get(k), (int, float))]
            msg = " ".join(f"{k}={last[k]:.4f}" for k in keys)
            # latest drained numerics gauges (--numerics runs): shown next
            # to the round line so a drifting p95 / a non-finite count / a
            # collapsing attack margin is visible live
            numerics = last.get("numerics") or {}
            gauges = [(short, numerics[key]) for short, key in
                      (("unorm_p95", "update_norm_all_p95"),
                       ("nonfinite", "nonfinite_count"),
                       ("sep", "sep_margin"))
                      if isinstance(numerics.get(key), (int, float))]
            if gauges:
                msg += ("  [" + " ".join(f"{k}={v:.4g}" for k, v in gauges)
                        + "]")
            if isinstance(depth, int):
                msg += f" depth={depth}"
            mesh = last.get("mesh_devices")
            if isinstance(mesh, int):
                # mesh shape on the round line (ISSUE 12): strategy
                # suffixed when the monitor knows it (sm = shard_map
                # collectives, gspmd = partitioned single program)
                strategy = last.get("mesh_strategy")
                msg += f" mesh={mesh}" + (
                    "sm" if strategy == "shard_map"
                    else ("g" if strategy == "gspmd" else ""))
            fraction = utilization.get("utilization_flops")
            achieved = utilization.get("achieved_flops_per_sec")
            if isinstance(fraction, (int, float)):
                msg += f" util={100 * fraction:.1f}%"
            elif isinstance(achieved, (int, float)):
                # no peak spec for this device kind (CPU): achieved-only
                msg += f" flops/s={achieved:.3g}"
            hostbound = [w.get("host_bound_fraction")
                         for w in hot_windows.values()
                         if isinstance(w.get("host_bound_fraction"),
                                       (int, float))]
            if hostbound:
                msg += f" hostbound={max(hostbound):.3f}"
            print(f"[watch] round {rnd} ok={last.get('ok')} "
                  f"{msg}".rstrip(), flush=True)
        if args.once:
            return 1 if stalled else 0
        time.sleep(args.interval)


def audit_main(argv=None) -> int:
    """``attackfl-tpu audit``: the static-analysis subsystem — AST rules
    (host-sync, donation-after-use, retrace-hazard, emit-kind), committed
    event-artifact schema validation, the jaxpr/HLO program auditor
    (sync-freedom, donation aliasing, dtype discipline) over the three
    round executors, and the transform-safety auditor (``--grad``):
    grad/double-backward programs of the post-defense damage objective
    plus the per-defense differentiability dataflow table.  ``--json``
    for the machine-readable report."""
    from attackfl_tpu.analysis.cli import audit_main as _audit_main

    return _audit_main(list(sys.argv[1:] if argv is None else argv))


def serve_main(argv=None) -> int:
    """``attackfl-tpu serve``: the resilient run service (ISSUE 8) — a
    persistent daemon with a durable on-disk job queue, supervised
    workers (restart-with-backoff, retry budget), admission control, an
    HTTP control plane (submit/status/cancel + aggregate /healthz) and
    crash recovery (kill -9 → queue replay → checkpoint resume).
    SIGTERM drains gracefully: in-flight rounds finish, the rest
    requeues."""
    from attackfl_tpu.service.cli import serve_main as _serve_main

    return _serve_main(list(sys.argv[1:] if argv is None else argv))


def job_main(argv=None) -> int:
    """``attackfl-tpu job``: jax-free run-service client —
    submit/list/status/cancel/wait against a live ``serve`` daemon."""
    from attackfl_tpu.service.cli import job_main as _job_main

    return _job_main(list(sys.argv[1:] if argv is None else argv))


def matrix_main(argv=None) -> int:
    """``attackfl-tpu matrix``: the scenario-matrix engine (ISSUE 9) —
    ``run`` executes a full (attack × defense × seed) grid as ONE
    compiled device program (host-side defenses fall back per-cell),
    ``status`` renders the sweep's per-cell ledger records."""
    from attackfl_tpu.matrix.cli import main as _matrix_main

    return _matrix_main(list(sys.argv[1:] if argv is None else argv))


def cost_main(argv=None) -> int:
    """``attackfl-tpu cost``: the predictive cost model (ISSUE 11) —
    ``estimate`` prices a config or matrix grid WITHOUT running it
    (fingerprint-peer ledger records, flops/bytes regression fallback),
    ``validate`` replays the predictor leave-one-out over a ledger
    corpus and gates on the median error factor (default 2x)."""
    from attackfl_tpu.costmodel.cli import main as _cost_main

    return _cost_main(list(sys.argv[1:] if argv is None else argv))


def fleet_main(argv=None) -> int:
    """``attackfl-tpu fleet``: the fleet observatory over a service
    spool — ``report`` prints the SLO gauges + the per-tenant
    device-time ledger (books must close: busy + idle = wall x slots),
    ``trace`` writes the Perfetto-loadable cross-job trace.  Jax-free,
    like ``metrics`` and ``ledger``."""
    from attackfl_tpu.telemetry.fleet import main as _fleet_main

    return _fleet_main(list(sys.argv[1:] if argv is None else argv))


def science_main(argv=None) -> int:
    """``attackfl-tpu science``: the scenario science observatory
    (ISSUE 17) — ``leaderboard`` ranks defenses by attack damage
    (clean-baseline quality minus cell quality, bootstrap-over-seeds
    CIs), ``report`` writes the auditable SCOREBOARD.json, ``diff
    --gate`` is the rank-stability CI hook (exit 1 when a ranking flips
    or damage regresses beyond the inter-seed noise floor).  Jax-free,
    like ``ledger``."""
    from attackfl_tpu.science.cli import main as _science_main

    return _science_main(list(sys.argv[1:] if argv is None else argv))


def ledger_main(argv=None) -> int:
    """``attackfl-tpu ledger``: the persistent cross-run store —
    ``list``/``show`` query it, ``compare`` diffs two runs (or a run
    against its rolling baseline), ``regress`` is the CI gate (exit 1 on
    a perf/quality regression), ``import`` backfills committed
    ``BENCH_*.json`` artifacts.  Jax-free, like ``metrics``."""
    from attackfl_tpu.ledger.cli import main as _ledger_main

    return _ledger_main(list(sys.argv[1:] if argv is None else argv))


def hotspots_main(argv=None) -> int:
    """``attackfl-tpu hotspots``: mine profiler traces into op-level
    device-time attribution (show) or gate drift between two profile
    dirs (diff).  Jax-free, like ``metrics`` and ``ledger``."""
    from attackfl_tpu.profiler.cli import main as _hotspots_main

    return _hotspots_main(list(sys.argv[1:] if argv is None else argv))


_SUBCOMMANDS = {
    "run": run_main,
    "server": server_main,
    "client": client_main,
    "metrics": metrics_main,
    "watch": watch_main,
    "audit": audit_main,
    "ledger": ledger_main,
    "cost": cost_main,
    "matrix": matrix_main,
    "serve": serve_main,
    "job": job_main,
    "fleet": fleet_main,
    "science": science_main,
    "hotspots": hotspots_main,
}

_USAGE = """usage: attackfl-tpu <command> [args]

commands:
  run      run the federation in-process (attackers from config; telemetry on)
  server   rendezvous server (waits for `client` registrations)
  client   register one client (reference client.py parity)
  metrics  summarize a run directory's events*.jsonl (p50/p95, rounds/s;
           --merge: cross-host skew; --forensics: defense TPR/FPR;
           --numerics: in-graph device-side round metrics)
  watch    poll a live run's monitor endpoint (/last-round, /healthz)
  audit    static analysis: AST rules + event-schema artifacts + jaxpr/HLO
           program invariants + grad/differentiability audit (--grad;
           --json for the machine-readable report)
  ledger   persistent cross-run store: list/show records, compare two runs
           (perf + numerics + forensics columns), regress = CI gate with
           noise-aware thresholds, import = backfill BENCH_*.json
  cost     predictive cost model: estimate = price a config or matrix grid
           without running it (peer ledger records, flops/bytes regression
           fallback); validate = leave-one-out accuracy gate on a ledger
  matrix   scenario-matrix engine: run a full (attack x defense x seed)
           grid as ONE compiled program (per-cell ledger records share a
           sweep_id); status renders the grid's completion table
  serve    resilient run service: durable job queue + supervised workers +
           admission control + HTTP control plane; SIGTERM drains, kill -9
           is recovered by queue replay + checkpoint resume
  job      service client (jax-free): submit/list/status/cancel/wait over
           HTTP (reads <spool>/service.json for discovery)
  fleet    fleet observatory over a service spool: report = per-tenant
           device-time ledger (busy + idle = wall x slots) + SLO gauges;
           trace = one Perfetto-loadable cross-job trace (slot occupancy,
           queue waits, preemption gaps, chunk spans)
  science  scenario science over matrix sweeps: leaderboard = defense
           robustness ranking by attack damage (clean 'none' baseline,
           bootstrap CIs); report = auditable SCOREBOARD.json; diff
           --gate = rank-stability CI hook (exit 1 past the inter-seed
           noise floor)
  hotspots profiler-trace mining (jax-free): show = per-op device-time
           attribution + dispatch-gap diagnosis for a profile dir
           (books-close gated); diff = host-bound-fraction / top-op
           share drift gate between two profile dirs (exit 1 on drift)
"""


def main(argv=None) -> int:
    """Umbrella ``attackfl-tpu`` entry point (also ``python -m attackfl_tpu``)."""
    args = list(sys.argv[1:] if argv is None else argv)
    if not args or args[0] in ("-h", "--help"):
        print(_USAGE, end="")
        return 0 if args else 2
    command = _SUBCOMMANDS.get(args[0])
    if command is None:
        print(f"unknown command {args[0]!r}\n{_USAGE}", end="", file=sys.stderr)
        return 2
    result = command(args[1:])
    return int(result) if isinstance(result, int) else 0
