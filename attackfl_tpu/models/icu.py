"""ICU mortality models: dual-branch (vitals 7-dim, labs 16-dim) binary
classifiers, architecture-parity rebuilds of the reference models
(src/Model.py:27-246) in Flax.

All models take ``(vitals (B,7), labs (B,16))`` and return sigmoid
probabilities of shape (B, 1).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from attackfl_tpu.models.layers import TransformerBlock, adaptive_avg_pool1d
from attackfl_tpu.registry import register_model


@register_model("CNNModel")
class CNNModel(nn.Module):
    """Dual-branch 1-D CNN (reference: src/Model.py:27-88).

    Per branch: the feature vector is treated as a 1-channel signal,
    3x Conv1d(k=3, same) with channels 32 -> 64 -> 128 + ReLU, adaptive
    average pool to 4 positions, flatten, dropout 0.3.  Merged through
    FC 1024 -> 128 -> 64 -> 32 -> 1 with sigmoid.
    """

    dropout_rate: float = 0.3

    def _branch(self, x: jnp.ndarray, prefix: str, deterministic: bool) -> jnp.ndarray:
        x = x[..., None]  # (B, L) -> (B, L, 1): NLC layout
        x = nn.relu(nn.Conv(32, (3,), padding="SAME", name=f"{prefix}_conv1")(x))
        x = nn.relu(nn.Conv(64, (3,), padding="SAME", name=f"{prefix}_conv2")(x))
        x = nn.relu(nn.Conv(128, (3,), padding="SAME", name=f"{prefix}_conv3")(x))
        x = adaptive_avg_pool1d(x, 4)  # (B, 4, 128)
        x = x.reshape(x.shape[0], -1)  # (B, 512)
        x = nn.Dropout(self.dropout_rate, deterministic=deterministic)(x)
        return x

    @nn.compact
    def __call__(self, vitals: jnp.ndarray, labs: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        det = not train
        v = self._branch(vitals, "vitals", det)
        l = self._branch(labs, "labs", det)
        x = jnp.concatenate([v, l], axis=1)  # (B, 1024)
        x = nn.relu(nn.Dense(128, name="fc1")(x))
        x = nn.relu(nn.Dense(64, name="fc2")(x))
        x = nn.relu(nn.Dense(32, name="fc3")(x))
        return nn.sigmoid(nn.Dense(1, name="output")(x))


class _BiGRUStack(nn.Module):
    """Three stacked bidirectional GRUs, hidden size ``hidden`` each
    direction (reference: src/Model.py:102-104)."""

    hidden: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i in range(3):
            x = nn.Bidirectional(
                nn.RNN(nn.GRUCell(self.hidden), name=f"fwd{i}"),
                nn.RNN(nn.GRUCell(self.hidden), name=f"bwd{i}"),
                name=f"bigru{i}",
            )(x)
        return x  # (B, T, 2*hidden)


@register_model("RNNModel")
class RNNModel(nn.Module):
    """Dual-branch 3-layer bidirectional GRU model
    (reference: src/Model.py:91-163).

    Inputs equal to the mask value (-2.0) are zeroed; 2-D inputs gain a
    singleton time axis; the last timestep is taken, LayerNorm'd and
    dropped out per branch; merged through FC (4h -> h -> h/2 -> 1),
    sigmoid.
    """

    vitals_input_dim: int = 7
    labs_input_dim: int = 16
    hidden_dim: int = 32
    dropout_rate: float = 0.3
    mask_value: float = -2.0

    def _branch(self, x: jnp.ndarray, prefix: str, deterministic: bool) -> jnp.ndarray:
        x = jnp.where(x == self.mask_value, jnp.zeros_like(x), x)
        if x.ndim == 2:
            x = x[:, None, :]  # (B, 1, F)
        x = _BiGRUStack(self.hidden_dim, name=f"{prefix}_gru")(x)
        x = x[:, -1, :]  # last timestep, (B, 2h)
        x = nn.LayerNorm(name=f"{prefix}_ln")(x)
        x = nn.Dropout(self.dropout_rate, deterministic=deterministic)(x)
        return x

    @nn.compact
    def __call__(self, vitals: jnp.ndarray, labs: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        det = not train
        v = self._branch(vitals, "vitals", det)
        l = self._branch(labs, "labs", det)
        x = jnp.concatenate([v, l], axis=1)  # (B, 4h)
        x = nn.relu(nn.Dense(self.hidden_dim, name="fc1")(x))
        x = nn.relu(nn.Dense(self.hidden_dim // 2, name="fc2")(x))
        return nn.sigmoid(nn.Dense(1, name="output")(x))


@register_model("TransformerModel")
class TransformerModel(nn.Module):
    """Dual-branch single-block Transformer (reference: src/Model.py:194-246;
    the config.yaml default model).

    Per branch: Dense(F -> 64) + GELU, one TransformerBlock (4 heads,
    ff_dim 6) over a singleton sequence, LayerNorm.  Merged through
    FC 128 -> 64 (GELU, dropout 0.3) -> 32 (GELU) -> 1, sigmoid.
    """

    vitals_input_dim: int = 7
    labs_input_dim: int = 16
    num_heads: int = 4
    ff_dim: int = 6
    dropout_rate: float = 0.3
    # exact seq-len-1 attention shortcut (see layers.Seq1Attention): same
    # math, same param tree, ~half the attention kernels per step
    seq1_fast: bool = True

    def _branch(self, x: jnp.ndarray, prefix: str, deterministic: bool) -> jnp.ndarray:
        x = nn.gelu(nn.Dense(64, name=f"{prefix}_dense")(x))
        x = x[:, None, :]  # seq len 1 (reference unsqueezes, Model.py:227)
        x = TransformerBlock(
            64, self.num_heads, self.ff_dim, dropout_rate=0.1,
            seq1_fast=self.seq1_fast, name=f"{prefix}_transformer"
        )(x, deterministic=deterministic)
        x = x[:, 0, :]
        x = nn.LayerNorm(name=f"{prefix}_bn")(x)
        return x

    @nn.compact
    def __call__(self, vitals: jnp.ndarray, labs: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        det = not train
        v = self._branch(vitals, "vitals", det)
        l = self._branch(labs, "labs", det)
        x = jnp.concatenate([v, l], axis=1)  # (B, 128)
        x = nn.gelu(nn.Dense(64, name="fc1")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=det)(x)
        x = nn.gelu(nn.Dense(32, name="fc2")(x))
        return nn.sigmoid(nn.Dense(1, name="output")(x))
