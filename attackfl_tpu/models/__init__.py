"""Model zoo.  Importing this package registers every model under its
reference class name (registry contract: see attackfl_tpu/registry.py)."""

from attackfl_tpu.models.icu import CNNModel, RNNModel, TransformerModel  # noqa: F401
from attackfl_tpu.models.har import TransformerClassifier  # noqa: F401
from attackfl_tpu.models.hyper import (  # noqa: F401
    CNNHyper,
    HyperNetwork,
    make_cnn_hyper,
    make_hypernetwork,
    target_spec,
)
from attackfl_tpu.models.resnet import ResNet18  # noqa: F401
