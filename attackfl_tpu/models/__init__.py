"""Model zoo.  Importing this package registers every model under its
reference class name (registry contract: see attackfl_tpu/registry.py)."""

from attackfl_tpu.models.icu import CNNModel, RNNModel, TransformerModel  # noqa: F401
from attackfl_tpu.models.har import TransformerClassifier  # noqa: F401
from attackfl_tpu.models.hyper import HyperNetwork, make_hypernetwork, target_spec  # noqa: F401
from attackfl_tpu.models.resnet import ResNet18  # noqa: F401
