"""Hypernetwork for personalized FL (pFedHN-style "hyper" server mode).

Re-design of the reference's generic HyperNetwork (src/Model.py:251-304):
a per-client embedding table feeding an MLP trunk whose features are mapped
by one linear head per *target-parameter leaf* into a full parameter pytree
for the target model.  The reference keys heads by sanitized state_dict
names (``create_hyper_layers``, src/Model.py:268-283); here heads are keyed
by the flattened path of the Flax param tree, and a factory closes over the
target template so callers get real parameter pytrees back.

The server-side update is the reference's
``torch.autograd.grad(outputs=weights, grad_outputs=delta_theta)``
(server.py:654-659) — which in JAX is literally ``jax.vjp`` applied to the
cotangent ``delta_theta`` (see training/hyper.py).
"""

from __future__ import annotations

import math
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


from attackfl_tpu.ops.pytree import path_name


def spectral_normalize(kernel: jnp.ndarray, n_iter: int = 15) -> jnp.ndarray:
    """Divide ``kernel`` by (an estimate of) its largest singular value.

    Stateless TPU-friendly redesign of ``torch.nn.utils.spectral_norm``:
    torch amortizes one power-iteration step per forward through a
    persistent ``u`` buffer; under jit that mutable buffer would be a
    second variable collection threaded through every vjp/optimizer path,
    so instead we run ``n_iter`` power iterations from a fixed start
    vector inside the forward — a few tiny matvecs, fully fused by XLA.
    Like torch, ``u``/``v`` are treated as constants for autodiff
    (stop_gradient); gradients flow through ``kernel / sigma``.
    """
    w = kernel.reshape(-1, kernel.shape[-1])  # (fan_in, fan_out)

    def body(_, uv):
        u, _v = uv
        v = w @ u
        v = v / (jnp.linalg.norm(v) + 1e-12)
        u = w.T @ v
        u = u / (jnp.linalg.norm(u) + 1e-12)
        return u, v

    u0 = jnp.full((w.shape[1],), 1.0 / math.sqrt(w.shape[1]), dtype=w.dtype)
    v0 = jnp.zeros((w.shape[0],), dtype=w.dtype)
    u, v = jax.lax.fori_loop(0, n_iter, body, (u0, v0))
    u, v = jax.lax.stop_gradient(u), jax.lax.stop_gradient(v)
    sigma = v @ (w @ u)
    return kernel / (sigma + 1e-12)


def _torch_linear_init(fan_in: int):
    """torch.nn.Linear's default init: U(-1/√fan_in, 1/√fan_in) for kernel
    AND bias."""
    lim = 1.0 / math.sqrt(fan_in)

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, -lim, lim)

    return init


class HyperDense(nn.Module):
    """Dense with ``torch.nn.Linear``'s default init — U(-1/√fan_in,
    1/√fan_in) for kernel AND bias — and optional application-time
    spectral normalization of the kernel (the rebuild's
    ``nn.utils.spectral_norm(nn.Linear(...))``, reference
    src/Model.py:258-262,328-332).

    The hypernetwork's init distribution IS the distribution of every
    client's initial model weights (the heads' outputs), so it uses the
    torch reference's init rather than flax's lecun-normal/zero-bias;
    final-metric parity is asserted in tests/test_torch_parity.py against
    torch_parity.run_hyper."""

    features: int
    spec_norm: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        init = _torch_linear_init(x.shape[-1])
        kernel = self.param("kernel", init, (x.shape[-1], self.features))
        bias = self.param("bias", init, (self.features,))
        if self.spec_norm:
            kernel = spectral_normalize(kernel)
        return x @ kernel + bias


def _dense(spec_norm: bool, features: int, name: str):
    return HyperDense(features, spec_norm=spec_norm, name=name)


# torch nn.Embedding default: N(0, 1) per element
_torch_embed_init = nn.initializers.normal(stddev=1.0)


def _trunk(m, idx: jnp.ndarray) -> jnp.ndarray:
    """Shared embed->MLP trunk (reference src/Model.py:255-265,313-327):
    client index -> (embedding, features).  ``m`` is a HyperNetwork or
    CNNHyper instance inside @nn.compact — identical parameter naming in
    both keeps their checkpoints head-for-head comparable."""
    emd = nn.Embed(m.n_nodes, m.embedding_dim, name="embeddings",
                   embedding_init=_torch_embed_init)(idx)
    f = _dense(m.spec_norm, m.hidden_dim, "mlp_in")(emd)
    for i in range(m.n_hidden):
        f = _dense(m.spec_norm, m.hidden_dim, f"mlp_hidden{i}")(nn.relu(f))
    return emd, f


def target_spec(template_params: Any) -> tuple[tuple[str, tuple[int, ...]], ...]:
    """Hashable (name, shape) spec for every leaf of a target param pytree.

    Head names are the canonical leaf path with "/" sanitized to "__" —
    the same name mangling the reference applies to state_dict keys
    (src/Model.py:277)."""
    flat = jax.tree_util.tree_flatten_with_path(template_params)[0]
    return tuple((path_name(p).replace("/", "__"), tuple(leaf.shape)) for p, leaf in flat)


class HyperNetwork(nn.Module):
    """Embedding(n_nodes, embedding_dim) -> MLP(hidden_dim, n_hidden) ->
    one Dense head per target leaf (reference: src/Model.py:251-304,
    instantiated ``HyperNetwork(net, total_clients, 8, 100, False, 2)`` at
    server.py:800).

    ``__call__(idx)`` with a scalar int index returns
    ``(flat_outputs: dict[name, array(shape)], embedding: (embedding_dim,))``.
    """

    spec: tuple[tuple[str, tuple[int, ...]], ...]
    n_nodes: int
    embedding_dim: int = 8
    hidden_dim: int = 100
    spec_norm: bool = False
    n_hidden: int = 2

    @nn.compact
    def __call__(self, idx: jnp.ndarray):
        emd, f = _trunk(self, idx)

        outputs: dict[str, jnp.ndarray] = {}
        for name, shape in self.spec:
            numel = math.prod(shape) if shape else 1
            out = _dense(self.spec_norm, numel, f"head_{name}")(f)
            outputs[name] = out.reshape(shape)
        return outputs, emd


def make_hypernetwork(
    template_params: Any,
    n_nodes: int,
    embedding_dim: int = 8,
    hidden_dim: int = 100,
    spec_norm: bool = False,
    n_hidden: int = 2,
) -> tuple[HyperNetwork, Callable]:
    """Build a HyperNetwork for a target param pytree.

    Returns ``(module, apply_fn)`` where
    ``apply_fn(hparams, idx) -> (target_params_pytree, embedding)``
    reconstructs the full target structure from the flat head outputs.
    """
    spec = target_spec(template_params)
    module = HyperNetwork(
        spec=spec,
        n_nodes=n_nodes,
        embedding_dim=embedding_dim,
        hidden_dim=hidden_dim,
        spec_norm=spec_norm,
        n_hidden=n_hidden,
    )
    treedef = jax.tree.structure(template_params)
    names = [name for name, _ in spec]

    def apply_fn(hparams, idx):
        flat, emd = module.apply({"params": hparams}, idx)
        params = jax.tree.unflatten(treedef, [flat[n] for n in names])
        return params, emd

    return module, apply_fn


# (head name, CNNModel leaf path, Flax-layout shape).  Hand-inlined for the
# CNNModel architecture exactly as the reference hand-writes one Linear
# head per layer (src/Model.py:328-356,389-414); shapes are the Flax
# layouts (Conv kernel (k, in, out), Dense kernel (in, out)) of the torch
# shapes the reference .view()s to (e.g. fc1 128x1024 <-> (1024, 128)).
_CNN_HYPER_HEADS: tuple[tuple[str, str, tuple[int, ...]], ...] = tuple(
    head
    for branch in ("vitals", "labs")
    for head in (
        (f"{branch}_conv1_weights", f"{branch}_conv1/kernel", (3, 1, 32)),
        (f"{branch}_conv1_bias", f"{branch}_conv1/bias", (32,)),
        (f"{branch}_conv2_weights", f"{branch}_conv2/kernel", (3, 32, 64)),
        (f"{branch}_conv2_bias", f"{branch}_conv2/bias", (64,)),
        (f"{branch}_conv3_weights", f"{branch}_conv3/kernel", (3, 64, 128)),
        (f"{branch}_conv3_bias", f"{branch}_conv3/bias", (128,)),
    )
) + (
    ("fc1_weights", "fc1/kernel", (128 * 2 * 4, 128)),
    ("fc1_bias", "fc1/bias", (128,)),
    ("fc2_weights", "fc2/kernel", (128, 64)),
    ("fc2_bias", "fc2/bias", (64,)),
    ("fc3_weights", "fc3/kernel", (64, 32)),
    ("fc3_bias", "fc3/bias", (32,)),
    ("output_weights", "output/kernel", (32, 1)),
    ("output_bias", "output/bias", (1,)),
)


class CNNHyper(nn.Module):
    """Hypernetwork hand-specialized to CNNModel (reference: CNNHyper,
    src/Model.py:309-416, the commented-out alternative at server.py:801).

    Same embedding -> MLP trunk as HyperNetwork but with one explicitly
    named head per CNNModel layer instead of spec-derived heads, and with
    spectral normalization applicable to trunk *and* heads
    (src/Model.py:359-381).  ``__call__(idx)`` returns
    ``(params pytree in CNNModel layout, embedding)``.
    """

    n_nodes: int
    embedding_dim: int = 8
    hidden_dim: int = 100
    spec_norm: bool = False
    n_hidden: int = 2

    @nn.compact
    def __call__(self, idx: jnp.ndarray):
        emd, f = _trunk(self, idx)

        params: dict[str, dict[str, jnp.ndarray]] = {}
        for head_name, path, shape in _CNN_HYPER_HEADS:
            module_name, param_name = path.split("/")
            out = _dense(self.spec_norm, math.prod(shape), head_name)(f)
            params.setdefault(module_name, {})[param_name] = out.reshape(shape)
        return params, emd


def make_cnn_hyper(
    template_params: Any,
    n_nodes: int,
    embedding_dim: int = 8,
    hidden_dim: int = 100,
    spec_norm: bool = False,
    n_hidden: int = 2,
) -> tuple[CNNHyper, Callable]:
    """Build a CNNHyper for a CNNModel param pytree; same
    ``(module, apply_fn)`` contract as :func:`make_hypernetwork` so the
    hyper-mode engine can use either interchangeably.

    Raises if ``template_params`` is not the CNNModel layout the heads are
    hand-written for (the reference analog would produce mis-shaped
    state_dicts silently).
    """
    expected = {path: shape for _, path, shape in _CNN_HYPER_HEADS}
    actual = {
        path_name(p): tuple(leaf.shape)
        for p, leaf in jax.tree_util.tree_flatten_with_path(template_params)[0]
    }
    if actual != expected:
        diff = {
            path: (actual.get(path), expected.get(path))
            for path in sorted(set(actual) | set(expected))
            if actual.get(path) != expected.get(path)
        }
        raise ValueError(
            "CNNHyper targets the CNNModel parameter layout only; "
            f"mismatched leaves (got, expected): {diff}"
        )

    module = CNNHyper(
        n_nodes=n_nodes,
        embedding_dim=embedding_dim,
        hidden_dim=hidden_dim,
        spec_norm=spec_norm,
        n_hidden=n_hidden,
    )
    treedef = jax.tree.structure(template_params)
    leaf_paths = [
        path_name(p).split("/")
        for p, _ in jax.tree_util.tree_flatten_with_path(template_params)[0]
    ]

    def apply_fn(hparams, idx):
        nested, emd = module.apply({"params": hparams}, idx)
        # rebuild through the template treedef so downstream pytree ops see
        # *exactly* the target structure (incl. dict ordering / FrozenDict)
        leaves = [nested[mod][param] for mod, param in leaf_paths]
        return jax.tree.unflatten(treedef, leaves), emd

    return module, apply_fn
