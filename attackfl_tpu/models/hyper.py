"""Hypernetwork for personalized FL (pFedHN-style "hyper" server mode).

Re-design of the reference's generic HyperNetwork (src/Model.py:251-304):
a per-client embedding table feeding an MLP trunk whose features are mapped
by one linear head per *target-parameter leaf* into a full parameter pytree
for the target model.  The reference keys heads by sanitized state_dict
names (``create_hyper_layers``, src/Model.py:268-283); here heads are keyed
by the flattened path of the Flax param tree, and a factory closes over the
target template so callers get real parameter pytrees back.

The server-side update is the reference's
``torch.autograd.grad(outputs=weights, grad_outputs=delta_theta)``
(server.py:654-659) — which in JAX is literally ``jax.vjp`` applied to the
cotangent ``delta_theta`` (see training/hyper.py).
"""

from __future__ import annotations

import math
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


from attackfl_tpu.ops.pytree import path_name


def target_spec(template_params: Any) -> tuple[tuple[str, tuple[int, ...]], ...]:
    """Hashable (name, shape) spec for every leaf of a target param pytree.

    Head names are the canonical leaf path with "/" sanitized to "__" —
    the same name mangling the reference applies to state_dict keys
    (src/Model.py:277)."""
    flat = jax.tree_util.tree_flatten_with_path(template_params)[0]
    return tuple((path_name(p).replace("/", "__"), tuple(leaf.shape)) for p, leaf in flat)


class HyperNetwork(nn.Module):
    """Embedding(n_nodes, embedding_dim) -> MLP(hidden_dim, n_hidden) ->
    one Dense head per target leaf (reference: src/Model.py:251-304,
    instantiated ``HyperNetwork(net, total_clients, 8, 100, False, 2)`` at
    server.py:800).

    ``__call__(idx)`` with a scalar int index returns
    ``(flat_outputs: dict[name, array(shape)], embedding: (embedding_dim,))``.
    """

    spec: tuple[tuple[str, tuple[int, ...]], ...]
    n_nodes: int
    embedding_dim: int = 8
    hidden_dim: int = 100
    spec_norm: bool = False
    n_hidden: int = 2

    @nn.compact
    def __call__(self, idx: jnp.ndarray):
        if self.spec_norm:
            raise NotImplementedError(
                "spectral-norm hypernetwork heads are not implemented; the "
                "reference always instantiates with spec_norm=False "
                "(server.py:800)"
            )
        emd = nn.Embed(self.n_nodes, self.embedding_dim, name="embeddings")(idx)
        f = nn.Dense(self.hidden_dim, name="mlp_in")(emd)
        for i in range(self.n_hidden):
            f = nn.Dense(self.hidden_dim, name=f"mlp_hidden{i}")(nn.relu(f))

        outputs: dict[str, jnp.ndarray] = {}
        for name, shape in self.spec:
            numel = math.prod(shape) if shape else 1
            out = nn.Dense(numel, name=f"head_{name}")(f)
            outputs[name] = out.reshape(shape)
        return outputs, emd


def make_hypernetwork(
    template_params: Any,
    n_nodes: int,
    embedding_dim: int = 8,
    hidden_dim: int = 100,
    spec_norm: bool = False,
    n_hidden: int = 2,
) -> tuple[HyperNetwork, Callable]:
    """Build a HyperNetwork for a target param pytree.

    Returns ``(module, apply_fn)`` where
    ``apply_fn(hparams, idx) -> (target_params_pytree, embedding)``
    reconstructs the full target structure from the flat head outputs.
    """
    spec = target_spec(template_params)
    module = HyperNetwork(
        spec=spec,
        n_nodes=n_nodes,
        embedding_dim=embedding_dim,
        hidden_dim=hidden_dim,
        spec_norm=spec_norm,
        n_hidden=n_hidden,
    )
    treedef = jax.tree.structure(template_params)
    names = [name for name, _ in spec]

    def apply_fn(hparams, idx):
        flat, emd = module.apply({"params": hparams}, idx)
        params = jax.tree.unflatten(treedef, [flat[n] for n in names])
        return params, emd

    return module, apply_fn
