"""HAR (human activity recognition) classifier: Conv stem + sinusoidal
positional encoding + 2-layer Transformer encoder + mean pool, 6 classes
(reference: src/Model.py:420-458).

Input: (B, 561) feature signal (or (B, 1, 561) torch layout, accepted for
compat).  Output: (B, 6) logits.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from attackfl_tpu.models.layers import TorchEncoderLayer, sinusoidal_position_encoding
from attackfl_tpu.registry import register_model


@register_model("TransformerClassifier")
class TransformerClassifier(nn.Module):
    d_model: int = 64
    num_heads: int = 4
    num_layers: int = 2
    num_classes: int = 6
    ff_dim: int = 256
    dropout_rate: float = 0.1
    max_len: int = 600

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        det = not train
        if x.ndim == 3:  # (B, 1, L) torch channel-first layout
            x = x[:, 0, :]
        x = x[..., None]  # (B, L, 1): NLC
        x = nn.Conv(self.d_model, (3,), padding="SAME", name="conv")(x)  # (B, L, d)
        pe = sinusoidal_position_encoding(self.max_len, self.d_model)
        x = x + pe[None, : x.shape[1], :]
        for i in range(self.num_layers):
            x = TorchEncoderLayer(
                self.d_model,
                self.num_heads,
                self.ff_dim,
                self.dropout_rate,
                name=f"encoder{i}",
            )(x, deterministic=det)
        x = jnp.mean(x, axis=1)  # global average pool over sequence
        x = nn.relu(nn.Dense(64, name="cls_dense1")(x))
        x = nn.Dropout(0.3, deterministic=det)(x)
        return nn.Dense(self.num_classes, name="cls_dense2")(x)
