"""Shared building blocks for the model zoo.

TPU notes: all sequence layouts are NLC (batch, length, channels) so convs
and matmuls feed the MXU with the channel dim innermost; pooling windows are
resolved statically at trace time (no dynamic shapes under jit).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


def adaptive_avg_pool1d(x: jnp.ndarray, output_size: int) -> jnp.ndarray:
    """PyTorch-style AdaptiveAvgPool1d over the length axis of (B, L, C).

    Matches torch semantics: output bin i averages input[floor(i*L/out) :
    ceil((i+1)*L/out)].  L is static under jit so the bins unroll at trace
    time.  (Reference uses nn.AdaptiveAvgPool1d(4), src/Model.py:38,46.)
    """
    length = x.shape[1]
    outs = []
    for i in range(output_size):
        start = (i * length) // output_size
        end = -(-((i + 1) * length) // output_size)  # ceil div
        outs.append(jnp.mean(x[:, start:end, :], axis=1))
    return jnp.stack(outs, axis=1)  # (B, output_size, C)


def adaptive_max_pool1d(x: jnp.ndarray, output_size: int) -> jnp.ndarray:
    length = x.shape[1]
    outs = []
    for i in range(output_size):
        start = (i * length) // output_size
        end = -(-((i + 1) * length) // output_size)
        outs.append(jnp.max(x[:, start:end, :], axis=1))
    return jnp.stack(outs, axis=1)


class TransformerBlock(nn.Module):
    """Pre-add/post-norm residual attention block.

    Mirrors the reference's TransformerBlock (src/Model.py:166-191):
    x = LN(x + Drop(MHA(x))); x = LN(x + Drop(FFN(x))), FFN = Dense(ff_dim)
    -> GELU -> Drop -> Dense(dim).
    """

    dim: int
    num_heads: int
    ff_dim: int
    dropout_rate: float = 0.1

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        attn = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads,
            qkv_features=self.dim,
            out_features=self.dim,
            dropout_rate=self.dropout_rate,
            deterministic=deterministic,
            name="attention",
        )(x, x)
        x = nn.LayerNorm(name="attention_norm")(
            x + nn.Dropout(self.dropout_rate, deterministic=deterministic)(attn)
        )
        y = nn.Dense(self.ff_dim, name="ffn_dense1")(x)
        y = nn.gelu(y)
        y = nn.Dropout(self.dropout_rate, deterministic=deterministic)(y)
        y = nn.Dense(self.dim, name="ffn_dense2")(y)
        x = nn.LayerNorm(name="ffn_norm")(
            x + nn.Dropout(self.dropout_rate, deterministic=deterministic)(y)
        )
        return x


class TorchEncoderLayer(nn.Module):
    """Post-norm Transformer encoder layer with ReLU FFN, matching
    torch.nn.TransformerEncoderLayer defaults (used by the reference HAR
    model, src/Model.py:441-442)."""

    dim: int
    num_heads: int
    ff_dim: int
    dropout_rate: float = 0.1

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        attn = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads,
            qkv_features=self.dim,
            out_features=self.dim,
            dropout_rate=self.dropout_rate,
            deterministic=deterministic,
            name="self_attn",
        )(x, x)
        x = nn.LayerNorm(name="norm1")(
            x + nn.Dropout(self.dropout_rate, deterministic=deterministic)(attn)
        )
        y = nn.Dense(self.ff_dim, name="linear1")(x)
        y = nn.relu(y)
        y = nn.Dropout(self.dropout_rate, deterministic=deterministic)(y)
        y = nn.Dense(self.dim, name="linear2")(y)
        x = nn.LayerNorm(name="norm2")(
            x + nn.Dropout(self.dropout_rate, deterministic=deterministic)(y)
        )
        return x


def sinusoidal_position_encoding(max_len: int, d_model: int) -> np.ndarray:
    """Classic sin/cos table (reference: src/Model.py:420-433)."""
    pe = np.zeros((max_len, d_model), dtype=np.float32)
    pos = np.arange(max_len, dtype=np.float32)[:, None]
    div = np.exp(np.arange(0, d_model, 2, dtype=np.float32) * (-np.log(10000.0) / d_model))
    pe[:, 0::2] = np.sin(pos * div)
    pe[:, 1::2] = np.cos(pos * div)
    return pe
