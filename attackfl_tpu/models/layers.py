"""Shared building blocks for the model zoo.

TPU notes: all sequence layouts are NLC (batch, length, channels) so convs
and matmuls feed the MXU with the channel dim innermost; pooling windows are
resolved statically at trace time (no dynamic shapes under jit).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


def adaptive_avg_pool1d(x: jnp.ndarray, output_size: int) -> jnp.ndarray:
    """PyTorch-style AdaptiveAvgPool1d over the length axis of (B, L, C).

    Matches torch semantics: output bin i averages input[floor(i*L/out) :
    ceil((i+1)*L/out)].  L is static under jit so the bins unroll at trace
    time.  (Reference uses nn.AdaptiveAvgPool1d(4), src/Model.py:38,46.)
    """
    length = x.shape[1]
    outs = []
    for i in range(output_size):
        start = (i * length) // output_size
        end = -(-((i + 1) * length) // output_size)  # ceil div
        outs.append(jnp.mean(x[:, start:end, :], axis=1))
    return jnp.stack(outs, axis=1)  # (B, output_size, C)


class _InertProjection(nn.Module):
    """Declares DenseGeneral-shaped kernel/bias params that take no part in
    the computation (zero-gradient placeholders for tree parity)."""

    kernel_shape: tuple[int, ...]
    bias_shape: tuple[int, ...]

    @nn.compact
    def __call__(self) -> None:
        def kernel_init(rng, shape, dtype=jnp.float32):
            # flax DenseGeneral flattens grouped output dims before
            # lecun_normal (fan computed on (in, H*dh)); match it so init
            # VALUES agree with the full-MHA module, not just shapes
            flat = (shape[0], int(np.prod(shape[1:])))
            return nn.initializers.lecun_normal()(rng, flat, dtype).reshape(shape)

        self.param("kernel", kernel_init, self.kernel_shape)
        self.param("bias", nn.initializers.zeros, self.bias_shape)


class Seq1Attention(nn.Module):
    """Multi-head self-attention specialized (EXACTLY) to sequence length 1.

    With one key, the softmax over attention logits is the constant 1
    whatever q·k is, so (a) the attention output is just
    ``out_proj(attn_dropout(1) * v_proj(x))`` and (b) the query/key
    projections receive exactly zero gradient (d softmax / d logit = 0 for a
    single logit) — true for the reference's torch MultiheadAttention over
    its unsqueezed seq-1 ICU inputs too (src/Model.py:227,234).  Skipping
    the q/k matmuls and the softmax is therefore an algebraic identity, not
    an approximation; it roughly halves the attention op count per training
    step.  Attention-weight dropout becomes one independent Bernoulli
    scalar per (batch, head) — torch's elementwise dropout on the
    (B,H,1,1) weight matrix, which is what the reference trains with.
    (flax MHA's default broadcast_dropout=True instead shares ONE draw
    across batch and heads at seq len 1, so under dropout this path matches
    the torch reference's stochastic dynamics, not flax's.)

    The parameter tree is IDENTICAL to flax's MultiHeadDotProductAttention
    (query/key/value/out with (in, H, dh) kernels) so checkpoints,
    hypernetwork heads and attack vectors are layout-compatible either way;
    q/k params exist, stay at init, and receive zero gradient — exactly as
    they (effectively) do in the reference.
    """

    num_heads: int
    qkv_features: int
    out_features: int
    dropout_rate: float = 0.0
    deterministic: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, s, d = x.shape
        assert s == 1, "Seq1Attention requires sequence length 1"
        head_dim = self.qkv_features // self.num_heads
        # q/k params declared for tree parity; mathematically inert (see
        # class docstring), so their matmuls are never computed
        _InertProjection((d, self.num_heads, head_dim),
                         (self.num_heads, head_dim), name="query")()
        _InertProjection((d, self.num_heads, head_dim),
                         (self.num_heads, head_dim), name="key")()
        value = nn.DenseGeneral(
            features=(self.num_heads, head_dim), axis=-1, name="value"
        )(x)  # (B, 1, H, dh)
        if self.dropout_rate > 0.0 and not self.deterministic:
            # attention-weight dropout over the (B, H, 1, 1) weight matrix
            # degenerates to one Bernoulli scalar per (batch, head)
            rng = self.make_rng("dropout")
            keep = jax.random.bernoulli(
                rng, 1.0 - self.dropout_rate, (b, 1, self.num_heads, 1)
            )
            value = value * keep / (1.0 - self.dropout_rate)
        return nn.DenseGeneral(
            features=self.out_features, axis=(-2, -1), name="out"
        )(value)


class TransformerBlock(nn.Module):
    """Pre-add/post-norm residual attention block.

    Mirrors the reference's TransformerBlock (src/Model.py:166-191):
    x = LN(x + Drop(MHA(x))); x = LN(x + Drop(FFN(x))), FFN = Dense(ff_dim)
    -> GELU -> Drop -> Dense(dim).

    ``seq1_fast`` switches to the algebraically identical seq-len-1
    attention (see Seq1Attention); forward values and gradients match flax
    MHA exactly in deterministic mode.  Under attention dropout the fast
    path follows the torch reference's per-(batch, head) masks rather than
    flax's batch-broadcast default — different stochastic draws, same
    architecture.
    """

    dim: int
    num_heads: int
    ff_dim: int
    dropout_rate: float = 0.1
    seq1_fast: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if self.seq1_fast and x.shape[1] == 1:
            attn = Seq1Attention(
                num_heads=self.num_heads,
                qkv_features=self.dim,
                out_features=self.dim,
                dropout_rate=self.dropout_rate,
                deterministic=deterministic,
                name="attention",
            )(x)
        else:
            attn = nn.MultiHeadDotProductAttention(
                num_heads=self.num_heads,
                qkv_features=self.dim,
                out_features=self.dim,
                dropout_rate=self.dropout_rate,
                deterministic=deterministic,
                name="attention",
            )(x, x)
        x = nn.LayerNorm(name="attention_norm")(
            x + nn.Dropout(self.dropout_rate, deterministic=deterministic)(attn)
        )
        y = nn.Dense(self.ff_dim, name="ffn_dense1")(x)
        y = nn.gelu(y)
        y = nn.Dropout(self.dropout_rate, deterministic=deterministic)(y)
        y = nn.Dense(self.dim, name="ffn_dense2")(y)
        x = nn.LayerNorm(name="ffn_norm")(
            x + nn.Dropout(self.dropout_rate, deterministic=deterministic)(y)
        )
        return x


class TorchEncoderLayer(nn.Module):
    """Post-norm Transformer encoder layer with ReLU FFN, matching
    torch.nn.TransformerEncoderLayer defaults (used by the reference HAR
    model, src/Model.py:441-442)."""

    dim: int
    num_heads: int
    ff_dim: int
    dropout_rate: float = 0.1

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        attn = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads,
            qkv_features=self.dim,
            out_features=self.dim,
            dropout_rate=self.dropout_rate,
            deterministic=deterministic,
            name="self_attn",
        )(x, x)
        x = nn.LayerNorm(name="norm1")(
            x + nn.Dropout(self.dropout_rate, deterministic=deterministic)(attn)
        )
        y = nn.Dense(self.ff_dim, name="linear1")(x)
        y = nn.relu(y)
        y = nn.Dropout(self.dropout_rate, deterministic=deterministic)(y)
        y = nn.Dense(self.dim, name="linear2")(y)
        x = nn.LayerNorm(name="norm2")(
            x + nn.Dropout(self.dropout_rate, deterministic=deterministic)(y)
        )
        return x


def sinusoidal_position_encoding(max_len: int, d_model: int) -> np.ndarray:
    """Classic sin/cos table (reference: src/Model.py:420-433)."""
    pe = np.zeros((max_len, d_model), dtype=np.float32)
    pos = np.arange(max_len, dtype=np.float32)[:, None]
    div = np.exp(np.arange(0, d_model, 2, dtype=np.float32) * (-np.log(10000.0) / d_model))
    pe[:, 0::2] = np.sin(pos * div)
    pe[:, 1::2] = np.cos(pos * div)
    return pe
