"""ResNet-18 for CIFAR-10 (BASELINE config 5).

The reference has no CIFAR model — only CIFAR10 *evaluation* plumbing
(src/Validation.py:38-44,69-90, expecting log-probability outputs for
``F.nll_loss``).  This is a new Flax model: standard CIFAR-style ResNet-18
(3x3 stem, no max-pool) with GroupNorm instead of BatchNorm — batch-stats
aggregation is ill-defined under federated averaging, and GroupNorm is the
standard substitution in FL (e.g. Hsieh et al., "The Non-IID Data Quagmire").
Outputs log-softmax over 10 classes to satisfy the NLL-based validation
contract.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from attackfl_tpu.registry import register_model


class ResidualBlock(nn.Module):
    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        y = nn.Conv(self.features, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, name="conv1")(x)
        y = nn.GroupNorm(num_groups=min(32, self.features), name="gn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False, name="conv2")(y)
        y = nn.GroupNorm(num_groups=min(32, self.features), name="gn2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1), strides=(self.strides, self.strides),
                               use_bias=False, name="proj")(x)
            residual = nn.GroupNorm(num_groups=min(32, self.features), name="gn_proj")(residual)
        return nn.relu(y + residual)


@register_model("ResNet18")
class ResNet18(nn.Module):
    num_classes: int = 10
    stage_sizes: tuple[int, ...] = (2, 2, 2, 2)
    stage_features: tuple[int, ...] = (64, 128, 256, 512)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        if x.ndim == 4 and x.shape[1] == 3 and x.shape[-1] != 3:
            x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW (torch layout) -> NHWC
        x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False, name="stem")(x)
        x = nn.GroupNorm(num_groups=32, name="gn_stem")(x)
        x = nn.relu(x)
        for stage, (num_blocks, features) in enumerate(zip(self.stage_sizes, self.stage_features)):
            for block in range(num_blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = ResidualBlock(features, strides, name=f"stage{stage}_block{block}")(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, name="classifier")(x)
        return nn.log_softmax(x, axis=-1)
