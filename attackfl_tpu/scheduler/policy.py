"""Pure scheduling policy: priced tickets -> pack/preempt decisions.

No clocks of its own (``now`` is always passed in), no I/O, no jax —
every decision is a function of the tickets it is shown, so the policy
is unit-testable with a fake clock and the daemon-facing layer
(:mod:`.core`) stays a thin sync loop.

Priority + aging
----------------
Jobs carry a priority CLASS (``high``/``normal``/``low`` — base scores
100/50/10).  A queued ticket's effective priority ages linearly and
WITHOUT BOUND::

    effective = base + wait_seconds * aging_rate

Queued tickets are ordered by effective-priority BAND (``band_width``
points per band), then by predicted remaining device-seconds (shortest
first — the cost model's packing lever), then FIFO.  Unbounded aging is
what makes starvation impossible under sustained high-priority load:
after ``starvation_bound_seconds()`` of waiting, a low-priority ticket
outranks EVERY high-priority ticket submitted after it, so the work
ahead of it is finite and it eventually runs.  That outrank bound —
``(max_base - min_base + band_width) / aging_rate`` — is the number the
starvation-freedom test asserts.

Preemption
----------
Aging promotes queue ORDER only.  A running job is preempted solely for
a candidate of a strictly higher priority CLASS (base score, not aged
score — equals never thrash each other), and only after
``min_runtime_seconds`` of execution (anti-thrash guard).  Victims are
picked lowest class first, longest predicted remainder first — the
degradation ordering the overload policy documents.  The mechanics of
stopping (round-boundary stop hook, chunk-boundary checkpoint) belong
to the worker; the policy only names the victim.

Overload
--------
``backlog_seconds`` is the predicted device-seconds of all live work
divided by the slot count.  When a shed horizon is configured and
admitting one more job would push the backlog past it, the policy
prices the rejection: ``retry_after`` is how long the backlog needs to
drain back to the horizon at full throughput.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# priority classes: base effective-priority scores.  The spread between
# classes is what aging has to climb — see starvation_bound_seconds.
PRIORITY_CLASSES: dict[str, int] = {"high": 100, "normal": 50, "low": 10}
DEFAULT_PRIORITY = "normal"
# one band = how many effective-priority points "equal rank" spans; jobs
# inside a band are ordered by predicted cost (shortest first), so the
# cost model packs within a class while aging still promotes across
BAND_WIDTH = 10.0


def priority_base(name: str) -> int:
    """Class name -> base score; unknown names are an explicit error
    (a typo'd submission must not silently run at normal priority)."""
    try:
        return PRIORITY_CLASSES[name]
    except KeyError:
        raise ValueError(
            f"unknown priority {name!r}; choose from "
            f"{sorted(PRIORITY_CLASSES)}") from None


@dataclass
class Ticket:
    """One live job as the scheduler sees it: identity, price, state."""

    job_id: str
    priority: str = DEFAULT_PRIORITY
    predicted_seconds: float = 0.0
    pricing: dict[str, Any] = field(default_factory=dict)
    enqueued_ts: float = 0.0   # last transition into `queued` (monotonic)
    started_ts: float | None = None  # None while queued
    completed_fraction: float = 0.0
    preemptions: int = 0
    wait_seconds: float = 0.0  # accumulated across dispatches
    preempt_requested: bool = False
    seq: int = 0
    # fleet-trace identity (ISSUE 16): the causal id every schedule/slot
    # event names (stamped at submit, durable in the sealed spec), and
    # the tenant the job's device time bills to.  Pure pass-through for
    # the policy — decisions never read either.
    fleet_id: str = ""
    tenant: str = ""

    @property
    def base(self) -> int:
        return priority_base(self.priority)

    def remaining_seconds(self) -> float:
        done = min(max(self.completed_fraction, 0.0), 1.0)
        return max(self.predicted_seconds * (1.0 - done), 0.0)


@dataclass
class Plan:
    """One tick's decisions: tickets to start, tickets to preempt, and
    the backlog evidence every decision is judged against."""

    start: list[Ticket] = field(default_factory=list)
    preempt: list[Ticket] = field(default_factory=list)
    backlog_seconds: float = 0.0


class SchedulerPolicy:
    """The pure decision engine.  ``slots`` is the device budget in
    concurrent jobs (the old ``max_workers`` bound, now a packing target
    instead of a FIFO gate)."""

    def __init__(self, slots: int = 1, aging_rate: float = 1.0,
                 band_width: float = BAND_WIDTH,
                 min_runtime_seconds: float = 2.0,
                 shed_horizon_seconds: float = 0.0):
        self.slots = max(int(slots), 1)
        if aging_rate <= 0:
            raise ValueError(
                f"aging_rate must be > 0 (aging is the starvation-freedom "
                f"guarantee), got {aging_rate}")
        self.aging_rate = aging_rate
        self.band_width = max(float(band_width), 1e-9)
        self.min_runtime_seconds = max(float(min_runtime_seconds), 0.0)
        self.shed_horizon_seconds = max(float(shed_horizon_seconds), 0.0)

    # ---- effective priority -----------------------------------------

    def effective_priority(self, ticket: Ticket, now: float) -> float:
        wait = max(now - ticket.enqueued_ts, 0.0)
        return ticket.base + wait * self.aging_rate

    def _band(self, ticket: Ticket, now: float) -> int:
        return int(self.effective_priority(ticket, now) // self.band_width)

    def starvation_bound_seconds(self) -> float:
        """After this much queued wait, the LOWEST class strictly
        outranks (by band) any freshly submitted ticket of the HIGHEST
        class — the asserted aging bound."""
        bases = PRIORITY_CLASSES.values()
        return (max(bases) - min(bases) + self.band_width) / self.aging_rate

    # ---- packing + preemption ---------------------------------------

    def _queue_order(self, queued: list[Ticket], now: float) -> list[Ticket]:
        return sorted(
            queued,
            key=lambda t: (-self._band(t, now), t.remaining_seconds(),
                           t.enqueued_ts, t.seq, t.job_id))

    def plan(self, queued: list[Ticket], running: list[Ticket],
             now: float) -> Plan:
        plan = Plan()
        live = [t for t in queued + running]
        plan.backlog_seconds = round(
            sum(t.remaining_seconds() for t in live) / self.slots, 6)
        free = self.slots - len(running)
        # victims: strictly lower class first, longest remainder first
        # (the job that would hold its slot longest gives the backlog
        # the most relief per preemption)
        victims = sorted(
            (t for t in running if not t.preempt_requested),
            key=lambda t: (t.base, -t.remaining_seconds(), t.job_id))
        for ticket in self._queue_order(queued, now):
            if free > 0:
                plan.start.append(ticket)
                free -= 1
                continue
            victim = next(
                (v for v in victims
                 if v.base < ticket.base
                 and v.started_ts is not None
                 and now - v.started_ts >= self.min_runtime_seconds),
                None)
            if victim is None:
                continue  # keep scanning: a lower class may still fit later
            victim.preempt_requested = True
            victims.remove(victim)
            plan.preempt.append(victim)
            # the slot frees only when the victim reaches its safe seam
            # (round/chunk boundary) — the NEXT tick starts the candidate
        return plan

    # ---- overload ---------------------------------------------------

    def shed_decision(self, live: list[Ticket], candidate_seconds: float
                      ) -> dict[str, Any] | None:
        """None = admit.  Otherwise the priced rejection: the predicted
        backlog including the candidate exceeds the horizon, and
        ``retry_after_seconds`` is the drain time back to the horizon at
        full throughput."""
        if self.shed_horizon_seconds <= 0:
            return None
        backlog = (sum(t.remaining_seconds() for t in live)
                   + max(candidate_seconds, 0.0)) / self.slots
        if backlog <= self.shed_horizon_seconds:
            return None
        return {
            "backlog_seconds": round(backlog, 6),
            "horizon_seconds": self.shed_horizon_seconds,
            "retry_after_seconds": round(
                backlog - self.shed_horizon_seconds, 6),
        }
