"""Preemptive multi-tenant device scheduler (ISSUE 15).

The run service's ``--max-workers`` admission control serialized device
access: a long matrix sweep starved every job behind it, and overload
was a blunt queue-depth 429.  This package replaces that with a
service-level scheduler built from three pieces the repo already
earned:

* :mod:`.pricing` — every job (run AND matrix sweep) is priced in
  predicted device-seconds through the PR-11 cost model
  (fingerprint-peer median first, flops/bytes regression over non-peer
  records second, an explicit default for honestly unpredictable work);
* :mod:`.policy` — pure packing/preemption/aging decisions over priced
  tickets: priority classes with linear aging (sustained high-priority
  load can never starve a low-priority job — the outrank bound is
  asserted in tests), cost-ordered packing within a priority band, and
  preemption ONLY of strictly lower priority classes at the existing
  safe seams;
* :mod:`.core` — the daemon-facing :class:`~.core.JobScheduler`: syncs
  tickets with the durable queue, trips the per-job circuit breaker on
  crash-looping jobs, sheds load explicitly when the predicted backlog
  exceeds the horizon, and emits a schema-v11 ``schedule`` event for
  every decision (admit/pack/preempt/resume/shed/break).

Everything here is jax-free (like :mod:`attackfl_tpu.service.queue`):
decisions read ledger JSON and spool state only.
"""

from attackfl_tpu.scheduler.core import JobScheduler, OverloadShedError
from attackfl_tpu.scheduler.policy import (
    PRIORITY_CLASSES, SchedulerPolicy, Ticket,
)
from attackfl_tpu.scheduler.pricing import JobPricer

__all__ = [
    "JobScheduler", "OverloadShedError", "JobPricer",
    "PRIORITY_CLASSES", "SchedulerPolicy", "Ticket",
]
