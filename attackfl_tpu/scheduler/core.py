"""The daemon-facing scheduler: tickets <-> durable queue <-> workers.

:class:`JobScheduler` replaces the service's oldest-first claim loop.
Each dispatch tick it

1. syncs its in-memory tickets with the durable queue (the queue stays
   the source of truth — tickets are derived state and rebuild from the
   spool after any restart, preemption counts included, because the
   workers persist them into the status records);
2. trips the per-job **circuit breaker**: a queued job whose persisted
   ``attempts`` already reached the threshold is quarantined ``failed``
   without killing the service (a crash-looping job would otherwise eat
   its full retry budget again after every daemon restart — PR 6's
   fail-open philosophy, applied to dispatch);
3. asks the pure :class:`~.policy.SchedulerPolicy` for a plan and acts
   on it: preempt victims via the worker's ``request_preempt`` (the
   round/chunk-boundary stop hook — the job checkpoints, requeues and
   later resumes byte-identical), start picks via the daemon's spawn
   callback with the scheduler's provenance (priority / preemptions /
   accumulated wait) riding the run header into the ledger.

Every decision emits a schema-v11 ``schedule`` event; the ``/schedule``
endpoint and the Prometheus gauges read :meth:`JobScheduler.snapshot`.

The ``preempt_storm`` fault kind forces preemptions of healthy running
jobs here (the chaos gate kills the daemon mid-storm and asserts
byte-identical completion after restart); ``estimate_skew`` lives in
:mod:`.pricing`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from attackfl_tpu.scheduler.policy import (
    DEFAULT_PRIORITY, SchedulerPolicy, Ticket, priority_base,
)
from attackfl_tpu.scheduler.pricing import JobPricer
from attackfl_tpu.service.queue import QueueFullError


class OverloadShedError(QueueFullError):
    """Load shed: predicted backlog past the horizon.  Carries the
    priced ``retry_after_seconds`` hint the HTTP 429 payload forwards —
    an overloaded service tells the submitter WHEN to come back, not
    just no."""

    def __init__(self, message: str, retry_after_seconds: float):
        super().__init__(message)
        self.retry_after_seconds = retry_after_seconds


def spec_priority(spec: dict[str, Any]) -> str:
    """The spec's validated priority class (submit-time 400 on typos)."""
    name = str(spec.get("priority") or DEFAULT_PRIORITY)
    priority_base(name)  # raises ValueError on unknown classes
    return name


def spec_tenant(spec: dict[str, Any], job_id: str) -> str:
    """The tenant a job's device time bills to (ISSUE 16): an explicit
    ``tenant`` spec field, else the submitter's job ``name``, else the
    job id itself — never empty, so the fleet books always have a row."""
    return str(spec.get("tenant") or spec.get("name") or job_id)


def spec_fleet_id(spec: dict[str, Any], job_id: str) -> str:
    """The job's causal fleet-trace id: stamped into the sealed spec at
    submit (so it survives daemon restarts and preemption requeues);
    legacy entries predating the field fall back to the job id, which is
    just as durable a join key."""
    return str(spec.get("fleet_id") or job_id)


class JobScheduler:
    """One service's scheduler.  Thread-safety mirrors the daemon: the
    dispatcher thread ticks; the HTTP thread calls ``admit_check`` and
    ``snapshot``; the shared state is lock-guarded."""

    def __init__(self, queue, telemetry, ledger_dir: str, *,
                 slots: int = 1, aging_rate: float = 1.0,
                 min_runtime_seconds: float = 2.0,
                 shed_horizon_seconds: float = 0.0,
                 breaker_attempts: int = 5,
                 default_cost_seconds: float = 30.0,
                 injector=None,
                 spawn: Callable[[Any, dict[str, Any]], None] | None = None,
                 workers: Callable[[], dict[str, Any]] | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 rescan_seconds: float = 0.25):
        self.queue = queue
        self.telemetry = telemetry
        self.policy = SchedulerPolicy(
            slots=slots, aging_rate=aging_rate,
            min_runtime_seconds=min_runtime_seconds,
            shed_horizon_seconds=shed_horizon_seconds)
        self.pricer = JobPricer(ledger_dir,
                                default_seconds=default_cost_seconds,
                                injector=injector)
        self.breaker_attempts = max(int(breaker_attempts), 1)
        self._injector = injector
        self._spawn = spawn
        self._workers = workers or (lambda: {})
        self._clock = clock
        self._lock = threading.Lock()
        self._tickets: dict[str, Ticket] = {}
        # slot occupancy (ISSUE 16): job_id -> (slot index, acquire
        # monotonic ts).  Rebuilt implicitly after a restart — replayed
        # jobs re-acquire on their resume pack, and the fleet stitcher
        # clamps any unreleased span at the session boundary.
        self._slot_book: dict[str, tuple[int, float]] = {}
        self._tick_seq = 0
        self.last_backlog_seconds = 0.0
        # change detection: a saturated slot must not cost a sealed-entry
        # queue rescan per poll interval (the legacy loop idles there) —
        # rescan only when the queue's durable version or the worker set
        # moved, or every ``rescan_seconds`` as the aging/anti-thrash
        # fallback (bounds preemption latency when nothing else mutates)
        self.rescan_seconds = float(rescan_seconds)
        self._seen_version: int | None = None
        self._seen_workers: int | None = None
        self._last_scan_mono: float | None = None

    # ---- events -----------------------------------------------------

    def _emit(self, action: str, **fields: Any) -> None:
        self.telemetry.events.emit("schedule", action=action, **fields)

    # ---- slot occupancy (ISSUE 16) ----------------------------------

    def _acquire_slot(self, ticket: Ticket) -> int:
        """Lowest free device-slot index for a starting job; emits the
        schema-v12 ``slot`` acquire record the fleet books are built
        from."""
        used = {slot for slot, _ in self._slot_book.values()}
        slot = next(i for i in range(len(used) + 1) if i not in used)
        self._slot_book[ticket.job_id] = (slot, self._clock())
        self.telemetry.events.emit(
            "slot", slot=slot, action="acquire", job_id=ticket.job_id,
            priority=ticket.priority, tenant=ticket.tenant,
            fleet_id=ticket.fleet_id)
        return slot

    def _release_slot(self, job_id: str, reason: str,
                      ticket: Ticket | None = None) -> None:
        """Release ``job_id``'s slot (job left the running set for any
        reason) with the measured busy time.  Idempotent — jobs that
        never held a slot (legacy dispatch, replay windows) are a
        no-op."""
        entry = self._slot_book.pop(job_id, None)
        if entry is None:
            return
        slot, acquired = entry
        fields: dict[str, Any] = {
            "slot": slot, "action": "release", "job_id": job_id,
            "reason": reason,
            "busy_seconds": round(max(self._clock() - acquired, 0.0), 6),
        }
        if ticket is not None:
            fields.update(priority=ticket.priority, tenant=ticket.tenant,
                          fleet_id=ticket.fleet_id)
        self.telemetry.events.emit("slot", **fields)

    # ---- admission (HTTP thread) ------------------------------------

    def admit_check(self, spec: dict[str, Any]) -> dict[str, Any]:
        """Validate priority + shed decision BEFORE the queue admits.
        Returns the price (the daemon's admit event reuses it); raises
        ValueError on a bad priority, :class:`OverloadShedError` when
        the backlog horizon says no."""
        priority = spec_priority(spec)
        price = self.pricer.price(spec)
        with self._lock:
            live = [t for t in self._tickets.values()]
        decision = self.policy.shed_decision(live, price["predicted_seconds"])
        if decision is not None:
            self.telemetry.counters.inc("jobs_shed")
            self._emit("shed", priority=priority,
                       predicted_seconds=price["predicted_seconds"],
                       backlog_seconds=decision["backlog_seconds"],
                       retry_after_seconds=decision["retry_after_seconds"])
            raise OverloadShedError(
                f"overloaded: predicted backlog "
                f"{decision['backlog_seconds']:.1f}s exceeds the "
                f"{decision['horizon_seconds']:.1f}s horizon — retry in "
                f"~{decision['retry_after_seconds']:.1f}s",
                decision["retry_after_seconds"])
        return {"priority": priority, **price}

    # ---- ticket sync ------------------------------------------------

    def _sync_tickets(self, jobs) -> tuple[list[Ticket], list[Ticket]]:
        """Durable queue -> tickets.  Returns (queued, running) tickets;
        terminal jobs drop out, crash-looping queued jobs trip the
        breaker."""
        now = self._clock()
        seen: set[str] = set()
        queued: list[Ticket] = []
        running: list[Ticket] = []
        workers = self._workers()
        for job in jobs:
            state = job.state
            if state not in ("queued", "running"):
                self._release_slot(job.job_id, reason=state,
                                   ticket=self._tickets.get(job.job_id))
                self._tickets.pop(job.job_id, None)
                continue
            seen.add(job.job_id)
            ticket = self._tickets.get(job.job_id)
            if ticket is None:
                ticket = self._admit_ticket(job, now)
            status = job.status
            if state == "queued":
                if int(status.get("attempts", 0)) >= self.breaker_attempts:
                    self._break_job(job, ticket)
                    seen.discard(job.job_id)
                    continue
                if ticket.started_ts is not None:
                    # came back from a preempt/drain requeue: refresh the
                    # persisted progress + preemption count and re-enter
                    # the wait clock (the slot came free with it)
                    self._release_slot(job.job_id, reason="preempt",
                                       ticket=ticket)
                    ticket.started_ts = None
                    ticket.preempt_requested = False
                    ticket.enqueued_ts = now
                    ticket.preemptions = int(status.get("preemptions", 0)
                                             or ticket.preemptions)
                self._refresh_progress(ticket, status)
                queued.append(ticket)
            else:  # running
                if job.job_id not in workers:
                    # replay window: marked running but no live worker
                    # yet (or the worker just exited) — not packable,
                    # not preemptable this tick
                    continue
                if ticket.started_ts is None:
                    ticket.started_ts = now
                running.append(ticket)
        for job_id in list(self._tickets):
            if job_id not in seen:
                self._release_slot(job_id, reason="gone",
                                   ticket=self._tickets.get(job_id))
                self._tickets.pop(job_id, None)
        return queued, running

    def _admit_ticket(self, job, now: float) -> Ticket:
        status = job.status
        price = self.pricer.price(job.spec)
        ticket = Ticket(
            job_id=job.job_id,
            priority=spec_priority(job.spec),
            predicted_seconds=float(price["predicted_seconds"]),
            pricing=price,
            enqueued_ts=now,
            preemptions=int(status.get("preemptions", 0)),
            wait_seconds=float(status.get("wait_seconds", 0.0) or 0.0),
            seq=int(job.spec.get("seq", 0)),
            fleet_id=spec_fleet_id(job.spec, job.job_id),
            tenant=spec_tenant(job.spec, job.job_id),
        )
        self._refresh_progress(ticket, status)
        self._tickets[job.job_id] = ticket
        self._emit("admit", job_id=job.job_id, priority=ticket.priority,
                   predicted_seconds=ticket.predicted_seconds,
                   fleet_id=ticket.fleet_id, tenant=ticket.tenant,
                   reason=str(price.get("method", "")))
        return ticket

    @staticmethod
    def _refresh_progress(ticket: Ticket, status: dict[str, Any]) -> None:
        completed = status.get("completed")
        target = status.get("target")
        if isinstance(completed, int) and isinstance(target, int) \
                and not isinstance(completed, bool) and target > 0:
            ticket.completed_fraction = min(max(completed / target, 0.0), 1.0)

    def _break_job(self, job, ticket: Ticket) -> None:
        attempts = int(job.status.get("attempts", 0))
        error = str(job.status.get("error") or "")
        self.queue.mark(
            job.job_id, "failed", attempts=attempts, circuit_broken=True,
            error=(f"circuit breaker open after {attempts} crash(es)"
                   + (f"; last: {error}" if error else "")))
        self._tickets.pop(job.job_id, None)
        self.telemetry.counters.inc("jobs_circuit_broken")
        self._emit("break", job_id=job.job_id, priority=ticket.priority,
                   reason=f"{attempts} attempts >= breaker threshold "
                          f"{self.breaker_attempts}")

    # ---- the tick (dispatcher thread) -------------------------------

    def tick(self) -> None:
        with self._lock:
            self._tick_seq += 1
            storm = 0
            if self._injector is not None:
                storm = self._injector.preempt_storm_count(self._tick_seq)
            workers = self._workers()
            version = getattr(self.queue, "version", None)
            mono = time.monotonic()
            if (not storm and version is not None
                    and version == self._seen_version
                    and len(workers) == self._seen_workers
                    and self._last_scan_mono is not None
                    and mono - self._last_scan_mono < self.rescan_seconds):
                return
            self._seen_version = version
            self._seen_workers = len(workers)
            self._last_scan_mono = mono
            queued, running = self._sync_tickets(self.queue.jobs())
            now = self._clock()
            plan = self.policy.plan(queued, running, now)
            self.last_backlog_seconds = plan.backlog_seconds
            victims = list(plan.preempt)
            if storm:
                forced = [t for t in running
                          if not t.preempt_requested][:storm]
                for ticket in forced:
                    ticket.preempt_requested = True
                victims += forced
            for ticket in victims:
                self._preempt(ticket, workers,
                              reason=("preempt_storm"
                                      if ticket not in plan.preempt
                                      else "priority"))
            for ticket in plan.start:
                self._start(ticket, now)

    def _preempt(self, ticket: Ticket, workers: dict[str, Any],
                 reason: str) -> None:
        worker = workers.get(ticket.job_id)
        if worker is None:
            ticket.preempt_requested = False
            return
        worker.request_preempt()
        self.telemetry.counters.inc("jobs_preempted")
        self._emit("preempt", job_id=ticket.job_id,
                   priority=ticket.priority, reason=reason,
                   preemptions=ticket.preemptions + 1,
                   fleet_id=ticket.fleet_id, tenant=ticket.tenant,
                   predicted_seconds=round(ticket.remaining_seconds(), 6))

    def _start(self, ticket: Ticket, now: float) -> None:
        job = self.queue.claim(ticket.job_id)
        if job is None:  # cancelled/raced away — drop, next tick resyncs
            self._tickets.pop(ticket.job_id, None)
            return
        ticket.wait_seconds = round(
            ticket.wait_seconds + max(now - ticket.enqueued_ts, 0.0), 6)
        ticket.started_ts = now
        slot = self._acquire_slot(ticket)
        sched_meta = {
            "priority": ticket.priority,
            "preemptions": ticket.preemptions,
            "wait_seconds": ticket.wait_seconds,
            "fleet_id": ticket.fleet_id,
            "tenant": ticket.tenant,
            "slot": slot,
        }
        # persist the accounting next to the job so it survives daemon
        # restarts and `job status` shows it without the event log
        self.queue.mark(job.job_id, "running", **sched_meta)
        job.status = dict(job.status, state="running", **sched_meta)
        self._emit("resume" if ticket.preemptions > 0 else "pack",
                   job_id=ticket.job_id, priority=ticket.priority,
                   predicted_seconds=round(ticket.remaining_seconds(), 6),
                   wait_seconds=ticket.wait_seconds,
                   preemptions=ticket.preemptions,
                   backlog_seconds=self.last_backlog_seconds,
                   fleet_id=ticket.fleet_id, tenant=ticket.tenant,
                   slot=slot,
                   reason=str(ticket.pricing.get("method", "")))
        if self._spawn is not None:
            self._spawn(job, sched_meta)

    # ---- observability (/schedule + gauges) -------------------------

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            now = self._clock()
            tickets = list(self._tickets.values())
            rows = []
            for ticket in sorted(
                    tickets, key=lambda t: (t.started_ts is None, t.seq)):
                waiting = ticket.started_ts is None
                slot_entry = self._slot_book.get(ticket.job_id)
                rows.append({
                    "job_id": ticket.job_id,
                    "state": "queued" if waiting else "running",
                    "priority": ticket.priority,
                    "fleet_id": ticket.fleet_id,
                    "tenant": ticket.tenant,
                    "slot": slot_entry[0] if slot_entry else None,
                    "effective_priority": round(
                        self.policy.effective_priority(ticket, now), 3)
                    if waiting else ticket.base,
                    "predicted_remaining_seconds": round(
                        ticket.remaining_seconds(), 3),
                    "pricing_method": ticket.pricing.get("method"),
                    "preemptions": ticket.preemptions,
                    "wait_seconds": round(
                        ticket.wait_seconds
                        + (max(now - ticket.enqueued_ts, 0.0)
                           if waiting else 0.0), 3),
                    "preempt_requested": ticket.preempt_requested,
                })
            waits = [r["wait_seconds"] for r in rows
                     if r["state"] == "queued"]
            # per-priority queue-wait evidence for the fleet SLO gauges
            # (ISSUE 16): count + p95 + max over the QUEUED rows of each
            # class, so /metrics can export them without replaying events
            from attackfl_tpu.telemetry.summary import percentile

            waits_by_priority: dict[str, dict[str, Any]] = {}
            for row in rows:
                if row["state"] != "queued":
                    continue
                bucket = waits_by_priority.setdefault(
                    row["priority"], {"waits": []})
                bucket["waits"].append(row["wait_seconds"])
            waits_by_priority = {
                prio: {
                    "count": len(b["waits"]),
                    "p95_seconds": round(percentile(b["waits"], 95.0), 3),
                    "max_seconds": round(max(b["waits"]), 3),
                }
                for prio, b in waits_by_priority.items()
            }
            counters = self.telemetry.counters.snapshot()
            return {
                "slots": self.policy.slots,
                "aging_rate": self.policy.aging_rate,
                "starvation_bound_seconds": round(
                    self.policy.starvation_bound_seconds(), 3),
                "shed_horizon_seconds": self.policy.shed_horizon_seconds,
                "breaker_attempts": self.breaker_attempts,
                "backlog_seconds": self.last_backlog_seconds,
                "queue_depth": len(waits),
                "running_jobs": sum(
                    1 for r in rows if r["state"] == "running"),
                "max_wait_seconds": round(max(waits), 3) if waits else 0.0,
                "waits_by_priority": waits_by_priority,
                "preempted_total": int(counters.get("jobs_preempted", 0)),
                "shed_total": int(counters.get("jobs_shed", 0)),
                "circuit_broken_total": int(
                    counters.get("jobs_circuit_broken", 0)),
                "jobs": rows,
            }
