"""The daemon-facing scheduler: tickets <-> durable queue <-> workers.

:class:`JobScheduler` replaces the service's oldest-first claim loop.
Each dispatch tick it

1. syncs its in-memory tickets with the durable queue (the queue stays
   the source of truth — tickets are derived state and rebuild from the
   spool after any restart, preemption counts included, because the
   workers persist them into the status records);
2. trips the per-job **circuit breaker**: a queued job whose persisted
   ``attempts`` already reached the threshold is quarantined ``failed``
   without killing the service (a crash-looping job would otherwise eat
   its full retry budget again after every daemon restart — PR 6's
   fail-open philosophy, applied to dispatch);
3. asks the pure :class:`~.policy.SchedulerPolicy` for a plan and acts
   on it: preempt victims via the worker's ``request_preempt`` (the
   round/chunk-boundary stop hook — the job checkpoints, requeues and
   later resumes byte-identical), start picks via the daemon's spawn
   callback with the scheduler's provenance (priority / preemptions /
   accumulated wait) riding the run header into the ledger.

Every decision emits a schema-v11 ``schedule`` event; the ``/schedule``
endpoint and the Prometheus gauges read :meth:`JobScheduler.snapshot`.

The ``preempt_storm`` fault kind forces preemptions of healthy running
jobs here (the chaos gate kills the daemon mid-storm and asserts
byte-identical completion after restart); ``estimate_skew`` lives in
:mod:`.pricing`.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from attackfl_tpu.scheduler.policy import (
    DEFAULT_PRIORITY, SchedulerPolicy, Ticket, priority_base,
)
from attackfl_tpu.scheduler.pricing import JobPricer
from attackfl_tpu.service.queue import QueueFullError


class OverloadShedError(QueueFullError):
    """Load shed: predicted backlog past the horizon.  Carries the
    priced ``retry_after_seconds`` hint the HTTP 429 payload forwards —
    an overloaded service tells the submitter WHEN to come back, not
    just no."""

    def __init__(self, message: str, retry_after_seconds: float):
        super().__init__(message)
        self.retry_after_seconds = retry_after_seconds


def spec_priority(spec: dict[str, Any]) -> str:
    """The spec's validated priority class (submit-time 400 on typos)."""
    name = str(spec.get("priority") or DEFAULT_PRIORITY)
    priority_base(name)  # raises ValueError on unknown classes
    return name


class JobScheduler:
    """One service's scheduler.  Thread-safety mirrors the daemon: the
    dispatcher thread ticks; the HTTP thread calls ``admit_check`` and
    ``snapshot``; the shared state is lock-guarded."""

    def __init__(self, queue, telemetry, ledger_dir: str, *,
                 slots: int = 1, aging_rate: float = 1.0,
                 min_runtime_seconds: float = 2.0,
                 shed_horizon_seconds: float = 0.0,
                 breaker_attempts: int = 5,
                 default_cost_seconds: float = 30.0,
                 injector=None,
                 spawn: Callable[[Any, dict[str, Any]], None] | None = None,
                 workers: Callable[[], dict[str, Any]] | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 rescan_seconds: float = 0.25):
        self.queue = queue
        self.telemetry = telemetry
        self.policy = SchedulerPolicy(
            slots=slots, aging_rate=aging_rate,
            min_runtime_seconds=min_runtime_seconds,
            shed_horizon_seconds=shed_horizon_seconds)
        self.pricer = JobPricer(ledger_dir,
                                default_seconds=default_cost_seconds,
                                injector=injector)
        self.breaker_attempts = max(int(breaker_attempts), 1)
        self._injector = injector
        self._spawn = spawn
        self._workers = workers or (lambda: {})
        self._clock = clock
        self._lock = threading.Lock()
        self._tickets: dict[str, Ticket] = {}
        self._tick_seq = 0
        self.last_backlog_seconds = 0.0
        # change detection: a saturated slot must not cost a sealed-entry
        # queue rescan per poll interval (the legacy loop idles there) —
        # rescan only when the queue's durable version or the worker set
        # moved, or every ``rescan_seconds`` as the aging/anti-thrash
        # fallback (bounds preemption latency when nothing else mutates)
        self.rescan_seconds = float(rescan_seconds)
        self._seen_version: int | None = None
        self._seen_workers: int | None = None
        self._last_scan_mono: float | None = None

    # ---- events -----------------------------------------------------

    def _emit(self, action: str, **fields: Any) -> None:
        self.telemetry.events.emit("schedule", action=action, **fields)

    # ---- admission (HTTP thread) ------------------------------------

    def admit_check(self, spec: dict[str, Any]) -> dict[str, Any]:
        """Validate priority + shed decision BEFORE the queue admits.
        Returns the price (the daemon's admit event reuses it); raises
        ValueError on a bad priority, :class:`OverloadShedError` when
        the backlog horizon says no."""
        priority = spec_priority(spec)
        price = self.pricer.price(spec)
        with self._lock:
            live = [t for t in self._tickets.values()]
        decision = self.policy.shed_decision(live, price["predicted_seconds"])
        if decision is not None:
            self.telemetry.counters.inc("jobs_shed")
            self._emit("shed", priority=priority,
                       predicted_seconds=price["predicted_seconds"],
                       backlog_seconds=decision["backlog_seconds"],
                       retry_after_seconds=decision["retry_after_seconds"])
            raise OverloadShedError(
                f"overloaded: predicted backlog "
                f"{decision['backlog_seconds']:.1f}s exceeds the "
                f"{decision['horizon_seconds']:.1f}s horizon — retry in "
                f"~{decision['retry_after_seconds']:.1f}s",
                decision["retry_after_seconds"])
        return {"priority": priority, **price}

    # ---- ticket sync ------------------------------------------------

    def _sync_tickets(self, jobs) -> tuple[list[Ticket], list[Ticket]]:
        """Durable queue -> tickets.  Returns (queued, running) tickets;
        terminal jobs drop out, crash-looping queued jobs trip the
        breaker."""
        now = self._clock()
        seen: set[str] = set()
        queued: list[Ticket] = []
        running: list[Ticket] = []
        workers = self._workers()
        for job in jobs:
            state = job.state
            if state not in ("queued", "running"):
                self._tickets.pop(job.job_id, None)
                continue
            seen.add(job.job_id)
            ticket = self._tickets.get(job.job_id)
            if ticket is None:
                ticket = self._admit_ticket(job, now)
            status = job.status
            if state == "queued":
                if int(status.get("attempts", 0)) >= self.breaker_attempts:
                    self._break_job(job, ticket)
                    seen.discard(job.job_id)
                    continue
                if ticket.started_ts is not None:
                    # came back from a preempt/drain requeue: refresh the
                    # persisted progress + preemption count and re-enter
                    # the wait clock
                    ticket.started_ts = None
                    ticket.preempt_requested = False
                    ticket.enqueued_ts = now
                    ticket.preemptions = int(status.get("preemptions", 0)
                                             or ticket.preemptions)
                self._refresh_progress(ticket, status)
                queued.append(ticket)
            else:  # running
                if job.job_id not in workers:
                    # replay window: marked running but no live worker
                    # yet (or the worker just exited) — not packable,
                    # not preemptable this tick
                    continue
                if ticket.started_ts is None:
                    ticket.started_ts = now
                running.append(ticket)
        for job_id in list(self._tickets):
            if job_id not in seen:
                self._tickets.pop(job_id, None)
        return queued, running

    def _admit_ticket(self, job, now: float) -> Ticket:
        status = job.status
        price = self.pricer.price(job.spec)
        ticket = Ticket(
            job_id=job.job_id,
            priority=spec_priority(job.spec),
            predicted_seconds=float(price["predicted_seconds"]),
            pricing=price,
            enqueued_ts=now,
            preemptions=int(status.get("preemptions", 0)),
            wait_seconds=float(status.get("wait_seconds", 0.0) or 0.0),
            seq=int(job.spec.get("seq", 0)),
        )
        self._refresh_progress(ticket, status)
        self._tickets[job.job_id] = ticket
        self._emit("admit", job_id=job.job_id, priority=ticket.priority,
                   predicted_seconds=ticket.predicted_seconds,
                   reason=str(price.get("method", "")))
        return ticket

    @staticmethod
    def _refresh_progress(ticket: Ticket, status: dict[str, Any]) -> None:
        completed = status.get("completed")
        target = status.get("target")
        if isinstance(completed, int) and isinstance(target, int) \
                and not isinstance(completed, bool) and target > 0:
            ticket.completed_fraction = min(max(completed / target, 0.0), 1.0)

    def _break_job(self, job, ticket: Ticket) -> None:
        attempts = int(job.status.get("attempts", 0))
        error = str(job.status.get("error") or "")
        self.queue.mark(
            job.job_id, "failed", attempts=attempts, circuit_broken=True,
            error=(f"circuit breaker open after {attempts} crash(es)"
                   + (f"; last: {error}" if error else "")))
        self._tickets.pop(job.job_id, None)
        self.telemetry.counters.inc("jobs_circuit_broken")
        self._emit("break", job_id=job.job_id, priority=ticket.priority,
                   reason=f"{attempts} attempts >= breaker threshold "
                          f"{self.breaker_attempts}")

    # ---- the tick (dispatcher thread) -------------------------------

    def tick(self) -> None:
        with self._lock:
            self._tick_seq += 1
            storm = 0
            if self._injector is not None:
                storm = self._injector.preempt_storm_count(self._tick_seq)
            workers = self._workers()
            version = getattr(self.queue, "version", None)
            mono = time.monotonic()
            if (not storm and version is not None
                    and version == self._seen_version
                    and len(workers) == self._seen_workers
                    and self._last_scan_mono is not None
                    and mono - self._last_scan_mono < self.rescan_seconds):
                return
            self._seen_version = version
            self._seen_workers = len(workers)
            self._last_scan_mono = mono
            queued, running = self._sync_tickets(self.queue.jobs())
            now = self._clock()
            plan = self.policy.plan(queued, running, now)
            self.last_backlog_seconds = plan.backlog_seconds
            victims = list(plan.preempt)
            if storm:
                forced = [t for t in running
                          if not t.preempt_requested][:storm]
                for ticket in forced:
                    ticket.preempt_requested = True
                victims += forced
            for ticket in victims:
                self._preempt(ticket, workers,
                              reason=("preempt_storm"
                                      if ticket not in plan.preempt
                                      else "priority"))
            for ticket in plan.start:
                self._start(ticket, now)

    def _preempt(self, ticket: Ticket, workers: dict[str, Any],
                 reason: str) -> None:
        worker = workers.get(ticket.job_id)
        if worker is None:
            ticket.preempt_requested = False
            return
        worker.request_preempt()
        self.telemetry.counters.inc("jobs_preempted")
        self._emit("preempt", job_id=ticket.job_id,
                   priority=ticket.priority, reason=reason,
                   preemptions=ticket.preemptions + 1,
                   predicted_seconds=round(ticket.remaining_seconds(), 6))

    def _start(self, ticket: Ticket, now: float) -> None:
        job = self.queue.claim(ticket.job_id)
        if job is None:  # cancelled/raced away — drop, next tick resyncs
            self._tickets.pop(ticket.job_id, None)
            return
        ticket.wait_seconds = round(
            ticket.wait_seconds + max(now - ticket.enqueued_ts, 0.0), 6)
        ticket.started_ts = now
        sched_meta = {
            "priority": ticket.priority,
            "preemptions": ticket.preemptions,
            "wait_seconds": ticket.wait_seconds,
        }
        # persist the accounting next to the job so it survives daemon
        # restarts and `job status` shows it without the event log
        self.queue.mark(job.job_id, "running", **sched_meta)
        job.status = dict(job.status, state="running", **sched_meta)
        self._emit("resume" if ticket.preemptions > 0 else "pack",
                   job_id=ticket.job_id, priority=ticket.priority,
                   predicted_seconds=round(ticket.remaining_seconds(), 6),
                   wait_seconds=ticket.wait_seconds,
                   preemptions=ticket.preemptions,
                   backlog_seconds=self.last_backlog_seconds,
                   reason=str(ticket.pricing.get("method", "")))
        if self._spawn is not None:
            self._spawn(job, sched_meta)

    # ---- observability (/schedule + gauges) -------------------------

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            now = self._clock()
            tickets = list(self._tickets.values())
            rows = []
            for ticket in sorted(
                    tickets, key=lambda t: (t.started_ts is None, t.seq)):
                waiting = ticket.started_ts is None
                rows.append({
                    "job_id": ticket.job_id,
                    "state": "queued" if waiting else "running",
                    "priority": ticket.priority,
                    "effective_priority": round(
                        self.policy.effective_priority(ticket, now), 3)
                    if waiting else ticket.base,
                    "predicted_remaining_seconds": round(
                        ticket.remaining_seconds(), 3),
                    "pricing_method": ticket.pricing.get("method"),
                    "preemptions": ticket.preemptions,
                    "wait_seconds": round(
                        ticket.wait_seconds
                        + (max(now - ticket.enqueued_ts, 0.0)
                           if waiting else 0.0), 3),
                    "preempt_requested": ticket.preempt_requested,
                })
            waits = [r["wait_seconds"] for r in rows
                     if r["state"] == "queued"]
            counters = self.telemetry.counters.snapshot()
            return {
                "slots": self.policy.slots,
                "aging_rate": self.policy.aging_rate,
                "starvation_bound_seconds": round(
                    self.policy.starvation_bound_seconds(), 3),
                "shed_horizon_seconds": self.policy.shed_horizon_seconds,
                "breaker_attempts": self.breaker_attempts,
                "backlog_seconds": self.last_backlog_seconds,
                "queue_depth": len(waits),
                "max_wait_seconds": round(max(waits), 3) if waits else 0.0,
                "preempted_total": int(counters.get("jobs_preempted", 0)),
                "shed_total": int(counters.get("jobs_shed", 0)),
                "circuit_broken_total": int(
                    counters.get("jobs_circuit_broken", 0)),
                "jobs": rows,
            }
