"""Job pricing: a queue spec -> predicted device-seconds.

The bin-packer's input.  Pricing goes through the PR-11 cost model
(:mod:`attackfl_tpu.costmodel.estimate`) against the service's SHARED
ledger — the same corpus ``attackfl-tpu cost estimate`` reads, so the
packer's decisions inherit the leave-one-out 2x accuracy contract
``cost validate`` enforces:

* a **run** job is priced by its config fingerprint: peer-median
  ``round_device_time`` x rounds first, the flops/bytes regression over
  non-peer records when a static profile is available;
* a **matrix** job is priced per cell (each cell has its own
  fingerprint, exactly like ``cost estimate --matrix``) and summed —
  the serial bound, which the batched sweep executor lands at or under;
* an honestly unpredictable job (cold ledger, no profile) gets the
  corpus-median wall time when the ledger has ANY measured history,
  else the configured default — explicit, recorded in the decision's
  ``schedule`` event, never a silent zero (a zero-priced job would pack
  for free and the backlog estimate would lie).

The ``estimate_skew`` fault kind multiplies prices here — the chaos
seam proving degradation stays graceful when the cost model is wrong.

Jax-free: the AOT-compile profiling path stays in
:mod:`attackfl_tpu.costmodel.cli`; the scheduler must price jobs in the
dispatch loop without touching the device.
"""

from __future__ import annotations

from typing import Any

from attackfl_tpu.config import config_from_dict
from attackfl_tpu.costmodel.estimate import (
    corpus_default_seconds, predict_run,
)
from attackfl_tpu.utils.fingerprint import config_fingerprint

DEFAULT_SECONDS = 30.0


class JobPricer:
    """Price specs against the service ledger, one load per price call
    (the corpus grows as jobs finish — a later job of the same
    fingerprint prices off its predecessors' measurements)."""

    def __init__(self, ledger_dir: str, default_seconds: float =
                 DEFAULT_SECONDS, injector=None):
        self.ledger_dir = ledger_dir
        self.default_seconds = max(float(default_seconds), 0.001)
        self._injector = injector
        self._skew_seq = 0

    # ---- ledger access ----------------------------------------------

    def _records(self) -> list[dict[str, Any]]:
        try:
            from attackfl_tpu.ledger.store import LedgerStore

            records, _ = LedgerStore(self.ledger_dir).load()
            return records
        except Exception:  # noqa: BLE001 — a cold/absent ledger prices default
            return []

    # ---- pricing ----------------------------------------------------

    def price(self, spec: dict[str, Any]) -> dict[str, Any]:
        """One spec -> ``{predicted_seconds, method, fingerprint, ...}``.
        Never raises on an unpriceable spec — unpredictable work gets
        the explicit default (the packer needs SOME number, and the
        decision record says which kind it was)."""
        try:
            records = self._records()
            if spec.get("type") == "matrix":
                out = self._price_matrix(spec, records)
            else:
                out = self._price_run(spec, records)
        except Exception as e:  # noqa: BLE001 — malformed spec: default price
            out = {"predicted_seconds": self.default_seconds,
                   "method": "default",
                   "error": f"{type(e).__name__}: {e}"[:200]}
        self._skew_seq += 1
        if self._injector is not None:
            factor = self._injector.estimate_skew_factor(self._skew_seq)
            if factor != 1.0:
                out["predicted_seconds"] = round(
                    out["predicted_seconds"] * factor, 6)
                out["skewed_by"] = factor
        return out

    def _default(self, records: list[dict[str, Any]]) -> tuple[float, str]:
        corpus = corpus_default_seconds(records)
        if corpus is not None:
            return corpus, "corpus_median"
        return self.default_seconds, "default"

    def _price_run(self, spec: dict[str, Any],
                   records: list[dict[str, Any]]) -> dict[str, Any]:
        cfg = config_from_dict(dict(spec.get("config") or {}))
        rounds = int(spec.get("num_rounds") or cfg.num_round)
        fingerprint = config_fingerprint(cfg)
        prediction = predict_run(records, fingerprint, rounds)
        if prediction is None:
            seconds, method = self._default(records)
            return {"predicted_seconds": round(seconds, 6),
                    "method": method, "fingerprint": fingerprint,
                    "rounds": rounds}
        return {"predicted_seconds": prediction["predicted_wall_seconds"],
                "method": prediction["method"],
                "fingerprint": fingerprint, "rounds": rounds,
                "round_device_time": prediction["round_device_time"]}

    def _price_matrix(self, spec: dict[str, Any],
                      records: list[dict[str, Any]]) -> dict[str, Any]:
        from attackfl_tpu.matrix.grid import (
            cell_config, expand_cells, grid_from_dict,
        )

        cfg = config_from_dict(dict(spec.get("config") or {}))
        if cfg.prng_impl != "threefry2x32":
            # the worker forces threefry for batched sweeps — price the
            # config that will actually run (fingerprints must match)
            cfg = cfg.replace(prng_impl="threefry2x32")
        grid = grid_from_dict(dict(spec.get("grid") or {}))
        cells = expand_cells(grid)
        total = 0.0
        predicted: list[float] = []
        for cell in cells:
            ccfg = cell_config(cfg, cell, rounds=grid.rounds)
            prediction = predict_run(records, config_fingerprint(ccfg),
                                     grid.rounds)
            if prediction is not None:
                predicted.append(prediction["predicted_wall_seconds"])
        if predicted:
            # unpredictable cells price at their siblings' mean — the
            # cells share the round program shape, so a peer-priced
            # sibling is the best available stand-in
            per_cell = sum(predicted) / len(predicted)
            total = sum(predicted) + per_cell * (len(cells) - len(predicted))
            method = "peer" if len(predicted) == len(cells) \
                else "peer_partial"
        else:
            seconds, method = self._default(records)
            total = seconds  # one sweep = one default job price
        return {"predicted_seconds": round(total, 6), "method": method,
                "cells": len(cells), "predicted_cells": len(predicted),
                "rounds": grid.rounds}
