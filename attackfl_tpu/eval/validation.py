"""Server-side validation: the round-acceptance gate.

Parity with the reference's Validation subsystem (src/Validation.py:19-214):
ICU rounds are scored by ROC-AUC and fail on NaN outputs; HAR by accuracy;
CIFAR10 by NLL + accuracy failing on NaN or |loss| > 1e6; hyper mode pools
every client's personalized outputs into one AUC.  Unlike the reference
(batched torch loops on host), evaluation here is a single jitted forward
over the device-resident test set, including a jit-compatible tie-aware
ROC-AUC (no sklearn).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

Batch = dict[str, jnp.ndarray]


def roc_auc(labels: jnp.ndarray, scores: jnp.ndarray) -> jnp.ndarray:
    """Area under the ROC curve, tie-aware, fully on-device.

    Uses the rank-statistic identity AUC = (Σ ranks⁺ − n⁺(n⁺+1)/2)/(n⁺ n⁻)
    with average ranks for tied scores — identical to trapezoidal
    integration over the tie-grouped ROC curve (what sklearn's
    roc_curve/auc computes for the reference, src/Validation.py:116-117).
    """
    labels = labels.reshape(-1)
    scores = scores.reshape(-1)
    sorted_scores = jnp.sort(scores)
    left = jnp.searchsorted(sorted_scores, scores, side="left")
    right = jnp.searchsorted(sorted_scores, scores, side="right")
    avg_rank = (left + right + 1).astype(jnp.float32) / 2.0  # 1-based average ranks
    n_pos = jnp.sum(labels)
    n_neg = labels.shape[0] - n_pos
    rank_sum = jnp.sum(jnp.where(labels > 0.5, avg_rank, 0.0))
    denom = n_pos * n_neg
    # single-class labels make AUC undefined — return NaN explicitly
    # (instead of a 0/0 or x/0 artifact) so callers can gate on finiteness;
    # the reference fails the round via sklearn's exception there
    # (src/Validation.py:104-122)
    return jnp.where(
        denom > 0,
        (rank_sum - n_pos * (n_pos + 1) / 2.0) / jnp.maximum(denom, 1.0),
        jnp.nan,
    )


def _forward_in_chunks(apply_fn: Callable, data: Batch, chunk: int = 4096):
    """Evaluate in fixed-size chunks to bound activation memory; the test
    set is padded to a multiple of the chunk size."""
    n = next(iter(data.values())).shape[0]
    num_chunks = -(-n // chunk)
    pad = num_chunks * chunk - n
    padded = {k: jnp.concatenate([v, jnp.repeat(v[:1], pad, axis=0)], axis=0) if pad else v
              for k, v in data.items()}
    chunks = {k: v.reshape((num_chunks, chunk) + v.shape[1:]) for k, v in padded.items()}
    outs = jax.lax.map(apply_fn, chunks)
    outs = outs.reshape((num_chunks * chunk,) + outs.shape[2:])
    return outs[:n]


def evaluate_icu(model, params: Any, test_data: Batch) -> dict[str, jnp.ndarray]:
    """ROC-AUC over the ICU test set; ok=False on NaN outputs
    (reference: test_icu, src/Validation.py:92-122)."""
    probs = _forward_in_chunks(
        lambda b: model.apply({"params": params}, b["vitals"], b["labs"])[:, 0],
        test_data,
    )
    auc_val = roc_auc(test_data["label"], probs)
    ok = ~jnp.any(jnp.isnan(probs)) & jnp.isfinite(auc_val)
    return {"roc_auc": auc_val, "ok": ok, "metric": auc_val}


def evaluate_har(model, params: Any, test_data: Batch) -> dict[str, jnp.ndarray]:
    """Accuracy over the HAR test set (reference: test_har,
    src/Validation.py:124-136 — always passes the round)."""
    logits = _forward_in_chunks(
        lambda b: model.apply({"params": params}, b["x"]), test_data
    )
    acc = jnp.mean((jnp.argmax(logits, axis=-1) == test_data["label"]).astype(jnp.float32))
    return {"accuracy": acc, "ok": jnp.asarray(True), "metric": acc}


def evaluate_cifar(model, params: Any, test_data: Batch) -> dict[str, jnp.ndarray]:
    """Mean NLL + accuracy; fails on NaN or |loss| > 1e6
    (reference: test_image, src/Validation.py:69-90)."""
    logp = _forward_in_chunks(
        lambda b: model.apply({"params": params}, b["x"]), test_data
    )
    nll = -jnp.take_along_axis(logp, test_data["label"][:, None], axis=1)[:, 0]
    loss = jnp.mean(nll)
    acc = jnp.mean((jnp.argmax(logp, axis=-1) == test_data["label"]).astype(jnp.float32))
    ok = jnp.isfinite(loss) & (jnp.abs(loss) <= 1e6)
    return {"nll": loss, "accuracy": acc, "ok": ok, "metric": acc}


def evaluate_hyper_icu(model, stacked_params: Any, test_data: Batch) -> dict[str, jnp.ndarray]:
    """Hyper-mode ICU validation: every client's personalized model runs the
    full test set and ALL outputs pool into one ROC-AUC
    (reference: test_hyper_icu, src/Validation.py:178-214)."""

    def one_client(params):
        return _forward_in_chunks(
            lambda b: model.apply({"params": params}, b["vitals"], b["labs"])[:, 0],
            test_data,
        )

    probs = jax.lax.map(one_client, stacked_params)  # (C, N)
    n_clients = probs.shape[0]
    labels = jnp.tile(test_data["label"], n_clients)
    auc_val = roc_auc(labels, probs.reshape(-1))
    ok = ~jnp.any(jnp.isnan(probs)) & jnp.isfinite(auc_val)
    return {"roc_auc": auc_val, "ok": ok, "metric": auc_val}


def evaluate_hyper_cifar(model, stacked_params: Any, test_data: Batch) -> dict[str, jnp.ndarray]:
    """Hyper-mode CIFAR validation: per-client personalized models over the
    full test set, losses/accuracy pooled (reference: test_hyper_image,
    src/Validation.py:147-176)."""

    def one_client(params):
        return _forward_in_chunks(
            lambda b: model.apply({"params": params}, b["x"]), test_data
        )

    logp = jax.lax.map(one_client, stacked_params)  # (C, N, 10)
    nll = -jnp.take_along_axis(logp, test_data["label"][None, :, None], axis=2)[..., 0]
    loss = jnp.mean(nll)
    acc = jnp.mean((jnp.argmax(logp, axis=-1) == test_data["label"][None, :]).astype(jnp.float32))
    ok = jnp.isfinite(loss) & (jnp.abs(loss) <= 1e6)
    return {"nll": loss, "accuracy": acc, "ok": ok, "metric": acc}


_EVALUATORS = {"ICU": evaluate_icu, "HAR": evaluate_har, "CIFAR10": evaluate_cifar}
_HYPER_EVALUATORS = {"ICU": evaluate_hyper_icu, "CIFAR10": evaluate_hyper_cifar}


class Validation:
    """Object-style wrapper mirroring the reference's ``Validation`` class
    surface (``test``/``test_hyper``, src/Validation.py:19-214), with jitted
    evaluators underneath."""

    def __init__(self, model, data_name: str, test_data: Batch, logger=None,
                 telemetry=None):
        if data_name not in _EVALUATORS:
            raise ValueError(f"Data name '{data_name}' is not valid.")
        self.data_name = data_name
        self.logger = logger
        # telemetry is host-side only: the raw eval_fns below stay pure so
        # the fused round-scan can still inline them into its XLA program
        self.telemetry = telemetry
        self.test_data = {k: jnp.asarray(v) for k, v in test_data.items()}
        # raw (unjitted) evaluators are exposed so the fused round-scan can
        # inline validation into its own XLA program
        self.eval_fn = partial(_EVALUATORS[data_name], model, test_data=self.test_data)
        self._eval = jax.jit(self.eval_fn)
        if data_name in _HYPER_EVALUATORS:
            self.eval_hyper_fn = partial(
                _HYPER_EVALUATORS[data_name], model, test_data=self.test_data
            )
            self._eval_hyper = jax.jit(self.eval_hyper_fn)
        else:
            # HAR has no hyper eval (reference: Validation.py:138-145)
            self.eval_hyper_fn = None
            self._eval_hyper = None

    def _record(self, ok: bool, metrics: dict[str, float]) -> None:
        """Failed validations are recorded as events (a failed gate retries
        the whole round — exactly the diagnosis-by-grep gap the telemetry
        layer closes); successes ride the round record instead."""
        if self.telemetry is None or not self.telemetry.enabled:
            return
        if not ok:
            self.telemetry.counters.inc("validation_failures")
            self.telemetry.events.emit(
                "validation", ok=False, data_name=self.data_name, **metrics)

    def test(self, params: Any) -> tuple[bool, dict[str, float]]:
        out = {k: np.asarray(v) for k, v in self._eval(params).items()}
        ok = bool(out.pop("ok"))
        metrics = {k: float(v) for k, v in out.items()}
        if self.logger:
            self.logger.log_info(
                " ".join(f"{k}={v:.4f}" for k, v in metrics.items())
            )
        self._record(ok, metrics)
        return ok, metrics

    def test_async(self, params: Any) -> dict[str, Any]:
        """Dispatch the evaluation program WITHOUT materializing results:
        returns the dict of in-flight device arrays.  The caller resolves
        it later with :meth:`resolve_async` — by then the device has
        evaluated round N's params while round N+1 was training
        (validation_async mode; the verdict does not gate the round)."""
        return self._eval(params)

    def test_hyper_async(self, stacked_params: Any) -> dict[str, Any]:
        """Hyper-mode variant of :meth:`test_async` (dispatch, no sync)."""
        if self._eval_hyper is None:
            raise ValueError(
                f"Not found hyper test function for data name {self.data_name}")
        return self._eval_hyper(stacked_params)

    def resolve_async(self, out: dict[str, Any],
                      record: bool = True) -> tuple[bool, dict[str, float]]:
        """Materialize a :meth:`test_async`/:meth:`test_hyper_async`
        result (blocks until the dispatched evaluation finishes).
        ``record=False`` leaves failure accounting to the caller (the
        engine emits one combined ``validation`` event instead)."""
        host = {k: np.asarray(v) for k, v in out.items()}
        ok = bool(host.pop("ok"))
        metrics = {k: float(v) for k, v in host.items()}
        if record:
            self._record(ok, metrics)
        return ok, metrics

    def test_hyper(self, stacked_params: Any) -> tuple[bool, dict[str, float]]:
        if self._eval_hyper is None:
            raise ValueError(f"Not found hyper test function for data name {self.data_name}")
        out = {k: np.asarray(v) for k, v in self._eval_hyper(stacked_params).items()}
        ok = bool(out.pop("ok"))
        metrics = {k: float(v) for k, v in out.items()}
        self._record(ok, metrics)
        return ok, metrics
