from attackfl_tpu.eval.validation import Validation, roc_auc  # noqa: F401
