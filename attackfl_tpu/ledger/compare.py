"""Cross-run comparison + the CI regression gate.

``compare_records`` diffs two ledger records across the perf, numerics
and forensics columns; ``rolling_baseline`` synthesizes a baseline from
the candidate's own history (median over the last ``window`` records
sharing its config fingerprint + executor — apples to apples only);
``regress_check`` turns the diff into pass/fail verdicts with
noise-aware thresholds.

**Noise awareness** reuses the lesson the ``bench.py
--numerics-overhead`` paired-means protocol encoded: on a drifting box,
comparing best-of single observations routinely overstates small deltas
by more than the delta itself.  So (a) when a record carries per-rep
rates (bench imports), its MEAN is compared, not its best; (b) the
effective slowdown threshold is floored by the baseline's own observed
inter-rep spread (a run can't be declared 10% slower by a gate whose
baseline wobbles 15% rep-to-rep); (c) the rolling baseline is a median,
not a max.
"""

from __future__ import annotations

import statistics
from typing import Any

# Default gate thresholds (overridable from the CLI).
DEFAULT_THRESHOLDS: dict[str, float] = {
    # relative steady-rounds/s slowdown (percent) that fails the gate
    "rounds_per_sec_pct": 10.0,
    # per-phase p95 regression: relative percent AND an absolute floor
    # (a 2ms phase doubling is noise, not a regression)
    "phase_p95_pct": 50.0,
    "phase_p95_floor_s": 0.010,
    # quality: absolute drop in roc_auc/accuracy that fails
    "quality_drop": 0.02,
    # forensics: absolute TPR drop / FPR rise that fails
    "tpr_drop": 0.05,
    "fpr_rise": 0.05,
    # cost observatory (ISSUE 11): relative achieved-FLOP/s drop
    # (percent) that fails — the roofline column the future scheduler's
    # bin-packing relies on.  Noise-floored like the rounds/s gate: the
    # denominator is the same measured device time, so it inherits the
    # same rep-to-rep wobble.
    "util_drop_pct": 10.0,
    # cap on how far the noise floor can stretch the perf threshold
    "noise_cap_pct": 30.0,
    # scheduler SLO (ISSUE 16): a candidate's queue wait may exceed the
    # fingerprint peers' p95 by this much (percent) before the gate
    # fails, with an absolute floor so a 0.2s-vs-0.1s wait on an idle
    # box (pure dispatch jitter) is never declared a regression
    "queue_wait_pct": 100.0,
    "queue_wait_floor_s": 5.0,
    # hotspot observatory (ISSUE 19): absolute host-bound-fraction rise
    # past the peers' median that fails the gate, noise-floored by the
    # peers' own observed spread (capped below — a wobbling baseline
    # can't demand the moon), and absolute top-op self-time share drift
    # (either direction: a kernel silently taking over the round and a
    # kernel silently vanishing are both news)
    "hostbound_rise": 0.15,
    "hostbound_noise_cap": 0.30,
    "top_op_share_drift": 0.15,
}

# The "perf columns" a comparison renders (record key, short label).
PERF_COLUMNS = (
    ("rounds_per_sec_steady", "steady r/s"),
    ("rounds_per_sec_incl_compile", "incl-compile r/s"),
    ("round_device_time", "device s/round"),
    ("host_resolution_latency", "host s/round"),
    ("wall_seconds", "wall s"),
)


def _num(value: Any) -> float | None:
    if isinstance(value, (int, float)) and not isinstance(value, bool) \
            and value == value:
        return float(value)
    return None


def effective_rate(record: dict[str, Any]) -> float | None:
    """The rate a comparison uses: the mean over reps when the record
    carries them (paired-means protocol), else the single steady rate."""
    per_rep = record.get("per_rep")
    if isinstance(per_rep, list):
        reps = [v for v in (_num(x) for x in per_rep) if v is not None]
        if reps:
            return sum(reps) / len(reps)
    for key in ("rounds_per_sec_mean", "rounds_per_sec_steady",
                "rounds_per_sec_incl_compile"):
        value = _num(record.get(key))
        if value is not None:
            return value
    return None


def rate_noise_pct(record: dict[str, Any]) -> float:
    """Observed inter-rep spread of a record's rate, as percent of its
    mean (0 when the record has no per-rep data — a single observation
    carries no self-noise estimate)."""
    per_rep = record.get("per_rep")
    if not isinstance(per_rep, list):
        return 0.0
    reps = [v for v in (_num(x) for x in per_rep) if v is not None]
    if len(reps) < 2:
        return 0.0
    mean = sum(reps) / len(reps)
    if mean <= 0:
        return 0.0
    return 100.0 * statistics.pstdev(reps) / mean


def _delta(old: float | None, new: float | None) -> dict[str, Any]:
    out: dict[str, Any] = {"old": old, "new": new}
    if old is not None and new is not None:
        out["delta"] = round(new - old, 6)
        if old != 0:
            out["pct"] = round(100.0 * (new - old) / abs(old), 2)
    return out


def compare_records(old: dict[str, Any],
                    new: dict[str, Any]) -> dict[str, Any]:
    """Column-wise diff: perf rates + time attribution, per-phase p95,
    quality finals, numerics gauges, forensics rates, lifecycle counts."""
    perf = {key: _delta(_num(old.get(key)), _num(new.get(key)))
            for key, _ in PERF_COLUMNS}
    perf["rate_effective"] = _delta(effective_rate(old), effective_rate(new))

    attribution = {}
    old_attr = old.get("time_attribution") or {}
    new_attr = new.get("time_attribution") or {}
    for key in sorted(set(old_attr) | set(new_attr)):
        attribution[key] = _delta(_num(old_attr.get(key)),
                                  _num(new_attr.get(key)))

    phases = {}
    old_phases = old.get("phases") or {}
    new_phases = new.get("phases") or {}
    for name in sorted(set(old_phases) | set(new_phases)):
        phases[name] = {
            "p50_s": _delta(_num((old_phases.get(name) or {}).get("p50_s")),
                            _num((new_phases.get(name) or {}).get("p50_s"))),
            "p95_s": _delta(_num((old_phases.get(name) or {}).get("p95_s")),
                            _num((new_phases.get(name) or {}).get("p95_s"))),
        }

    quality = {}
    for key in sorted(set(old.get("final") or {}) | set(new.get("final")
                                                        or {})):
        quality[key] = _delta(_num((old.get("final") or {}).get(key)),
                              _num((new.get("final") or {}).get(key)))

    numerics = {}
    old_num = old.get("numerics") or {}
    new_num = new.get("numerics") or {}
    for key in sorted(set(old_num) | set(new_num)):
        numerics[key] = _delta(_num(old_num.get(key)), _num(new_num.get(key)))

    forensics = {}
    old_for = old.get("forensics") or {}
    new_for = new.get("forensics") or {}
    for key in sorted(set(old_for) | set(new_for)):
        forensics[key] = _delta(_num(old_for.get(key)), _num(new_for.get(key)))

    utilization = {}
    old_util = old.get("utilization") or {}
    new_util = new.get("utilization") or {}
    for key in sorted(set(old_util) | set(new_util)):
        delta = _delta(_num(old_util.get(key)), _num(new_util.get(key)))
        if delta.get("old") is not None or delta.get("new") is not None:
            utilization[key] = delta

    counts = {}
    old_counts = old.get("counts") or {}
    new_counts = new.get("counts") or {}
    for key in sorted(set(old_counts) | set(new_counts)):
        counts[key] = _delta(_num(old_counts.get(key)),
                             _num(new_counts.get(key)))

    # scheduler accounting (ISSUE 16): wait/preemption deltas + the
    # priority identity (a cross-priority comparison is apples to
    # oranges for wait time — rendered, never silently hidden)
    sched = None
    if any(r.get(k) is not None for r in (old, new)
           for k in ("sched_priority", "sched_wait_seconds",
                     "sched_preemptions")):
        sched = {
            "priority": {"old": old.get("sched_priority"),
                         "new": new.get("sched_priority")},
            "wait_seconds": _delta(_num(old.get("sched_wait_seconds")),
                                   _num(new.get("sched_wait_seconds"))),
            "preemptions": _delta(_num(old.get("sched_preemptions")),
                                  _num(new.get("sched_preemptions"))),
        }

    # hotspot observatory (ISSUE 19): host-bound fraction + measured
    # device time deltas, prediction-error factors, and per-op share
    # drift across the union of both records' top-op tables
    hotspots = None
    old_hot = old.get("hotspots") or {}
    new_hot = new.get("hotspots") or {}
    if old_hot or new_hot:
        def shares(block: dict[str, Any]) -> dict[str, float]:
            out: dict[str, float] = {}
            for row in block.get("top_ops") or []:
                if isinstance(row, dict) and row.get("name"):
                    value = _num(row.get("share"))
                    if value is not None:
                        out[str(row["name"])] = value
            return out

        old_shares, new_shares = shares(old_hot), shares(new_hot)
        hotspots = {
            "host_bound_fraction": _delta(
                _num(old_hot.get("host_bound_fraction")),
                _num(new_hot.get("host_bound_fraction"))),
            "measured_round_device_s": _delta(
                _num(old_hot.get("measured_round_device_s")),
                _num(new_hot.get("measured_round_device_s"))),
            "prediction_error_factor": _delta(
                _num(old_hot.get("hotspot_prediction_error_factor")),
                _num(new_hot.get("hotspot_prediction_error_factor"))),
            "top_op_shares": {
                name: _delta(old_shares.get(name), new_shares.get(name))
                for name in sorted(set(old_shares) | set(new_shares))},
            "books_close": {"old": old_hot.get("books_close"),
                            "new": new_hot.get("books_close")},
        }

    return {
        "old_id": old.get("record_id"),
        "new_id": new.get("record_id"),
        "fingerprint_match": (old.get("fingerprint") == new.get("fingerprint")
                              and bool(old.get("fingerprint"))),
        "executor": {"old": old.get("executor"), "new": new.get("executor")},
        "pipeline_depth": {"old": old.get("pipeline_depth"),
                           "new": new.get("pipeline_depth")},
        "mesh_devices": {"old": old.get("mesh_devices"),
                         "new": new.get("mesh_devices")},
        "perf": perf,
        "time_attribution": attribution,
        "phases": phases,
        "quality": quality,
        "numerics": numerics,
        "forensics": forensics,
        "utilization": utilization,
        "counts": counts,
        "sched": sched,
        "hotspots": hotspots,
    }


def rolling_baseline(records: list[dict[str, Any]],
                     candidate: dict[str, Any],
                     window: int = 5) -> dict[str, Any] | None:
    """Synthetic baseline record: the median over the last ``window``
    records sharing the candidate's fingerprint + executor + matrix cell
    (the candidate itself excluded — by record_id when it has one, by
    identity otherwise).  None when no peer exists.

    The ``cell`` key (ISSUE 9): per-cell matrix records can share a
    config fingerprint (the sweep's base config collapses in edge cases
    — e.g. records imported without full configs), so baseline peers
    must ALSO agree on the (attack × defense × seed) cell identity.
    Non-matrix records carry no ``cell`` and match each other as before
    (None == None).

    The ``pipeline_depth`` key (ISSUE 10, same lesson): the depth knob
    is fingerprint-VOLATILE — params are bit-identical at every depth —
    but throughput is exactly what depth changes, so records at
    different depths are non-peers for the rolling baseline (a depth-4
    run must not be gated against depth-0 history).  Non-pipelined
    records carry None and keep matching each other.

    The ``mesh_devices`` key (ISSUE 12, same lesson again): mesh size
    is a placement knob — fingerprints don't see it (num-devices: 0
    means "whatever is visible"), yet throughput is exactly what it
    changes, so an 8-device run must never be gated against 1-device
    history.  Records predating the field carry None; ``0`` (explicitly
    meshless) and None are treated as the same pool so old baselines
    keep working."""
    fingerprint = candidate.get("fingerprint")

    def mesh_key(record: dict[str, Any]) -> int:
        value = record.get("mesh_devices")
        if isinstance(value, bool) or not isinstance(value, int):
            return 0
        return value

    peers = [r for r in records
             if r is not candidate
             and r.get("fingerprint") == fingerprint
             and r.get("executor") == candidate.get("executor")
             and r.get("cell") == candidate.get("cell")
             and r.get("pipeline_depth") == candidate.get("pipeline_depth")
             and mesh_key(r) == mesh_key(candidate)
             and (candidate.get("record_id") is None
                  or r.get("record_id") != candidate.get("record_id"))]
    if not peers or not fingerprint:
        return None
    peers = peers[-window:]

    def median_of(path: tuple[str, ...]) -> float | None:
        values = []
        for record in peers:
            node: Any = record
            for key in path:
                node = (node or {}).get(key) if isinstance(node, dict) \
                    else None
            value = _num(node)
            if value is not None:
                values.append(value)
        return statistics.median(values) if values else None

    baseline: dict[str, Any] = {
        "record_id": f"baseline[{len(peers)}]",
        "source": "baseline",
        "fingerprint": fingerprint,
        "executor": candidate.get("executor"),
        "cell": candidate.get("cell"),
        "pipeline_depth": candidate.get("pipeline_depth"),
        "mesh_devices": candidate.get("mesh_devices"),
        "baseline_of": [r.get("record_id") for r in peers],
    }
    for key, _ in PERF_COLUMNS:
        baseline[key] = median_of((key,))
    # queue-wait evidence (ISSUE 16): pool the peers' scheduler waits so
    # regress_check can gate the candidate's wait against the peers' p95
    # (the baseline alone — one median — can't carry a distribution)
    peer_waits = [w for w in (_num(r.get("sched_wait_seconds"))
                              for r in peers) if w is not None]
    if peer_waits:
        baseline["sched_wait_peers"] = [round(w, 6) for w in peer_waits]
        baseline["sched_wait_seconds"] = round(
            statistics.median(peer_waits), 6)
    # effective-rate noise floor: pool the peers' rates as pseudo-reps so
    # the gate sees the baseline's own run-to-run wobble
    rates = [effective_rate(r) for r in peers]
    rates = [r for r in rates if r is not None]
    if rates:
        baseline["per_rep"] = [round(r, 6) for r in rates]
    baseline["phases"] = {}
    names = {name for r in peers for name in (r.get("phases") or {})}
    for name in sorted(names):
        baseline["phases"][name] = {
            "p50_s": median_of(("phases", name, "p50_s")),
            "p95_s": median_of(("phases", name, "p95_s")),
        }
    baseline["final"] = {
        key: median_of(("final", key))
        for key in {k for r in peers for k in (r.get("final") or {})}}
    baseline["numerics"] = {
        key: median_of(("numerics", key))
        for key in {k for r in peers for k in (r.get("numerics") or {})}}
    if not any(v is not None for v in baseline["numerics"].values()):
        baseline["numerics"] = None
    baseline["forensics"] = {
        key: median_of(("forensics", key))
        for key in {k for r in peers for k in (r.get("forensics") or {})}}
    if not any(v is not None for v in baseline["forensics"].values()):
        baseline["forensics"] = None
    # roofline columns (ISSUE 11): medians over the numeric utilization
    # fields (device_kind/basis are identity, not medianable)
    baseline["utilization"] = {
        key: median_of(("utilization", key))
        for key in {k for r in peers for k in (r.get("utilization") or {})
                    if _num((r.get("utilization") or {}).get(k)) is not None}}
    if not any(v is not None for v in baseline["utilization"].values()):
        baseline["utilization"] = None
    # hotspot peers (ISSUE 19): median host-bound fraction + the pooled
    # per-peer fractions (the gate's noise floor — same design as
    # sched_wait_peers) and per-name median top-op shares
    peer_fractions = [
        f for f in (_num((r.get("hotspots") or {})
                         .get("host_bound_fraction")) for r in peers)
        if f is not None]
    if peer_fractions:
        share_pool: dict[str, list[float]] = {}
        for record in peers:
            for row in (record.get("hotspots") or {}).get("top_ops") or []:
                if isinstance(row, dict) and row.get("name"):
                    value = _num(row.get("share"))
                    if value is not None:
                        share_pool.setdefault(
                            str(row["name"]), []).append(value)
        baseline["hotspots"] = {
            "host_bound_fraction": round(
                statistics.median(peer_fractions), 4),
            "hostbound_peers": [round(f, 4) for f in peer_fractions],
            "measured_round_device_s": median_of(
                ("hotspots", "measured_round_device_s")),
            "top_ops": [
                {"name": name,
                 "share": round(statistics.median(values), 4)}
                for name, values in sorted(share_pool.items())],
        }
    baseline["counts"] = {}
    baseline["time_attribution"] = {}
    return baseline


def regress_check(baseline: dict[str, Any], candidate: dict[str, Any],
                  thresholds: dict[str, float] | None = None
                  ) -> dict[str, Any]:
    """Gate verdict: ``{ok, violations: [...], checks: N, ...}`` —
    ``ok`` is False when any perf/quality/forensics/numerics column
    regresses past its (noise-floored) threshold."""
    th = dict(DEFAULT_THRESHOLDS)
    th.update(thresholds or {})
    violations: list[dict[str, Any]] = []
    checks = 0

    # --- perf: steady rounds/s (paired means + noise floor) -----------
    base_rate = effective_rate(baseline)
    cand_rate = effective_rate(candidate)
    noise_pct = min(max(rate_noise_pct(baseline), rate_noise_pct(candidate)),
                    th["noise_cap_pct"])
    rate_threshold = max(th["rounds_per_sec_pct"], noise_pct)
    if base_rate is not None and cand_rate is not None and base_rate > 0:
        checks += 1
        drop_pct = 100.0 * (base_rate - cand_rate) / base_rate
        if drop_pct > rate_threshold:
            violations.append({
                "check": "rounds_per_sec",
                "baseline": round(base_rate, 4),
                "candidate": round(cand_rate, 4),
                "drop_pct": round(drop_pct, 2),
                "threshold_pct": round(rate_threshold, 2),
            })

    # --- perf: per-phase p95 ------------------------------------------
    base_phases = baseline.get("phases") or {}
    cand_phases = candidate.get("phases") or {}
    for name in sorted(set(base_phases) & set(cand_phases)):
        old = _num((base_phases.get(name) or {}).get("p95_s"))
        new = _num((cand_phases.get(name) or {}).get("p95_s"))
        if old is None or new is None or old <= 0:
            continue
        checks += 1
        if (new - old) > th["phase_p95_floor_s"] \
                and 100.0 * (new - old) / old > th["phase_p95_pct"]:
            violations.append({
                "check": f"phase_p95:{name}",
                "baseline": round(old, 6), "candidate": round(new, 6),
                "rise_pct": round(100.0 * (new - old) / old, 2),
                "threshold_pct": th["phase_p95_pct"],
            })

    # --- quality: final metric drops ----------------------------------
    for key in ("roc_auc", "accuracy"):
        old = _num((baseline.get("final") or {}).get(key))
        new = _num((candidate.get("final") or {}).get(key))
        if old is None or new is None:
            continue
        checks += 1
        if (old - new) > th["quality_drop"]:
            violations.append({
                "check": f"quality:{key}",
                "baseline": round(old, 4), "candidate": round(new, 4),
                "drop": round(old - new, 4),
                "threshold": th["quality_drop"],
            })

    # --- forensics: detection quality ---------------------------------
    base_for = baseline.get("forensics") or {}
    cand_for = candidate.get("forensics") or {}
    old_tpr, new_tpr = _num(base_for.get("tpr")), _num(cand_for.get("tpr"))
    if old_tpr is not None and new_tpr is not None:
        checks += 1
        if (old_tpr - new_tpr) > th["tpr_drop"]:
            violations.append({
                "check": "forensics:tpr",
                "baseline": round(old_tpr, 4), "candidate": round(new_tpr, 4),
                "drop": round(old_tpr - new_tpr, 4),
                "threshold": th["tpr_drop"]})
    old_fpr, new_fpr = _num(base_for.get("fpr")), _num(cand_for.get("fpr"))
    if old_fpr is not None and new_fpr is not None:
        checks += 1
        if (new_fpr - old_fpr) > th["fpr_rise"]:
            violations.append({
                "check": "forensics:fpr",
                "baseline": round(old_fpr, 4), "candidate": round(new_fpr, 4),
                "rise": round(new_fpr - old_fpr, 4),
                "threshold": th["fpr_rise"]})

    # --- utilization: achieved-FLOP/s drop (ISSUE 11) -----------------
    # Same noise floor as the rounds/s gate: achieved FLOP/s divides a
    # STATIC flop count by the measured device time, so its wobble is
    # exactly the rate wobble — a gate tighter than the noise would cry
    # wolf on every loaded-box rep.
    old_util = _num((baseline.get("utilization") or {})
                    .get("achieved_flops_per_sec"))
    new_util = _num((candidate.get("utilization") or {})
                    .get("achieved_flops_per_sec"))
    util_threshold = max(th["util_drop_pct"], noise_pct)
    if old_util is not None and new_util is not None and old_util > 0:
        checks += 1
        drop_pct = 100.0 * (old_util - new_util) / old_util
        if drop_pct > util_threshold:
            violations.append({
                "check": "utilization:achieved_flops_per_sec",
                "baseline": round(old_util, 3),
                "candidate": round(new_util, 3),
                "drop_pct": round(drop_pct, 2),
                "threshold_pct": round(util_threshold, 2),
            })

    # --- scheduler SLO: p95 queue wait over fingerprint peers ---------
    # (ISSUE 16) Noise-floored like the perf gates: the allowed wait is
    # the peers' p95 stretched by queue_wait_pct AND at least
    # queue_wait_floor_s above it, so an idle-box dispatch-jitter delta
    # can never fail the gate.  Only fires when the baseline carries the
    # pooled peer waits (rolling_baseline) or at least a single wait.
    peer_waits = baseline.get("sched_wait_peers")
    if not isinstance(peer_waits, list) or not peer_waits:
        single = _num(baseline.get("sched_wait_seconds"))
        peer_waits = [single] if single is not None else []
    peer_waits = [w for w in (_num(x) for x in peer_waits)
                  if w is not None]
    cand_wait = _num(candidate.get("sched_wait_seconds"))
    if peer_waits and cand_wait is not None:
        from attackfl_tpu.telemetry.summary import percentile

        checks += 1
        p95 = percentile(peer_waits, 95.0)
        allowed = max(p95 * (1.0 + th["queue_wait_pct"] / 100.0),
                      p95 + th["queue_wait_floor_s"])
        if cand_wait > allowed:
            violations.append({
                "check": "sched:queue_wait_p95",
                "baseline": round(p95, 3),
                "candidate": round(cand_wait, 3),
                "allowed": round(allowed, 3),
                "peers": len(peer_waits),
            })

    # --- hotspots: host-bound-fraction rise (ISSUE 19) ----------------
    # Absolute rise past the baseline, floored by the peers' own spread
    # (pooled fractions when the rolling baseline carries them) and
    # capped — the dispatch-gap diagnosis is exactly what the
    # sweep-regroup work moves, so a silent host-bound drift must fail
    # loudly, but a baseline that itself wobbles 0.2 can't gate at 0.15.
    base_hot = baseline.get("hotspots") or {}
    cand_hot = candidate.get("hotspots") or {}
    old_hb = _num(base_hot.get("host_bound_fraction"))
    new_hb = _num(cand_hot.get("host_bound_fraction"))
    if old_hb is not None and new_hb is not None:
        checks += 1
        peer_fractions = [f for f in
                          (_num(x) for x in
                           base_hot.get("hostbound_peers") or [])
                          if f is not None]
        spread = (max(peer_fractions) - min(peer_fractions)
                  if len(peer_fractions) >= 2 else 0.0)
        hb_threshold = min(max(th["hostbound_rise"], spread),
                           th["hostbound_noise_cap"])
        if (new_hb - old_hb) > hb_threshold:
            violations.append({
                "check": "hotspots:host_bound_fraction",
                "baseline": round(old_hb, 4),
                "candidate": round(new_hb, 4),
                "rise": round(new_hb - old_hb, 4),
                "threshold": round(hb_threshold, 4),
            })

    # --- hotspots: top-op self-time share drift (ISSUE 19) ------------
    # Either direction, ops named in BOTH top tables only (an op absent
    # from one side is a table-depth artifact, not evidence).
    def _shares(block: dict[str, Any]) -> dict[str, float]:
        out: dict[str, float] = {}
        for row in block.get("top_ops") or []:
            if isinstance(row, dict) and row.get("name"):
                value = _num(row.get("share"))
                if value is not None:
                    out[str(row["name"])] = value
        return out

    base_shares, cand_shares = _shares(base_hot), _shares(cand_hot)
    for name in sorted(set(base_shares) & set(cand_shares)):
        checks += 1
        drift = cand_shares[name] - base_shares[name]
        if abs(drift) > th["top_op_share_drift"]:
            violations.append({
                "check": f"hotspots:op_share:{name}",
                "baseline": round(base_shares[name], 4),
                "candidate": round(cand_shares[name], 4),
                "drift": round(drift, 4),
                "threshold": th["top_op_share_drift"],
            })

    # --- numerics: non-finite values are never an acceptable delta ----
    old_nf = _num((baseline.get("numerics") or {}).get("nonfinite_total"))
    new_nf = _num((candidate.get("numerics") or {}).get("nonfinite_total"))
    if new_nf is not None:
        checks += 1
        if new_nf > (old_nf or 0.0):
            violations.append({
                "check": "numerics:nonfinite_total",
                "baseline": old_nf or 0, "candidate": new_nf})

    return {
        "ok": not violations,
        "checks": checks,
        "violations": violations,
        "baseline_id": baseline.get("record_id"),
        "candidate_id": candidate.get("record_id"),
        "rate_threshold_pct": round(rate_threshold, 2),
        "rate_noise_pct": round(noise_pct, 2),
    }
