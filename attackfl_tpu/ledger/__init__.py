"""Cross-run ledger: the persistent, machine-readable run record store.

PRs 1-6 made a *single run* observable (events.jsonl, live monitor,
in-graph numerics, forensics); this package makes the *sequence of runs*
observable.  Every run's ``_finish_run`` distills its event log into one
schema-versioned ledger record (:mod:`~attackfl_tpu.ledger.record`) and
appends it to a persistent JSONL ledger with an atomically-published
index (:mod:`~attackfl_tpu.ledger.store`).  ``attackfl-tpu ledger
list|show|compare|regress|import`` (:mod:`~attackfl_tpu.ledger.cli`)
turns that store into queries, diffs and a CI-gateable regression check
(:mod:`~attackfl_tpu.ledger.compare`).

Everything here is pure event-log post-processing — jax-free, zero new
host syncs, and never on the round loop's critical path.
"""

from attackfl_tpu.ledger.record import (  # noqa: F401
    LEDGER_SCHEMA_VERSION, derive_record, records_from_bench,
    validate_record,
)
from attackfl_tpu.ledger.store import (  # noqa: F401
    ENV_LEDGER_DIR, LedgerStore, resolve_ledger_dir,
)
