"""Ledger record derivation: one run's events -> one cross-run record.

``derive_record`` is pure post-processing over the run's already-written
telemetry (the events.jsonl slice for this run, plus the host-side trace
spans): it adds ZERO host syncs and never touches the round loop.  The
same function serves the engine's ``_finish_run`` (in-memory trace spans)
and the offline CLI (``trace.json`` read back from disk).

**Wall-time attribution** is mined from the existing tracer spans, per
executor:

* sync — ``device_compute_s`` = the phases that block on device programs
  (train + aggregate + hyper_update + numerics dispatch);
* fused — ``device_compute_s`` = the ``chunk`` spans (each chunk is one
  blocking device dispatch);
* pipelined — ``device_compute_s`` = ``resolve`` + ``dispatch`` spans: at
  depth-1 the host blocks inside ``resolve`` precisely while the device
  finishes the in-flight round, so this is the host-observable (upper
  bound) device time.

``validation_s`` / ``checkpoint_s`` are the foreground spans;
``checkpoint_overlapped_s`` sums the ``background=True`` checkpoint spans
(the async writer's submit window — wall time that OVERLAPS device
compute instead of adding to it) and ``validation_overlapped`` counts
async validations (dispatch-only: their wall cost is by construction
hidden).  ``host_resolution_s`` is the remainder — everything the host
spends per run that is neither device wait, validation, checkpointing,
compilation nor host-side defense work.  By construction::

    wall_s = device_compute_s + validation_s + checkpoint_s + compile_s
             + defense_host_s + host_resolution_s        (each >= 0)

The two per-round derivatives — ``round_device_time`` and
``host_resolution_latency`` — are exactly the measured inputs the
ROADMAP's depth-k auto-tuner needs (pipeline depth k should cover
host-resolution latency with in-flight device rounds).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
from typing import Any

from attackfl_tpu.utils.fingerprint import fingerprint_from_dict

LEDGER_SCHEMA_VERSION = 1

# Span names that block on device programs, per executor (see module doc).
_DEVICE_SPANS = {
    "sync": ("train", "aggregate", "hyper_update", "numerics"),
    "fused": ("chunk",),
    "pipelined": ("resolve", "dispatch"),
}
_DEFENSE_SPANS = ("defense", "detect", "attribution")

_REQUIRED_RECORD_FIELDS: dict[str, type | tuple[type, ...]] = {
    "ledger_schema": int, "source": str, "executor": str,
    "fingerprint": str, "rounds": int, "ok_rounds": int,
    "time_attribution": dict, "counts": dict,
}

_git_rev_cache: str | None = None


def git_revision(root: str | None = None) -> str:
    """Working-tree revision (``-dirty`` suffixed), cached per process;
    empty string outside a git checkout.  Called once per run header —
    never on the round loop."""
    global _git_rev_cache
    if _git_rev_cache is not None and root is None:
        return _git_rev_cache
    cwd = root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    rev = ""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"], cwd=cwd,
            capture_output=True, text=True, timeout=5)
        if out.returncode == 0:
            rev = out.stdout.strip()
            dirty = subprocess.run(
                ["git", "status", "--porcelain", "--untracked-files=no"],
                cwd=cwd, capture_output=True, text=True, timeout=5)
            if dirty.returncode == 0 and dirty.stdout.strip():
                rev += "-dirty"
    except (OSError, subprocess.SubprocessError):
        rev = ""
    if root is None:
        _git_rev_cache = rev
    return rev


# ---------------------------------------------------------------------------
# span mining
# ---------------------------------------------------------------------------

def _span_totals(trace_events: list[dict[str, Any]] | None
                 ) -> dict[str, list]:
    """Chrome-trace "X" events -> {name: [total_seconds, count]}, with
    checkpoint spans split by their ``background`` arg into
    ``checkpoint`` (foreground) and ``checkpoint_bg`` (overlapped)."""
    totals: dict[str, list] = {}
    for event in trace_events or []:
        if event.get("ph") != "X":
            continue
        name = str(event.get("name", ""))
        dur = event.get("dur")
        if not isinstance(dur, (int, float)) or isinstance(dur, bool):
            continue
        if name == "checkpoint" and (event.get("args") or {}).get(
                "background"):
            name = "checkpoint_bg"
        bucket = totals.setdefault(name, [0.0, 0])
        bucket[0] += float(dur) / 1e6  # trace durations are microseconds
        bucket[1] += 1
    return totals


def detect_executor(events: list[dict[str, Any]]) -> str:
    """Which executor produced this run — derivable from the event record
    alone: pipelined rounds stamp ``pipelined: true``, the fused path
    emits ``chunk`` events, everything else is the synchronous loop."""
    for event in events:
        if event.get("kind") == "round" and event.get("pipelined"):
            return "pipelined"
    if any(e.get("kind") == "chunk" for e in events):
        return "fused"
    return "sync"


def mine_attribution(events: list[dict[str, Any]],
                     trace_events: list[dict[str, Any]] | None,
                     executor: str, wall_s: float) -> dict[str, Any]:
    """The device/host/overlap wall-time split (see module doc)."""
    spans = _span_totals(trace_events)

    def total(*names: str) -> float:
        return sum(spans.get(n, (0.0, 0))[0] for n in names)

    device = total(*_DEVICE_SPANS.get(executor, ()))
    validation = total("validate")
    checkpoint = total("checkpoint")
    checkpoint_bg = total("checkpoint_bg")
    compile_s = total("compile")
    if executor in ("fused", "pipelined"):
        # the AOT compile spans nest INSIDE the chunk/dispatch spans
        # (engine._fused_executable / _pipeline_executable run under
        # them); subtract so compile time is not double-counted
        device = max(device - compile_s, 0.0)
    defense = total(*_DEFENSE_SPANS)
    accounted = device + validation + checkpoint + compile_s + defense
    host_resolution = max(wall_s - accounted, 0.0)
    background_validations = sum(
        1 for e in events
        if e.get("kind") == "validation" and e.get("background"))
    return {
        "wall_s": round(wall_s, 6),
        "device_compute_s": round(device, 6),
        "host_resolution_s": round(host_resolution, 6),
        "validation_s": round(validation, 6),
        "checkpoint_s": round(checkpoint, 6),
        "checkpoint_overlapped_s": round(checkpoint_bg, 6),
        "validation_overlapped": background_validations,
        "compile_s": round(compile_s, 6),
        "defense_host_s": round(defense, 6),
    }


# ---------------------------------------------------------------------------
# record derivation
# ---------------------------------------------------------------------------

def derive_record(events: list[dict[str, Any]],
                  trace_events: list[dict[str, Any]] | None = None,
                  fingerprint: str | None = None,
                  source: str = "run",
                  ledger_records: list[dict[str, Any]] | None = None
                  ) -> dict[str, Any] | None:
    """Distill one run's event slice (+ optional trace spans) into a
    ledger record.  Returns None for an empty slice (nothing ran).

    ``ledger_records`` (optional) is the existing corpus: when given and
    the run carried profiling windows, the hotspot observatory's
    measured per-round device time is reconciled against the cost
    observatory's prediction (``hotspot_prediction_error_factor``,
    the symmetric max(p/a, a/p) convention of costmodel/estimate)."""
    from attackfl_tpu.costmodel.report import profiles_from_events
    from attackfl_tpu.costmodel.roofline import utilization_summary
    from attackfl_tpu.profiler.mine import hotspots_from_events
    from attackfl_tpu.telemetry.forensics import forensics_summary
    from attackfl_tpu.telemetry.numerics import numerics_summary
    from attackfl_tpu.telemetry.summary import summarize

    if not events:
        return None
    summary = summarize(events)
    header = next((e for e in events if e.get("kind") == "run_header"), None)
    header = header or {}
    executor = detect_executor(events)
    run_end = summary.get("run_end") or {}
    wall_s = float(run_end.get("seconds") or 0.0)
    rounds = int(summary.get("rounds_attempted") or 0)
    attribution = mine_attribution(events, trace_events, executor, wall_s)

    if fingerprint is None:
        config = header.get("config")
        fingerprint = (fingerprint_from_dict(config)
                       if isinstance(config, dict) else "")

    rates = summary.get("rates") or {}
    counters = summary.get("counters") or {}
    counts = {
        "retries": int(summary.get("retries") or 0),
        "rollbacks": sum(1 for e in events if e.get("kind") == "rollback"),
        "faults_injected": sum(
            1 for f in summary.get("faults") or []
            if f.get("action") == "injected"),
        "faults_recovered": sum(
            1 for f in summary.get("faults") or []
            if f.get("action") == "recovered"),
        "degrades": len(summary.get("degrades") or []),
        "rounds_failed": int(counters.get("rounds_failed") or 0),
        "checkpoint_fallbacks": int(
            counters.get("checkpoint_fallbacks") or 0),
        "checkpoint_write_failures": int(
            counters.get("checkpoint_write_failures") or 0),
    }

    # persistent-compile-cache stats ride a compile event with
    # program == "persistent_cache" (engine._emit_run_end); every other
    # compile event is a real program compile
    compile_info: dict[str, Any] = {"programs": 0, "seconds": 0.0}
    for event in summary.get("compiles") or []:
        if event.get("program") == "persistent_cache":
            compile_info["cache_hits"] = event.get("cache_hits")
            compile_info["cache_misses"] = event.get("cache_misses")
            compile_info["backend_compile_s"] = event.get("seconds")
        else:
            compile_info["programs"] += 1
            seconds = event.get("seconds")
            if isinstance(seconds, (int, float)):
                compile_info["seconds"] = round(
                    compile_info["seconds"] + float(seconds), 6)

    numerics = numerics_summary(events)
    numerics_out = None
    if numerics is not None:
        numerics_out = {
            "rounds": numerics.get("rounds"),
            "nonfinite_total": numerics.get("nonfinite_total"),
            **(numerics.get("final") or {}),
        }
        separation = numerics.get("separation")
        if separation:
            numerics_out["sep_margin_mean"] = separation.get("margin_mean")
            numerics_out["sep_margin_min"] = separation.get("margin_min")

    forensics = forensics_summary(events)
    forensics_out = None
    if forensics is not None:
        forensics_out = {k: forensics.get(k) for k in
                         ("tpr", "fpr", "precision", "rounds",
                          "attack_rounds", "rollbacks")}

    # depth-k executor provenance (ISSUE 10): the resolved depth from the
    # run header (schema v8) plus the run's MINIMUM effective depth — 0
    # when the demote state machine fired at any point, else the resolved
    # k.  Both None on non-pipelined runs.  `ledger regress` treats
    # records at different depths as non-peers (compare.rolling_baseline)
    # — the same lesson as the matrix `cell` key.
    depth = header.get("pipeline_depth")
    if isinstance(depth, bool) or not isinstance(depth, int):
        depth = None
    demoted = any(e.get("kind") == "degrade"
                  and e.get("state") == "demoted" for e in events)
    configured = header.get("pipeline_depth_configured")

    # cost observatory (ISSUE 11): the run's program profiles (schema-v9
    # program_profile events, deduplicated per fingerprint) and the
    # roofline join — per-round flops/bytes against the MEASURED
    # round_device_time mined above.  CPU and unknown device kinds carry
    # achieved-only figures (no peak spec → no utilization fraction).
    # mesh provenance (ISSUE 12): the run header's device-mesh size is a
    # NON-PEER baseline key (compare.rolling_baseline — the PR-10 depth
    # lesson: throughput is exactly what the mesh changes, so a 1-device
    # and an 8-device run of the same fingerprint must never share a
    # rolling baseline), and the roofline divides by it so utilization
    # stays per-chip-honest on slices
    mesh_devices = header.get("mesh_devices")
    if isinstance(mesh_devices, bool) or not isinstance(mesh_devices, int):
        mesh_devices = 0
    mesh_strategy = header.get("mesh_strategy")

    # scheduler provenance (ISSUE 15, schema v11): the service's
    # scheduler stamps priority + preemption/wait accounting into the
    # run header — mined here so per-job fairness (wait time, preemption
    # counts by priority class) is answerable from the ledger alone
    sched_priority = header.get("sched_priority")
    sched_preemptions = header.get("sched_preemptions")
    if isinstance(sched_preemptions, bool) \
            or not isinstance(sched_preemptions, int):
        sched_preemptions = None
    sched_wait = header.get("sched_wait_seconds")
    if isinstance(sched_wait, bool) \
            or not isinstance(sched_wait, (int, float)):
        sched_wait = None
    # fleet-trace provenance (ISSUE 16, schema v12): the causal id, the
    # device slot and the tenant the dispatching scheduler stamped, so a
    # ledger record joins the fleet timeline/accounting by id
    sched_fleet_id = header.get("sched_fleet_id")
    sched_tenant = header.get("sched_tenant")
    sched_slot = header.get("sched_slot")
    if isinstance(sched_slot, bool) or not isinstance(sched_slot, int):
        sched_slot = None

    programs = profiles_from_events(events) or None
    utilization = None
    if programs:
        device_kind = next((p["device_kind"] for p in programs.values()
                            if p.get("device_kind")), "")
        utilization = utilization_summary(
            programs,
            (attribution["device_compute_s"] / rounds) if rounds else None,
            device_kind, mesh_devices=mesh_devices)

    # hotspot observatory (ISSUE 19, schema v14): the run's mined
    # profiling windows distilled into the compact block (top ops,
    # category shares, host-bound fraction, window status counts), plus
    # the join against the cost observatory when a corpus is at hand —
    # measured Σ device-busy / Σ profiled rounds priced against
    # predict_device_time's peers-first estimate.  None when the run
    # profiled nothing; a run whose every window degraded still records
    # the status counts (unavailable windows are evidence, not holes).
    hotspots = hotspots_from_events(events)
    if hotspots is not None:
        from attackfl_tpu.costmodel.estimate import (
            predict_device_time, prediction_error_factor,
        )

        measured = hotspots.get("measured_round_device_s")
        predicted = None
        if measured is not None and ledger_records:
            prediction = predict_device_time(
                ledger_records, fingerprint or "", profile=utilization)
            if prediction is not None:
                predicted, info = prediction
                hotspots["prediction_method"] = info.get("method")
        hotspots["predicted_round_device_s"] = (
            round(predicted, 6) if predicted is not None else None)
        hotspots["hotspot_prediction_error_factor"] = \
            prediction_error_factor(predicted, measured)

    steady = rates.get("rounds_per_sec_steady")
    record: dict[str, Any] = {
        "ledger_schema": LEDGER_SCHEMA_VERSION,
        "ts": _latest_ts(events),
        "source": source,
        "run_id": summary.get("run_id") or next(
            (e.get("run_id") for e in events if e.get("run_id")), None),
        "executor": executor,
        "pipeline_depth": depth,
        "pipeline_depth_configured": (str(configured)
                                      if configured is not None else None),
        "pipeline_depth_effective": ((0 if demoted else depth)
                                     if depth is not None else None),
        "mesh_devices": mesh_devices,
        "mesh_strategy": (str(mesh_strategy)
                          if mesh_strategy is not None else None),
        "sched_priority": (str(sched_priority)
                           if sched_priority is not None else None),
        "sched_preemptions": sched_preemptions,
        "sched_wait_seconds": (round(sched_wait + 0.0, 6)
                               if sched_wait is not None else None),
        "sched_fleet_id": (str(sched_fleet_id)
                           if sched_fleet_id is not None else None),
        "sched_tenant": (str(sched_tenant)
                         if sched_tenant is not None else None),
        "sched_slot": sched_slot,
        "resumed": summary.get("resumed_from") is not None,
        "fingerprint": fingerprint,
        "git_rev": str(header.get("git_rev") or ""),
        "jax_version": str(header.get("jax_version") or ""),
        "jaxlib_version": str(header.get("jaxlib_version") or ""),
        "backend": str(header.get("backend") or ""),
        "platform": str(header.get("platform") or ""),
        "mode": header.get("mode"),
        "model": header.get("model"),
        "data_name": header.get("data_name"),
        "total_clients": header.get("total_clients"),
        "rounds": rounds,
        "ok_rounds": int(summary.get("rounds_ok") or 0),
        "wall_seconds": round(wall_s, 6),
        "rounds_per_sec_steady": steady,
        "rounds_per_sec_incl_compile": rates.get(
            "rounds_per_sec_incl_compile"),
        "phases": {name: {k: stats[k] for k in ("p50_s", "p95_s", "count")}
                   for name, stats in (summary.get("phases") or {}).items()},
        "time_attribution": attribution,
        # the depth-k auto-tuner's two measured inputs (ROADMAP)
        "round_device_time": (
            round(attribution["device_compute_s"] / rounds, 6)
            if rounds else None),
        "host_resolution_latency": (
            round(attribution["host_resolution_s"] / rounds, 6)
            if rounds else None),
        "compile": compile_info,
        "programs": programs,
        "utilization": utilization,
        "hotspots": hotspots,
        "numerics": numerics_out,
        "forensics": forensics_out,
        "counts": counts,
        "final": summary.get("final") or {},
    }
    return record


def _latest_ts(events: list[dict[str, Any]]) -> float | None:
    latest = None
    for event in events:
        ts = event.get("ts")
        if isinstance(ts, (int, float)) and not isinstance(ts, bool):
            latest = ts if latest is None else max(latest, ts)
    return latest


def validate_record(record: Any) -> list[str]:
    """Schema floor for one ledger record (empty list = valid); extra
    fields are always allowed, like the event schema."""
    if not isinstance(record, dict):
        return [f"record is not an object: {type(record).__name__}"]
    errors: list[str] = []
    for name, typ in _REQUIRED_RECORD_FIELDS.items():
        if name not in record:
            errors.append(f"missing field '{name}'")
        elif typ is int and isinstance(record[name], bool):
            errors.append(f"'{name}' must be int, got bool")
        elif not isinstance(record[name], typ):
            errors.append(f"'{name}' has type {type(record[name]).__name__}")
    schema = record.get("ledger_schema")
    if isinstance(schema, int) and schema > LEDGER_SCHEMA_VERSION:
        errors.append(f"ledger schema {schema} is newer than "
                      f"{LEDGER_SCHEMA_VERSION}; update the tooling")
    return errors


# ---------------------------------------------------------------------------
# bench backfill (`ledger import` / bench.py auto-append)
# ---------------------------------------------------------------------------

def _bench_fingerprint(metric: str, variant: str, label: str) -> str:
    """Baseline-matching key for bench records: same bench mode + variant
    + workload label -> same fingerprint (the bench has no Config dict)."""
    blob = f"{metric}|{variant}|{label}"
    return "bench-" + hashlib.sha256(blob.encode()).hexdigest()[:12]


def _bench_base(parsed: dict[str, Any], variant: str,
                executor: str) -> dict[str, Any]:
    metric = str(parsed.get("metric") or "")
    detail = parsed.get("detail") if isinstance(parsed.get("detail"), dict) \
        else {}
    label = str(detail.get("config") or "")
    return {
        "ledger_schema": LEDGER_SCHEMA_VERSION,
        "ts": parsed.get("ts"),
        "source": "bench",
        "run_id": None,
        "executor": executor,
        "resumed": False,
        "fingerprint": _bench_fingerprint(metric, variant, label),
        "bench_metric": metric,
        "bench_variant": variant,
        "config_label": label,
        "rounds": 0,
        "ok_rounds": 0,
        "time_attribution": {},
        "counts": {},
        "final": {},
    }


def records_from_bench(parsed: dict[str, Any]) -> list[dict[str, Any]]:
    """One bench metric line (or a ``BENCH_r0N.json`` driver wrapper with
    a ``parsed`` field) -> ledger records.  Comparative bench modes yield
    one record per measured variant so each variant gets its own baseline
    trajectory.  Unrecognized/contentless lines yield []."""
    if isinstance(parsed.get("parsed"), dict):
        parsed = parsed["parsed"]
    metric = str(parsed.get("metric") or "")
    detail = parsed.get("detail") if isinstance(parsed.get("detail"), dict) \
        else {}
    if not metric:
        return []
    records: list[dict[str, Any]] = []

    def rate_record(variant: str, executor: str,
                    block: dict[str, Any]) -> dict[str, Any]:
        record = _bench_base(parsed, variant, executor)
        record["rounds_per_sec_steady"] = (
            block.get("rounds_per_sec_steady")
            or block.get("rounds_per_sec"))
        if isinstance(block.get("rounds_per_sec_mean"), (int, float)):
            record["rounds_per_sec_mean"] = block["rounds_per_sec_mean"]
        if isinstance(block.get("per_rep"), list):
            record["per_rep"] = block["per_rep"]
        return record

    if metric.startswith("fl_pipeline_vs_sync"):
        for variant, executor in (("sync", "sync"),
                                  ("pipelined_async_ckpt", "pipelined")):
            block = detail.get(variant)
            if isinstance(block, dict):
                records.append(rate_record(variant, executor, block))
    elif metric.startswith("fl_numerics_on"):
        for variant in ("metrics_off", "metrics_on"):
            block = detail.get(variant)
            if isinstance(block, dict):
                record = rate_record(variant, "pipelined", block)
                if "overhead_pct" in detail:
                    record["overhead_pct"] = detail["overhead_pct"]
                records.append(record)
    elif metric.startswith("fl_matrix_vs_serial"):
        # matrix-compare (ISSUE 9): one record per sweep variant so the
        # serial and batched trajectories each get their own baseline
        for variant, executor in (("serial", "fused"),
                                  ("batched", "matrix")):
            block = detail.get(variant)
            if isinstance(block, dict):
                record = rate_record(variant, executor, block)
                record["wall_seconds"] = block.get("warm_wall_s")
                record["cold_wall_s"] = block.get("cold_wall_s")
                for key in ("speedup_cold", "speedup_warm",
                            "compile_once_saving_s"):
                    if key in detail:
                        record[key] = detail[key]
                records.append(record)
    elif metric.startswith("fl_depth_sweep"):
        # depth sweep (ISSUE 10): one record per measured depth so every
        # k gets its own baseline trajectory — `pipeline_depth` rides the
        # record, making depths non-peers for `ledger regress` exactly
        # like engine-run records
        by_depth = detail.get("by_depth")
        if isinstance(by_depth, dict):
            def depth_key(name: str) -> int:
                return int(name) if str(name).lstrip("-").isdigit() else -1

            for key in sorted(by_depth, key=depth_key):
                block = by_depth[key]
                if not isinstance(block, dict):
                    continue
                record = rate_record(f"depth{key}", "pipelined", block)
                if depth_key(key) >= 0:
                    record["pipeline_depth"] = depth_key(key)
                    record["pipeline_depth_effective"] = depth_key(key)
                if isinstance(detail.get("auto_pick"), dict):
                    record["auto_pick"] = detail["auto_pick"]
                records.append(record)
    elif metric.startswith("fl_mesh_sweep"):
        # mesh sweep (ISSUE 12): one record per (device count x
        # workload) so every mesh size gets its own baseline trajectory
        # — `mesh_devices` rides the record, making sizes non-peers for
        # `ledger regress` exactly like engine-run records (the PR-10
        # depth-key lesson)
        by_devices = detail.get("by_devices")
        if isinstance(by_devices, dict):
            def dev_key(name: str) -> int:
                return int(name) if str(name).isdigit() else -1

            for key in sorted(by_devices, key=dev_key):
                child = by_devices[key]
                if not isinstance(child, dict):
                    continue
                for workload, executor in (("fused", "fused"),
                                           ("matrix", "matrix")):
                    block = child.get(workload)
                    if not isinstance(block, dict):
                        continue
                    record = rate_record(f"{workload}@{key}dev", executor,
                                         block)
                    if dev_key(key) > 0:
                        record["mesh_devices"] = dev_key(key)
                    speedups = detail.get(f"{workload}_speedup")
                    if isinstance(speedups, dict) and key in speedups:
                        record["mesh_speedup"] = speedups[key]
                    records.append(record)
    elif metric.startswith("fl_contention"):
        # contention bench (ISSUE 15): scheduler vs serialized dispatch
        # over the same N-job mixed workload — one record per dispatch
        # mode so each keeps its own baseline trajectory
        for variant in ("serialized", "scheduler"):
            block = detail.get(variant)
            if not isinstance(block, dict):
                continue
            record = _bench_base(parsed, variant, "service")
            record["wall_seconds"] = block.get("makespan_s_mean")
            for key in ("mean_wait_s", "throughput_jobs_per_s",
                        "preemptions", "jobs"):
                if key in block:
                    record[key] = block[key]
            if isinstance(block.get("per_rep"), list):
                record["per_rep"] = block["per_rep"]
            if "throughput_ratio" in detail:
                record["throughput_ratio"] = detail["throughput_ratio"]
            records.append(record)
    elif metric.startswith("fl_compile_cache"):
        for variant in ("first_run", "warm_cache"):
            block = detail.get(variant)
            if not isinstance(block, dict):
                continue
            record = _bench_base(parsed, variant, "fused")
            record["compile"] = {
                "backend_compile_s": block.get("backend_compile_s"),
                "cache_hits": block.get("cache_hits"),
                "cache_misses": block.get("cache_misses"),
                "seconds": block.get("backend_compile_s"),
                "programs": 0,
            }
            records.append(record)
    else:
        # single-rate modes: fl_rounds_per_sec_100c / _configN /
        # _1000c / fl_e2e_N — the headline value IS the rate
        value = parsed.get("value")
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            record = _bench_base(parsed, "headline", "fused")
            record["rounds_per_sec_steady"] = value
            for key in ("roc_auc_final", "roc_auc"):
                best = detail.get(key)
                if isinstance(best, (int, float)):
                    record["final"] = {"roc_auc": best}
                    break
            records.append(record)
    return records
