"""``attackfl-tpu ledger``: query the cross-run store, diff runs, gate CI.

Subcommands (all jax-free — they read JSON and print; safe on any box
that merely holds the artifacts):

* ``list`` — the store's index as a table (or ``--json``);
* ``show ID`` — one full record (id prefixes resolve when unambiguous);
* ``compare A [B]`` — column diff of two records; with one id, A is
  diffed against its rolling baseline (median of its fingerprint+executor
  peers);
* ``regress [ID]`` — the CI gate: noise-aware thresholds over perf,
  quality, forensics and numerics columns; exit 0 = pass, 1 = regression,
  2 = nothing to compare.  Default candidate: the newest record;
  default baseline: its rolling baseline (``--against ID`` pins one);
* ``import FILE...`` — backfill committed bench artifacts
  (``BENCH_*.json`` metric lines or driver wrappers) into the store.
"""

from __future__ import annotations

import argparse
import datetime
import json
import sys
from typing import Any

from attackfl_tpu.ledger.compare import (
    compare_records, regress_check, rolling_baseline,
)
from attackfl_tpu.ledger.record import records_from_bench, validate_record
from attackfl_tpu.ledger.store import LedgerStore, resolve_ledger_dir


def _fmt_ts(ts: Any) -> str:
    if not isinstance(ts, (int, float)) or isinstance(ts, bool):
        return "-"
    return datetime.datetime.fromtimestamp(ts).strftime("%Y-%m-%d %H:%M")


def _fmt(value: Any, nd: int = 4) -> str:
    if isinstance(value, bool):
        return str(value)
    if isinstance(value, (int, float)):
        return f"{value:.{nd}g}" if isinstance(value, float) else str(value)
    return "-" if value is None else str(value)


def _fmt_depth(entry: dict[str, Any]) -> str:
    """`ledger list` depth column: configured depth, with the effective
    depth appended when a demotion dropped it mid-run (``4>0``)."""
    depth = entry.get("pipeline_depth")
    if not isinstance(depth, int) or isinstance(depth, bool):
        return "-"
    effective = entry.get("pipeline_depth_effective")
    if isinstance(effective, int) and not isinstance(effective, bool) \
            and effective != depth:
        return f"{depth}>{effective}"
    return str(depth)


def _fmt_mesh(entry: dict[str, Any]) -> str:
    """`ledger list` mesh column: device count of the run's mesh (``-``
    for meshless runs and records predating the field)."""
    devices = entry.get("mesh_devices")
    if not isinstance(devices, int) or isinstance(devices, bool) \
            or devices < 1:
        return "-"
    return str(devices)


def _fmt_sched(entry: dict[str, Any]) -> tuple[str, str]:
    """`ledger list` scheduler columns: priority class (with the
    preemption count appended when nonzero, ``low*2``) and queue wait —
    ``-`` on runs that never went through the service scheduler."""
    priority = entry.get("sched_priority")
    if not priority:
        return "-", "-"
    preemptions = entry.get("sched_preemptions")
    prio = str(priority)
    if isinstance(preemptions, int) and not isinstance(preemptions, bool) \
            and preemptions > 0:
        prio += f"*{preemptions}"
    wait = entry.get("sched_wait_seconds")
    wait_text = (f"{wait:.1f}s" if isinstance(wait, (int, float))
                 and not isinstance(wait, bool) else "-")
    return prio, wait_text


def format_list(entries: list[dict[str, Any]]) -> str:
    lines = [f"{'id':<22}{'when':<18}{'exec':<11}{'depth':<7}{'mesh':<6}"
             f"{'src':<7}"
             f"{'workload':<28}{'rounds':>7}{'steady r/s':>11}"
             f"{'prio':>8}{'wait':>7}"]
    for entry in entries:
        workload = "-"
        if entry.get("cell"):
            # a matrix cell record: the cell key IS the workload identity
            workload = str(entry["cell"])
        elif entry.get("model") or entry.get("mode"):
            workload = (f"{entry.get('model') or '?'}/"
                        f"{entry.get('mode') or '?'}"
                        f" c{entry.get('total_clients') or '?'}")
        rounds = entry.get("rounds")
        ok = entry.get("ok_rounds")
        rounds_text = (f"{ok}/{rounds}" if isinstance(rounds, int)
                       and isinstance(ok, int) and rounds else "-")
        prio, wait_text = _fmt_sched(entry)
        lines.append(
            f"{str(entry.get('record_id') or '?')[:21]:<22}"
            f"{_fmt_ts(entry.get('ts')):<18}"
            f"{str(entry.get('executor') or '-'):<11}"
            f"{_fmt_depth(entry):<7}"
            f"{_fmt_mesh(entry):<6}"
            f"{str(entry.get('source') or '-'):<7}"
            f"{workload[:27]:<28}"
            f"{rounds_text:>7}"
            f"{_fmt(entry.get('rounds_per_sec_steady')):>11}"
            f"{prio:>8}{wait_text:>7}")
    return "\n".join(lines)


def format_record(record: dict[str, Any]) -> str:
    lines = [f"record {record.get('record_id')} "
             f"[{record.get('source')}/{record.get('executor')}"
             + (f"/depth={_fmt_depth(record)}"
                if isinstance(record.get("pipeline_depth"), int)
                and not isinstance(record.get("pipeline_depth"), bool)
                else "")
             + (f"/mesh={_fmt_mesh(record)}"
                if _fmt_mesh(record) != "-" else "")
             + ("/resumed" if record.get("resumed") else "") + "]"]
    lines.append(
        f"  run_id={record.get('run_id') or '-'} "
        f"fingerprint={record.get('fingerprint') or '-'} "
        f"git={record.get('git_rev') or '-'}")
    lines.append(
        f"  jax={record.get('jax_version') or '-'}"
        f"/{record.get('jaxlib_version') or '-'} "
        f"backend={record.get('backend') or '-'} "
        f"platform={record.get('platform') or '-'}")
    if record.get("model") or record.get("mode"):
        lines.append(
            f"  workload: {record.get('model')}/{record.get('data_name')} "
            f"mode={record.get('mode')} clients={record.get('total_clients')}")
    lines.append(
        f"  rounds: {record.get('ok_rounds')}/{record.get('rounds')} ok "
        f"in {_fmt(record.get('wall_seconds'))}s, "
        f"steady={_fmt(record.get('rounds_per_sec_steady'))} r/s, "
        f"incl-compile={_fmt(record.get('rounds_per_sec_incl_compile'))} r/s")
    attribution = record.get("time_attribution") or {}
    if attribution:
        lines.append(
            "  time: device={} host={} validate={} ckpt={} "
            "ckpt-overlap={} compile={} defense={} (of wall {})".format(
                *(_fmt(attribution.get(k)) for k in (
                    "device_compute_s", "host_resolution_s", "validation_s",
                    "checkpoint_s", "checkpoint_overlapped_s", "compile_s",
                    "defense_host_s", "wall_s"))))
    if record.get("sched_priority") is not None:
        sched_line = (f"  sched: priority={record.get('sched_priority')} "
                      f"wait={_fmt(record.get('sched_wait_seconds'))}s "
                      f"preemptions="
                      f"{_fmt(record.get('sched_preemptions'))}")
        if record.get("sched_tenant"):
            sched_line += f" tenant={record['sched_tenant']}"
        if record.get("sched_fleet_id"):
            sched_line += f" fleet={record['sched_fleet_id']}"
        if record.get("sched_slot") is not None:
            sched_line += f" slot={record['sched_slot']}"
        lines.append(sched_line)
    if record.get("round_device_time") is not None:
        lines.append(
            f"  per-round: device={_fmt(record.get('round_device_time'))}s "
            f"host-resolution="
            f"{_fmt(record.get('host_resolution_latency'))}s "
            "(depth-k auto-tune inputs)")
    utilization = record.get("utilization") or {}
    if utilization:
        achieved = utilization.get("achieved_flops_per_sec")
        fraction = utilization.get("utilization_flops")
        line = (f"  cost: flops/round={_fmt(utilization.get('flops_per_round'))} "
                f"bytes/round={_fmt(utilization.get('bytes_per_round'))}")
        if achieved is not None:
            line += f" achieved={_fmt(achieved)}FLOP/s"
        if fraction is not None:
            line += (f" roofline={100 * fraction:.2f}% of "
                     f"{_fmt(utilization.get('peak_flops_per_sec'))} peak")
        elif achieved is not None:
            line += (f" (achieved-only: no peak spec for "
                     f"{utilization.get('device_kind') or 'this device'})")
        lines.append(line)
    hotspots = record.get("hotspots") or {}
    if hotspots:
        line = (f"  hotspots: windows={_fmt(hotspots.get('windows'))} "
                f"hostbound={_fmt(hotspots.get('host_bound_fraction'))} "
                f"({hotspots.get('classification') or '-'}) "
                f"books={'close' if hotspots.get('books_close') else 'OPEN'}")
        factor = hotspots.get("hotspot_prediction_error_factor")
        if factor is not None:
            line += f" pred-err={_fmt(factor)}x"
        lines.append(line)
        top = hotspots.get("top_ops") or []
        if top:
            lines.append("  top ops: " + " ".join(
                f"{row.get('name')}={_fmt(row.get('share'))}"
                for row in top[:5] if isinstance(row, dict)))
    programs = record.get("programs") or {}
    if programs:
        lines.append(
            "  programs: " + " ".join(
                f"{name}[flops={_fmt(p.get('flops'))}]"
                for name, p in sorted(programs.items())
                if isinstance(p, dict)))
    compile_info = record.get("compile") or {}
    if compile_info.get("programs") or compile_info.get("cache_hits") \
            is not None:
        lines.append(
            f"  compile: {compile_info.get('programs', 0)} program(s) "
            f"{_fmt(compile_info.get('seconds'))}s"
            + (f", persistent cache {compile_info.get('cache_hits')} hit(s) "
               f"/ {compile_info.get('cache_misses')} miss(es)"
               if compile_info.get("cache_hits") is not None else ""))
    for section in ("final", "numerics", "forensics", "counts"):
        data = record.get(section)
        if data:
            shown = {k: v for k, v in data.items() if v not in (None, 0)}
            if shown:
                lines.append(f"  {section}: " + " ".join(
                    f"{k}={_fmt(v)}" for k, v in shown.items()))
    phases = record.get("phases") or {}
    if phases:
        lines.append(f"  {'phase':<14}{'p50':>10}{'p95':>10}{'n':>6}")
        for name, stats in phases.items():
            p50, p95 = stats.get("p50_s"), stats.get("p95_s")
            lines.append(
                f"  {name:<14}"
                f"{(p50 or 0) * 1e3:>8.1f}ms{(p95 or 0) * 1e3:>8.1f}ms"
                f"{stats.get('count', 0):>6}")
    return "\n".join(lines)


def format_compare(diff: dict[str, Any]) -> str:
    lines = [f"compare {diff.get('old_id')} -> {diff.get('new_id')}"
             + ("" if diff.get("fingerprint_match")
                else "  [WARNING: different config fingerprints — "
                     "not apples to apples]")]
    executor = diff.get("executor") or {}
    if executor.get("old") != executor.get("new"):
        lines.append(f"  executor: {executor.get('old')} -> "
                     f"{executor.get('new')}")
    depth = diff.get("pipeline_depth") or {}
    if depth.get("old") != depth.get("new"):
        lines.append(f"  pipeline depth: {depth.get('old')} -> "
                     f"{depth.get('new')}  [different depths are "
                     "non-peers for rolling baselines]")
    mesh = diff.get("mesh_devices") or {}
    if mesh.get("old") != mesh.get("new"):
        lines.append(f"  mesh devices: {mesh.get('old')} -> "
                     f"{mesh.get('new')}  [different mesh sizes are "
                     "non-peers for rolling baselines]")

    def render(title: str, columns: dict[str, Any], pct: bool = True):
        rows = []
        for name, delta in columns.items():
            if not isinstance(delta, dict) or delta.get("old") is None \
                    and delta.get("new") is None:
                continue
            row = (f"    {name:<26}{_fmt(delta.get('old')):>12}"
                   f"{_fmt(delta.get('new')):>12}")
            if "pct" in delta and pct:
                row += f"{delta['pct']:>+9.1f}%"
            elif "delta" in delta:
                row += f"{delta['delta']:>+10.4g}"
            rows.append(row)
        if rows:
            lines.append(f"  {title}:")
            lines.append(f"    {'column':<26}{'old':>12}{'new':>12}"
                         f"{'delta':>10}")
            lines.extend(rows)

    render("perf", diff.get("perf") or {})
    render("time attribution", diff.get("time_attribution") or {})
    phase_rows = {f"{name}.p95": (data or {}).get("p95_s")
                  for name, data in (diff.get("phases") or {}).items()}
    render("phases", {k: v for k, v in phase_rows.items() if v})
    render("quality", diff.get("quality") or {}, pct=False)
    render("numerics", diff.get("numerics") or {}, pct=False)
    render("forensics", diff.get("forensics") or {}, pct=False)
    render("utilization", diff.get("utilization") or {})
    sched = diff.get("sched") or {}
    if sched:
        prio = sched.get("priority") or {}
        if prio.get("old") != prio.get("new"):
            lines.append(f"  sched priority: {prio.get('old')} -> "
                         f"{prio.get('new')}  [cross-priority waits are "
                         "not apples to apples]")
        render("sched", {"wait_seconds": sched.get("wait_seconds"),
                         "preemptions": sched.get("preemptions")},
               pct=False)
    hotspots = diff.get("hotspots") or {}
    if hotspots:
        render("hotspots", {
            "host_bound_fraction": hotspots.get("host_bound_fraction"),
            "measured_device_s": hotspots.get("measured_round_device_s"),
            "pred_error_factor": hotspots.get("prediction_error_factor"),
        }, pct=False)
        share_rows = {f"share:{name}": delta for name, delta in
                      (hotspots.get("top_op_shares") or {}).items()}
        render("top-op shares", share_rows, pct=False)
    counts = {k: v for k, v in (diff.get("counts") or {}).items()
              if isinstance(v, dict) and v.get("delta")}
    render("counts (changed)", counts, pct=False)
    return "\n".join(lines)


def format_regress(verdict: dict[str, Any]) -> str:
    lines = [
        f"regress {verdict.get('candidate_id')} vs "
        f"{verdict.get('baseline_id')}: "
        + ("PASS" if verdict.get("ok") else "REGRESSION")
        + f" ({verdict.get('checks')} check(s), rate threshold "
          f"{verdict.get('rate_threshold_pct')}%"
        + (f", noise floor {verdict.get('rate_noise_pct')}%"
           if verdict.get("rate_noise_pct") else "") + ")"]
    for violation in verdict.get("violations") or []:
        detail = " ".join(f"{k}={_fmt(v)}" for k, v in violation.items()
                          if k != "check")
        lines.append(f"  FAIL {violation.get('check')}: {detail}")
    return "\n".join(lines)


def sweep_rollup(records: list[dict[str, Any]], sweep_id: str) -> str:
    """One-line sweep summary for ``ledger list --sweep``: cell
    completion (a cell with lost rounds was quarantined by the per-cell
    retry budget or cut by an interruption) + median final quality.
    Reads FULL records — the index carries no quality columns."""
    import statistics

    from attackfl_tpu.science.outcomes import pick_quality_key

    cells = [r for r in records if r.get("source") == "matrix"
             and r.get("sweep_id") == sweep_id]
    if not cells:
        return f"sweep {sweep_id}: no cell records"
    done = sum(
        1 for r in cells
        if isinstance(r.get("ok_rounds"), int)
        and isinstance(r.get("rounds"), int)
        and r["rounds"] > 0 and r["ok_rounds"] >= r["rounds"])
    quality_key = pick_quality_key(cells)
    line = (f"sweep {sweep_id}: {len(cells)} cell(s), {done} complete, "
            f"{len(cells) - done} quarantined/cut")
    if quality_key:
        values = [
            (r.get("final") or {}).get(quality_key) for r in cells]
        values = [v for v in values if isinstance(v, (int, float))
                  and not isinstance(v, bool)]
        if values:
            line += (f", median {quality_key} "
                     f"{statistics.median(values):.4f}")
    return line


def _store(args) -> LedgerStore:
    # an explicit --dir beats the env var (the user typed it); without
    # one, fall back to $ATTACKFL_LEDGER_DIR then ./ledger
    return LedgerStore(args.dir or resolve_ledger_dir())


def _get_or_die(store: LedgerStore, record_id: str) -> dict[str, Any]:
    record = store.get(record_id)
    if record is None:
        print(f"no ledger record {record_id!r} in {store.directory!r}",
              file=sys.stderr)
        raise SystemExit(2)
    return record


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="attackfl-tpu ledger",
        description="Query the persistent cross-run ledger, diff runs and "
                    "gate regressions.")
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--dir", type=str, default=None,
                        help="ledger directory (default: "
                             "$ATTACKFL_LEDGER_DIR or ./ledger)")
    sub = parser.add_subparsers(dest="command", required=True)

    p_list = sub.add_parser("list", parents=[common],
                            help="index of every recorded run")
    p_list.add_argument("--fingerprint", type=str, default=None)
    p_list.add_argument("--executor", type=str, default=None)
    p_list.add_argument("--sweep", type=str, default=None,
                        help="only this matrix sweep's cell records, "
                             "plus a one-line completion/quality rollup")
    p_list.add_argument("--json", action="store_true")

    p_show = sub.add_parser("show", parents=[common],
                            help="one full record")
    p_show.add_argument("id")
    p_show.add_argument("--json", action="store_true")

    p_cmp = sub.add_parser("compare", parents=[common],
                           help="diff two records (or one vs its rolling "
                                "baseline)")
    p_cmp.add_argument("a")
    p_cmp.add_argument("b", nargs="?", default=None)
    p_cmp.add_argument("--window", type=int, default=5,
                       help="rolling-baseline depth (records)")
    p_cmp.add_argument("--json", action="store_true")

    p_reg = sub.add_parser("regress", parents=[common],
                           help="CI gate: exit 1 on perf/quality regression")
    p_reg.add_argument("id", nargs="?", default=None,
                       help="candidate record (default: newest)")
    p_reg.add_argument("--against", type=str, default=None,
                       help="explicit baseline record id (default: rolling "
                            "baseline by config fingerprint)")
    p_reg.add_argument("--window", type=int, default=5)
    p_reg.add_argument("--threshold-pct", type=float, default=None,
                       help="steady-rounds/s slowdown that fails "
                            "(default 10; noise-floored)")
    p_reg.add_argument("--sweeps", nargs=2, metavar=("OLD", "NEW"),
                       default=None,
                       help="rank-stability gate between two matrix "
                            "sweeps instead of a record pair (delegates "
                            "to `science diff --gate`)")
    p_reg.add_argument("--json", action="store_true")

    p_imp = sub.add_parser("import", parents=[common],
                           help="backfill bench artifacts (BENCH_*.json)")
    p_imp.add_argument("files", nargs="+")
    p_imp.add_argument("--json", action="store_true")

    args = parser.parse_args(argv)
    store = _store(args)

    if args.command == "list":
        entries = store.index()
        if args.fingerprint:
            entries = [e for e in entries
                       if e.get("fingerprint") == args.fingerprint]
        if args.executor:
            entries = [e for e in entries
                       if e.get("executor") == args.executor]
        if args.sweep:
            entries = [e for e in entries
                       if e.get("sweep_id") == args.sweep]
        if args.json:
            print(json.dumps(entries, indent=1))
        elif not entries:
            print(f"empty ledger at {store.directory!r}", file=sys.stderr)
            return 2
        else:
            print(format_list(entries))
            if args.sweep:
                records, _ = store.load()
                print(sweep_rollup(records, args.sweep))
        return 0

    if args.command == "show":
        record = _get_or_die(store, args.id)
        print(json.dumps(record, indent=1) if args.json
              else format_record(record))
        return 0

    if args.command == "compare":
        new = _get_or_die(store, args.a if args.b is None else args.b)
        if args.b is None:
            records, _ = store.load()
            old = rolling_baseline(records, new, window=args.window)
            if old is None:
                print(f"no baseline peers for {args.a!r} (fingerprint "
                      f"{new.get('fingerprint')!r})", file=sys.stderr)
                return 2
        else:
            old = _get_or_die(store, args.a)
        diff = compare_records(old, new)
        print(json.dumps(diff, indent=1) if args.json
              else format_compare(diff))
        return 0

    if args.command == "regress" and args.sweeps:
        # the ISSUE 17 rank gate rides the familiar CI entry point
        from attackfl_tpu.science.cli import main as science_main

        return science_main(
            ["diff", args.sweeps[0], args.sweeps[1], "--gate"]
            + (["--dir", args.dir] if args.dir else [])
            + (["--json"] if args.json else []))

    if args.command == "regress":
        records, _ = store.load()
        if not records:
            print(f"empty ledger at {store.directory!r}", file=sys.stderr)
            return 2
        candidate = (_get_or_die(store, args.id) if args.id
                     else records[-1])
        if args.against:
            baseline = _get_or_die(store, args.against)
        else:
            baseline = rolling_baseline(records, candidate,
                                        window=args.window)
            if baseline is None:
                print(
                    f"no baseline peers for "
                    f"{candidate.get('record_id')!r} (fingerprint "
                    f"{candidate.get('fingerprint')!r}) — nothing to gate",
                    file=sys.stderr)
                return 2
        thresholds = ({"rounds_per_sec_pct": args.threshold_pct}
                      if args.threshold_pct is not None else None)
        verdict = regress_check(baseline, candidate, thresholds)
        print(json.dumps(verdict, indent=1) if args.json
              else format_regress(verdict))
        return 0 if verdict["ok"] else 1

    if args.command == "import":
        imported: list[str] = []
        problems = 0
        for path in args.files:
            try:
                with open(path) as fh:
                    parsed = json.load(fh)
            except (OSError, json.JSONDecodeError) as e:
                print(f"skipping {path}: {e}", file=sys.stderr)
                problems += 1
                continue
            records = records_from_bench(parsed) \
                if isinstance(parsed, dict) else []
            if not records:
                print(f"skipping {path}: no recognizable bench metric",
                      file=sys.stderr)
                problems += 1
                continue
            for record in records:
                bad = validate_record(record)
                if bad:
                    print(f"skipping a record from {path}: {bad}",
                          file=sys.stderr)
                    problems += 1
                    continue
                rid = store.append(record)
                imported.append(rid)
                if not args.json:
                    print(f"imported {rid} "
                          f"[{record.get('bench_metric')}"
                          f"/{record.get('bench_variant')}] from {path}")
        if args.json:
            print(json.dumps({"imported": imported,
                              "skipped": problems}, indent=1))
        return 0 if imported and not problems else (0 if imported else 2)

    raise AssertionError(f"unhandled command {args.command!r}")


if __name__ == "__main__":
    raise SystemExit(main())
