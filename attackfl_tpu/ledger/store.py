"""Persistent ledger store: append-only JSONL + atomically-published index.

Layout (one directory, shared by every run of an experiment family):

* ``ledger.jsonl`` — one full ledger record per line, append-only.  A
  crash mid-append can tear at most the final line; readers skip torn
  lines and count them (the same contract ``summary.load_events`` keeps
  for event files), so the store never needs repair.
* ``index.json`` — small per-record summaries (id, ts, run_id, config
  fingerprint, executor, source, steady rounds/s) for instant ``ledger
  list`` / monitor ``/runs`` queries without parsing every full record.
  Rewritten on every append via the checkpoint layer's temp+fsync+rename
  pattern, so it is always either the old or the new complete index.
  A missing/stale index is rebuilt from ``ledger.jsonl`` (the JSONL is
  the source of truth).

Crash-safety mirrors ``utils/checkpoint`` (ISSUE 6): orphaned
``index.json.tmp*`` temps from killed writes are swept at store open
(surfaced through the existing ``orphan_tmp_swept`` counter by the
engine), and a failed index write unlinks its own temp.

Multi-writer safety (ISSUE 8): the run service executes N concurrent
runs whose Simulators each hold their OWN ``LedgerStore`` over the one
shared service ledger, so the in-instance ``threading.Lock`` no longer
serializes appends.  :meth:`LedgerStore.append` therefore also takes an
advisory ``fcntl`` lock on a sidecar ``ledger.lock`` file around the
JSONL append + index republish: the append stays atomic across
instances AND processes, id-collision suffixes are assigned under the
lock, and the index never loses a record to a concurrent republish.
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from typing import Any, Iterable

from attackfl_tpu.utils.atomicio import file_lock, write_bytes_atomic

ENV_LEDGER_DIR = "ATTACKFL_LEDGER_DIR"
LEDGER_NAME = "ledger.jsonl"
INDEX_NAME = "index.json"
LOCK_NAME = "ledger.lock"
INDEX_VERSION = 1

# The per-record summary the index carries (and `ledger list` renders).
# `sweep_id`/`cell` (ISSUE 9) are None on non-matrix records, as are the
# `pipeline_depth*` fields (ISSUE 10) on non-pipelined ones — the index
# self-heals from the JSONL, so older indexes simply rebuild with them.
INDEX_FIELDS = ("record_id", "ts", "run_id", "fingerprint", "executor",
                "source", "mode", "model", "total_clients", "rounds",
                "ok_rounds", "rounds_per_sec_steady", "sweep_id", "cell",
                "pipeline_depth", "pipeline_depth_effective",
                "mesh_devices",
                # scheduler accounting (ISSUE 15/16): None on runs that
                # never went through the service scheduler
                "sched_priority", "sched_preemptions",
                "sched_wait_seconds", "sched_tenant")


def resolve_ledger_dir(explicit: str | None = None,
                       base: str | None = None) -> str:
    """Ledger directory resolution: the ``ATTACKFL_LEDGER_DIR`` env var
    (test/CI harness redirect — same precedence the compile cache gives
    ``ATTACKFL_COMPILE_CACHE``) wins over the config's explicit dir, which
    wins over ``<base>/ledger`` (base = the run's telemetry directory)."""
    return (os.environ.get(ENV_LEDGER_DIR) or explicit
            or os.path.join(base or ".", "ledger"))


def _write_json_atomic(path: str, payload: Any) -> None:
    """Temp + fsync + rename publish (utils/atomicio, jax-free); the
    pid+uuid temp suffix keeps concurrent writers' temps distinct."""
    write_bytes_atomic(
        path, json.dumps(payload).encode(),
        tmp_suffix=f".tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}")


def sweep_orphans(directory: str, dry_run: bool = False) -> list[str]:
    """Remove ``index.json.tmp*`` / ``ledger.jsonl.tmp*`` leftovers from
    killed writes (only the ledger's own temp patterns — the directory
    may be shared).  Returns the removed (or, with ``dry_run``, the
    matching) paths."""
    removed: list[str] = []
    try:
        names = os.listdir(directory or ".")
    except OSError:
        return removed
    for name in names:
        if not (name.startswith(INDEX_NAME + ".tmp")
                or name.startswith(LEDGER_NAME + ".tmp")):
            continue
        path = os.path.join(directory or ".", name)
        if not dry_run:
            try:
                os.unlink(path)
            except OSError:
                continue
        removed.append(path)
    return removed


class LedgerStore:
    """One ledger directory: append records, query them, keep the index
    honest.  Appends are lock-serialized (the monitor thread reads while
    the round loop's ``_finish_run`` writes)."""

    def __init__(self, directory: str):
        self.directory = directory or "."
        os.makedirs(self.directory, exist_ok=True)
        self.path = os.path.join(self.directory, LEDGER_NAME)
        self.index_path = os.path.join(self.directory, INDEX_NAME)
        self.lock_path = os.path.join(self.directory, LOCK_NAME)
        self._lock = threading.Lock()
        # sweep under the file lock: a store opening while a sibling
        # instance republishes the index must not delete the live temp
        # out from under that writer's os.replace.  The lock file is
        # only materialized when there is something to sweep (or an
        # append happens later) — opening a committed/read-only ledger
        # dir for queries must not litter it.
        if sweep_orphans(self.directory, dry_run=True):
            with file_lock(self.lock_path):
                self.swept_orphans = sweep_orphans(self.directory)
        else:
            self.swept_orphans = []

    # ------------------------------------------------------------------
    # writes
    # ------------------------------------------------------------------

    def append(self, record: dict[str, Any]) -> str:
        """Append one record; returns its (assigned) ``record_id``.

        The JSONL append lands first (flush+fsync — the record is durable
        before the index names it), then the index is atomically
        republished.  An id collision (same run_id appended twice, e.g.
        bench reps sharing a Simulator) gets a ``-N`` suffix.

        Serialized twice over: the instance lock (monitor thread vs the
        round loop) AND an advisory file lock, because N service workers
        each hold their own store instance over this one directory — the
        index reload, the collision-suffix assignment, the JSONL append
        and the index republish must be one atomic step across all of
        them."""
        with self._lock, file_lock(self.lock_path):
            index = self._load_index_unlocked()
            taken = {e.get("record_id") for e in index}
            rid = str(record.get("record_id") or record.get("run_id")
                      or uuid.uuid4().hex[:12])
            if rid in taken:
                n = 2
                while f"{rid}-{n}" in taken:
                    n += 1
                rid = f"{rid}-{n}"
            record = dict(record, record_id=rid)
            with open(self.path, "a") as fh:
                fh.write(json.dumps(record) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
            index.append(self._index_entry(record))
            _write_json_atomic(self.index_path, {
                "index_version": INDEX_VERSION, "records": index})
            return rid

    @staticmethod
    def _index_entry(record: dict[str, Any]) -> dict[str, Any]:
        return {k: record.get(k) for k in INDEX_FIELDS}

    # ------------------------------------------------------------------
    # reads
    # ------------------------------------------------------------------

    def index(self) -> list[dict[str, Any]]:
        """Per-record summaries, oldest first.  Falls back to (and heals
        from) a full JSONL scan when the index file is missing or behind
        the JSONL (a crash between the two writes)."""
        with self._lock:
            return self._load_index_unlocked()

    def _load_index_unlocked(self) -> list[dict[str, Any]]:
        entries: list[dict[str, Any]] | None = None
        try:
            with open(self.index_path) as fh:
                payload = json.load(fh)
            if isinstance(payload, dict):
                raw = payload.get("records")
                if isinstance(raw, list):
                    entries = [e for e in raw if isinstance(e, dict)]
        except (OSError, json.JSONDecodeError):
            entries = None
        records, _ = self._scan_unlocked()
        if entries is None or len(entries) != len(records):
            # rebuild from the source of truth (missing/torn/stale index)
            entries = [self._index_entry(r) for r in records]
        return entries

    def load(self) -> tuple[list[dict[str, Any]], int]:
        """Every full record (oldest first) plus the count of skipped
        torn/malformed lines."""
        with self._lock:
            return self._scan_unlocked()

    def _scan_unlocked(self) -> tuple[list[dict[str, Any]], int]:
        records: list[dict[str, Any]] = []
        skipped = 0
        try:
            fh = open(self.path)
        except OSError:
            return records, skipped
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except json.JSONDecodeError:
                    skipped += 1
                    continue
                if isinstance(record, dict):
                    records.append(record)
                else:
                    skipped += 1
        return records, skipped

    def get(self, record_id: str) -> dict[str, Any] | None:
        """Full record by id; unambiguous id prefixes resolve too."""
        records, _ = self.load()
        for record in records:
            if record.get("record_id") == record_id:
                return record
        matches = [r for r in records
                   if str(r.get("record_id", "")).startswith(record_id)]
        return matches[0] if len(matches) == 1 else None

    def records(self, fingerprint: str | None = None,
                executor: str | None = None,
                source: str | None = None) -> list[dict[str, Any]]:
        records, _ = self.load()
        out: Iterable[dict[str, Any]] = records
        if fingerprint is not None:
            out = (r for r in out if r.get("fingerprint") == fingerprint)
        if executor is not None:
            out = (r for r in out if r.get("executor") == executor)
        if source is not None:
            out = (r for r in out if r.get("source") == source)
        return list(out)
