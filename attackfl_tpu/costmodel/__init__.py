"""Program cost observatory (ISSUE 11): the fifth observability layer.

Three halves, one contract:

* **capture** (:mod:`~attackfl_tpu.costmodel.capture`) — guarded
  ``compiled.cost_analysis()`` + ``memory_analysis()`` snapshots taken at
  the engines' existing AOT-compile seams, emitted as schema-v9
  ``program_profile`` events keyed by program name + config fingerprint
  and folded into the cross-run ledger record;
* **utilization** (:mod:`~attackfl_tpu.costmodel.roofline` +
  :mod:`~attackfl_tpu.costmodel.peaks`) — the static profile combined
  with the ledger's MEASURED ``round_device_time`` into achieved FLOP/s
  and bytes/s, and — on device types with a known peak spec — roofline
  utilization fractions (CPU reports achieved-only: no honest peak
  exists for a shared, frequency-scaled host);
* **prediction** (:mod:`~attackfl_tpu.costmodel.estimate`) —
  ``attackfl-tpu cost estimate`` prices a config or matrix grid WITHOUT
  running it (fingerprint-peer ledger records first, a flops/bytes
  regression over non-peer records as the fallback) and ``cost
  validate`` replays predictions against a ledger corpus, reporting the
  error distribution the future multi-tenant scheduler's bin-packing
  will rely on.

Standing invariants: everything here is observational — zero new host
syncs (compiling/lowering never materializes device values; the
host-sync lint covers this package with NO allowlist) and params are
bit-identical with the observatory on or off.
"""

from attackfl_tpu.costmodel.capture import (
    compiled_profile, guarded_cost_analysis, guarded_memory_analysis,
)
from attackfl_tpu.costmodel.peaks import peak_for
from attackfl_tpu.costmodel.roofline import (
    per_round_cost, utilization_summary,
)

__all__ = [
    "compiled_profile", "guarded_cost_analysis", "guarded_memory_analysis",
    "peak_for", "per_round_cost", "utilization_summary",
]
