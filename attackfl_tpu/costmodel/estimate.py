"""Predictive cost model: price a config BEFORE running it.

Two prediction paths, tried in order:

* **peer** — ledger records sharing the candidate's config fingerprint
  already measured ``round_device_time``; the prediction is their median
  (newest ``window`` records), exactly the statistic the depth-k
  auto-tuner trusts.  This is the path the future multi-tenant
  scheduler's bin-packing takes for warm workloads.
* **regression** — no fingerprint peer exists (a NEW config).  Fit
  ``device_time ≈ a·flops + b·bytes`` by least squares over every
  non-peer record that carries both a measured ``round_device_time`` and
  a per-round cost profile (``utilization.flops_per_round`` /
  ``bytes_per_round`` — the schema-v9 capture layer writes these), then
  apply it to the candidate's OWN static profile.  Degenerate corpora
  (fewer than two usable records, singular normal equations) fall back
  to the median seconds-per-flop ratio.

``validate_predictions`` replays the whole corpus leave-one-out —
every measured record is re-predicted from the others — and reports the
error distribution (median/p90 of the symmetric error factor
``max(pred/meas, meas/pred)``).  That distribution is the accuracy
contract: ``attackfl-tpu cost validate`` exits non-zero when the median
factor exceeds the bound (default 2×, the ISSUE 11 acceptance bar).

Jax-free: reads JSON-shaped ledger records only.  The CLI's
no-peer path compiles the candidate's programs to GET a profile — that
import lives in :mod:`attackfl_tpu.costmodel.cli`, not here.
"""

from __future__ import annotations

import statistics
from typing import Any

DEFAULT_WINDOW = 5
# leave-one-out acceptance bar: median symmetric error factor
DEFAULT_MAX_MEDIAN_FACTOR = 2.0


def _num(value: Any) -> float | None:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    if value != value:
        return None
    return value + 0.0


def _measured(record: dict[str, Any]) -> float | None:
    value = _num(record.get("round_device_time"))
    return value if value is not None and value > 0 else None


def _cost_features(record: dict[str, Any]) -> tuple[float, float] | None:
    """(flops_per_round, bytes_per_round) from a record's utilization
    block; bytes default to 0 when only flops is known."""
    utilization = record.get("utilization")
    if not isinstance(utilization, dict):
        return None
    flops = _num(utilization.get("flops_per_round"))
    if flops is None or flops <= 0:
        return None
    size = _num(utilization.get("bytes_per_round"))
    return flops, (size if size is not None and size > 0 else 0.0)


def peer_prediction(records: list[dict[str, Any]], fingerprint: str,
                    window: int = DEFAULT_WINDOW,
                    exclude_id: str | None = None
                    ) -> tuple[float, dict[str, Any]] | None:
    """Median measured device time over the newest fingerprint peers."""
    peers = [r for r in records
             if r.get("fingerprint") == fingerprint
             and _measured(r) is not None
             and (exclude_id is None or r.get("record_id") != exclude_id)]
    if not peers or not fingerprint:
        return None
    peers = peers[-window:]
    times = [_measured(r) for r in peers]
    prediction = statistics.median(times)
    spread = (max(times) - min(times)) / prediction if prediction else 0.0
    return prediction, {
        "method": "peer",
        "peers": len(peers),
        "peer_ids": [r.get("record_id") for r in peers],
        "peer_spread": round(spread, 4),
    }


def fit_regression(records: list[dict[str, Any]],
                   exclude_fingerprint: str | None = None,
                   exclude_id: str | None = None
                   ) -> dict[str, Any] | None:
    """``time ≈ a·flops + b·bytes`` over records carrying both a measured
    device time and a cost profile.  No intercept: zero work takes zero
    time, and the corpora are small enough that an intercept just soaks
    up noise.  Returns ``{a, b, n}`` (b = 0 on the ratio fallback), or
    None when nothing is usable."""
    rows: list[tuple[float, float, float]] = []
    for record in records:
        if exclude_fingerprint is not None \
                and record.get("fingerprint") == exclude_fingerprint:
            continue
        if exclude_id is not None \
                and record.get("record_id") == exclude_id:
            continue
        measured = _measured(record)
        features = _cost_features(record)
        if measured is None or features is None:
            continue
        rows.append((features[0], features[1], measured))
    if not rows:
        return None
    if len(rows) >= 2 and any(b > 0 for _, b, _ in rows):
        # 2x2 normal equations for [a, b]
        sff = sum(f * f for f, _, _ in rows)
        sbb = sum(b * b for _, b, _ in rows)
        sfb = sum(f * b for f, b, _ in rows)
        sft = sum(f * t for f, _, t in rows)
        sbt = sum(b * t for _, b, t in rows)
        det = sff * sbb - sfb * sfb
        if det > 0 and sff > 0:
            a = (sft * sbb - sbt * sfb) / det
            b = (sbt * sff - sft * sfb) / det
            if a >= 0 and b >= 0 and (a > 0 or b > 0):
                return {"a": a, "b": b, "n": len(rows),
                        "method": "regression"}
    # ratio fallback: median seconds-per-flop (always well-defined)
    ratios = [t / f for f, _, t in rows if f > 0]
    if not ratios:
        return None
    return {"a": statistics.median(ratios), "b": 0.0, "n": len(rows),
            "method": "flops_ratio"}


def apply_regression(fit: dict[str, Any], flops: float,
                     size_bytes: float) -> float:
    return fit["a"] * flops + fit["b"] * size_bytes


def predict_device_time(records: list[dict[str, Any]], fingerprint: str,
                        profile: dict[str, Any] | None = None,
                        window: int = DEFAULT_WINDOW,
                        exclude_id: str | None = None
                        ) -> tuple[float, dict[str, Any]] | None:
    """Per-round device-time prediction for a config: fingerprint peers
    first, the flops/bytes regression over NON-peer records when none
    exist (``profile`` must then carry ``flops_per_round`` — without it
    there is nothing to regress onto, and the result is None)."""
    peer = peer_prediction(records, fingerprint, window, exclude_id)
    if peer is not None:
        return peer
    if profile is None:
        return None
    flops = _num(profile.get("flops_per_round"))
    if flops is None or flops <= 0:
        return None
    size = _num(profile.get("bytes_per_round")) or 0.0
    fit = fit_regression(records, exclude_fingerprint=fingerprint,
                         exclude_id=exclude_id)
    if fit is None:
        return None
    prediction = apply_regression(fit, flops, size)
    if prediction <= 0:
        return None
    return prediction, {"method": fit["method"], "fit_records": fit["n"],
                        "a_s_per_flop": fit["a"], "b_s_per_byte": fit["b"]}


def predict_run(records: list[dict[str, Any]], fingerprint: str,
                rounds: int, profile: dict[str, Any] | None = None,
                window: int = DEFAULT_WINDOW) -> dict[str, Any] | None:
    """Whole-run prediction: per-round device time × rounds, plus the
    peers' median host-resolution latency when available (regression
    predictions carry no host estimate — flagged ``device_only``)."""
    prediction = predict_device_time(records, fingerprint, profile, window)
    if prediction is None:
        return None
    device, info = prediction
    host_values = [
        _num(r.get("host_resolution_latency")) for r in records
        if r.get("fingerprint") == fingerprint
        and _num(r.get("host_resolution_latency")) is not None]
    host = statistics.median(host_values) if host_values else None
    per_round = device + (host or 0.0)
    return {
        "rounds": rounds,
        "round_device_time": round(device, 6),
        "host_resolution_latency": (round(host, 6)
                                    if host is not None else None),
        "device_only": host is None,
        "predicted_wall_seconds": round(per_round * rounds, 3),
        **info,
    }


def corpus_default_seconds(records: list[dict[str, Any]]
                           ) -> float | None:
    """Median measured wall time across the whole corpus — the
    scheduler's price for an honestly unpredictable job (no fingerprint
    peer, no static profile).  A corpus-derived default keeps the
    packer's backlog estimate in the right order of magnitude on warm
    services; None on an empty/unmeasured corpus (the caller falls back
    to its configured constant)."""
    walls = [w for w in (_num(r.get("wall_seconds")) for r in records)
             if w is not None and w > 0]
    if not walls:
        return None
    return statistics.median(walls)


def prediction_error_factor(predicted: float | None,
                            actual: float | None) -> float | None:
    """The symmetric error factor ``max(pred/actual, actual/pred)`` —
    the same statistic the leave-one-out validation reports — as a
    None-safe join for the fleet observatory's predicted-vs-actual
    column.  None (or a non-positive side) means "no joinable pair",
    never a crash: the ledger row shows the hole instead of hiding it."""
    p, a = _num(predicted), _num(actual)
    if p is None or a is None or p <= 0 or a <= 0:
        return None
    return round(max(p / a, a / p), 4)


def validate_predictions(records: list[dict[str, Any]],
                         window: int = DEFAULT_WINDOW) -> dict[str, Any]:
    """Leave-one-out replay: predict every measured record from the rest
    and report the error-factor distribution (the scheduler's accuracy
    contract)."""
    rows: list[dict[str, Any]] = []
    for record in records:
        measured = _measured(record)
        fingerprint = record.get("fingerprint")
        if measured is None or not fingerprint:
            continue
        features = _cost_features(record)
        profile = ({"flops_per_round": features[0],
                    "bytes_per_round": features[1]}
                   if features is not None else None)
        prediction = predict_device_time(
            records, fingerprint, profile, window,
            exclude_id=record.get("record_id"))
        if prediction is None:
            # peerless AND profile-less: honestly unpredictable — counted,
            # never silently dropped
            rows.append({"record_id": record.get("record_id"),
                         "measured_s": measured, "predicted_s": None,
                         "method": "unpredictable"})
            continue
        predicted, info = prediction
        factor = max(predicted / measured, measured / predicted)
        rows.append({"record_id": record.get("record_id"),
                     "measured_s": round(measured, 6),
                     "predicted_s": round(predicted, 6),
                     "error_factor": round(factor, 4),
                     "method": info["method"]})
    factors = sorted(r["error_factor"] for r in rows
                     if r.get("error_factor") is not None)

    def quantile(q: float) -> float | None:
        if not factors:
            return None
        rank = min(int(q * (len(factors) - 1) + 0.5), len(factors) - 1)
        return factors[rank]

    by_method: dict[str, int] = {}
    for row in rows:
        by_method[row["method"]] = by_method.get(row["method"], 0) + 1
    return {
        "records": len(rows),
        "predicted": len(factors),
        "unpredictable": by_method.get("unpredictable", 0),
        "by_method": by_method,
        "median_error_factor": (round(statistics.median(factors), 4)
                                if factors else None),
        "p90_error_factor": (round(quantile(0.9), 4) if factors else None),
        "worst_error_factor": (round(factors[-1], 4) if factors else None),
        "rows": rows,
    }
