"""``metrics --programs``: the cost observatory's offline report.

Turns a run's schema-v9 ``program_profile`` events back into the
per-program table (flops, bytes accessed, peak scheduled memory,
rounds/dispatch) plus the per-round roofline summary — achieved FLOP/s
and utilization when the run also carries enough ``round``/``chunk``
events to estimate per-round device seconds.

**Multi-process dedup** (the numerics broadcast-dedup discipline,
:func:`attackfl_tpu.telemetry.numerics.numerics_summary`): under a DCN
mesh every process compiles — and therefore profiles — the SAME program,
so a merged event stream carries one profile per host.  Profiles are
deduplicated on (run_id, program, fingerprint): a DCN run reports one
profile per program, not one per host.

Jax-free, like every reader in :mod:`attackfl_tpu.telemetry`.
"""

from __future__ import annotations

from typing import Any

_PROFILE_FIELDS = ("flops", "transcendentals", "bytes_accessed",
                   "rounds_per_dispatch", "cells", "memory")


def profiles_from_events(events: list[dict[str, Any]]
                         ) -> dict[str, dict[str, Any]]:
    """``program_profile`` events -> {program: profile}, deduplicated per
    (run_id, program, fingerprint) — first record wins, so a merged
    multi-process stream yields one profile per program."""
    seen: set[tuple] = set()
    programs: dict[str, dict[str, Any]] = {}
    for event in events:
        if event.get("kind") != "program_profile":
            continue
        name = event.get("program")
        if not isinstance(name, str):
            continue
        key = (event.get("run_id"), name, event.get("fingerprint"))
        if key in seen:
            continue
        seen.add(key)
        profile = {field: event[field] for field in _PROFILE_FIELDS
                   if field in event}
        profile["fingerprint"] = event.get("fingerprint")
        if isinstance(event.get("device_kind"), str):
            profile["device_kind"] = event["device_kind"]
        programs.setdefault(name, profile)
    return programs


def programs_summary(events: list[dict[str, Any]]) -> dict[str, Any] | None:
    """One run's (or one merged stream's) program-cost summary, or None
    when it carries no ``program_profile`` events (a pre-v9 artifact or a
    costmodel-off run)."""
    from attackfl_tpu.costmodel.roofline import utilization_summary
    from attackfl_tpu.telemetry.summary import summarize

    programs = profiles_from_events(events)
    if not programs:
        return None
    device_kind = next((p["device_kind"] for p in programs.values()
                        if p.get("device_kind")), "")
    summary = summarize(events)
    # seconds_per_round_steady is WALL cadence, not pure device time — an
    # upper bound on device seconds, so the achieved rates it yields are
    # lower bounds.  The ledger record (derive_record) uses the mined
    # round_device_time instead; this offline report says which it used.
    seconds = (summary.get("rates") or {}).get("seconds_per_round_steady")
    utilization = utilization_summary(programs, seconds, device_kind)
    if utilization is not None and seconds is not None:
        utilization["denominator"] = "seconds_per_round_steady"
    return {
        "programs": programs,
        "device_kind": device_kind,
        "utilization": utilization,
        "rounds": summary.get("rounds_attempted"),
    }


def _fmt_bytes(value: Any) -> str:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return "-"
    size = value + 0.0
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if size < 1024 or unit == "TB":
            return f"{size:.1f}{unit}" if unit != "B" else f"{int(size)}B"
        size /= 1024
    return "-"  # pragma: no cover — loop always returns


def _fmt_count(value: Any) -> str:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return "-"
    size = value + 0.0
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(size) < 1000 or unit == "P":
            return f"{size:.4g}{unit}"
        size /= 1000
    return "-"  # pragma: no cover


def format_programs(summary: dict[str, Any],
                    run_id: str | None = None) -> str:
    lines = [f"program profiles — run {run_id or '<merged>'}"
             + (f" [{summary['device_kind']}]"
                if summary.get("device_kind") else "")]
    lines.append(f"{'program':<28}{'flops':>10}{'bytes':>10}"
                 f"{'peak mem':>10}{'r/disp':>8}")
    for name in sorted(summary.get("programs") or {}):
        profile = summary["programs"][name]
        memory = profile.get("memory") or {}
        lines.append(
            f"{name[:27]:<28}"
            f"{_fmt_count(profile.get('flops')):>10}"
            f"{_fmt_bytes(profile.get('bytes_accessed')):>10}"
            f"{_fmt_bytes(memory.get('peak')):>10}"
            f"{profile.get('rounds_per_dispatch', 1):>8}")
    utilization = summary.get("utilization")
    if utilization:
        parts = [f"flops/round={_fmt_count(utilization.get('flops_per_round'))}",
                 f"bytes/round={_fmt_bytes(utilization.get('bytes_per_round'))}"]
        if utilization.get("achieved_flops_per_sec") is not None:
            parts.append("achieved="
                         + _fmt_count(utilization["achieved_flops_per_sec"])
                         + "FLOP/s")
        if utilization.get("utilization_flops") is not None:
            parts.append(
                f"roofline={100 * utilization['utilization_flops']:.2f}% "
                f"of {_fmt_count(utilization.get('peak_flops_per_sec'))}"
                "FLOP/s peak")
        elif utilization.get("achieved_flops_per_sec") is not None:
            parts.append("(no peak spec for "
                         f"{summary.get('device_kind') or 'this device'}"
                         " — achieved-only)")
        if utilization.get("denominator"):
            parts.append(f"[per-round s = {utilization['denominator']}]")
        lines.append("per-round: " + " ".join(parts))
    return "\n".join(lines)
