"""Guarded XLA compiled-program introspection — the ONE shared guard.

``compiled.cost_analysis()`` and ``compiled.memory_analysis()`` both
drift across JAX/backend versions (ADVICE.md finding 3: return None,
raise, or change shape — cost_analysis returns a list of dicts on some
backends and a bare dict on others).  Every caller in the tree goes
through this module so version drift degrades to a PARTIAL profile
instead of killing the run: a raising ``cost_analysis`` still yields the
memory half, and vice versa (regression-tested in
tests/test_costmodel.py).

This is the factored-out successor of the guarded ``memory_analysis``
helper that lived in ``telemetry/xla.py`` (and was duplicated in spirit
by ``scripts/config5_footprint.py``); ``telemetry.xla.
memory_analysis_bytes`` is now a shim over :func:`guarded_memory_analysis`.

Deliberately jax-free at import time: it only touches the ``compiled``
object it is handed, so the jax-free reporting/estimation halves of the
costmodel can import the module without dragging a backend in.
"""

from __future__ import annotations

from typing import Any

# CompiledMemoryStats attributes -> profile keys (device-side sizes; the
# host_* mirror attributes exist on newer jaxlibs but are zero for the
# programs we compile and are deliberately not recorded).
_BYTE_ATTRS = (
    ("argument", "argument_size_in_bytes"),
    ("output", "output_size_in_bytes"),
    ("temp", "temp_size_in_bytes"),
    ("alias", "alias_size_in_bytes"),
    ("generated_code", "generated_code_size_in_bytes"),
)

# cost_analysis keys -> profile keys.  Per-operand entries like
# "bytes accessed0{}" are operand detail, not program totals — skipped.
_COST_KEYS = (
    ("flops", "flops"),
    ("transcendentals", "transcendentals"),
    ("bytes accessed", "bytes_accessed"),
)


def _number(value: Any) -> int | None:
    """Plain non-negative int out of an XLA stat (never ``float(...)`` —
    these are host analysis values, but the host-sync lint covers this
    package with no allowlist, so stay trivially clean)."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    if value != value or value < 0:  # NaN / sentinel negatives
        return None
    return int(value)


def guarded_cost_analysis(compiled: Any) -> dict[str, int] | None:
    """``{flops, transcendentals, bytes_accessed}`` (whichever keys the
    backend reports) from ``compiled.cost_analysis()``, or None.  Never
    raises; handles both the list-of-dicts and bare-dict return shapes.
    """
    try:
        analysis = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — unimplemented on some backends
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    out: dict[str, int] = {}
    for key, name in _COST_KEYS:
        value = _number(analysis.get(key))
        if value is not None:
            out[name] = value
    return out or None


def guarded_memory_analysis(compiled: Any) -> dict[str, int] | None:
    """Byte sizes from ``compiled.memory_analysis()`` plus the derived
    ``peak`` (argument + output + temp + alias: the scheduler-visible
    resident upper bound XLA planned for one dispatch), or None when the
    backend provides nothing.  Never raises."""
    try:
        analysis = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — unimplemented on some backends
        return None
    if analysis is None:
        return None
    out: dict[str, int] = {}
    for key, attr in _BYTE_ATTRS:
        value = _number(getattr(analysis, attr, None))
        if value is not None:
            out[key] = value
    if out:
        out["peak"] = sum(out.get(k, 0)
                          for k in ("argument", "output", "temp", "alias"))
    return out or None


def compiled_profile(compiled: Any) -> dict[str, Any] | None:
    """One program's static cost/memory profile: the union of both
    guarded analyses.  A raising/absent half degrades to a PARTIAL
    profile; None only when neither analysis yields anything."""
    profile: dict[str, Any] = {}
    cost = guarded_cost_analysis(compiled)
    if cost:
        profile.update(cost)
    memory = guarded_memory_analysis(compiled)
    if memory:
        profile["memory"] = memory
    return profile or None
