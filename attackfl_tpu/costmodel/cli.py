"""``attackfl-tpu cost estimate|validate``: the predictive front door.

``estimate`` prices a config — or, with ``--matrix``, a whole
(attack × defense × seed) grid — WITHOUT running it: fingerprint-peer
ledger records first (their median measured ``round_device_time``), a
flops/bytes regression over non-peer records when the config is new.
The no-peer path needs the candidate's static profile, which means
AOT-compiling its round programs (compile ≠ run: no round executes, no
state advances, no device value is materialized); ``--no-compile``
suppresses that and reports the config as unpredictable instead.

``validate`` is the accuracy contract: leave-one-out replay of the
predictor over a ledger corpus, exit 1 when the median symmetric error
factor exceeds ``--max-median-factor`` (default 2× — the bound the
multi-tenant scheduler's bin-packing is allowed to rely on), exit 2 when
the corpus has nothing measurable.  Jax-free.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import Any

from attackfl_tpu.costmodel.estimate import (
    DEFAULT_MAX_MEDIAN_FACTOR, predict_run, validate_predictions,
)


def _load_records(directory: str | None) -> tuple[list[dict[str, Any]], str]:
    from attackfl_tpu.ledger.store import LedgerStore, resolve_ledger_dir

    resolved = directory or resolve_ledger_dir()
    store = LedgerStore(resolved)
    records, _ = store.load()
    return records, resolved


def profile_config(cfg) -> dict[str, Any] | None:
    """AOT-compile the config's synchronous round programs (telemetry
    off, nothing runs) and fold them into a per-round cost profile — the
    regression fallback's input.  None when the backend reports no cost
    stats."""
    from attackfl_tpu.costmodel.capture import compiled_profile
    from attackfl_tpu.costmodel.roofline import per_round_cost
    from attackfl_tpu.training.engine import Simulator

    quiet = cfg.replace(
        telemetry=dataclasses.replace(cfg.telemetry, enabled=False,
                                      monitor=False))
    sim = Simulator(quiet)
    try:
        programs: dict[str, dict[str, Any]] = {}
        for name, fn, args in sim.sync_profile_programs():
            try:
                profile = compiled_profile(fn.lower(*args).compile())
            except Exception:  # noqa: BLE001 — profiling is best-effort
                profile = None
            if profile:
                profile["rounds_per_dispatch"] = 1
                programs[name] = profile
        return per_round_cost(programs)
    finally:
        sim.close()


def _estimate_one(records, fingerprint: str, rounds: int,
                  cfg, compile_ok: bool) -> dict[str, Any]:
    prediction = predict_run(records, fingerprint, rounds)
    if prediction is None and compile_ok and cfg is not None:
        profile = profile_config(cfg)
        if profile is not None:
            prediction = predict_run(records, fingerprint, rounds,
                                     profile=profile)
            if prediction is not None:
                prediction["profile"] = {
                    k: profile.get(k)
                    for k in ("flops_per_round", "bytes_per_round")}
    if prediction is None:
        return {"fingerprint": fingerprint, "rounds": rounds,
                "method": "unpredictable"}
    return {"fingerprint": fingerprint, **prediction}


def estimate_main(args) -> int:
    import yaml

    from attackfl_tpu.config import load_config
    from attackfl_tpu.utils.fingerprint import config_fingerprint

    cfg = load_config(args.config)
    if args.rounds is not None:
        cfg = cfg.replace(num_round=args.rounds)
    records, directory = _load_records(args.dir)
    out: dict[str, Any] = {"ledger": directory,
                           "ledger_records": len(records)}

    if args.matrix:
        from attackfl_tpu.matrix.grid import (
            cell_config, expand_cells, grid_from_dict,
        )

        with open(args.config) as fh:
            raw = yaml.safe_load(fh) or {}
        grid = grid_from_dict(dict(raw.get("matrix") or {}))
        cells = expand_cells(grid)
        per_cell = []
        total = 0.0
        predictable = 0
        for cell in cells:
            ccfg = cell_config(cfg, cell, rounds=grid.rounds)
            estimate = _estimate_one(
                records, config_fingerprint(ccfg), grid.rounds,
                # one compile covers the grid: cells share the round
                # program shape, so the FIRST no-peer cell's profile
                # prices its siblings too (flops differ only by the
                # defense branch — second-order)
                ccfg if predictable == 0 else None,
                not args.no_compile)
            estimate["cell"] = cell.key
            per_cell.append(estimate)
            wall = estimate.get("predicted_wall_seconds")
            if wall is not None:
                total += wall
                predictable += 1
        out.update({
            "grid": grid.describe(),
            "cells": per_cell,
            "predictable_cells": predictable,
            # serial bound: the batched sweep executor shares compiles
            # and vmaps the cell axis, so the real sweep lands at or
            # under this (BENCH_MATRIX: 1.52x cold)
            "predicted_sweep_wall_seconds_serial_bound": round(total, 3),
        })
    else:
        estimate = _estimate_one(records, config_fingerprint(cfg),
                                 cfg.num_round, cfg, not args.no_compile)
        out.update(estimate)

    if args.json:
        print(json.dumps(out, indent=1))
    else:
        print(format_estimate(out))
    return 0 if out.get("method") != "unpredictable" else 2


def format_estimate(out: dict[str, Any]) -> str:
    lines = [f"cost estimate — ledger {out['ledger']} "
             f"({out['ledger_records']} record(s))"]
    if "cells" in out:
        lines.append(
            f"matrix grid: {out['grid']['n_cells']} cells x "
            f"{out['grid']['rounds']} rounds")
        for cell in out["cells"]:
            wall = cell.get("predicted_wall_seconds")
            lines.append(
                f"  {cell['cell']:<32} "
                + (f"{wall:>9.2f}s  [{cell.get('method')}]"
                   if wall is not None else "unpredictable "
                   "(no peer, no profile)"))
        lines.append(
            f"predicted sweep wall (serial bound): "
            f"{out['predicted_sweep_wall_seconds_serial_bound']}s over "
            f"{out['predictable_cells']} predictable cell(s)")
        return "\n".join(lines)
    if out.get("method") == "unpredictable":
        lines.append("unpredictable: no fingerprint peer in the ledger and "
                     "no static profile to regress on (run once with "
                     "telemetry.ledger on, or drop --no-compile)")
        return "\n".join(lines)
    lines.append(
        f"method: {out['method']}"
        + (f" over {out['peers']} peer record(s)" if "peers" in out else "")
        + (f" fit on {out['fit_records']} record(s)"
           if "fit_records" in out else ""))
    lines.append(
        f"per-round: device={out['round_device_time']}s"
        + (f" host={out['host_resolution_latency']}s"
           if out.get("host_resolution_latency") is not None
           else " (device-only: no host-latency peer)"))
    lines.append(f"predicted wall for {out['rounds']} round(s): "
                 f"{out['predicted_wall_seconds']}s")
    return "\n".join(lines)


def validate_main(args) -> int:
    records, directory = _load_records(args.dir)
    report = validate_predictions(records, window=args.window)
    report["ledger"] = directory
    ok = (report["predicted"] > 0
          and report["median_error_factor"] is not None
          and report["median_error_factor"] <= args.max_median_factor)
    if args.json:
        print(json.dumps({**report, "ok": ok,
                          "max_median_factor": args.max_median_factor},
                         indent=1))
    else:
        lines = [f"cost validate — ledger {directory}: "
                 f"{report['predicted']}/{report['records']} record(s) "
                 f"predicted leave-one-out "
                 f"({report['unpredictable']} unpredictable)"]
        if report["median_error_factor"] is not None:
            lines.append(
                f"error factor: median={report['median_error_factor']}x "
                f"p90={report['p90_error_factor']}x "
                f"worst={report['worst_error_factor']}x "
                f"(bound {args.max_median_factor}x: "
                + ("PASS" if ok else "FAIL") + ")")
        by_method = ", ".join(f"{k}={v}" for k, v in
                              sorted(report["by_method"].items()))
        if by_method:
            lines.append(f"paths: {by_method}")
        for row in report["rows"]:
            predicted = row.get("predicted_s")
            lines.append(
                f"  {str(row.get('record_id'))[:28]:<29}"
                f"measured={row['measured_s']:<10} "
                + (f"predicted={predicted:<10} "
                   f"x{row['error_factor']} [{row['method']}]"
                   if predicted is not None else "[unpredictable]"))
        print("\n".join(lines))
    if report["predicted"] == 0:
        print("nothing to validate: no record carries a measured "
              "round_device_time", file=sys.stderr)
        return 2
    return 0 if ok else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="attackfl-tpu cost",
        description="Predictive cost model over the cross-run ledger: "
                    "estimate a config or matrix grid without running "
                    "it; validate the predictor against a ledger corpus.")
    sub = parser.add_subparsers(dest="command", required=True)

    p_est = sub.add_parser("estimate",
                           help="predict per-round device time and wall "
                                "time for a config (or --matrix grid)")
    p_est.add_argument("--config", type=str, default="config.yaml")
    p_est.add_argument("--rounds", type=int, default=None,
                       help="override num-round for the wall prediction")
    p_est.add_argument("--matrix", action="store_true",
                       help="price the config's matrix: grid per cell")
    p_est.add_argument("--dir", type=str, default=None,
                       help="ledger directory (default: "
                            "$ATTACKFL_LEDGER_DIR or ./ledger)")
    p_est.add_argument("--no-compile", action="store_true",
                       help="never AOT-compile for a profile; peerless "
                            "configs report as unpredictable")
    p_est.add_argument("--json", action="store_true")

    p_val = sub.add_parser("validate",
                           help="leave-one-out accuracy replay over a "
                                "ledger corpus (exit 1 past the bound)")
    p_val.add_argument("--dir", type=str, default=None)
    p_val.add_argument("--window", type=int, default=5,
                       help="peer-median window (records)")
    p_val.add_argument("--max-median-factor", type=float,
                       default=DEFAULT_MAX_MEDIAN_FACTOR,
                       help="median error-factor bound (default 2.0)")
    p_val.add_argument("--json", action="store_true")

    args = parser.parse_args(argv)
    if args.command == "estimate":
        return estimate_main(args)
    if args.command == "validate":
        return validate_main(args)
    raise AssertionError(f"unhandled command {args.command!r}")


if __name__ == "__main__":
    raise SystemExit(main())
