"""Peak-spec table: what the silicon could do, per device kind.

Per-chip dense peak FLOP/s (bf16 — the matrix-unit rate every published
TPU spec quotes) and HBM bandwidth for the device kinds this project can
land on, keyed by the substrings ``jax.devices()[0].device_kind`` uses.
The roofline layer divides achieved FLOP/s and bytes/s by these to get
utilization fractions.

**Extending the table for a new device type**: add one entry mapping a
lowercase substring of the new kind string to its per-chip
``flops_per_sec`` / ``bytes_per_sec`` (from the vendor spec sheet), and
it is picked up everywhere — the monitor gauges, ``metrics --programs``,
``ledger`` utilization columns and the regress gate.  Kinds with no
entry (CPU above all) report ACHIEVED-only: a shared, frequency-scaled
host has no honest peak, and a made-up one would turn the utilization
gate into noise.

Values are marketing-sheet peaks, deliberately so: utilization numbers
are comparable across papers exactly because everyone divides by the
same published figure.
"""

from __future__ import annotations

from typing import Any

# lowercase device_kind substring -> per-chip peak spec.  Ordered
# longest-match-first at lookup so "tpu v5p" never matches a bare "v5".
PEAK_SPECS: dict[str, dict[str, float]] = {
    # kind strings observed from jax: "TPU v2", "TPU v3", "TPU v4",
    # "TPU v4i", "TPU v5 lite" (v5e), "TPU v5p", "TPU v6 lite" (v6e)
    "tpu v2": {"flops_per_sec": 45e12, "bytes_per_sec": 700e9},
    "tpu v3": {"flops_per_sec": 123e12, "bytes_per_sec": 900e9},
    "tpu v4i": {"flops_per_sec": 138e12, "bytes_per_sec": 614e9},
    "tpu v4": {"flops_per_sec": 275e12, "bytes_per_sec": 1228e9},
    "tpu v5 lite": {"flops_per_sec": 197e12, "bytes_per_sec": 819e9},
    "tpu v5e": {"flops_per_sec": 197e12, "bytes_per_sec": 819e9},
    "tpu v5p": {"flops_per_sec": 459e12, "bytes_per_sec": 2765e9},
    "tpu v6 lite": {"flops_per_sec": 918e12, "bytes_per_sec": 1640e9},
    "tpu v6e": {"flops_per_sec": 918e12, "bytes_per_sec": 1640e9},
}


def peak_for(device_kind: Any) -> dict[str, float] | None:
    """The peak spec for a ``device_kind`` string, or None for kinds with
    no honest peak (CPU, unknown accelerators) — callers then report
    achieved-only."""
    if not isinstance(device_kind, str) or not device_kind:
        return None
    kind = device_kind.lower()
    for key in sorted(PEAK_SPECS, key=len, reverse=True):
        if key in kind:
            return dict(PEAK_SPECS[key])
    return None
