"""Static profiles × measured device time -> achieved rates + roofline.

The capture layer records what XLA *scheduled* per dispatch (flops,
bytes, peak memory); the ledger records what the device *measured*
(``round_device_time``).  This module joins them:

* :func:`per_round_cost` normalizes a run's program profiles to
  per-round totals.  A chunked scan program (``fused_scan[16]``,
  ``matrix_chunk[8]``) IS the whole round×chunk, so the largest chunk's
  profile divided by its length wins over summing (which would double
  count the length-1 retry-tail program of the same body); a per-round
  program set (sync ``round_step`` + ``aggregate``, the pipelined
  ``pipeline_step``) sums.
* :func:`utilization_summary` divides the per-round totals by the
  measured per-round device seconds into achieved FLOP/s and bytes/s,
  and — when :mod:`~attackfl_tpu.costmodel.peaks` knows the device kind
  — into roofline utilization fractions.  Unknown kinds (CPU) report
  achieved-only by design.

Jax-free: pure arithmetic over JSON-shaped dicts, importable by the
ledger CLI and the monitor alike.
"""

from __future__ import annotations

from typing import Any

from attackfl_tpu.costmodel.peaks import peak_for


def _value(profile: dict[str, Any], key: str) -> int | None:
    value = profile.get(key)
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return None
    return int(value)


def _rounds(profile: dict[str, Any]) -> int:
    value = profile.get("rounds_per_dispatch")
    if isinstance(value, bool) or not isinstance(value, int) or value < 1:
        return 1
    return value


def per_round_cost(programs: dict[str, dict[str, Any]]
                   ) -> dict[str, Any] | None:
    """Per-round flops / bytes-accessed / transcendentals totals from a
    run's program profiles (see module doc for the chunk-vs-sum rule).
    ``basis`` names the programs the figure came from.  None when no
    profile carries a usable flops or bytes figure."""
    usable = {name: p for name, p in (programs or {}).items()
              if isinstance(p, dict)
              and (_value(p, "flops") is not None
                   or _value(p, "bytes_accessed") is not None)}
    if not usable:
        return None
    chunked = {name: p for name, p in usable.items() if _rounds(p) > 1}
    if chunked:
        name = max(chunked, key=lambda n: _rounds(chunked[n]))
        profile, rounds = chunked[name], _rounds(chunked[name])
        basis = [name]
        totals = {key: _value(profile, key) for key in
                  ("flops", "bytes_accessed", "transcendentals")}
        out = {key: (value / rounds if value is not None else None)
               for key, value in totals.items()}
    else:
        basis = sorted(usable)
        out = {}
        for key in ("flops", "bytes_accessed", "transcendentals"):
            values = [_value(p, key) for p in usable.values()]
            values = [v for v in values if v is not None]
            out[key] = sum(values) if values else None
    return {
        "flops_per_round": out.get("flops"),
        "bytes_per_round": out.get("bytes_accessed"),
        "transcendentals_per_round": out.get("transcendentals"),
        "basis": basis,
    }


def utilization_summary(programs: dict[str, dict[str, Any]],
                        round_device_time: Any,
                        device_kind: Any,
                        mesh_devices: Any = None) -> dict[str, Any] | None:
    """Achieved FLOP/s + bytes/s (and, with a known peak, utilization
    fractions) for one run.  ``round_device_time`` is the ledger's
    measured device seconds per round; None/0 yields the static
    per-round totals with no rates (a crashed run still reports what it
    compiled).

    ``mesh_devices`` (ISSUE 12): on an N-device slice the per-round
    totals are the WHOLE program's work, so the roofline denominator is
    N single-chip peaks — utilization is ``achieved / (N · peak)``.
    Without it a perfectly-scaled 8-chip run would report 8x a chip's
    ceiling.  ``achieved_*_per_sec`` stays the whole-slice rate (the
    scaling-curve quantity); the fraction is what normalizes per chip.
    None/0/1 keeps the single-device math byte-for-byte."""
    cost = per_round_cost(programs)
    if cost is None:
        return None
    out: dict[str, Any] = dict(cost)
    out["device_kind"] = device_kind if isinstance(device_kind, str) else ""
    devices = mesh_devices
    if isinstance(devices, bool) or not isinstance(devices, int) \
            or devices < 2:
        devices = 1
    if devices > 1:
        out["mesh_devices"] = devices
    seconds = round_device_time
    if isinstance(seconds, bool) or not isinstance(seconds, (int, float)) \
            or seconds <= 0:
        seconds = None
    peak = peak_for(device_kind)
    if peak is not None:
        out["peak_flops_per_sec"] = peak["flops_per_sec"]
        out["peak_bytes_per_sec"] = peak["bytes_per_sec"]
    if seconds is not None:
        flops = cost.get("flops_per_round")
        if flops is not None:
            achieved = flops / seconds
            out["achieved_flops_per_sec"] = round(achieved, 3)
            if peak is not None and peak["flops_per_sec"] > 0:
                # 12 decimals: toy CPU programs land at ~1e-6 of a TPU
                # peak — 6 decimals would round a real fraction to zero
                out["utilization_flops"] = round(
                    achieved / (devices * peak["flops_per_sec"]), 12)
        size = cost.get("bytes_per_round")
        if size is not None:
            achieved = size / seconds
            out["achieved_bytes_per_sec"] = round(achieved, 3)
            if peak is not None and peak["bytes_per_sec"] > 0:
                out["utilization_bytes"] = round(
                    achieved / (devices * peak["bytes_per_sec"]), 12)
    return out
