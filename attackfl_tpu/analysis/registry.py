"""Rule registry + audit context: the lint-framework half of the graph
auditor (ISSUE 5 tentpole).

A *rule* is a named, documented pass producing :class:`~.findings.Finding`
objects.  Rules register themselves via :func:`register` at import time
(importing :mod:`attackfl_tpu.analysis.ast_rules` /
:mod:`attackfl_tpu.analysis.artifacts` populates the registry); the
``attackfl-tpu audit`` CLI and tier-1 run them through :func:`run_rules`.

The :class:`AuditContext` carries what every rule needs — the repo root,
the package root, and a parse cache so five AST rules over the same module
cost one ``ast.parse``.  Per-rule allowlists live with the rule that owns
them (e.g. the host-sync audited-function allowlist in ``ast_rules``) —
the framework only insists that allowlisting is *visible*: every rule
declares a ``fix_hint`` that says how to allowlist and why a comment is
required.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable

from attackfl_tpu.analysis.findings import Finding, sort_findings

REPO_ROOT = Path(__file__).resolve().parent.parent.parent
PACKAGE_ROOT = Path(__file__).resolve().parent.parent


@dataclass
class AuditContext:
    """Shared state for one audit run: roots + a per-file parse cache."""

    root: Path = REPO_ROOT
    package: Path = PACKAGE_ROOT
    _trees: dict[Path, ast.Module] = field(default_factory=dict)

    def tree(self, path: Path) -> ast.Module:
        path = Path(path).resolve()
        cached = self._trees.get(path)
        if cached is None:
            cached = ast.parse(path.read_text(), filename=str(path))
            self._trees[path] = cached
        return cached

    def package_sources(self) -> list[Path]:
        """Every package module, analysis/ included (the auditor audits
        itself), stable-sorted for deterministic reports."""
        return sorted(self.package.rglob("*.py"))


@dataclass(frozen=True)
class Rule:
    """One registered pass: id, docs, and the runner."""

    rule_id: str
    description: str
    fix_hint: str
    runner: Callable[[AuditContext], list[Finding]]

    def run(self, ctx: AuditContext) -> list[Finding]:
        return self.runner(ctx)


RULES: dict[str, Rule] = {}
# Passes that run OUTSIDE run_rules (the jaxpr/HLO program auditor, the
# grad/dataflow transform-safety passes — they need jax and trace real
# programs, so the CLI drives them behind --skip-programs/--skip-grad
# gates) still declare their rule ids here so describe_rules() and the
# report's rule table document every id a Finding can carry.
INFO_RULES: dict[str, Rule] = {}


def register(rule_id: str, description: str, fix_hint: str):
    """Decorator: add a ``Callable[[AuditContext], list[Finding]]`` to the
    registry under ``rule_id``.  Ids are unique by construction."""
    def deco(fn: Callable[[AuditContext], list[Finding]]):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        RULES[rule_id] = Rule(rule_id, description, fix_hint, fn)
        return fn
    return deco


def register_info(rule_id: str, description: str, fix_hint: str) -> None:
    """Document a rule id whose pass runs outside :func:`run_rules`
    (program/grad auditors).  Idempotent re-registration with identical
    docs is allowed (modules re-import); a conflicting id is an error."""
    existing = INFO_RULES.get(rule_id)
    if existing is not None:
        if (existing.description, existing.fix_hint) != (description,
                                                         fix_hint):
            raise ValueError(f"conflicting info rule id {rule_id!r}")
        return
    if rule_id in RULES:
        raise ValueError(f"duplicate rule id {rule_id!r}")
    INFO_RULES[rule_id] = Rule(rule_id, description, fix_hint,
                               lambda ctx: [])


def load_rules() -> dict[str, Rule]:
    """Import every rule module (idempotent) and return the registry.
    The program/grad/dataflow modules only *document* their ids here
    (register_info) — their passes import jax lazily, so this stays
    cheap enough for --skip-programs runs."""
    from attackfl_tpu.analysis import (  # noqa: F401
        artifacts, ast_rules, dataflow, grad_audit, program_audit)

    return RULES


def run_rules(ctx: AuditContext | None = None,
              rule_ids: Iterable[str] | None = None) -> list[Finding]:
    """Run the selected rules (default: all) and return sorted findings."""
    rules = load_rules()
    ctx = ctx or AuditContext()
    ids = list(rule_ids) if rule_ids is not None else sorted(rules)
    unknown = [i for i in ids if i not in rules]
    if unknown:
        raise KeyError(f"unknown rule id(s) {unknown}; known: {sorted(rules)}")
    findings: list[Finding] = []
    for rule_id in ids:
        findings.extend(rules[rule_id].run(ctx))
    return sort_findings(findings)


def describe_rules() -> list[dict[str, str]]:
    """Machine-readable rule table for the report / README: the AST/
    artifact rules run_rules drives plus the documented program/grad
    pass ids (:func:`register_info`)."""
    load_rules()
    merged = dict(RULES)
    merged.update(INFO_RULES)
    return [{"id": r.rule_id, "description": r.description,
             "fix_hint": r.fix_hint}
            for _, r in sorted(merged.items())]
