"""Jaxpr/HLO program auditor: machine-checked invariants of the compiled
round programs themselves.

The AST rules bound what *host* code may do; this module audits what the
*programs* actually contain.  For every round program the engine exposes
through its audit hook (:meth:`Simulator.audit_programs` — sync
``round_step``/``aggregate`` (or ``hyper_update``), the fused scan chunk,
and the pipelined single-round step), it abstractly traces the raw
callable (``jax.make_jaxpr``) and lowers the jitted one
(``jax.jit(...).lower(...)``) — nothing is executed — and asserts:

* **sync-freedom** — no callback primitive (``pure_callback`` /
  ``io_callback`` / ``debug_callback`` / ...) and no ``infeed``/``outfeed``
  anywhere in the program, sub-jaxprs included.  A callback inside a
  sync-free executor would fence the dispatch queue every round — exactly
  the class of regression the pipelined executor (BENCH_PIPELINE.json
  1.24x) cannot absorb.
* **donation** — for every argument the engine *claims* to donate
  (:meth:`Simulator.donation_spec`), the aliasing XLA actually established
  matches expectation: each donated input buffer with a shape/dtype-
  matching output is aliased (``tf.aliasing_output`` in the lowered
  StableHLO).  Donated-but-unaliasable buffers (the (C, P) stacked deltas
  feeding a (P,) aggregation) are *early-free* hints and legitimately
  alias nothing — the expectation is computed by multiset shape matching,
  so that case audits as 0 == 0 rather than being waved through.
* **dtype discipline** — no float64/complex128 value anywhere in the
  program (an accidental x64 promotion in metrics/aggregation math would
  double memory traffic and break cross-run comparability).
* **transfer budget** — the programs contain zero device->host transfer
  primitives, so every per-round transfer must originate in host code,
  which the ``host-sync`` rule bounds to its audited allowlist.  The
  budget (the resolved allowlist) is reported alongside the program
  results so the two halves are reviewed together.

Run on a CPU-sized representative config (:func:`attackfl_tpu.config.
audit_config`) — the invariants are properties of program *structure*,
identical on CPU and TPU.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Any

from attackfl_tpu.analysis.findings import Finding
from attackfl_tpu.analysis.registry import register_info

# Primitives that fence or transfer; "callback" as a substring catches the
# whole jax callback family (pure_callback, io_callback, debug_callback)
# plus whatever future variant keeps the naming convention.
FORBIDDEN_PRIMITIVES = frozenset({"infeed", "outfeed"})
FORBIDDEN_SUBSTRINGS = ("callback",)

# Cross-device collective primitives (ISSUE 12): what a shard_map'd round
# program may legitimately contain.  The per-defense expectation table
# below is asserted against the traced program — a defense growing an
# unexpected collective (or losing its required one) fails the audit.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "all_gather", "all_to_all", "ppermute", "pmin", "pmax",
    "reduce_scatter", "pbroadcast", "psum_invariant",
})

# defense mode -> the exact collective sets its sharded aggregation
# chain may use, per transform (parallel/shard.shard_aggregator's design
# table): the "forward" column is the round program as dispatched —
# partial-sum defenses reduce with psum only; order-statistic/pairwise/
# quantile/anchor defenses reassemble the full client matrix with
# all_gather and nothing else.  The "grad" column (ISSUE 20) is the
# grad-transformed program: AD transposes each collective into its dual
# (psum is self-dual; all_gather's cotangent is a reduce_scatter, plus
# the re-forwarded gather and a psum over replicated residuals — see
# parallel/shard.grad_collectives).  Training itself (shard_local_update)
# is collective-free by construction, so these sets describe the WHOLE
# round program under either transform.
_PSUM_FWD = frozenset({"psum"})
_GATHER_FWD = frozenset({"all_gather"})
_PSUM_GRAD = frozenset({"psum"})
_GATHER_GRAD = frozenset({"all_gather", "psum", "reduce_scatter"})
EXPECTED_COLLECTIVES: dict[str, dict[str, frozenset[str]]] = {
    "fedavg": {"forward": _PSUM_FWD, "grad": _PSUM_GRAD},
    "fltracer": {"forward": _PSUM_FWD, "grad": _PSUM_GRAD},
    "gmm": {"forward": _PSUM_FWD, "grad": _PSUM_GRAD},
    "shieldfl": {"forward": _PSUM_FWD, "grad": _PSUM_GRAD},
    "FLTrust": {"forward": _PSUM_FWD, "grad": _PSUM_GRAD},
    "median": {"forward": _GATHER_FWD, "grad": _GATHER_GRAD},
    "trimmed_mean": {"forward": _GATHER_FWD, "grad": _GATHER_GRAD},
    "krum": {"forward": _GATHER_FWD, "grad": _GATHER_GRAD},
    "scionfl": {"forward": _GATHER_FWD, "grad": _GATHER_GRAD},
    "byzantine": {"forward": _GATHER_FWD, "grad": _GATHER_GRAD},
}


def expected_collectives(mode: str, transform: str = "forward"
                         ) -> frozenset[str]:
    """The :data:`EXPECTED_COLLECTIVES` entry for one defense under one
    transform (``"forward"`` or ``"grad"``)."""
    return EXPECTED_COLLECTIVES[mode][transform]

FORBIDDEN_HINT = (
    "host work must live in the engine's audited resolve points (see the "
    "host-sync rule), never inside a jitted round program")
DONATION_AUDIT_HINT = (
    "the donation declared in Simulator.donation_spec() did not produce "
    "the expected input-output aliasing — check that the donated argument "
    "is the program's last consumer and shapes still line up")
F64_HINT = (
    "keep round math in f32/bf16: find the promotion (np.float64 scalar, "
    "Python float in a jnp op under x64) and cast it explicitly")

register_info(
    "program-audit",
    "every jitted round program (sync/fused/pipelined/matrix, sharded "
    "included) is sync-free, f64-free, donation-aliased as declared by "
    "Simulator.donation_spec(), and carries exactly its defense's "
    "expected collective set",
    FORBIDDEN_HINT,
)


def _iter_subjaxprs(value: Any):
    """Yield every Jaxpr reachable from an eqn param value (ClosedJaxpr,
    Jaxpr, or lists of either)."""
    values = value if isinstance(value, (list, tuple)) else [value]
    for v in values:
        if hasattr(v, "eqns"):          # Jaxpr
            yield v
        elif hasattr(v, "jaxpr"):       # ClosedJaxpr
            yield v.jaxpr


def walk_jaxpr(jaxpr) -> Counter:
    """Primitive-name counts over a jaxpr and all sub-jaxprs (scan/cond/
    while bodies, inner pjit calls, custom-derivative rules)."""
    counts: Counter = Counter()
    stack = [jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            counts[eqn.primitive.name] += 1
            for v in eqn.params.values():
                stack.extend(_iter_subjaxprs(v))
    return counts


def forbidden_primitives(counts: Counter) -> list[str]:
    bad = []
    for name in counts:
        if name in FORBIDDEN_PRIMITIVES or any(
                s in name for s in FORBIDDEN_SUBSTRINGS):
            bad.append(name)
    return sorted(bad)


def collective_primitives(counts: Counter) -> list[str]:
    """Cross-device collectives present in the program (sorted).  An
    unsharded program must report none; a sharded one exactly its
    defense's expectation-table entry."""
    return sorted(name for name in counts if name in COLLECTIVE_PRIMITIVES)


def wide_dtype_outputs(jaxpr) -> int:
    """Count of equation outputs with a 64-bit float/complex dtype
    anywhere in the program (0 on a dtype-disciplined program)."""
    import numpy as np

    wide = (np.dtype("float64"), np.dtype("complex128"))
    n = 0
    stack = [jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr]
    while stack:
        j = stack.pop()
        for eqn in j.eqns:
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                dtype = getattr(aval, "dtype", None)
                if dtype is not None and dtype in wide:
                    n += 1
            for v in eqn.params.values():
                stack.extend(_iter_subjaxprs(v))
    return n


def _aval_key(x) -> tuple:
    return (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", "?")))


def expected_alias_count(donated_leaves, output_leaves) -> int:
    """How many donated input buffers SHOULD alias an output: greedy
    multiset matching on (shape, dtype) — the same criterion jax uses when
    deciding which donated buffers are usable."""
    available = Counter(_aval_key(o) for o in output_leaves)
    n = 0
    for leaf in donated_leaves:
        key = _aval_key(leaf)
        if available[key] > 0:
            available[key] -= 1
            n += 1
    return n


@dataclass
class ProgramReport:
    """Audit result for one round program (JSON-ready via ``to_dict``)."""

    name: str
    executor: str
    eqns: int
    distinct_primitives: int
    forbidden: list[str]
    donated_args: tuple[int, ...]
    donated_leaves: int
    expected_aliases: int
    aliased_leaves: int
    f64_outputs: int
    collectives: list[str] = field(default_factory=list)
    expected_collectives: list[str] = field(default_factory=list)
    problems: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.problems

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name, "executor": self.executor, "ok": self.ok,
            "eqns": self.eqns,
            "distinct_primitives": self.distinct_primitives,
            "forbidden_primitives": self.forbidden,
            "donated_args": list(self.donated_args),
            "donated_leaves": self.donated_leaves,
            "expected_aliases": self.expected_aliases,
            "aliased_leaves": self.aliased_leaves,
            "f64_outputs": self.f64_outputs,
            "collectives": list(self.collectives),
            "expected_collectives": list(self.expected_collectives),
            "problems": self.problems,
        }


def audit_program(name: str, executor: str, raw, jit_fn, args: tuple,
                  donate: tuple[int, ...],
                  expected_collectives: frozenset[str] = frozenset(),
                  ) -> ProgramReport:
    """Audit one program: jaxpr invariants from ``raw``, donation aliasing
    from lowering ``jit_fn``.  Pure analysis — nothing executes.

    ``expected_collectives`` is the exact cross-device collective set the
    program may contain: empty (the default) for single-device programs,
    the :data:`EXPECTED_COLLECTIVES` entry for a sharded defense chain.
    Any deviation — an extra collective OR a missing required one — is a
    problem (a lost psum means the sharded aggregate silently went
    device-local)."""
    import jax

    jaxpr = jax.make_jaxpr(raw)(*args)
    counts = walk_jaxpr(jaxpr)
    forbidden = forbidden_primitives(counts)
    collectives = collective_primitives(counts)
    f64 = wide_dtype_outputs(jaxpr)

    donated_leaves = [leaf for i in donate
                      for leaf in jax.tree.leaves(args[i])]
    outputs = jax.tree.leaves(jax.eval_shape(raw, *args))
    expected = expected_alias_count(donated_leaves, outputs)
    # the lowered StableHLO carries one tf.aliasing_output attribute per
    # input buffer jax actually donated AND found an aliasable output for
    lowered = jit_fn.lower(*args)
    aliased = lowered.as_text().count("tf.aliasing_output")
    if aliased != expected and expected > 0:
        # Sharded programs (ISSUE 12): jax defers donation aliasing to
        # COMPILE time when the program carries mesh shardings — the
        # StableHLO has no tf.aliasing_output attributes, yet the
        # compiled module's input_output_alias header holds the full
        # alias map (verified: donation survives shard_map).  Read it
        # from the executable instead; entries look like
        # ``{0}: (0, {}, may-alias)`` on the HloModule header line.
        try:
            header = lowered.compile().as_text().split("\n", 1)[0]
        except Exception:  # noqa: BLE001 — fall back to the lowered count
            header = ""
        if "input_output_alias" in header:
            aliased = header.count("-alias)")

    report = ProgramReport(
        name=name, executor=executor,
        eqns=sum(counts.values()), distinct_primitives=len(counts),
        forbidden=forbidden, donated_args=tuple(donate),
        donated_leaves=len(donated_leaves), expected_aliases=expected,
        aliased_leaves=aliased, f64_outputs=f64,
        collectives=collectives,
        expected_collectives=sorted(expected_collectives),
    )
    if forbidden:
        report.problems.append(
            f"forbidden host-transfer primitive(s) in a sync-free program: "
            f"{', '.join(forbidden)}")
    if set(collectives) != set(expected_collectives):
        report.problems.append(
            f"collective set mismatch: program contains "
            f"[{', '.join(collectives) or 'none'}], expected "
            f"[{', '.join(sorted(expected_collectives)) or 'none'}] "
            "(see EXPECTED_COLLECTIVES / parallel/shard's design table)")
    if aliased != expected:
        report.problems.append(
            f"donation aliasing mismatch: {aliased} aliased buffer(s) in "
            f"the lowered program, expected {expected} (donated leaves: "
            f"{len(donated_leaves)})")
    if f64 > 0:
        report.problems.append(
            f"{f64} float64/complex128 value(s) in the program — "
            "unexpected wide-dtype promotion")
    return report


def audit_simulator(sim) -> list[ProgramReport]:
    """Audit every program the Simulator's audit hook exposes."""
    return [
        audit_program(p["name"], p["executor"], p["raw"], p["jit"],
                      p["args"], p["donate"])
        for p in sim.audit_programs()
    ]


def audit_default_programs(modes: tuple[str, ...] = ("fedavg",)
                           ) -> list[ProgramReport]:
    """Build the representative CPU-sized Simulator(s) and audit their
    programs.  ``modes`` extends coverage (e.g. ``("fedavg", "hyper")``)
    at ~seconds of tracing per mode."""
    from attackfl_tpu.config import audit_config
    from attackfl_tpu.training.engine import Simulator

    reports: list[ProgramReport] = []
    for mode in modes:
        cfg = audit_config(mode=mode)
        sim = Simulator(cfg)
        try:
            for report in audit_simulator(sim):
                report.name = f"{mode}:{report.name}"
                reports.append(report)
        finally:
            sim.close()
    return reports


def audit_sharded_programs(modes: tuple[str, ...] = ("fedavg", "median",
                                                     "FLTrust"),
                           ) -> list[ProgramReport]:
    """Audit the mesh-native (shard_map) executors (ISSUE 12): for each
    defense mode, build a Simulator over a 1-D mesh spanning every
    visible device (threefry keys — the shard_map gate) and audit the
    sync round/aggregate pair, the fused chunk and the pipelined step
    against the SAME invariants as the single-device programs PLUS the
    per-defense collective expectation table: zero callbacks, donation
    aliasing surviving shard_map unchanged, and exactly the collectives
    :data:`EXPECTED_COLLECTIVES` allows.  Device-count agnostic — on one
    device the mesh has size 1 and the collectives still appear in the
    jaxpr (the invariants are structural)."""
    import jax

    from attackfl_tpu.config import audit_config
    from attackfl_tpu.training.engine import Simulator

    ndev = len(jax.devices())
    reports: list[ProgramReport] = []
    for mode in modes:
        expected = EXPECTED_COLLECTIVES[mode]["forward"]
        cfg = audit_config(mode=mode, prng_impl="threefry2x32",
                           total_clients=2 * ndev)
        sim = Simulator(cfg, use_mesh=True)
        try:
            assert sim.mesh_strategy == "shard_map", sim.mesh_strategy
            for p in sim.audit_programs():
                report = audit_program(
                    p["name"], p["executor"], p["raw"], p["jit"],
                    p["args"], p["donate"],
                    # round_step alone carries the collective-free
                    # shard_map'd trainer; every program containing the
                    # aggregation chain carries the defense's set
                    expected_collectives=(frozenset()
                                          if p["name"] == "round_step"
                                          else expected))
                report.name = f"sharded-{mode}[{ndev}dev]:{report.name}"
                reports.append(report)
        finally:
            sim.close()
    return reports


def audit_sharded_matrix_program() -> list[ProgramReport]:
    """Audit the CELL-sharded scenario-matrix program (ISSUE 12): the
    cell axis is embarrassingly parallel, so the partitioned grid body
    must contain NO collectives at all — the placement is pure GSPMD
    constraints, and any collective appearing means cells started
    communicating."""
    import jax

    from attackfl_tpu.config import audit_config
    from attackfl_tpu.matrix.grid import grid_from_dict
    from attackfl_tpu.training.matrix_exec import MatrixRun

    cfg = audit_config(prng_impl="threefry2x32")
    grid = grid_from_dict({
        "attacks": ["LIE"], "attack-clients": 1, "attack-round": 2,
        "defenses": ["fedavg", "krum", "FLTrust"], "seeds": [1],
        "rounds": 2,
    })
    runner = MatrixRun(cfg, grid, use_mesh=True)
    ndev = len(jax.devices())
    try:
        reports = []
        for p in runner.audit_programs():
            report = audit_program(p["name"], p["executor"], p["raw"],
                                   p["jit"], p["args"], p["donate"])
            report.name = f"sharded[{ndev}dev]:{report.name}"
            reports.append(report)
        return reports
    finally:
        runner.close()


def audit_matrix_program() -> list[ProgramReport]:
    """Audit the scenario-matrix engine's batched grid program (ISSUE 9)
    on a representative small grid: the vmapped/switched/mapped sweep
    body must satisfy the same invariants as the single-run executors —
    zero callback/transfer primitives, donation aliasing as declared,
    no wide dtypes."""
    from attackfl_tpu.config import audit_config
    from attackfl_tpu.matrix.grid import grid_from_dict
    from attackfl_tpu.training.matrix_exec import MatrixRun

    cfg = audit_config(prng_impl="threefry2x32")
    # one attack keeps the audit's trace/lower cost bounded (tier-1 runs
    # this via scripts/audit.sh); the slow acceptance test audits the
    # full 5-attack grid program
    grid = grid_from_dict({
        "attacks": ["LIE"], "attack-clients": 1, "attack-round": 2,
        "defenses": ["fedavg", "krum", "FLTrust"], "seeds": [1],
        "rounds": 2,
    })
    runner = MatrixRun(cfg, grid)
    try:
        return [audit_program(p["name"], p["executor"], p["raw"],
                              p["jit"], p["args"], p["donate"])
                for p in runner.audit_programs()]
    finally:
        runner.close()


def reports_to_findings(reports: list[ProgramReport],
                        rule: str = "program-audit") -> list[Finding]:
    """Program-level problems as findings (rule ``program-audit``, or
    ``grad-audit`` for grad-transformed programs; the 'file' is the
    program name — there is no single source line)."""
    findings = []
    for report in reports:
        for problem in report.problems:
            hint = FORBIDDEN_HINT
            if "aliasing" in problem:
                hint = DONATION_AUDIT_HINT
            elif "float64" in problem:
                hint = F64_HINT
            findings.append(Finding(
                rule=rule, file=f"<program:{report.name}>",
                line=0, message=problem, hint=hint))
    return findings


def transfer_budget() -> dict[str, Any]:
    """The audited device->host transfer budget: since the programs carry
    zero transfer primitives (checked above), every per-round transfer
    originates in an allowlisted host function.  Returns the resolved
    allowlist as the budget, with per-file entries."""
    from attackfl_tpu.analysis.ast_rules import (
        ALLOWED_FUNCTIONS, resolve_host_sync_allowlist)

    drift = resolve_host_sync_allowlist()
    return {
        "audited_functions": {name: sorted(quals)
                              for name, quals in sorted(
                                  ALLOWED_FUNCTIONS.items())},
        "total": sum(len(q) for q in ALLOWED_FUNCTIONS.values()),
        "resolved": not drift,
    }
