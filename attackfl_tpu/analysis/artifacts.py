"""Event-schema artifact rule: every committed ``events*.jsonl`` validates.

Migrated from ``scripts/check_event_schema.py`` (ISSUE 1/2 satellites; the
script is now a shim over this module).  Schema v2 aware: per-process
multi-host files (``events.<i>.jsonl``) are globbed too, and v2/v3 kinds
and optional fields validate through the same
:func:`attackfl_tpu.telemetry.events.validate_event` the writers use, so
tooling and writers cannot disagree.  v1 artifacts stay green — each
schema version only adds kinds and optional fields.
"""

from __future__ import annotations

import json
from pathlib import Path

from attackfl_tpu.analysis.findings import Finding, relativize
from attackfl_tpu.analysis.registry import AuditContext, register

EVENT_SCHEMA_HINT = (
    "regenerate the artifact with the current writers, or — for a new "
    "kind/field — extend REQUIRED_FIELDS in telemetry/events.py and bump "
    "SCHEMA_VERSION")


def find_event_files(path: Path) -> list[Path]:
    path = Path(path)
    if path.is_file():
        return [path]
    return sorted(set(path.rglob("events.jsonl")) |
                  set(path.rglob("events.*.jsonl")) |
                  set(path.rglob("*.events.jsonl")))


def event_schema_findings(path: Path, root: Path | None = None) -> list[Finding]:
    """Validate one JSONL file; one finding per invalid line/field."""
    from attackfl_tpu.telemetry.events import validate_event

    path = Path(path)
    rel = relativize(path, root) if root is not None else str(path)
    findings: list[Finding] = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError as e:
                findings.append(Finding(
                    rule="event-schema", file=rel, line=lineno,
                    message=f"not JSON ({e})", hint=EVENT_SCHEMA_HINT))
                continue
            for problem in validate_event(record):
                findings.append(Finding(
                    rule="event-schema", file=rel, line=lineno,
                    message=problem, hint=EVENT_SCHEMA_HINT))
    return findings


@register(
    "event-schema",
    "every committed events*.jsonl line validates against the telemetry "
    "event schema (telemetry/events.py validate_event)",
    EVENT_SCHEMA_HINT,
)
def _event_schema_rule(ctx: AuditContext) -> list[Finding]:
    findings: list[Finding] = []
    for path in find_event_files(ctx.root):
        findings.extend(event_schema_findings(path, ctx.root))
    return findings


# --- scripts/check_event_schema.py shim compatibility ----------------------

def event_schema_check_file(path: Path) -> list[str]:
    """Old lint output format: ``path:line: problem`` strings."""
    return [f"{f.file}:{f.line}: {f.message}"
            for f in event_schema_findings(Path(path))]


def event_schema_main(argv: list[str] | None = None) -> int:
    """Old CLI behavior (scripts/check_event_schema.py)."""
    import sys

    repo = Path(__file__).resolve().parent.parent.parent
    args = list(sys.argv[1:] if argv is None else argv)
    roots = [Path(a) for a in args] or [repo]
    files: list[Path] = []
    for root in roots:
        if not root.exists():
            print(f"error: no such path {root}", file=sys.stderr)
            return 1
        files.extend(find_event_files(root))
    errors: list[str] = []
    for path in files:
        errors.extend(event_schema_check_file(path))
    for problem in errors:
        print(problem)
    print(f"checked {len(files)} file(s): "
          f"{'OK' if not errors else f'{len(errors)} schema violation(s)'}")
    return 1 if errors else 0
