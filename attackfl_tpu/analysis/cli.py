"""``attackfl-tpu audit``: one CLI over every static-analysis pass.

Runs the AST rules (host-sync, donation-after-use, retrace-hazard,
emit-kind), the event-schema artifact check, and the jaxpr/HLO program
auditor, then prints a report — human text by default, a machine-readable
JSON document with ``--json`` (deterministic: no timestamps, repo-relative
paths — committed once under ``tests/data/audit_report.json`` as the
golden format corpus).  Exit 0 when the tree is clean, 1 otherwise.

``--retrace`` additionally runs the dynamic retrace guard (executes a few
CPU rounds per executor — seconds of compile, so opt-in; tier-1 exercises
the guard through tests/test_analysis.py instead).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

from attackfl_tpu.analysis.findings import Finding, sort_findings
from attackfl_tpu.analysis.registry import (
    AuditContext, describe_rules, run_rules)

REPORT_SCHEMA = 1


def build_report(skip_programs: bool = False, retrace: bool = False,
                 rule_ids: list[str] | None = None,
                 skip_sharded: bool = False) -> dict[str, Any]:
    """Run the selected passes and assemble the audit report."""
    ctx = AuditContext()
    findings: list[Finding] = run_rules(ctx, rule_ids)
    programs: list[dict[str, Any]] = []
    budget: dict[str, Any] = {}
    if not skip_programs:
        from attackfl_tpu.analysis import program_audit

        reports = (program_audit.audit_default_programs()
                   + program_audit.audit_matrix_program())
        if not skip_sharded:
            # mesh-native executors (ISSUE 12): sharded fused/pipelined/
            # sync programs against the per-defense collective
            # expectation table, and the cell-sharded matrix program
            # (collective-free by design).  --skip-sharded exists for
            # time-budgeted harnesses: the donation check compiles the
            # sharded programs (aliasing is resolved at compile time
            # under a mesh), which costs minutes on a small CPU box.
            reports += (program_audit.audit_sharded_programs()
                        + program_audit.audit_sharded_matrix_program())
        programs = [r.to_dict() for r in reports]
        findings.extend(program_audit.reports_to_findings(reports))
        budget = program_audit.transfer_budget()
    if retrace:
        from attackfl_tpu.analysis.retrace import guard_findings

        findings.extend(guard_findings())
    findings = sort_findings(findings)
    return {
        "schema": REPORT_SCHEMA,
        "tool": "attackfl-tpu audit",
        "rules": describe_rules(),
        "findings": [f.to_dict() for f in findings],
        "programs": programs,
        "transfer_budget": budget,
        "ok": not findings,
    }


def format_report(report: dict[str, Any]) -> str:
    lines = []
    for f in report["findings"]:
        lines.append(Finding(**f).format())
    for p in report["programs"]:
        status = "OK" if p["ok"] else "FAIL"
        collectives = p.get("collectives") or []
        lines.append(
            f"program {p['name']} [{p['executor']}]: {status} — "
            f"{p['eqns']} eqns, donated {p['donated_leaves']} leaf(s), "
            f"aliased {p['aliased_leaves']}/{p['expected_aliases']} "
            f"expected, forbidden={p['forbidden_primitives'] or 'none'}, "
            f"collectives={','.join(collectives) or 'none'}, "
            f"f64={p['f64_outputs']}")
    budget = report.get("transfer_budget") or {}
    if budget:
        lines.append(
            f"transfer budget: {budget['total']} audited host "
            f"function(s), allowlist "
            f"{'resolved' if budget['resolved'] else 'STALE'}")
    n = len(report["findings"])
    lines.append(
        f"audit: {len(report['rules'])} rule(s), "
        f"{len(report['programs'])} program(s), "
        f"{n} finding(s) — {'OK' if report['ok'] else 'FAIL'}")
    return "\n".join(lines)


def audit_main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="attackfl-tpu audit",
        description="Static-analysis audit: AST rules + event-schema "
                    "artifacts + jaxpr/HLO program invariants.")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--skip-programs", action="store_true",
                        help="AST/artifact rules only (no jax import, no "
                             "program tracing — fast)")
    parser.add_argument("--retrace", action="store_true",
                        help="also run the dynamic retrace guard "
                             "(EXECUTES a few CPU rounds per executor, "
                             "sharded runs across mesh sizes included)")
    parser.add_argument("--skip-sharded", action="store_true",
                        help="skip the mesh-native (shard_map) program "
                             "audits — their donation check COMPILES the "
                             "sharded programs (minutes on a small box)")
    parser.add_argument("--rules", nargs="*", default=None, metavar="RULE",
                        help="run only these rule ids (default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    args = parser.parse_args(list(sys.argv[1:] if argv is None else argv))

    if args.list_rules:
        for rule in describe_rules():
            print(f"{rule['id']}: {rule['description']}")
        return 0
    report = build_report(skip_programs=args.skip_programs,
                          retrace=args.retrace, rule_ids=args.rules,
                          skip_sharded=args.skip_sharded)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(format_report(report))
    return 0 if report["ok"] else 1
