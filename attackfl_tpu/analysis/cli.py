"""``attackfl-tpu audit``: one CLI over every static-analysis pass.

Runs the AST rules (host-sync, donation-after-use, retrace-hazard,
emit-kind), the event-schema artifact check, and the jaxpr/HLO program
auditor, then prints a report — human text by default, a machine-readable
JSON document with ``--json`` (deterministic: no timestamps, repo-relative
paths — committed once under ``tests/data/audit_report.json`` as the
golden format corpus).  Exit 0 when the tree is clean, 1 otherwise.

``--retrace`` additionally runs the dynamic retrace guard (executes a few
CPU rounds per executor — seconds of compile, so opt-in; tier-1 exercises
the guard through tests/test_analysis.py instead).

The transform-safety auditor (ISSUE 20) runs by default whenever programs
do: grad + double-backward programs of the post-defense damage objective
(sync + fused per representative defense, mesh collective duals included)
and the per-defense differentiability dataflow table.  ``--grad`` states
the intent explicitly; ``--skip-grad`` drops it for time-budgeted
harnesses, mirroring ``--skip-sharded``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any

from attackfl_tpu.analysis.findings import Finding, sort_findings
from attackfl_tpu.analysis.registry import (
    AuditContext, describe_rules, run_rules)

REPORT_SCHEMA = 2


def build_report(skip_programs: bool = False, retrace: bool = False,
                 rule_ids: list[str] | None = None,
                 skip_sharded: bool = False,
                 grad: bool | None = None) -> dict[str, Any]:
    """Run the selected passes and assemble the audit report.  ``grad``
    defaults to following the program audit (on unless
    ``skip_programs``); pass True/False to force it either way."""
    ctx = AuditContext()
    findings: list[Finding] = run_rules(ctx, rule_ids)
    programs: list[dict[str, Any]] = []
    grad_programs: list[dict[str, Any]] = []
    dataflow_table: list[dict[str, Any]] = []
    budget: dict[str, Any] = {}
    if grad is None:
        grad = not skip_programs
    if not skip_programs:
        from attackfl_tpu.analysis import program_audit

        reports = (program_audit.audit_default_programs()
                   + program_audit.audit_matrix_program())
        if not skip_sharded:
            # mesh-native executors (ISSUE 12): sharded fused/pipelined/
            # sync programs against the per-defense collective
            # expectation table, and the cell-sharded matrix program
            # (collective-free by design).  --skip-sharded exists for
            # time-budgeted harnesses: the donation check compiles the
            # sharded programs (aliasing is resolved at compile time
            # under a mesh), which costs minutes on a small CPU box.
            reports += (program_audit.audit_sharded_programs()
                        + program_audit.audit_sharded_matrix_program())
        programs = [r.to_dict() for r in reports]
        findings.extend(program_audit.reports_to_findings(reports))
        budget = program_audit.transfer_budget()
    if grad:
        # transform-safety auditor (ISSUE 20): grad + double-backward
        # programs (first-order lowered with donation checked; second-
        # order and mesh-collective audits are jaxpr-only, so this whole
        # section fits tier-1 even with --skip-sharded)
        from attackfl_tpu.analysis import dataflow, grad_audit
        from attackfl_tpu.analysis import program_audit as pa

        greports = (grad_audit.audit_grad_programs()
                    + grad_audit.audit_grad_collectives())
        grad_programs = [r.to_dict() for r in greports]
        findings.extend(pa.reports_to_findings(greports, rule="grad-audit"))
        dreports = dataflow.defense_dataflow_reports()
        dataflow_table = [r.to_dict() for r in dreports]
        findings.extend(dataflow.defense_findings(dreports))
    if retrace:
        from attackfl_tpu.analysis.retrace import guard_findings

        findings.extend(guard_findings())
    findings = sort_findings(findings)
    return {
        "schema": REPORT_SCHEMA,
        "tool": "attackfl-tpu audit",
        "rules": describe_rules(),
        "findings": [f.to_dict() for f in findings],
        "programs": programs,
        "grad_programs": grad_programs,
        "dataflow": dataflow_table,
        "transfer_budget": budget,
        "ok": not findings,
    }


def _format_program(p: dict[str, Any], prefix: str = "program") -> str:
    status = "OK" if p["ok"] else "FAIL"
    collectives = p.get("collectives") or []
    return (
        f"{prefix} {p['name']} [{p['executor']}]: {status} — "
        f"{p['eqns']} eqns, donated {p['donated_leaves']} leaf(s), "
        f"aliased {p['aliased_leaves']}/{p['expected_aliases']} "
        f"expected, forbidden={p['forbidden_primitives'] or 'none'}, "
        f"collectives={','.join(collectives) or 'none'}, "
        f"f64={p['f64_outputs']}")


def format_report(report: dict[str, Any]) -> str:
    lines = []
    for f in report["findings"]:
        lines.append(Finding(**f).format())
    for p in report["programs"]:
        lines.append(_format_program(p))
    for p in report.get("grad_programs") or []:
        lines.append(_format_program(p, prefix="grad program"))
    for d in report.get("dataflow") or []:
        cliffs = ",".join(sorted({c["primitive"] for c in d["cliffs"]}))
        lines.append(
            f"dataflow {d['name']}: {d['verdict']} — reachability "
            f"{d['reachability']:.3f} ({d['live_eqns']}/"
            f"{d['touched_eqns']} path eqns), "
            f"piecewise={','.join(d['piecewise']) or 'none'}, "
            f"cliffs={cliffs or 'none'}")
    budget = report.get("transfer_budget") or {}
    if budget:
        lines.append(
            f"transfer budget: {budget['total']} audited host "
            f"function(s), allowlist "
            f"{'resolved' if budget['resolved'] else 'STALE'}")
    n = len(report["findings"])
    lines.append(
        f"audit: {len(report['rules'])} rule(s), "
        f"{len(report['programs'])} program(s), "
        f"{len(report.get('grad_programs') or [])} grad program(s), "
        f"{len(report.get('dataflow') or [])} dataflow verdict(s), "
        f"{n} finding(s) — {'OK' if report['ok'] else 'FAIL'}")
    return "\n".join(lines)


def audit_main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="attackfl-tpu audit",
        description="Static-analysis audit: AST rules + event-schema "
                    "artifacts + jaxpr/HLO program invariants.")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--skip-programs", action="store_true",
                        help="AST/artifact rules only (no jax import, no "
                             "program tracing — fast)")
    parser.add_argument("--retrace", action="store_true",
                        help="also run the dynamic retrace guard "
                             "(EXECUTES a few CPU rounds per executor, "
                             "sharded runs across mesh sizes included)")
    parser.add_argument("--skip-sharded", action="store_true",
                        help="skip the mesh-native (shard_map) program "
                             "audits — their donation check COMPILES the "
                             "sharded programs (minutes on a small box)")
    parser.add_argument("--grad", action="store_true",
                        help="run the transform-safety auditor (grad + "
                             "double-backward damage-objective programs "
                             "and the per-defense differentiability "
                             "table) — on by default whenever programs "
                             "are audited; this flag forces it even "
                             "with --skip-programs")
    parser.add_argument("--skip-grad", action="store_true",
                        help="skip the transform-safety auditor "
                             "(time-budgeted harnesses, mirroring "
                             "--skip-sharded)")
    parser.add_argument("--rules", nargs="*", default=None, metavar="RULE",
                        help="run only these rule ids (default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    args = parser.parse_args(list(sys.argv[1:] if argv is None else argv))

    if args.list_rules:
        for rule in describe_rules():
            print(f"{rule['id']}: {rule['description']}")
        return 0
    if args.grad and args.skip_grad:
        parser.error("--grad and --skip-grad are mutually exclusive")
    grad = True if args.grad else (False if args.skip_grad else None)
    report = build_report(skip_programs=args.skip_programs,
                          retrace=args.retrace, rule_ids=args.rules,
                          skip_sharded=args.skip_sharded, grad=grad)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(format_report(report))
    return 0 if report["ok"] else 1
