"""Static-analysis subsystem (ISSUE 5): machine-checked guarantees over
the invariants the framework's performance claims rest on.

* :mod:`~attackfl_tpu.analysis.registry` — the lint framework: rule
  registry, audit context, structured findings.
* :mod:`~attackfl_tpu.analysis.ast_rules` — source-level rules: host-sync
  (with live allowlist resolution), donation-after-use, retrace-hazard,
  emit-kind.
* :mod:`~attackfl_tpu.analysis.artifacts` — event-schema validation of
  committed telemetry JSONL.
* :mod:`~attackfl_tpu.analysis.program_audit` — jaxpr/HLO invariants of
  the compiled round programs (no callbacks, donation aliasing, dtype
  discipline, transfer budget).
* :mod:`~attackfl_tpu.analysis.retrace` — the dynamic retrace guard.
* :mod:`~attackfl_tpu.analysis.cli` — the ``attackfl-tpu audit`` entry
  point.

``scripts/check_host_sync.py`` and ``scripts/check_event_schema.py`` are
thin shims over this package.
"""

from attackfl_tpu.analysis.findings import Finding, sort_findings
from attackfl_tpu.analysis.registry import (
    AuditContext, Rule, describe_rules, load_rules, run_rules)

__all__ = [
    "AuditContext",
    "Finding",
    "Rule",
    "describe_rules",
    "load_rules",
    "run_rules",
]
