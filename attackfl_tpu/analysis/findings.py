"""Structured findings: the one output type every audit pass produces.

A :class:`Finding` is one violation at one source location — rule id,
severity, repo-relative file, 1-based line, human message and a fix hint.
AST rules, the event-schema artifact check, the jaxpr/HLO program auditor
and the dynamic retrace guard all emit this shape, so the ``attackfl-tpu
audit`` CLI can render one report (text or ``--json``) and tier-1 can
assert on exact ``(rule, file, line)`` triples.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from pathlib import Path
from typing import Any, Iterable

SEVERITIES = ("error", "warning")


@dataclass(frozen=True)
class Finding:
    """One audit violation.

    ``file`` is repo-relative wherever possible (fixture files under a tmp
    dir stay absolute); ``line`` is 1-based (0 = whole-file / program-level
    finding with no single source line).
    """

    rule: str
    file: str
    line: int
    message: str
    severity: str = "error"
    hint: str = ""

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity must be one of {SEVERITIES}, got {self.severity!r}")

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    def format(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        text = f"{loc}: [{self.rule}] {self.message}"
        if self.hint:
            text += f"  (fix: {self.hint})"
        return text


def relativize(path: Path | str, root: Path) -> str:
    """Repo-relative POSIX path when ``path`` is under ``root``; the
    original path otherwise (fixtures in tmp dirs, absolute inputs)."""
    p = Path(path)
    try:
        return p.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return str(path)


def sort_findings(findings: Iterable[Finding]) -> list[Finding]:
    """Stable report order: errors first, then by file / line / rule."""
    return sorted(findings, key=lambda f: (f.severity != "error", f.file,
                                           f.line, f.rule, f.message))
