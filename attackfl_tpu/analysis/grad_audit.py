"""Grad-program audit: the round's invariants survive ``jax.grad``
(ISSUE 20 tentpole, part 1).

The ROADMAP's learned-attack work differentiates a scalar post-defense
damage objective through the whole round — local training, the attack
templates, aggregation, the defense — and the resulting grad (and
double-backward grad-of-grad-norm) program must keep every contract the
forward programs pass under :mod:`attackfl_tpu.analysis.program_audit`:

* **sync-freedom** — AD must not smuggle a callback/infeed into the
  cotangent program (a custom_vjp backed by ``pure_callback`` would);
* **dtype discipline** — no f64/complex cotangent promotion;
* **donation** — the perturbation argument is donated to its own
  gradient: ``grad(objective)`` returns the perturbation's exact tree,
  so every donated leaf must alias 1:1 in the lowered StableHLO (this is
  the buffer reuse the learned-attack ascent loop will live on);
* **collectives under the mesh** — AD *transposes* collectives
  (psum<->all_gather duals), so the grad program gets its own expected
  table: the ``grad`` column of :data:`~attackfl_tpu.analysis.
  program_audit.EXPECTED_COLLECTIVES`, derived in
  :func:`attackfl_tpu.parallel.shard.grad_collectives`.

The objectives come from the engine's :meth:`Simulator.damage_objective`
audit seam (sync round->aggregate and the fused 2-round scan chunk —
grad through local Adam training included).  First-order grads get the
full audit (trace + lower, donation aliasing checked); double-backward
programs are audited at the jaxpr level (tracing proves
differentiability twice over; lowering them would double the audit's
compile bill for no new invariant).  The mesh collective audit is
jaxpr-only too — collectives appear at trace time, no compile needed —
so it runs even under ``--skip-sharded`` budgets.

Nothing in this module executes a program.
"""

from __future__ import annotations

from typing import Any, Callable

from attackfl_tpu.analysis.program_audit import (
    EXPECTED_COLLECTIVES,
    ProgramReport,
    audit_program,
    collective_primitives,
    forbidden_primitives,
    walk_jaxpr,
    wide_dtype_outputs,
)
from attackfl_tpu.analysis.registry import register_info

# Representative defense triad (ISSUE 20 acceptance): a psum/mean
# defense, an order-statistic defense, an anchor/trust defense.  The
# slow full-grid test widens this to every mode.
GRAD_MODES = ("fedavg", "median", "FLTrust")

GRAD_AUDIT_HINT = (
    "the grad/double-backward program broke a round invariant — look for "
    "a custom_vjp with host callbacks, an f64 cotangent promotion, or a "
    "collective AD transposed outside the `grad` column of "
    "EXPECTED_COLLECTIVES")

register_info(
    "grad-audit",
    "jax.grad and grad-of-grad-norm of the post-defense damage objective "
    "(sync + fused, per representative defense) stay sync-free and "
    "f64-free, donate the perturbation 1:1 into its gradient, and under "
    "the mesh carry exactly the transposed collective set",
    GRAD_AUDIT_HINT,
)


def _jit_donating(fn: Callable, donate: tuple[int, ...]):
    """One audit-time ``jax.jit`` per grad program.  These jits exist to
    be ``.lower()``'d exactly once for the donation-aliasing check —
    nothing dispatches them — so the per-call program cache the
    retrace-hazard rule protects does not apply here (and the rule sees
    no jit-in-loop because this wrapper owns the call site)."""
    import jax

    return jax.jit(fn, donate_argnums=donate)


def double_backward(objective: Callable) -> Callable:
    """``grad`` of the squared gradient norm: the canonical second-order
    program (what a curvature-aware learned attacker or an auto-tuned
    client optimizer dispatches)."""
    import jax
    import jax.numpy as jnp

    g = jax.grad(objective)

    def grad_norm(*args):
        cotangent = g(*args)
        sq = jax.tree.map(lambda x: jnp.sum(x * x), cotangent)
        return 0.5 * jax.tree.reduce(lambda a, b: a + b, sq)

    return jax.grad(grad_norm)


def audit_jaxpr_program(name: str, executor: str, raw: Callable,
                        args: tuple,
                        expected_collectives: frozenset[str] = frozenset(),
                        ) -> ProgramReport:
    """Trace-only audit: sync-freedom, dtype discipline and the
    collective table from the jaxpr alone — no lowering, no compile (the
    double-backward and mesh-grad paths, where tracing already proves
    what we need and lowering would only burn minutes)."""
    import jax

    jaxpr = jax.make_jaxpr(raw)(*args)
    counts = walk_jaxpr(jaxpr)
    forbidden = forbidden_primitives(counts)
    collectives = collective_primitives(counts)
    f64 = wide_dtype_outputs(jaxpr)
    report = ProgramReport(
        name=name, executor=executor,
        eqns=sum(counts.values()), distinct_primitives=len(counts),
        forbidden=forbidden, donated_args=(), donated_leaves=0,
        expected_aliases=0, aliased_leaves=0, f64_outputs=f64,
        collectives=collectives,
        expected_collectives=sorted(expected_collectives))
    if forbidden:
        report.problems.append(
            f"forbidden host-transfer primitive(s) in a grad program: "
            f"{', '.join(forbidden)}")
    if set(collectives) != set(expected_collectives):
        report.problems.append(
            f"grad collective set mismatch: program contains "
            f"[{', '.join(collectives) or 'none'}], expected "
            f"[{', '.join(sorted(expected_collectives)) or 'none'}] "
            "(the `grad` column of EXPECTED_COLLECTIVES — transposition "
            "duals, see parallel/shard.grad_collectives)")
    if f64 > 0:
        report.problems.append(
            f"{f64} float64/complex128 value(s) in the grad program — "
            "unexpected wide-dtype promotion under AD")
    return report


def audit_grad_programs(modes: tuple[str, ...] = GRAD_MODES
                        ) -> list[ProgramReport]:
    """For each representative defense: the full audit of
    ``grad(damage)`` for every executor path the engine exposes (sync
    round->aggregate, fused 2-round chunk), donation aliasing included,
    plus the jaxpr-level audit of the double-backward program."""
    import jax

    from attackfl_tpu.config import audit_config
    from attackfl_tpu.training.engine import Simulator

    reports: list[ProgramReport] = []
    for mode in modes:
        cfg = audit_config(mode=mode)
        sim = Simulator(cfg)
        try:
            for entry in sim.damage_objective():
                g = jax.grad(entry["objective"])
                reports.append(audit_program(
                    f"{mode}:grad[{entry['name']}]", entry["executor"],
                    g, _jit_donating(g, entry["donate"]),
                    entry["args"], entry["donate"]))
                gg = double_backward(entry["objective"])
                reports.append(audit_jaxpr_program(
                    f"{mode}:grad2[{entry['name']}]", entry["executor"],
                    gg, entry["args"]))
        finally:
            sim.close()
    return reports


def audit_grad_collectives(modes: tuple[str, ...] = GRAD_MODES
                           ) -> list[ProgramReport]:
    """The mesh half: trace ``grad(damage)`` through each defense's
    shard_map'd aggregation chain and assert exactly the transposed
    collective set the ``grad`` column of EXPECTED_COLLECTIVES allows.
    Jaxpr-only (collectives are trace-time structure), so this stays in
    the tier-1 budget even though sharded *compiles* don't."""
    import jax
    import jax.numpy as jnp

    from attackfl_tpu.config import audit_config
    from attackfl_tpu.data.synthetic import get_dataset
    from attackfl_tpu.parallel.mesh import make_client_mesh
    from attackfl_tpu.registry import get_model
    from attackfl_tpu.training.round import build_aggregator

    ndev = len(jax.devices())
    cfg0 = audit_config(prng_impl="threefry2x32", total_clients=2 * ndev)
    model = get_model(cfg0.model)
    test_np = get_dataset(cfg0.data_name, "test", cfg0.test_size,
                          cfg0.random_seed)
    mesh = make_client_mesh()
    n = cfg0.total_clients
    rng = jax.random.key(0, impl="threefry2x32")
    params = model.init(rng, jnp.zeros((1, 7)),
                        jnp.zeros((1, 16)))["params"]
    stacked = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), params)
    sizes = jnp.ones((n,), jnp.int32)
    wmask = jnp.ones((n,), jnp.float32)
    perturb = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), stacked)

    def make_damage(agg):
        def damage(perturb, params, stacked, sizes, wmask, rng):
            poisoned = jax.tree.map(lambda s, p: s + p, stacked, perturb)
            new = agg(params, poisoned, sizes, wmask, rng)
            sq = jax.tree.map(lambda a, b: jnp.sum((a - b) ** 2),
                              new, params)
            return jax.tree.reduce(lambda a, b: a + b, sq)
        return damage

    reports: list[ProgramReport] = []
    for mode in modes:
        agg = build_aggregator(model, cfg0.replace(mode=mode), test_np,
                               mesh=mesh)
        g = jax.grad(make_damage(agg))
        reports.append(audit_jaxpr_program(
            f"sharded-{mode}[{ndev}dev]:grad[aggregate]", "sync", g,
            (perturb, params, stacked, sizes, wmask, rng),
            expected_collectives=EXPECTED_COLLECTIVES[mode]["grad"]))
    return reports


def grad_report(modes: tuple[str, ...] = GRAD_MODES,
                dataflow_modes: tuple[str, ...] | None = None
                ) -> dict[str, Any]:
    """The full transform-safety document: grad/double-backward program
    reports (sync + fused + mesh collectives) and the per-defense
    differentiability dataflow table.  Committed as
    ``tests/data/grad_audit_report.json`` via scripts/regen_goldens.py;
    the ``--grad`` audit rebuilds it live."""
    from attackfl_tpu.analysis import dataflow

    programs = audit_grad_programs(modes) + audit_grad_collectives(modes)
    reports = dataflow.defense_dataflow_reports(dataflow_modes)
    findings = dataflow.defense_findings(reports)
    return {
        "grad_modes": list(modes),
        "programs": [p.to_dict() for p in programs],
        "dataflow": [r.to_dict() for r in reports],
        "ok": (not findings) and all(p.ok for p in programs),
    }
