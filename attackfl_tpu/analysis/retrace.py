"""Retrace guard: the dynamic half of the graph auditor.

A jitted round program must compile on round 1 and never again — a silent
retrace (shape drift, a fresh Python scalar in the signature, a rebuilt
closure) re-pays multi-second compiles every round and is invisible in
wall-clock noise until it dominates.  The static ``retrace-hazard`` rule
catches the *patterns*; this harness catches the *fact*: it snapshots the
per-callable jit-cache sizes after the first round and fails if any cache
grows over the rest of a multi-round run.

``_cache_size()`` is jax's per-PjitFunction compiled-signature count: one
entry per distinct (structure, shape, dtype) signature.  A new jitted
callable appearing after the snapshot (e.g. the fused path's length-1
retry-tail program) is a new *program* — allowed one entry; an existing
callable growing beyond its snapshot is a retrace — failed.
"""

from __future__ import annotations

from typing import Any, Callable

from attackfl_tpu.analysis.findings import Finding

RETRACE_GUARD_HINT = (
    "find what changed in the call signature after round 1 (shape, dtype, "
    "weak type, container structure) and make it round-invariant — the "
    "static retrace-hazard rule lists the usual sources")


def jitted_programs(sim) -> dict[str, Any]:
    """Every jitted callable a Simulator owns, by a stable name: direct
    attributes, the fused/pipeline program caches, and the validation
    evaluators."""
    programs: dict[str, Any] = {}
    for name, value in vars(sim).items():
        if hasattr(value, "_cache_size"):
            programs[name] = value
    for length, fn in getattr(sim, "_fused_cache", {}).items():
        if hasattr(fn, "_cache_size"):
            programs[f"_fused_cache[{length}]"] = fn
    for key, fn in getattr(sim, "_pipeline_cache", {}).items():
        if hasattr(fn, "_cache_size"):
            programs[f"_pipeline_cache[{key}]"] = fn
    validation = getattr(sim, "validation", None)
    if validation is not None:
        for name, value in vars(validation).items():
            if hasattr(value, "_cache_size"):
                programs[f"validation.{name}"] = value
    return programs


class RetraceGuard:
    """Snapshot-then-check trace counter over one Simulator's programs."""

    def __init__(self, sim):
        self.sim = sim
        self.baseline: dict[str, int] | None = None

    def snapshot(self) -> dict[str, int]:
        """Record the current per-program trace counts (call after the
        first round, i.e. after every program has compiled once)."""
        self.baseline = {name: fn._cache_size()
                         for name, fn in jitted_programs(self.sim).items()}
        return dict(self.baseline)

    def violations(self) -> list[str]:
        """Programs that retraced since :meth:`snapshot`."""
        if self.baseline is None:
            raise RuntimeError("snapshot() the guard before checking it")
        problems = []
        for name, fn in jitted_programs(self.sim).items():
            size = fn._cache_size()
            before = self.baseline.get(name)
            if before is None:
                if size > 1:  # new program: one compile is legitimate
                    problems.append(
                        f"{name}: new jitted callable already holds {size} "
                        "traced signatures")
            elif size > before:
                problems.append(
                    f"{name}: retraced after round 1 "
                    f"({before} -> {size} signatures)")
        return problems


def run_with_guard(sim, num_rounds: int = 3, pipeline: bool = False,
                   runner: Callable | None = None) -> list[str]:
    """Run one round, snapshot, run the remaining rounds, return retrace
    violations.  ``runner(sim, state, target_rounds)`` overrides the
    default ``sim.run`` loop (run_fast chunks, custom drivers)."""
    if runner is None:
        def runner(sim, state, target):
            state, _ = sim.run(num_rounds=target, state=state,
                               save_checkpoints=False, verbose=False,
                               pipeline=pipeline)
            return state

    state = runner(sim, None, 1)
    guard = RetraceGuard(sim)
    guard.snapshot()
    runner(sim, state, num_rounds)
    return guard.violations()


def guard_findings(modes_and_executors=(("fedavg", False),
                                        ("fedavg", True),
                                        ("fedavg", True, 4))
                   ) -> list[Finding]:
    """CLI entry (``audit --retrace``): run the guard over the
    representative config on the sync and pipelined executors (the fused
    executor shares the pipelined body), including a depth-4 pipelined
    run — depth changes must dispatch the one cached step program
    (ISSUE 10).  Entries are ``(mode, pipeline[, depth])``.  EXECUTES
    rounds — seconds of compile + train on CPU, unlike the purely static
    passes."""
    from attackfl_tpu.config import audit_config
    from attackfl_tpu.training.engine import Simulator

    findings = []
    for entry in modes_and_executors:
        mode, pipeline, depth = (*entry, 1)[:3]
        sim = Simulator(audit_config(mode=mode, pipeline_depth=depth))
        try:
            label = (f"pipelined[depth={depth}]" if pipeline else "sync")
            for problem in run_with_guard(sim, num_rounds=3,
                                          pipeline=pipeline):
                findings.append(Finding(
                    rule="retrace-guard",
                    file=f"<run:{mode}:{label}>",
                    line=0, message=problem, hint=RETRACE_GUARD_HINT))
        finally:
            sim.close()
    findings.extend(sharded_guard_findings())
    return findings


def sharded_guard_findings() -> list[Finding]:
    """Retrace guard over the mesh-native executors ACROSS MESH SIZES
    (ISSUE 12): the shard_map'd sync and pipelined programs at a 1-device
    mesh and at the full visible mesh must each compile once and never
    again — mesh size is program structure (it changes shard shapes), so
    each size legitimately compiles its own program, but rounds within
    one size must never retrace."""
    import jax

    from attackfl_tpu.config import audit_config
    from attackfl_tpu.parallel.mesh import make_client_mesh
    from attackfl_tpu.training.engine import Simulator

    ndev = len(jax.devices())
    sizes = sorted({1, ndev})
    findings = []
    for size in sizes:
        for pipeline in (False, True):
            cfg = audit_config(mode="fedavg", prng_impl="threefry2x32",
                               total_clients=2 * ndev)
            sim = Simulator(cfg, mesh=make_client_mesh(size))
            try:
                label = ("pipelined" if pipeline else "sync")
                for problem in run_with_guard(sim, num_rounds=3,
                                              pipeline=pipeline):
                    findings.append(Finding(
                        rule="retrace-guard",
                        file=f"<run:sharded[{size}dev]:{label}>",
                        line=0, message=problem, hint=RETRACE_GUARD_HINT))
            finally:
                sim.close()
    return findings
