"""AST rules: the source-level half of the graph auditor.

Four rules over the package's Python sources:

* ``host-sync`` — no host-device sync barrier (``block_until_ready``,
  ``float(...)``, ``np.asarray``/``np.array``, ``device_get``) on the
  training hot path outside the audited allowlist.  Migrated from
  ``scripts/check_host_sync.py`` (ISSUE 3), which is now a shim over this
  module.  The linted file set is *discovered*, not hand-maintained
  (ISSUE 20 satellite): every source under ``attackfl_tpu/`` must
  classify against the TRACED_ONLY / HOST_SIDE prefix registries, and an
  unclassified file is itself a finding.  The allowlist is likewise
  *resolved against the live modules* at lint time: an allowlisted
  qualified name that no longer exists (renamed, deleted) is itself a
  finding, so the audited-transfer budget can't silently drift from the
  code it audits.
* ``donation-after-use`` — a buffer donated to a jitted program
  (``jax.jit(..., donate_argnums=...)``) is read again after the donating
  call.  Donated buffers are invalidated by dispatch; re-reading one is a
  runtime ``RuntimeError`` on real hardware and silent wrong-buffer reuse
  at worst.  Literal donate_argnums are tracked, and so is the
  conditional-literal idiom (``(0,) if donate else ()``, the engine's
  numerics-aware policy — ISSUE 20 satellite): an *unguarded* read after
  a conditional donation is flagged, a read inside an ``if`` is assumed
  correlated with the non-donating branch and exempt.  Computed argnums
  (subscripts into donation_spec()) stay with the jaxpr auditor.
* ``retrace-hazard`` — patterns that make a jitted program retrace after
  round 1: ``jax.jit`` inside a loop (a fresh program per iteration),
  Python scalar conversions (``float()``/``int()``) flowing into a
  ``static_argnums`` position (a fresh signature per value), and
  iteration over a ``set`` (nondeterministic order feeding program
  structure — a persistent-compile-cache miss across processes).
* ``emit-kind`` — every ``.emit("<kind>", ...)`` literal exists in the
  telemetry schema for the version it targets
  (:data:`attackfl_tpu.telemetry.events.KINDS_BY_VERSION`), so a typo'd
  event kind fails the audit instead of producing forever-invalid JSONL.

Every check is also exposed as a per-file function so tests can run it on
fixture files with seeded violations and assert exact rule id + line.
"""

from __future__ import annotations

import ast
import importlib
from pathlib import Path

from attackfl_tpu.analysis.findings import Finding, relativize
from attackfl_tpu.analysis.registry import AuditContext, register

# ---------------------------------------------------------------------------
# host-sync (migrated from scripts/check_host_sync.py — ISSUE 3 satellite)
# ---------------------------------------------------------------------------

REPO = Path(__file__).resolve().parent.parent.parent
PACKAGE = REPO / "attackfl_tpu"

# --- host-sync coverage registry (ISSUE 20 satellite) ----------------------
# Every .py under attackfl_tpu/ is DISCOVERED (rglob) and must classify
# into exactly one of two prefix registries.  Keys are package-relative
# POSIX paths; a trailing "/" marks a directory prefix; the LONGEST
# matching prefix wins across both tables, so a file-level override
# (telemetry/numerics.py) beats its directory's default (telemetry/).
#
# TRACED_ONLY files are linted: any sync shape outside ALLOWED_FUNCTIONS
# is a finding.  HOST_SIDE files are exempt, each carrying the reason the
# exemption is sound.  A discovered file matching NEITHER registry is
# itself a finding — a new package can never silently escape the lint
# (the hand-maintained per-PR file lists this replaces grew one package
# behind the tree more than once between ISSUEs 3 and 19).
TRACED_ONLY: dict[str, str] = {
    "__init__.py": "top-level re-exports — import-time code may never "
                   "materialize a device value",
    "__main__.py": "python -m entry stub (delegates to the CLI)",
    "registry.py": "name->constructor tables read at program-build time",
    # the round hot path (ISSUE 3): every deliberate materialization is
    # an ALLOWED_FUNCTIONS resolve point below
    "training/": "round builders, executors and the engine hot path — "
                 "deliberate materializations are audited resolve points",
    "models/": "model init/apply run under trace",
    # ISSUE 6: device-side mask builders compile the plan into the round
    # program; the host injector only touches host values
    "faults/": "fault plans compile into the round program; NO allowlist "
               "by design — injection may never add a hot-path sync",
    # ISSUE 8: pure host orchestration over the engine's audited paths
    "service/": "host orchestration that must never materialize device "
                "values itself (every needed sync lives behind the "
                "engine's audited resolve points); NO allowlist by design",
    # ISSUE 9: the sweep's single materialization lives in
    # training/matrix_exec.py (covered by training/ above)
    "matrix/": "grid logic + batched round-body builders are traced-only; "
               "NO allowlist by design",
    # ISSUE 11: profiling a program is lower+compile, not dispatch
    "costmodel/": "capture reads XLA analysis objects, estimate/report do "
                  "JSON arithmetic; NO allowlist by design",
    # ISSUE 19: numeric coercion in profiler/ uses the `+ 0.0` idiom
    "profiler/": "profiler start/stop seams + stdlib JSON trace mining; "
                 "NO allowlist by design",
    # ISSUE 20: the auditor holds itself to its own standard
    "analysis/": "static passes, tracing and lowering never block on a "
                 "device value; NO allowlist by design",
    # ISSUE 12: a collective is device-device, never device-host
    "parallel/shard.py": "mapped bodies + collective aggregation; NO "
                         "allowlist by design",
    "parallel/__init__.py": "re-export stub",
    "ops/__init__.py": "re-export stub",
    # ISSUE 4: the single audited drain lives in telemetry/numerics.py
    "ops/metrics.py": "numerics metric compute fns are traced-only; NO "
                      "allowlist by design",
    "ops/aggregators.py": "defense aggregation chains run under trace",
    "ops/attacks.py": "attack templates run under trace",
    "ops/pytree.py": "pytree flatten/mask helpers used under jit",
    "ops/fused_step.py": "the fused Pallas executor; run_epoch's float() "
                         "on host config scalars at kernel-build time is "
                         "allowlisted",
    "telemetry/numerics.py": "traced metric ring buffer; "
                             "NumericsDrainer.drain is the subsystem's "
                             "single audited device->host transfer",
}
HOST_SIDE: dict[str, str] = {
    "cli.py": "CLI entry point — parses argv and Prometheus text, host "
              "strings only",
    "config.py": "config parsing coerces JSON/env host scalars (float()) "
                 "before any device program exists",
    "data/": "dataset synthesis/partitioning — host numpy producing the "
             "arrays rounds consume",
    "eval/": "validation resolve points: Validation.test/resolve_async "
             "are the designed synchronous reads, one per round/chunk, "
             "off the hot path",
    "ledger/": "run-ledger JSON I/O over already-resolved host values",
    "ops/defenses.py": "host-side statistical defense halves "
                       "(gmm/dbscan/fltracer) reached only through the "
                       "engine's allowlisted resolve points",
    "ops/stats.py": "numpy statistical kernels (PCA/GMM/DBSCAN) backing "
                    "the host defense halves — pure host math",
    "parallel/mesh.py": "host<->device placement plumbing; "
                        "gather_to_host IS the designated mesh read",
    "scheduler/": "job admission/pricing over resolved telemetry JSON — "
                  "float() on host scalars",
    "science/": "outcome analytics over the ledger's resolved host "
                "values",
    "telemetry/": "host-side observability consuming values the audited "
                  "drains already materialized (numerics.py overridden "
                  "to traced-only above)",
    "utils/": "host utilities; checkpoint.host_state is the audited "
              "device->host gather, called only from the engine's "
              "allowlisted _save_checkpoint",
}

# Call shapes that materialize device values on host.
SYNC_ATTRS = {"block_until_ready", "device_get"}
SYNC_NAMES = {"float"}
SYNC_NP_ATTRS = {"asarray", "array"}
NP_MODULES = {"np", "numpy"}

# file -> audited functions (qualified as Class.method for methods).
# Every entry is a deliberate materialization point:
#   - _run_plain_round / _run_hyper_round: the synchronous path's round
#     gate (train ok flag, host-side gmm/fltracer defenses, loss print)
#   - _emit_attribution: forensics read the defense verdict per round
#   - _resolve_pipeline_round / _resolve_inflight_validations: the
#     pipelined path's designated one-round-late resolve points
#   - run_fast: per-chunk materialization of the fused scan's metrics
#   - _save_checkpoint (via checkpoint.host_state): the device->host
#     gather deliberately stays on the round loop (ISSUE 3 tentpole)
#   - _init_host_state / __init__: np.asarray on host-Python constants
#     and raw dataset numpy (not device values) while building templates
#   - run_scan: one pre-dispatch guard materializing a resumed state's
#     active_mask (once per scan call, not per round)
#   - round.py build_round_step: float() on a host model attribute at
#     program-build time
#   - numerics.py NumericsDrainer.drain: the numerics subsystem's SINGLE
#     audited device->host transfer — one np.asarray of the whole ring
#     buffer, amortized over up to `window` rounds (ops/metrics.py is
#     traced-only and has NO allowlisted functions by design)
ALLOWED_FUNCTIONS: dict[str, set[str]] = {
    "engine.py": {
        "Simulator.__init__",
        "Simulator._run_plain_round",
        "Simulator._run_hyper_round",
        "Simulator._emit_attribution",
        "Simulator._resolve_pipeline_round",
        "Simulator._resolve_inflight_validations",
        "Simulator.run_fast",
        "Simulator.run_scan",
        "Simulator._init_host_state",
    },
    "round.py": {
        "build_round_step",
    },
    "numerics.py": {
        "NumericsDrainer.drain",
    },
    #   - matrix_exec.py MatrixRun._resolve_chunk: the sweep's ONE
    #     device->host materialization — a single batched copy of each
    #     chunk's metrics covering every cell x round in the dispatch
    #     (per-cell numerics rows ride it); also the async-dispatch
    #     block, run_fast-style
    #   - MatrixRun._min_completed: the sweep's progress gate — a few
    #     int32 scalars per chunk (the analog of run_fast's
    #     completed_rounds read)
    "matrix_exec.py": {
        "MatrixRun._resolve_chunk",
        "MatrixRun._min_completed",
    },
    #   - fused_step.py run_epoch: float() on host config scalars
    #     (lr/clip/dropout rates) partial'd into the Pallas kernel at
    #     build time — Python numbers from the config, never device values
    "fused_step.py": {
        "run_epoch",
    },
}

# basename -> live module the allowlist entries must resolve against.
# Resolution (resolve_host_sync_allowlist) runs on every lint/audit so a
# rename of an audited function fails loudly instead of leaving a dead
# allowlist entry that would green-light a NEW sync under the old name.
ALLOWLIST_MODULES: dict[str, str] = {
    "engine.py": "attackfl_tpu.training.engine",
    "round.py": "attackfl_tpu.training.round",
    "numerics.py": "attackfl_tpu.telemetry.numerics",
    "matrix_exec.py": "attackfl_tpu.training.matrix_exec",
    "fused_step.py": "attackfl_tpu.ops.fused_step",
}

HOST_SYNC_HINT = (
    "move the materialization into an audited resolve function, or add the "
    "function to ALLOWED_FUNCTIONS in attackfl_tpu/analysis/ast_rules.py "
    "WITH a comment saying why it must block (allowlist entries are "
    "resolved against the live module, so they cannot outlive the code)")


def _qualname(stack: list[str]) -> str:
    return ".".join(stack) if stack else "<module>"


def _sync_call_name(node: ast.Call) -> str | None:
    func = node.func
    if isinstance(func, ast.Name) and func.id in SYNC_NAMES:
        return func.id
    if isinstance(func, ast.Attribute):
        if func.attr in SYNC_ATTRS:
            return func.attr
        if (func.attr in SYNC_NP_ATTRS and isinstance(func.value, ast.Name)
                and func.value.id in NP_MODULES):
            return f"{func.value.id}.{func.attr}"
    return None


class _SyncFinder(ast.NodeVisitor):
    def __init__(self, allowed: set[str]):
        self.allowed = allowed
        self.stack: list[str] = []
        self.hits: list[tuple[int, str, str]] = []  # (line, call, qualname)

    def _visit_scope(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_scope
    visit_AsyncFunctionDef = _visit_scope
    visit_ClassDef = _visit_scope

    def visit_Call(self, node: ast.Call) -> None:
        name = _sync_call_name(node)
        if name is not None:
            # qualify against the nearest class.method / function pair so
            # nested closures inherit their enclosing function's audit
            qual = _qualname(self.stack[:2])
            if qual not in self.allowed:
                self.hits.append((node.lineno, name, qual))
        self.generic_visit(node)


def host_sync_findings(path: Path, tree: ast.Module | None = None,
                       root: Path = REPO) -> list[Finding]:
    """Host-sync violations in one file (allowlist keyed by basename, as
    fixture tests rely on)."""
    path = Path(path)
    finder = _SyncFinder(ALLOWED_FUNCTIONS.get(path.name, set()))
    finder.visit(tree if tree is not None
                 else ast.parse(path.read_text(), filename=str(path)))
    return [
        Finding(rule="host-sync", file=relativize(path, root), line=line,
                message=f"host sync `{name}` in {qual} — materializes a "
                        "device value on the round hot path",
                hint=HOST_SYNC_HINT)
        for line, name, qual in finder.hits
    ]


def resolve_host_sync_allowlist() -> list[Finding]:
    """Resolve every allowlist entry against the live module (the
    audited-allowlist drift check).  A missing symbol is an error finding
    pointing at the allowlist itself."""
    findings: list[Finding] = []
    here = relativize(Path(__file__), REPO)
    for basename, quals in ALLOWED_FUNCTIONS.items():
        module_name = ALLOWLIST_MODULES.get(basename)
        if module_name is None:
            findings.append(Finding(
                rule="host-sync", file=here, line=0,
                message=f"allowlist file {basename!r} has no live-module "
                        "mapping in ALLOWLIST_MODULES",
                hint="add the module path so entries can be resolved"))
            continue
        try:
            module = importlib.import_module(module_name)
        except Exception as e:  # noqa: BLE001 — import failure IS drift
            findings.append(Finding(
                rule="host-sync", file=here, line=0,
                message=f"allowlist module {module_name} failed to import: "
                        f"{type(e).__name__}: {e}",
                hint="fix the module or drop its allowlist entries"))
            continue
        for qual in sorted(quals):
            obj = module
            for part in qual.split("."):
                obj = getattr(obj, part, None)
                if obj is None:
                    break
            if obj is None:
                findings.append(Finding(
                    rule="host-sync", file=here, line=0,
                    message=f"audited allowlist entry {qual!r} no longer "
                            f"exists in {module_name} — the allowlist has "
                            "drifted from the code it audits",
                    hint="remove the stale entry, or re-point it at the "
                         "renamed audited function (with its comment)"))
    return findings


def classify_host_sync(rel: str) -> tuple[str, str] | None:
    """``("traced-only" | "host-side", reason)`` for a package-relative
    POSIX path, or None when the coverage registry does not know the file.
    Longest matching prefix wins across both registries."""
    best: tuple[int, str, str] | None = None
    for kind, table in (("traced-only", TRACED_ONLY),
                        ("host-side", HOST_SIDE)):
        for prefix, reason in table.items():
            if rel == prefix or (prefix.endswith("/")
                                 and rel.startswith(prefix)):
                if best is None or len(prefix) > best[0]:
                    best = (len(prefix), kind, reason)
    return (best[1], best[2]) if best is not None else None


def host_sync_coverage(package: Path = PACKAGE,
                       root: Path = REPO
                       ) -> tuple[list[Path], list[Finding]]:
    """Discovery: every ``*.py`` under the package, classified against the
    coverage registry.  Returns ``(traced-only files to lint, findings)``
    where each unclassified file is a finding — new code fails the audit
    until someone decides which side of the sync contract it lives on."""
    traced: list[Path] = []
    findings: list[Finding] = []
    here = relativize(Path(__file__), root)
    for path in sorted(package.rglob("*.py")):
        rel = path.relative_to(package).as_posix()
        cls = classify_host_sync(rel)
        if cls is None:
            findings.append(Finding(
                rule="host-sync", file=here, line=0,
                message=f"source file {package.name}/{rel} is not "
                        "classified in the host-sync coverage registry — "
                        "it would silently escape the lint",
                hint="add the file (or its package) to TRACED_ONLY if its "
                     "code runs under trace / must stay sync-free, or to "
                     "HOST_SIDE with the reason the exemption is sound"))
        elif cls[0] == "traced-only":
            traced.append(path)
    return traced, findings


def host_sync_files() -> list[Path]:
    """The linted (traced-only) file set — now derived from discovery, not
    hand-maintained lists (ISSUE 20 satellite)."""
    return host_sync_coverage()[0]


@register(
    "host-sync",
    "no host-device sync (block_until_ready / float / np.asarray / "
    "device_get) on the training hot path outside the audited allowlist; "
    "allowlist entries must resolve against the live module",
    HOST_SYNC_HINT,
)
def _host_sync_rule(ctx: AuditContext) -> list[Finding]:
    findings = resolve_host_sync_allowlist()
    traced, coverage = host_sync_coverage(ctx.package, ctx.root)
    findings.extend(coverage)
    for path in traced:
        findings.extend(host_sync_findings(path, ctx.tree(path), ctx.root))
    return findings


# --- scripts/check_host_sync.py shim compatibility -------------------------

def host_sync_check_file(path: Path) -> list[str]:
    """Old lint output format: one string per violation (kept verbatim for
    the shim + tests/test_host_sync_lint.py)."""
    path = Path(path)
    finder = _SyncFinder(ALLOWED_FUNCTIONS.get(path.name, set()))
    finder.visit(ast.parse(path.read_text(), filename=str(path)))
    return [
        f"{path}:{line}: host sync `{name}` in {qual} — materializes a "
        "device value on the round hot path (see scripts/check_host_sync.py)"
        for line, name, qual in finder.hits
    ]


def host_sync_main(argv: list[str] | None = None) -> int:
    """Old CLI behavior (scripts/check_host_sync.py), plus the live
    allowlist resolution: stale audited symbols fail the lint."""
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    files = [Path(a) for a in args]
    violations: list[str] = []
    if not args:  # full-tree runs also verify allowlist + coverage
        files, coverage = host_sync_coverage()
        violations.extend(f.format() for f in resolve_host_sync_allowlist())
        violations.extend(f.format() for f in coverage)
    for path in files:
        if not path.exists():
            print(f"error: no such file {path}", file=sys.stderr)
            return 1
        violations.extend(host_sync_check_file(path))
    for line in violations:
        print(line)
    print(f"checked {len(files)} file(s): "
          f"{'OK' if not violations else f'{len(violations)} host sync(s)'}")
    return 1 if violations else 0


# ---------------------------------------------------------------------------
# donation-after-use
# ---------------------------------------------------------------------------

DONATION_HINT = (
    "re-order so the donating call is the LAST consumer of the buffer, "
    "rebind the name from the call's result, or drop donate_argnums for "
    "this argument (donation is an optimization hint, never semantics)")


def _dotted(node: ast.AST) -> str | None:
    """`a.b.c` -> "a.b.c" for Name/Attribute chains; None otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _literal_argnums(node: ast.AST | None,
                     consts: dict[str, tuple[int, ...]] | None = None
                     ) -> tuple[int, ...] | None:
    """Literal donate_argnums/static_argnums: int, tuple of ints, or a
    module-level constant bound to one (e.g. ``EPOCH_DONATE_ARGNUMS``).
    Conditional / computed expressions return None (not tracked)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, ast.Name) and consts:
        return consts.get(node.id)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, int)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _argnums_spec(node: ast.AST | None,
                  consts: dict[str, tuple[int, ...]] | None = None
                  ) -> tuple[tuple[int, ...], bool] | None:
    """``(argnums, conditional)`` for a donate_argnums expression.

    A plain literal is ``(argnums, False)``.  A conditional literal pair —
    ``(0,) if donate else ()``, the engine/matrix numerics-aware donation
    policy — is ``(union of both arms, True)``: the donation *may* happen,
    so an unguarded later read of the buffer is a hazard in whichever
    configuration donates.  Anything else (computed arms, subscripts into
    donation_spec()) returns None — the jaxpr auditor covers the actual
    aliasing there."""
    lits = _literal_argnums(node, consts)
    if lits is not None:
        return lits, False
    if isinstance(node, ast.IfExp):
        body = _literal_argnums(node.body, consts)
        orelse = _literal_argnums(node.orelse, consts)
        if body is not None and orelse is not None:
            return tuple(sorted(set(body) | set(orelse))), True
    return None


def _module_const_argnums(tree: ast.Module) -> dict[str, tuple[int, ...]]:
    """Top-level ``NAME = <int or tuple-of-int literal>`` bindings, so a
    donation/static policy named as a module constant stays trackable."""
    consts: dict[str, tuple[int, ...]] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            value = _literal_argnums(node.value)
            if value is not None:
                consts[node.targets[0].id] = value
    return consts


def _jit_call(node: ast.AST) -> ast.Call | None:
    """The Call node when ``node`` is ``jax.jit(...)`` / ``jit(...)``."""
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name in ("jax.jit", "jit"):
            return node
    return None


def _jit_kwarg(call: ast.Call, kwarg: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == kwarg:
            return kw.value
    return None


class _ScopeWalker(ast.NodeVisitor):
    """Shared qualname-stack visitor for the donation / retrace scanners."""

    def __init__(self):
        self.stack: list[str] = []

    def scope(self) -> str:
        return ".".join(self.stack)

    def _visit_scope(self, node) -> None:
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_scope
    visit_AsyncFunctionDef = _visit_scope
    visit_ClassDef = _visit_scope


class _DonatingDefs(_ScopeWalker):
    """Pass 1: names bound to ``jax.jit(..., donate_argnums=<literal or
    conditional-literal>)``.

    Records ``(scope, dotted_target) -> (argnums, conditional)``;
    ``self.x`` targets are visible module-wide, bare names only within
    their defining scope (and nested closures) — so a local ``fn`` in one
    method can't shadow-track an unrelated ``fn`` in another.
    """

    def __init__(self, consts: dict[str, tuple[int, ...]] | None = None):
        super().__init__()
        self.consts = consts or {}
        self.defs: dict[str, tuple[str, tuple[int, ...], bool]] = {}

    def visit_Assign(self, node: ast.Assign) -> None:
        call = _jit_call(node.value)
        if call is not None:
            spec = _argnums_spec(_jit_kwarg(call, "donate_argnums"),
                                 self.consts)
            if spec is not None and spec[0]:
                argnums, conditional = spec
                for target in node.targets:
                    name = _dotted(target)
                    if name:
                        scope = "" if name.startswith("self.") else self.scope()
                        self.defs[name] = (scope, argnums, conditional)
        self.generic_visit(node)


class _DonationUseScanner(_ScopeWalker):
    """Pass 2: calls of donating callables, then later loads of the
    donated argument names within the same function."""

    def __init__(self, defs: dict[str, tuple[str, tuple[int, ...], bool]],
                 consts: dict[str, tuple[int, ...]] | None = None):
        super().__init__()
        self.defs = defs
        self.consts = consts or {}
        self.hits: list[tuple[int, str, str, int, bool]] = []
        # (use_line, donated_name, callee, call_line, conditional)

    def _donating_call(self, call: ast.Call
                       ) -> tuple[str, tuple[int, ...], bool] | None:
        # direct form: jax.jit(f, donate_argnums=...)(args)
        inner = _jit_call(call.func)
        if inner is not None:
            spec = _argnums_spec(_jit_kwarg(inner, "donate_argnums"),
                                 self.consts)
            if spec is not None and spec[0]:
                return ("jax.jit(...)",) + spec
        name = _dotted(call.func)
        if name is None:
            return None
        rec = self.defs.get(name)
        if rec is None:
            return None
        def_scope, argnums, conditional = rec
        scope = self.scope()
        if def_scope and not (scope == def_scope
                              or scope.startswith(def_scope + ".")):
            return None  # a different function's local name
        return (name, argnums, conditional)

    def _function_scope(self, fn_node: ast.AST) -> None:
        """Analyze one function body: every donating call's donated names
        vs. subsequent loads/stores of those names."""
        calls: list[tuple[ast.Call, str, list[str], bool]] = []
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Call):
                rec = self._donating_call(node)
                if rec is None:
                    continue
                callee, argnums, conditional = rec
                donated = []
                for i in argnums:
                    if i < len(node.args):
                        name = _dotted(node.args[i])
                        if name:
                            donated.append(name)
                if donated:
                    calls.append((node, callee, donated, conditional))
        if not calls:
            return
        # reads guarded by an `if` are exempt for CONDITIONAL donations:
        # the donation decision is host-level, and a guarded read is
        # assumed correlated with the non-donating branch (the engine's
        # `if self._numerics is not None:` idiom); an UNguarded read is
        # wrong in whichever configuration donates
        guarded: set[int] = set()
        for node in ast.walk(fn_node):
            if isinstance(node, ast.If):
                for sub in node.body + node.orelse:
                    guarded.update(id(n) for n in ast.walk(sub))
        # name -> store lines across the function body (a rebind after the
        # donating call makes subsequent loads refer to the new buffer)
        stores: dict[str, list[int]] = {}
        inside_call: dict[int, set[int]] = {}
        for call, _, _, _ in calls:
            inside_call.setdefault(id(call), set()).update(
                id(n) for n in ast.walk(call))
        for node in ast.walk(fn_node):
            name = _dotted(node)
            if name is not None and isinstance(getattr(node, "ctx", None),
                                               ast.Store):
                stores.setdefault(name, []).append(node.lineno)
        # loads are re-walked per call with node identity so arguments of
        # the donating call itself (which may span lines) are excluded
        for call, callee, donated, conditional in calls:
            call_ids = inside_call[id(call)]
            end = getattr(call, "end_lineno", call.lineno)
            for name in donated:
                rebinds = [s for s in stores.get(name, [])
                           if s >= call.lineno]
                first_rebind = min(rebinds) if rebinds else None
                for node in ast.walk(fn_node):
                    if id(node) in call_ids:
                        continue
                    if _dotted(node) != name:
                        continue
                    if not isinstance(getattr(node, "ctx", None), ast.Load):
                        continue
                    if node.lineno <= end:
                        continue
                    if first_rebind is not None and node.lineno > first_rebind:
                        continue
                    if conditional and id(node) in guarded:
                        continue
                    self.hits.append((node.lineno, name, callee,
                                      call.lineno, conditional))
                    break  # one finding per (call, name) is enough

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.stack.append(node.name)
        self._function_scope(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def donation_after_use_findings(path: Path, tree: ast.Module | None = None,
                                root: Path = REPO) -> list[Finding]:
    tree = tree if tree is not None else ast.parse(Path(path).read_text(),
                                                  filename=str(path))
    consts = _module_const_argnums(tree)
    defs = _DonatingDefs(consts)
    defs.visit(tree)
    scanner = _DonationUseScanner(defs.defs, consts)
    scanner.visit(tree)
    rel = relativize(path, root)
    return [
        Finding(rule="donation-after-use", file=rel, line=use_line,
                message=(f"`{name}` is read after being conditionally "
                         f"donated to {callee} at line {call_line} — the "
                         "read is unguarded, so whichever configuration "
                         "donates invalidates this buffer before it"
                         if conditional else
                         f"`{name}` is read after being donated to "
                         f"{callee} at line {call_line} — the donated "
                         "buffer is invalidated by that dispatch"),
                hint=DONATION_HINT)
        for use_line, name, callee, call_line, conditional
        in sorted(scanner.hits)
    ]


@register(
    "donation-after-use",
    "a buffer donated via jax.jit(donate_argnums=...) must not be read "
    "after the donating call (training/ and ops/)",
    DONATION_HINT,
)
def _donation_rule(ctx: AuditContext) -> list[Finding]:
    findings: list[Finding] = []
    for sub in ("training", "ops"):
        for path in sorted((ctx.package / sub).glob("*.py")):
            findings.extend(
                donation_after_use_findings(path, ctx.tree(path), ctx.root))
    return findings


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

RETRACE_HINT = (
    "hoist jax.jit out of the loop (cache the jitted callable), pass "
    "traced arrays instead of fresh Python scalars at static positions, "
    "and sort any set before it shapes a jitted program")


class _RetraceScanner(_ScopeWalker):
    def __init__(self):
        super().__init__()
        self.loop_depth = 0
        self.hits: list[tuple[int, str]] = []
        # bare jitted names with literal static_argnums, per scope
        self.static_defs: dict[str, tuple[str, tuple[int, ...]]] = {}

    def _visit_loop(self, node) -> None:
        self._check_iter(getattr(node, "iter", None))
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop

    def visit_While(self, node: ast.While) -> None:
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    def visit_comprehension(self, node: ast.comprehension) -> None:
        self._check_iter(node.iter)
        self.generic_visit(node)

    def _check_iter(self, it: ast.AST | None) -> None:
        if it is None:
            return
        is_set = isinstance(it, ast.Set) or (
            isinstance(it, ast.Call) and _dotted(it.func) == "set")
        if is_set:
            self.hits.append((
                it.lineno,
                "iteration over a set: nondeterministic order can reshape "
                "a jitted program between processes/runs (retrace + "
                "persistent-compile-cache miss)"))

    def visit_Assign(self, node: ast.Assign) -> None:
        call = _jit_call(node.value)
        if call is not None:
            argnums = _literal_argnums(_jit_kwarg(call, "static_argnums"))
            if argnums:
                for target in node.targets:
                    name = _dotted(target)
                    if name:
                        scope = "" if name.startswith("self.") else self.scope()
                        self.static_defs[name] = (scope, argnums)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if _jit_call(node) is not None and self.loop_depth > 0:
            self.hits.append((
                node.lineno,
                "jax.jit inside a loop: every iteration builds a fresh "
                "program (guaranteed retrace; the jit cache is per "
                "callable object)"))
        # Python scalar conversion flowing into a static position
        name = _dotted(node.func)
        rec = self.static_defs.get(name) if name else None
        if rec is not None:
            def_scope, argnums = rec
            scope = self.scope()
            if not def_scope or scope == def_scope or \
                    scope.startswith(def_scope + "."):
                for i in argnums:
                    if i < len(node.args):
                        arg = node.args[i]
                        if (isinstance(arg, ast.Call)
                                and _dotted(arg.func) in ("float", "int")):
                            self.hits.append((
                                arg.lineno,
                                f"Python scalar `{_dotted(arg.func)}(...)` "
                                f"at static_argnums position {i} of "
                                f"{name}: every distinct value is a new "
                                "signature (retrace per round)"))
        self.generic_visit(node)


def retrace_hazard_findings(path: Path, tree: ast.Module | None = None,
                            root: Path = REPO) -> list[Finding]:
    tree = tree if tree is not None else ast.parse(Path(path).read_text(),
                                                  filename=str(path))
    scanner = _RetraceScanner()
    scanner.visit(tree)
    rel = relativize(path, root)
    return [Finding(rule="retrace-hazard", file=rel, line=line,
                    message=message, hint=RETRACE_HINT)
            for line, message in sorted(scanner.hits)]


@register(
    "retrace-hazard",
    "no pattern that retraces a jitted program after round 1: jit-in-loop, "
    "Python scalars into static_argnums, set-order-dependent structure",
    RETRACE_HINT,
)
def _retrace_rule(ctx: AuditContext) -> list[Finding]:
    findings: list[Finding] = []
    for path in ctx.package_sources():
        findings.extend(
            retrace_hazard_findings(path, ctx.tree(path), ctx.root))
    return findings


# ---------------------------------------------------------------------------
# emit-kind
# ---------------------------------------------------------------------------

EMIT_KIND_HINT = (
    "fix the typo, or add the new kind to REQUIRED_FIELDS and "
    "KINDS_BY_VERSION in attackfl_tpu/telemetry/events.py (bump the "
    "schema version when the kind is new)")


class _EmitKindScanner(ast.NodeVisitor):
    def __init__(self, known: frozenset[str]):
        self.known = known
        self.hits: list[tuple[int, str]] = []

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "emit":
            kind_node: ast.AST | None = node.args[0] if node.args else None
            if kind_node is None:
                kind_node = next((kw.value for kw in node.keywords
                                  if kw.arg == "kind"), None)
            if (isinstance(kind_node, ast.Constant)
                    and isinstance(kind_node.value, str)
                    and kind_node.value not in self.known):
                self.hits.append((kind_node.lineno, kind_node.value))
        self.generic_visit(node)


def emit_kind_findings(path: Path, tree: ast.Module | None = None,
                       root: Path = REPO,
                       known: frozenset[str] | None = None) -> list[Finding]:
    if known is None:
        from attackfl_tpu.telemetry.events import known_kinds

        known = known_kinds()
    tree = tree if tree is not None else ast.parse(Path(path).read_text(),
                                                  filename=str(path))
    scanner = _EmitKindScanner(known)
    scanner.visit(tree)
    rel = relativize(path, root)
    return [
        Finding(rule="emit-kind", file=rel, line=line,
                message=f"emit kind {kind!r} is not in the telemetry "
                        f"schema (known kinds: {', '.join(sorted(known))})",
                hint=EMIT_KIND_HINT)
        for line, kind in sorted(scanner.hits)
    ]


@register(
    "emit-kind",
    "every .emit(\"<kind>\") literal exists in the telemetry event schema "
    "for the targeted version (telemetry/events.py KINDS_BY_VERSION)",
    EMIT_KIND_HINT,
)
def _emit_kind_rule(ctx: AuditContext) -> list[Finding]:
    from attackfl_tpu.telemetry.events import known_kinds

    known = known_kinds()
    findings: list[Finding] = []
    for path in ctx.package_sources():
        findings.extend(
            emit_kind_findings(path, ctx.tree(path), ctx.root, known))
    return findings
