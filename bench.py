"""Benchmark: FL rounds/sec across the BASELINE.md configurations.

Default invocation (the driver's) measures the headline workload —
BASELINE.json config 4: ICU TransformerModel, 100 clients, FedAvg, 20 LIE
attackers at genuine-rate 0.5, full reference hyperparameters (5 local
epochs, batch 128, 12k-15k samples/client/round — config.yaml:17-20,31-37),
validation on — on every local-training variant (xla f32, xla bf16
compute, and the Pallas fused kernel) when running on TPU, and
additionally runs the north-star-scale 1000-client workload.

Prints ONE JSON line:
  {"metric": "fl_rounds_per_sec_100c", "value": N, "unit": "rounds/s",
   "vs_baseline": N, "detail": {...}}

``value`` is the best backend's rounds/s at 100 clients.  ``vs_baseline``
divides by the north-star rate (1000 clients x 100 rounds < 60 s on a
v4-8 => 1.667 rounds/s; /root/repo/BASELINE.json — the reference itself
publishes no numbers, BASELINE.md).  HONEST FRAMING: the headline runs
100 clients on ONE chip while the north star is 1000 clients on a v4-8
(4 chips, 250 clients/chip) — the per-chip-equivalent comparison is the
``north_star_1000c`` detail entry, which runs the full 1000-client
workload on this single chip against the same 1.667 rounds/s bar.

Other configs: ``python bench.py --config N`` (N in 1..5) measures one
BASELINE table row; ``--backend``, ``--clients``, ``--rounds`` override
the workload (VERDICT round-2 next-steps #1/#2).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time

NORTH_STAR_ROUNDS_PER_SEC = 100.0 / 60.0  # BASELINE.json north star

BENCH_LEDGER_BASE = "/tmp/attackfl_bench"


def ledger_append(metric_record: dict) -> list[str]:
    """Append this bench result to the cross-run ledger (ISSUE 7) so the
    measured trajectory is machine-readable going forward — one record
    per measured variant (``attackfl_tpu.ledger.record.records_from_bench``
    is the same mapping ``attackfl-tpu ledger import`` uses on committed
    BENCH_*.json artifacts).  Destination: ``$ATTACKFL_LEDGER_DIR`` or
    ``/tmp/attackfl_bench/ledger``.  Best-effort — the bench's one-line
    JSON contract must survive a read-only ledger disk."""
    try:
        from attackfl_tpu.ledger.record import records_from_bench
        from attackfl_tpu.ledger.store import LedgerStore, resolve_ledger_dir

        store = LedgerStore(resolve_ledger_dir(base=BENCH_LEDGER_BASE))
        return [store.append(record)
                for record in records_from_bench(metric_record)]
    except Exception:  # noqa: BLE001 — observability, never fail the bench
        return []


def _base_kwargs(log_path: str) -> dict:
    """Reference hyperparameters shared by every BASELINE config
    (config.yaml:17-20,31-37)."""
    return dict(
        num_data_range=(12000, 15000),
        epochs=5,
        batch_size=128,
        lr=0.004,
        clip_grad_norm=1.0,
        genuine_rate=0.5,
        validation=True,
        train_size=20000,
        test_size=4000,
        scan_unroll=4,
        log_path=log_path,
    )


def make_config(n: int, log_path: str = "/tmp/attackfl_bench"):
    """BASELINE.json configs 1-5 (BASELINE.md table)."""
    from attackfl_tpu.config import AttackSpec, Config

    base = _base_kwargs(log_path)
    if n == 1:  # ICU CNNModel, 3 clients, FedAvg, no attack (config.yaml defaults)
        return Config(num_round=30, total_clients=3, mode="fedavg",
                      model="CNNModel", data_name="ICU", **base)
    if n == 2:  # ICU RNNModel, 3 clients, hyper mode, no attack
        return Config(num_round=30, total_clients=3, mode="hyper",
                      model="RNNModel", data_name="ICU", **base)
    if n == 3:  # ICU TransformerModel, 100 clients, FedAvg, non-IID split
        return Config(num_round=30, total_clients=100, mode="fedavg",
                      model="TransformerModel", data_name="ICU",
                      partition="dirichlet", dirichlet_alpha=0.5, **base)
    if n == 4:  # headline: +LIE attackers
        return Config(num_round=30, total_clients=100, mode="fedavg",
                      model="TransformerModel", data_name="ICU",
                      attacks=(AttackSpec(mode="LIE", num_clients=20,
                                          attack_round=2, args=(0.74,)),),
                      **base)
    if n == 5:  # CIFAR-10 ResNet-18, FedAvg + Opt-Fang.  The BASELINE row
        # says 1000 clients sharded over a v4 pod; 1000 stacked ResNet-18
        # replicas (~44 GB of params+opt state) exceed one chip's HBM, so
        # the single-chip row measures 16 clients and the 1000-client
        # geometry is validated on the virtual mesh (tests/test_sharding).
        base = dict(base, num_data_range=(256, 512), train_size=4096,
                    test_size=1024, epochs=1, batch_size=64)
        return Config(num_round=10, total_clients=16, mode="fedavg",
                      model="ResNet18", data_name="CIFAR10",
                      attacks=(AttackSpec(mode="Opt-Fang", num_clients=3,
                                          attack_round=2, args=(50.0, 1.0)),),
                      **base)
    raise ValueError(f"unknown BASELINE config {n}")


def tpu_init_watchdog(metric: str, seconds: float = 600.0):
    """TPU backend init goes through the axon tunnel, which can hang
    indefinitely when the chip lease is wedged — emit a diagnostic JSON
    line and exit instead of hanging the caller.  Returns a cancel()
    callable to invoke once backend init has completed.  Shared by
    bench.main and scripts/measure_baseline.py."""
    import os
    import threading

    done = threading.Event()

    def _boom():
        if not done.is_set():
            # a dead tunnel must not leave the record contentless: point at
            # the committed same-host CPU evidence (BASELINE.md) with just
            # the few headline numbers per artifact — inlining the full
            # files would grow the one-line JSON contract without bound and
            # duplicate data already committed in the repo (ADVICE r4 #3)
            evidence = {}
            from pathlib import Path
            headline_keys = ("rounds_per_sec", "rounds_per_sec_steady",
                             "rounds_per_sec_incl_compile", "final_roc_auc",
                             "jax_final_accuracy", "torch_final_accuracy",
                             "midrange_abs_diff")
            for p in ("parity_full_torch.json", "FULL_PARITY_JAX.json",
                      "FULL_PARITY_JAX_STEADY.json", "NORTHSTAR_CPU.json",
                      "HAR_PARITY.json"):
                f = Path(__file__).parent / p
                if f.exists():
                    try:
                        data = json.loads(f.read_text())
                    except ValueError:
                        continue
                    evidence[p] = {k: data[k] for k in headline_keys
                                   if isinstance(data, dict) and k in data
                                   and isinstance(data[k], (int, float))}
            detail = {
                "error": "TPU backend init did not complete "
                         f"within {seconds:.0f}s (axon tunnel down?)",
                "cpu_evidence_committed": evidence,
                "probe_log": "tpu_probe.log",
            }
            from attackfl_tpu.telemetry import metric_line

            print(json.dumps(metric_line(
                metric, 0.0, unit="rounds/s", vs_baseline=0.0, detail=detail,
            )), flush=True)
            os._exit(2)

    timer = threading.Timer(seconds, _boom)
    timer.daemon = True
    timer.start()

    def cancel():
        done.set()
        timer.cancel()

    return cancel


def _with_dtype(cfg, dtype: str):
    """Override mesh.compute-dtype (nested frozen dataclass)."""
    return cfg.replace(mesh=dataclasses.replace(cfg.mesh, compute_dtype=dtype))


def north_star_config(log_path: str = "/tmp/attackfl_bench"):
    """The BASELINE.json north-star workload: 1000 clients, 20% LIE
    attackers, full reference hyperparameters (single source of truth —
    scripts/measure_baseline.py reuses this)."""
    from attackfl_tpu.config import AttackSpec

    return make_config(4, log_path).replace(
        total_clients=1000,
        attacks=(AttackSpec(mode="LIE", num_clients=200, attack_round=2,
                            args=(0.74,)),),
    )


def measure(cfg, n_rounds: int, metric_keys=("roc_auc", "accuracy", "nll"),
            trace_dir: str | None = None, progress: dict | None = None) -> dict:
    """Compile + run ``n_rounds`` via the fused scan (or run() for
    host-side modes), return rounds/s and the final quality metric.
    ``trace_dir`` captures a jax.profiler trace of the timed section
    (inspect with tensorboard / xprof — SURVEY.md §5 tracing).
    ``progress``, if given, is mutated in place as results land so a
    deadline handler can emit best-so-far JSON (ADVICE r3 #1).  Failed
    (NaN) rounds are *reported*, not asserted — at never-before-run
    scales (the 1000-client north star) a NaN round is a realistic
    first-run outcome and must not crash the measurement (VERDICT r3
    weak #8)."""
    import contextlib

    import jax

    from attackfl_tpu.training.engine import Simulator

    sim = Simulator(cfg)
    out: dict = {} if progress is None else progress
    tracer = (jax.profiler.trace(trace_dir) if trace_dir
              else contextlib.nullcontext())
    if sim.supports_fused():
        state = sim.init_state()
        t0 = time.perf_counter()
        state, metrics = sim.run_scan(state, n_rounds)  # compile + run
        jax.block_until_ready(metrics)
        warm_s = time.perf_counter() - t0
        out["compile_plus_run_s"] = round(warm_s, 3)
        # best-so-far rate for the deadline handler: if the TIMED dispatch
        # wedges (the scenario --deadline exists for), the warmup already
        # ran n_rounds — a conservative incl-compile rate beats value 0.0
        out["warmup_rounds_per_sec_incl_compile"] = round(n_rounds / warm_s, 4)
        warm_fail = sum(1 for ok in metrics["ok"] if not bool(ok))
        if warm_fail:
            out["warmup_failed_rounds"] = warm_fail
        t0 = time.perf_counter()
        with tracer:
            state, metrics = sim.run_scan(state, n_rounds)
            jax.block_until_ready(metrics)
        elapsed = time.perf_counter() - t0
        out["failed_rounds"] = sum(1 for ok in metrics["ok"]
                                   if not bool(ok))
        final = {k: float(v[-1]) for k, v in metrics.items() if k != "ok"}
    else:  # host-side defense modes: per-round path
        state = sim.init_state()
        state, m = sim.run_round(state)  # warmup/compile
        if not m["ok"]:
            out["warmup_failed_rounds"] = 1
        t0 = time.perf_counter()
        hist = []
        with tracer:
            for _ in range(n_rounds):
                state, m = sim.run_round(state)
                hist.append(m)
                out["interim_rounds_per_sec"] = round(
                    len(hist) / (time.perf_counter() - t0), 4)
        elapsed = time.perf_counter() - t0
        out.pop("interim_rounds_per_sec", None)
        out["failed_rounds"] = sum(1 for h in hist if not h["ok"])
        final = {k: v for k, v in hist[-1].items()
                 if isinstance(v, float)}
    if not out["failed_rounds"]:
        del out["failed_rounds"]  # keep the common all-ok JSON compact
    out["rounds_per_sec"] = round(n_rounds / elapsed, 4)
    out["seconds_per_round"] = round(elapsed / n_rounds, 4)
    for k in metric_keys:
        if k in final and final[k] == final[k]:
            out[k] = round(final[k], 4)
    return out


def pipeline_compare_config(log_path: str = "/tmp/attackfl_bench"):
    """Workload for --pipeline-compare: a checkpoint-heavy round (192
    clients -> a ~37 MB state: the genuine-leak pool scales with C x P)
    with modest per-round device compute, so the synchronous path's host
    overheads (per-phase sync barriers, validation blocking, checkpoint
    serialize+write+fsync every round) are a visible fraction of the round
    — exactly the costs the pipelined executor takes off the critical
    path.  On a single-core CPU box the async win is mostly last-write-
    wins coalescing (the writer skips intermediate snapshots under load);
    with free cores the serialize+write overlaps device compute as well."""
    from attackfl_tpu.config import Config

    return Config(
        num_round=30, total_clients=192, mode="fedavg",
        model="TransformerModel", data_name="ICU",
        num_data_range=(32, 64), epochs=1, batch_size=64,
        train_size=2048, test_size=256, validation=True,
        log_path=log_path, checkpoint_dir=log_path,
    )


def measure_pipeline_compare(rounds: int, log_path: str,
                             reps: int = 3) -> dict:
    """Steady-state rounds/s: synchronous run() with per-round synchronous
    checkpointing (the default) vs run(pipeline=True) with the async
    checkpoint writer, on the SAME config.

    Each variant warms its programs once (untimed round), then the two
    variants run INTERLEAVED `reps` times and the best rate per variant is
    reported — on a loaded single-core box a single short window is noise
    (background load swings a 2 s measurement by 30%); interleaving
    cancels drift and best-of discards the windows a noisy neighbor ate.
    Per-rep rates are included in the detail for honesty."""
    import os

    from attackfl_tpu.training.engine import Simulator

    os.makedirs(log_path, exist_ok=True)
    base = pipeline_compare_config(log_path)
    out: dict = {"config": "pipeline-compare: 192 clients ICU Transformer, "
                           "validation on, per-round checkpoints",
                 "timed_rounds_per_rep": rounds, "reps": reps}

    def make(cfg, pipeline: bool):
        sim = Simulator(cfg)
        # warmup: compile every program on this path
        sim.run(num_rounds=1, state=sim.init_state(),
                save_checkpoints=True, verbose=False, pipeline=pipeline)
        return sim

    def timed_rep(sim, pipeline: bool) -> float:
        state = sim.init_state()
        t0 = time.perf_counter()
        _, hist = sim.run(num_rounds=rounds, state=state,
                          save_checkpoints=True, verbose=False,
                          pipeline=pipeline)
        return len(hist) / (time.perf_counter() - t0)

    sync_sim = make(base, pipeline=False)
    pipe_sim = make(base.replace(pipeline=True, checkpoint_async=True),
                    pipeline=True)
    sync_rates, pipe_rates = [], []
    for _ in range(reps):
        sync_rates.append(round(timed_rep(sync_sim, False), 4))
        pipe_rates.append(round(timed_rep(pipe_sim, True), 4))
    sync_sim.close()
    pipe_sim.close()

    out["sync"] = {"rounds_per_sec_steady": max(sync_rates),
                   "per_rep": sync_rates}
    out["pipelined_async_ckpt"] = {"rounds_per_sec_steady": max(pipe_rates),
                                   "per_rep": pipe_rates}
    out["speedup"] = round(max(pipe_rates) / max(sync_rates), 4)
    return out


def measure_depth_sweep(rounds: int, log_path: str, reps: int = 4,
                        depths: tuple[int, ...] = (0, 1, 2, 4, 8)) -> dict:
    """Depth-vs-throughput curve of the depth-k pipelined executor
    (ISSUE 10) on the pipeline-compare workload (192-client ICU
    Transformer, validation on) with per-round SYNCHRONOUS checkpoints —
    the serialize+write+fsync of a ~37 MB state rides every resolve, so
    there is real host latency for the queue to hide (the async-writer
    variant — BENCH_PIPELINE's depth-1 win — already hides it at any
    depth and measures flat; an `async_ckpt_reference` row is included
    for comparability with BENCH_PIPELINE.json's 3.60 r/s).

    Protocol (the PR 4/7 noise lessons): every depth's Simulator warms
    its programs once untimed, then the timed reps walk the depth list in
    ALTERNATING order so linear drift cancels, and the headline per-depth
    rates are PAIRED MEANS over the same rep slots — with best-of and the
    per-rep arrays riding the detail for honesty.  Depth 0 is the
    no-overlap floor (dispatch-then-resolve), depth 1 the historical
    executor.  The measured optimum is the SMALLEST depth whose mean
    lands within 3% of the best mean (the knee) — a flat tail must not
    let rep noise crown an arbitrarily deep k.

    The `auto` validation runs on the same box: a depth-1 run with the
    ledger enabled records the auto-tuner's measured inputs
    (round_device_time / host_resolution_latency + the foreground
    checkpoint seconds), then the REAL resolution path
    (Simulator.resolve_pipeline_depth) picks k from that ledger; the
    committed JSON carries the pick next to the measured optimum
    (`auto_within_one_step` = the acceptance criterion)."""
    import os

    from attackfl_tpu.training.engine import Simulator

    os.makedirs(log_path, exist_ok=True)
    base = pipeline_compare_config(log_path).replace(pipeline=True)
    out: dict = {"config": "depth-sweep: 192 clients ICU Transformer, "
                           "validation on, per-round SYNCHRONOUS "
                           "checkpoints",
                 "timed_rounds_per_rep": rounds, "reps": reps,
                 "depths": list(depths)}

    sims = {}
    for k in depths:
        sim = Simulator(base.replace(pipeline_depth=k))
        sim.run(num_rounds=1, state=sim.init_state(),
                save_checkpoints=True, verbose=False)
        sims[k] = sim
    rates: dict = {k: [] for k in depths}
    for rep in range(reps):
        order = list(depths) if rep % 2 == 0 else list(reversed(depths))
        for k in order:
            sim = sims[k]
            state = sim.init_state()
            t0 = time.perf_counter()
            _, hist = sim.run(num_rounds=rounds, state=state,
                              save_checkpoints=True, verbose=False)
            rates[k].append(round(len(hist)
                                  / (time.perf_counter() - t0), 4))
    for sim in sims.values():
        sim.close()

    by_depth: dict = {}
    for k in depths:
        mean = sum(rates[k]) / len(rates[k])
        by_depth[str(k)] = {"rounds_per_sec_steady": max(rates[k]),
                            "rounds_per_sec_mean": round(mean, 4),
                            "per_rep": rates[k]}
    out["by_depth"] = by_depth
    best_mean = max(b["rounds_per_sec_mean"] for b in by_depth.values())
    optimum = min(k for k in depths
                  if by_depth[str(k)]["rounds_per_sec_mean"]
                  >= 0.97 * best_mean)
    out["measured_optimum_depth"] = optimum
    out["argmax_mean_depth"] = max(
        depths, key=lambda k: by_depth[str(k)]["rounds_per_sec_mean"])
    depth1 = by_depth.get("1") or {}
    # paired MEANS, not best-of: the whole point of the alternating-rep
    # protocol (one lucky depth-1 rep must not hide the curve)
    deeper = [k for k in depths
              if k > 1 and by_depth[str(k)]["rounds_per_sec_mean"]
              >= depth1.get("rounds_per_sec_mean", float("inf"))]
    out["deeper_beats_depth1_mean"] = deeper
    if "0" in by_depth and deeper:
        out["best_deeper_vs_depth0"] = round(
            max(by_depth[str(k)]["rounds_per_sec_mean"] for k in deeper)
            / by_depth["0"]["rounds_per_sec_mean"], 4)

    # BENCH_PIPELINE comparability: one depth-1 + async-writer rep (its
    # exact conditions), so the committed curve records how today's tree
    # re-measures against the historical 3.60 r/s depth-1 artifact
    ref = Simulator(base.replace(pipeline_depth=1, checkpoint_async=True))
    ref.run(num_rounds=1, state=ref.init_state(),
            save_checkpoints=True, verbose=False)
    t0 = time.perf_counter()
    _, hist = ref.run(num_rounds=rounds, state=ref.init_state(),
                      save_checkpoints=True, verbose=False)
    ref.close()
    out["async_ckpt_reference"] = {
        "depth": 1,
        "rounds_per_sec_steady": round(len(hist)
                                       / (time.perf_counter() - t0), 4),
        "bench_pipeline_json": 3.5984,
    }

    # --- `auto` validation on this box's own measurement ---------------
    ledger_dir = os.path.join(log_path, "depth_sweep_ledger")
    env_ledger = os.environ.pop("ATTACKFL_LEDGER_DIR", None)
    try:
        import dataclasses as _dc

        feed_cfg = base.replace(
            pipeline_depth=1,
            telemetry=_dc.replace(base.telemetry, ledger=True,
                                  ledger_dir=ledger_dir))
        feeder = Simulator(feed_cfg)
        feeder.run(num_rounds=rounds, state=feeder.init_state(),
                   save_checkpoints=True, verbose=False)
        feeder.close()
        auto_sim = Simulator(feed_cfg.replace(pipeline_depth="auto"))
        picked = auto_sim.resolve_pipeline_depth(save_checkpoints=True)
        out["auto_pick"] = {"depth": picked, **(auto_sim._depth_info or {})}
        auto_sim.close()

        def nearest_pos(k: int) -> int:
            return min(range(len(depths)),
                       key=lambda i: (abs(depths[i] - k), depths[i]))

        out["auto_within_one_step"] = bool(
            abs(nearest_pos(picked) - nearest_pos(optimum)) <= 1)
    except Exception as e:  # noqa: BLE001 — the curve is the headline
        out["auto_pick"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    finally:
        if env_ledger is not None:
            os.environ["ATTACKFL_LEDGER_DIR"] = env_ledger
    return out


def measure_numerics_overhead(rounds: int, log_path: str,
                              reps: int = 4) -> dict:
    """Steady-state rounds/s of the pipelined executor with the full
    in-graph numerics metric set OFF vs ON (``telemetry.numerics``), on
    the pipeline-compare workload.  The acceptance bar (ISSUE 4) is a
    <= 3% steady regression: on the pipelined path the metric reductions
    live inside the same jitted program and their rows ride the existing
    one-round-late resolve, so the added cost is pure device compute.

    Protocol: the off/on measurement order ALTERNATES per rep (even reps
    so both orders appear equally) and the overhead is computed from the
    PAIRED MEANS, not best-of — on a drifting CPU box best-of compares
    two different time slots and routinely overstates a small delta by
    more than the delta itself (alternation cancels linear drift in the
    mean).  Both the best and mean rates are reported.  Unlike --pipeline-compare — which deliberately thins local
    training to one step per client to amplify the host overheads it
    measures — this workload trains 3 local epochs per client (the
    reference config trains 5): the numerics cost is pure device compute,
    so its honest denominator is a round with representative device
    compute, not a host-overhead microbenchmark.
    Also asserts the bit-identical-params guarantee: a short run from the
    same seed must produce byte-equal global params on vs off.
    """
    import os

    import jax
    import numpy as np

    from attackfl_tpu.config import Config  # noqa: F401 (doc pointer)
    from attackfl_tpu.training.engine import Simulator

    os.makedirs(log_path, exist_ok=True)
    base = pipeline_compare_config(log_path).replace(pipeline=True, epochs=3)
    on_cfg = base.replace(telemetry=dataclasses.replace(
        base.telemetry, numerics=True))
    out: dict = {"config": "numerics-overhead: 192 clients ICU Transformer, "
                           "3 local epochs, pipelined, validation on, no "
                           "checkpoints",
                 "timed_rounds_per_rep": rounds, "reps": reps}

    def make(cfg):
        sim = Simulator(cfg)
        sim.run(num_rounds=1, state=sim.init_state(),
                save_checkpoints=False, verbose=False)
        return sim

    def timed_rep(sim) -> float:
        state = sim.init_state()
        t0 = time.perf_counter()
        _, hist = sim.run(num_rounds=rounds, state=state,
                          save_checkpoints=False, verbose=False)
        return len(hist) / (time.perf_counter() - t0)

    off_sim, on_sim = make(base), make(on_cfg)
    off_rates, on_rates = [], []
    for rep in range(reps):
        pair = [(off_sim, off_rates), (on_sim, on_rates)]
        for sim, rates in pair if rep % 2 == 0 else reversed(pair):
            rates.append(round(timed_rep(sim), 4))

    # bit-identical params: 3 rounds from the same seed, on vs off
    state_off, _ = off_sim.run(num_rounds=3, state=off_sim.init_state(),
                               save_checkpoints=False, verbose=False)
    state_on, _ = on_sim.run(num_rounds=3, state=on_sim.init_state(),
                             save_checkpoints=False, verbose=False)
    out["bit_identical_params"] = bool(all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(state_off["global_params"]),
                        jax.tree.leaves(state_on["global_params"]))))
    off_sim.close()
    on_sim.close()

    off_mean = sum(off_rates) / len(off_rates)
    on_mean = sum(on_rates) / len(on_rates)
    out["metrics_off"] = {"rounds_per_sec_steady": max(off_rates),
                          "rounds_per_sec_mean": round(off_mean, 4),
                          "per_rep": off_rates}
    out["metrics_on"] = {"rounds_per_sec_steady": max(on_rates),
                         "rounds_per_sec_mean": round(on_mean, 4),
                         "per_rep": on_rates}
    out["overhead_pct"] = round((off_mean - on_mean) / off_mean * 100.0, 2)
    return out


def measure_matrix_compare(rounds: int, log_path: str, reps: int = 2,
                           seeds: int = 1) -> dict:
    """Serial 45-run sweep vs the batched scenario-matrix program
    (ISSUE 9): the paper's full 5-attack × 9-defense grid on the
    CPU-sized representative workload (config.audit_config — the object
    of measurement is the ORCHESTRATION cost: per-cell compiles and
    dispatch overhead, which do not shrink with workload size).

    Protocol (the --numerics-overhead noise-floor lesson): each variant
    runs a COLD rep (fresh programs — the serial side pays one compile
    per cell, the matrix side one compile per sweep) and a WARM rep
    (programs cached), with the variant order alternating per rep pair;
    the headline speedups come from PAIRED MEANS over the walls, and
    per-rep arrays ride the detail so the ledger gate can see the
    spread.  The compile-once saving is quantified as the cold-wall
    delta minus the warm-wall delta."""
    import os

    from attackfl_tpu.config import ATTACK_MODES, TelemetryConfig, audit_config
    from attackfl_tpu.matrix.grid import (
        BATCHED_DEFENSES, HOST_DEFENSES, MAPPED_DEFENSES,
        cell_config, expand_cells, grid_from_dict,
    )

    os.makedirs(log_path, exist_ok=True)
    base = audit_config(
        prng_impl="threefry2x32",
        telemetry=TelemetryConfig(enabled=False),
        log_path=log_path, checkpoint_dir=log_path)
    defenses = BATCHED_DEFENSES + MAPPED_DEFENSES + ("gmm",)
    # Random's reference-default sigma (1e6) detonates the CPU-sized CNN
    # into the inf/NaN overflow regime, where round verdicts are
    # FP-order-chaotic (any lowering difference flips them) and every
    # post-attack round retries forever — bench a sigma that perturbs
    # without overflowing, like the committed e2e workloads do for LIE
    attacks: list[Any] = [
        {"mode": m} if m != "Random" else {"mode": "Random", "args": [1.0]}
        for m in ATTACK_MODES]
    grid = grid_from_dict({
        "attacks": attacks, "attack-clients": 1,
        "attack-round": 2, "defenses": list(defenses),
        "seeds": list(range(1, seeds + 1)), "rounds": rounds,
    })
    cells = expand_cells(grid)
    out: dict = {
        "config": f"matrix-compare: audit workload, "
                  f"{len(grid.attacks)} attacks x {len(grid.defenses)} "
                  f"defenses x {seeds} seed(s) = {grid.n_cells} cells, "
                  f"{rounds} rounds",
        "reps": reps,
    }

    def serial_sweep(sims=None):
        """One serial pass over every cell.  ``sims=None`` = cold: a
        fresh Simulator (and a fresh compile) per cell, exactly the
        45×k-run workflow the matrix replaces."""
        from attackfl_tpu.training.engine import Simulator

        cold = sims is None
        if cold:
            sims = {}
        t0 = time.perf_counter()
        for cell in cells:
            sim = sims.get(cell.key)
            if sim is None:
                sim = sims[cell.key] = Simulator(
                    cell_config(base, cell, rounds=rounds))
            state = sim.init_state()
            if sim.supports_fused():
                sim.run_fast(num_rounds=rounds, state=state,
                             save_checkpoints=False, verbose=False)
            else:
                sim.run(num_rounds=rounds, state=state,
                        save_checkpoints=False, verbose=False)
        return time.perf_counter() - t0, sims

    def matrix_sweep(runner=None):
        from attackfl_tpu.training.matrix_exec import MatrixRun

        if runner is None:
            runner = MatrixRun(base, grid)
        t0 = time.perf_counter()
        runner.run(save_checkpoints=False, verbose=False)
        return time.perf_counter() - t0, runner

    serial_cold: list[float] = []
    serial_warm: list[float] = []
    matrix_cold: list[float] = []
    matrix_warm: list[float] = []
    for rep in range(reps):
        order = [("serial", serial_cold, serial_warm),
                 ("batched", matrix_cold, matrix_warm)]
        for name, cold_list, warm_list in (order if rep % 2 == 0
                                           else reversed(order)):
            if name == "serial":
                wall, sims = serial_sweep()
                cold_list.append(round(wall, 3))
                wall, _ = serial_sweep(sims)
                warm_list.append(round(wall, 3))
            else:
                wall, runner = matrix_sweep()
                cold_list.append(round(wall, 3))
                wall, _ = matrix_sweep(runner)
                warm_list.append(round(wall, 3))

    def mean(values: list[float]) -> float:
        return round(sum(values) / len(values), 3)

    rounds_total = grid.n_cells * rounds
    out["serial"] = {
        "cold_wall_s": mean(serial_cold), "warm_wall_s": mean(serial_warm),
        "per_rep_cold": serial_cold, "per_rep_warm": serial_warm,
        "rounds_per_sec_steady": round(rounds_total / mean(serial_warm), 4),
        "per_rep": [round(rounds_total / w, 4) for w in serial_warm],
    }
    out["batched"] = {
        "cold_wall_s": mean(matrix_cold), "warm_wall_s": mean(matrix_warm),
        "per_rep_cold": matrix_cold, "per_rep_warm": matrix_warm,
        "rounds_per_sec_steady": round(rounds_total / mean(matrix_warm), 4),
        "per_rep": [round(rounds_total / w, 4) for w in matrix_warm],
    }
    out["speedup_cold"] = round(mean(serial_cold) / mean(matrix_cold), 4)
    out["speedup_warm"] = round(mean(serial_warm) / mean(matrix_warm), 4)
    # the compile-once saving: how much of the cold-sweep advantage is
    # the 45 per-cell compiles the batched program never pays
    out["compile_once_saving_s"] = round(
        (mean(serial_cold) - mean(serial_warm))
        - (mean(matrix_cold) - mean(matrix_warm)), 3)
    out["host_fallback_cells"] = sum(
        1 for c in cells if c.defense in HOST_DEFENSES)
    # honest framing: the headline (cold) is what a one-submit sweep
    # pays end-to-end; the warm rate OVERSTATES the switch's relative
    # cost on this deliberately tiny workload (a vmapped lax.switch
    # computes every branch, and at audit scale the 7 aggregate branches
    # rival the 1-epoch/4-client training term they ride on — at the
    # paper's 100-client × 5-epoch scale local training dominates)
    out["note"] = (
        "cold = one-submit end-to-end (the workflow the matrix "
        "replaces); warm isolates steady dispatch, where the vmapped "
        "switch pays all-branches aggregation — a toy-scale artifact, "
        "train-dominated at reference scale")
    return out


def measure_hotspots_matrix(rounds: int, log_path: str) -> dict:
    """Profile the warm dispatch paths the matrix hypothesis argues
    about (ISSUE 19, the ROADMAP sweep-dispatch item): one warm serial
    cell, the warm batched sweep over a representative grid, and a
    fedavg-only batched control, each under a ``jax.profiler`` window
    mined by :mod:`attackfl_tpu.profiler.mine`.

    The evidence target: BENCH_MATRIX's 0.61× warm speedup is blamed on
    the vmapped ``lax.switch`` computing every aggregation branch.  On
    this backend the switch lowers to select fusions — a profiled
    matrix program shows NO ``conditional`` HLO — so the measurable
    branch signature is the robust-aggregation work the training step
    never emits: ``sort`` (median / trimmed-mean / krum distances) plus
    the ``select`` mux fusions.  ReLU backward also emits selects, so
    the fedavg-only batched control differences training + dispatch
    away: full-grid signature share minus control share = the
    all-branches aggregation share actually paid per warm dispatch.
    The per-variant host-bound fractions say how much of the remaining
    gap is dispatch, not device work."""
    import os
    import shutil

    import jax

    from attackfl_tpu.config import TelemetryConfig, audit_config
    from attackfl_tpu.matrix.grid import cell_config, expand_cells, \
        grid_from_dict
    from attackfl_tpu.profiler.mine import find_traces, mine_trace
    from attackfl_tpu.training.engine import Simulator
    from attackfl_tpu.training.matrix_exec import MatrixRun

    os.makedirs(log_path, exist_ok=True)
    base = audit_config(
        prng_impl="threefry2x32",
        telemetry=TelemetryConfig(enabled=False),
        log_path=log_path, checkpoint_dir=log_path)
    attacks = [{"mode": "none"}, {"mode": "LIE"}]
    robust = ["fedavg", "median", "trimmed_mean", "krum"]

    def _grid(defenses):
        return grid_from_dict({
            "attacks": attacks, "attack-clients": 1, "attack-round": 2,
            "defenses": defenses, "seeds": [1], "rounds": rounds,
        })

    def _profiled(tag, fn):
        """Warm the variant once untimed, then run it again inside a
        profiler window; mine the written trace."""
        fn()
        path = os.path.join(log_path, f"hotspots_{tag}")
        shutil.rmtree(path, ignore_errors=True)
        jax.profiler.start_trace(path)
        t0 = time.perf_counter()
        fn()
        wall = time.perf_counter() - t0
        jax.profiler.stop_trace()
        traces = find_traces(path)
        report = mine_trace(traces[-1]) if traces else None
        return round(wall, 3), report

    def _signature_rows(report):
        rows = []
        for row in report["ops"]:
            tokens = set(row["name"].replace("-", "_")
                         .replace(".", "_").split("_"))
            if tokens & {"sort", "select", "conditional"}:
                rows.append(row)
        return rows

    def _summary(wall, report):
        signature = _signature_rows(report)
        return {
            "warm_wall_s": wall,
            "device_busy_us": report["device_busy_us"],
            "trace_wall_us": report["wall_us"],
            "host_bound_fraction": report["host_bound_fraction"],
            "classification": report["classification"],
            "books_close": report["books"]["close"],
            "category_shares": {
                name: bucket["share"]
                for name, bucket in sorted(report["categories"].items())},
            "top_ops": [
                {"name": r["name"], "category": r["category"],
                 "share": r["share"]} for r in report["ops"][:5]],
            "aggregation_signature_share": round(
                sum(r["share"] for r in signature), 4),
            "aggregation_signature_ops": [
                {"name": r["name"], "share": r["share"]}
                for r in signature[:6]],
        }

    full_grid = _grid(robust)
    cell = next(c for c in expand_cells(full_grid)
                if c.attack.mode == "LIE" and c.defense == "fedavg")
    serial_sim = Simulator(cell_config(base, cell, rounds=rounds))

    def run_serial():
        state = serial_sim.init_state()
        if serial_sim.supports_fused():
            serial_sim.run_fast(num_rounds=rounds, state=state,
                                save_checkpoints=False, verbose=False)
        else:
            serial_sim.run(num_rounds=rounds, state=state,
                           save_checkpoints=False, verbose=False)

    full_runner = MatrixRun(base, full_grid)
    control_runner = MatrixRun(base, _grid(["fedavg"]))

    out: dict = {
        "config": f"hotspots-matrix: audit workload, "
                  f"{len(attacks)} attacks x {len(robust)} defenses x "
                  f"1 seed = {full_grid.n_cells} cells, {rounds} rounds; "
                  f"control = same attacks x fedavg only",
    }
    wall, report = _profiled("serial_cell", run_serial)
    out["serial_cell"] = _summary(wall, report)
    wall, report = _profiled(
        "batched_full",
        lambda: full_runner.run(save_checkpoints=False, verbose=False))
    out["batched_full"] = _summary(wall, report)
    wall, report = _profiled(
        "batched_fedavg_only",
        lambda: control_runner.run(save_checkpoints=False, verbose=False))
    out["batched_fedavg_only"] = _summary(wall, report)

    out["aggregation_branch_share"] = round(
        out["batched_full"]["aggregation_signature_share"]
        - out["batched_fedavg_only"]["aggregation_signature_share"], 4)
    out["hostbound"] = {
        "serial_cell": out["serial_cell"]["host_bound_fraction"],
        "batched_full": out["batched_full"]["host_bound_fraction"],
        "batched_fedavg_only":
            out["batched_fedavg_only"]["host_bound_fraction"],
    }
    share = out["aggregation_branch_share"]
    out["verdict"] = (
        f"robust-aggregation branches cost {share:.1%} of batched device "
        "self-time beyond the fedavg-only control"
        + (" — all-branches switch overhead alone does NOT explain the "
           "0.61x warm loss; see the host-bound fractions for the "
           "dispatch side" if share < 0.2 else
           " — consistent with the all-branches switch hypothesis"))
    return out


def measure_contention(log_path: str, jobs: int = 6, reps: int = 2) -> dict:
    """Multi-tenant contention bench (ISSUE 15): the SAME N-job mixed
    workload burst-submitted to an in-process RunService under the
    preemptive scheduler vs the legacy serialized (oldest-first)
    dispatch, one device slot each.

    Protocol (the alternating-order paired-means discipline of
    --matrix-compare): an untimed warmup batch first — it absorbs the
    one-off compiles AND seeds a ledger whose records give the packer
    real fingerprint-peer prices — then ``reps`` timed rep pairs with
    the variant order alternating per rep.  The workload is adversarial
    for FIFO on purpose: long low-priority jobs submitted FIRST, short
    high-priority jobs behind them, so serialized dispatch convoys the
    shorts while the scheduler's band-then-SJF order services them
    early.  Headline = scheduler throughput; vs_baseline = ratio over
    serialized (same jobs, same slot — it must not be < 1 beyond
    noise, because with no mid-run preemption the batch is
    work-conserving either way).  The detail carries the packer's
    accuracy evidence: leave-one-out ``validate_predictions`` over the
    rep ledger plus per-job predicted-vs-measured factors (the 2x
    cost-validate contract the scheduler's decisions lean on)."""
    import os
    import statistics

    from attackfl_tpu.service.daemon import RunService

    root = os.path.join(log_path, "contention")
    if os.path.isdir(root):
        import shutil

        shutil.rmtree(root)
    os.makedirs(root, exist_ok=True)
    # one shape for every job (shared compile), rounds/priority mixed;
    # submission order = longest+lowest first (FIFO's worst case)
    config = {
        "server": {
            "num-round": 2, "clients": 3, "mode": "fedavg",
            "model": "CNNModel", "data-name": "ICU", "validation": False,
            "train-size": 256, "test-size": 128, "random-seed": 1,
            "data-distribution": {"num-data-range": [48, 64]},
        },
        "learning": {"epoch": 1, "batch-size": 32},
    }
    # rounds sized so training dominates the ~3s/job fixed trace +
    # cache-load overhead (which is order-invariant noise both variants
    # pay identically)
    rounds_pattern = [8, 2, 5, 2, 8, 5]
    priority_pattern = ["low", "high", "normal", "high", "low", "normal"]
    specs = [{"config": config, "num_rounds": rounds_pattern[i % 6],
              "name": f"contend-{i}", "priority": priority_pattern[i % 6]}
             for i in range(jobs)]
    # ... plus ONE matrix sweep riding the same queue (the satellite's
    # "runs + one sweep" mixed workload): 2 cells, priced per-cell
    grid = {"attacks": ["LIE"], "attack-clients": 1, "attack-round": 2,
            "defenses": ["fedavg", "median"], "seeds": [1], "rounds": 4}
    sweep_spec = {"type": "matrix", "name": "contend-sweep",
                  "priority": "normal", "config": config, "grid": grid}
    specs.insert(min(3, len(specs)), dict(sweep_spec))

    def job_events(spool: str) -> dict[str, dict[str, float]]:
        """job_id -> {submitted: ts, started: ts (first)} from the
        service event stream — wait is identical bookkeeping for both
        variants (same queue, same spawn path)."""
        stamps: dict[str, dict[str, float]] = {}
        with open(os.path.join(spool, "service.events.jsonl")) as fh:
            for line in fh:
                event = json.loads(line)
                if event.get("kind") != "job":
                    continue
                per = stamps.setdefault(event.get("job_id", ""), {})
                action = event.get("action")
                if action in ("submitted", "started") and action not in per:
                    per[action] = event["ts"]
                if action == "completed":
                    per[action] = event["ts"]  # last one wins (resume)
        return stamps

    def run_batch(variant: str, tag: str, seed_ledger: str | None,
                  batch: list[dict]) -> dict:
        spool = os.path.join(root, f"{variant}-{tag}")
        if seed_ledger and os.path.isdir(seed_ledger):
            import shutil

            shutil.copytree(seed_ledger, os.path.join(spool, "ledger"))
        svc = RunService(spool, port=0, max_workers=1, run_monitors=False,
                         poll_interval=0.02, worker_backoff=0.05,
                         worker_backoff_cap=0.2,
                         scheduler=(variant == "scheduler"))
        try:
            ids = [svc.submit(dict(spec)) for spec in batch]
            t0 = time.perf_counter()
            svc.start()
            deadline = t0 + 900.0
            while time.perf_counter() < deadline:
                # one queue scan per poll, coarse interval: queue.get()
                # is a full sealed-entry rescan, and a hot poll loop
                # steals CPU from the single-core training it measures
                snapshot = {j.job_id: j.state for j in svc.queue.jobs()}
                states = {i: snapshot.get(i, "unknown") for i in ids}
                if all(s == "done" for s in states.values()):
                    break
                if any(s in ("failed", "cancelled") for s in states.values()):
                    raise RuntimeError(f"contention job died: {states}")
                time.sleep(0.2)
            else:
                raise RuntimeError("contention batch timed out")
            makespan = time.perf_counter() - t0
            preemptions = sum(
                int((svc.queue.get(i).status or {}).get("preemptions", 0))
                for i in ids)
        finally:
            svc.drain(timeout=10.0)
            svc.close()
        stamps = job_events(spool)
        waits = {i: stamps[i]["started"] - stamps[i]["submitted"]
                 for i in ids if "started" in stamps.get(i, {})}
        # total in-worker execution time: makespan - service_s is the
        # dispatch overhead the variants actually differ by
        service = sum(s["completed"] - s["started"] for s in stamps.values()
                      if "completed" in s and "started" in s)
        by_priority: dict[str, list[float]] = {}
        for i, spec in zip(ids, batch):
            if i in waits:
                by_priority.setdefault(spec["priority"], []).append(waits[i])
        return {
            "spool": spool, "makespan_s": round(makespan, 3),
            "service_s": round(service, 3),
            "mean_wait_s": round(statistics.mean(waits.values()), 3),
            "wait_by_priority": {p: round(statistics.mean(v), 3)
                                 for p, v in sorted(by_priority.items())},
            "preemptions": preemptions,
        }

    # untimed warmup: compiles + a seeded ledger (fingerprint peers for
    # the packer's "peer" pricing method in the timed scheduler reps)
    warm = run_batch("scheduler", "warmup", None,
                     [{"config": config, "num_rounds": 1,
                       "name": "contend-warmup", "priority": "normal"},
                      dict(sweep_spec, name="contend-warmup-sweep")])
    seed_ledger = os.path.join(warm["spool"], "ledger")

    per_variant: dict[str, list[dict]] = {"serialized": [], "scheduler": []}
    for rep in range(reps):
        order = ["serialized", "scheduler"]
        for variant in (order if rep % 2 == 0 else reversed(order)):
            per_variant[variant].append(
                run_batch(variant, f"rep{rep}", seed_ledger, specs))

    def mean(values: list[float]) -> float:
        return round(sum(values) / len(values), 3)

    total = len(specs)
    out: dict = {
        "config": f"contention: {jobs} runs (rounds "
                  f"{rounds_pattern[:jobs]}, priorities "
                  f"{priority_pattern[:jobs]}) + 1 matrix sweep "
                  f"({len(grid['defenses'])} cells), 1 slot, "
                  f"{reps} rep(s)",
        "jobs": total, "reps": reps,
    }
    for variant, rows in per_variant.items():
        makespans = [r["makespan_s"] for r in rows]
        out[variant] = {
            "makespan_s_mean": mean(makespans),
            "service_s_mean": mean([r["service_s"] for r in rows]),
            "mean_wait_s": mean([r["mean_wait_s"] for r in rows]),
            "wait_by_priority": rows[-1]["wait_by_priority"],
            "preemptions": sum(r["preemptions"] for r in rows),
            "jobs": total,
            "per_rep": makespans,
            "throughput_jobs_per_s": round(total / mean(makespans), 4),
        }
    out["throughput_ratio"] = round(
        out["scheduler"]["throughput_jobs_per_s"]
        / out["serialized"]["throughput_jobs_per_s"], 4)
    out["wait_ratio"] = round(
        out["scheduler"]["mean_wait_s"]
        / max(out["serialized"]["mean_wait_s"], 1e-9), 4)

    # the packer's accuracy contract: replay the last scheduler rep's
    # ledger through leave-one-out validation, and price each submitted
    # spec against its measured wall (records matched by round count —
    # every job of one length is the same program here)
    from attackfl_tpu.costmodel.estimate import validate_predictions
    from attackfl_tpu.ledger.store import LedgerStore
    from attackfl_tpu.scheduler.pricing import JobPricer

    last_spool = per_variant["scheduler"][-1]["spool"]
    records, _ = LedgerStore(os.path.join(last_spool, "ledger")).load()
    validation = validate_predictions(records)
    validation.pop("rows", None)  # summary only; rows are per-record noise
    pricer = JobPricer(os.path.join(warm["spool"], "ledger"))
    seeded_ids = {r.get("record_id")
                  for r in LedgerStore(seed_ledger).load()[0]}
    fresh = [r for r in records if r.get("record_id") not in seeded_ids
             and not r.get("cell")]  # per-cell sweep records priced apart
    per_job = []
    for spec in specs:
        if spec.get("type") == "matrix":
            continue
        priced = pricer.price(spec)
        measured = [r.get("wall_seconds") for r in fresh
                    if r.get("rounds") == spec["num_rounds"]
                    and isinstance(r.get("wall_seconds"), (int, float))]
        if not measured:
            continue
        actual = statistics.median(measured)
        factor = max(priced["predicted_seconds"] / actual,
                     actual / priced["predicted_seconds"])
        per_job.append({"name": spec["name"],
                        "rounds": spec["num_rounds"],
                        "method": priced["method"],
                        "predicted_s": round(priced["predicted_seconds"], 3),
                        "measured_s": round(actual, 3),
                        "error_factor": round(factor, 3)})
    factors = [row["error_factor"] for row in per_job]
    sweep_price = pricer.price(sweep_spec)
    sweep_walls = [r.get("wall_seconds") for r in records
                   if r.get("record_id") not in seeded_ids and r.get("cell")
                   and isinstance(r.get("wall_seconds"), (int, float))]
    if sweep_walls:
        sweep_price["measured_s"] = round(sum(sweep_walls), 3)
    out["cost_contract"] = {
        "leave_one_out": validation,
        "per_job": per_job,
        "sweep": sweep_price,
        "worst_job_factor": round(max(factors), 3) if factors else None,
        "within_2x": bool(factors) and max(factors) <= 2.0,
    }
    return out


def mesh_sweep_config(log_path: str = "/tmp/attackfl_bench"):
    """The mesh-sweep workload: 64-client ICU Transformer under FedAvg
    with LIE attackers and threefry keys (the shard_map gate — rbg
    hardware bits are batch-shape-dependent, parallel/shard).  64 clients
    divide every swept device count (1/2/4/8)."""
    from attackfl_tpu.config import AttackSpec, Config

    return Config(
        num_round=4, total_clients=64, mode="fedavg",
        model="TransformerModel", data_name="ICU",
        attacks=(AttackSpec(mode="LIE", num_clients=12, attack_round=2),),
        genuine_rate=0.5, epochs=1, batch_size=64,
        num_data_range=(192, 256), train_size=4096, test_size=512,
        validation=True, prng_impl="threefry2x32",
        **{k: v for k, v in _base_kwargs(log_path).items()
           if k in ("log_path", "checkpoint_dir", "telemetry")},
    )


def measure_mesh_child(rounds: int, log_path: str, reps: int = 3) -> dict:
    """ONE device count's measurements (runs inside a subprocess whose
    XLA_FLAGS pinned the virtual device count before jax init): the
    shard_map fused executor's steady rounds/s and the cell-sharded
    matrix sweep's wall, each rep from a fresh state after an untimed
    warm-up dispatch (compile excluded — scaling is a steady-state
    question)."""
    import os

    import jax

    from attackfl_tpu.matrix.grid import grid_from_dict
    from attackfl_tpu.training.engine import Simulator
    from attackfl_tpu.training.matrix_exec import MatrixRun

    os.makedirs(log_path, exist_ok=True)
    ndev = len(jax.devices())
    out: dict = {"devices": ndev}

    # --- fused executor over the client mesh ---------------------------
    cfg = mesh_sweep_config(log_path)
    sim = Simulator(cfg, use_mesh=True)
    assert (sim.mesh is not None and sim.mesh.size == ndev
            and (ndev == 1 or sim.mesh_strategy == "shard_map")), (
        ndev, sim.mesh_strategy)
    # warm the SAME chunk-length program the timed reps dispatch (a
    # different scan length is a different compiled program)
    sim.run_fast(num_rounds=rounds, state=sim.init_state(),
                 chunk_size=rounds, save_checkpoints=False, verbose=False)
    fused_rates = []
    for _ in range(reps):
        state = sim.init_state()
        t0 = time.perf_counter()
        _, hist = sim.run_fast(num_rounds=rounds, state=state,
                               chunk_size=rounds, save_checkpoints=False,
                               verbose=False)
        fused_rates.append(round(len(hist) / (time.perf_counter() - t0), 4))
    sim.close()
    out["fused"] = {
        "rounds_per_sec_steady": max(fused_rates),
        "rounds_per_sec_mean": round(sum(fused_rates) / len(fused_rates), 4),
        "per_rep": fused_rates,
        "mesh_strategy": "shard_map" if ndev > 1 else "shard_map[1dev]",
    }

    # --- cell-sharded matrix sweep -------------------------------------
    mcfg = cfg.replace(num_round=rounds, total_clients=16,
                       num_data_range=(64, 96), attacks=())
    grid = grid_from_dict({
        "attacks": ["LIE"], "attack-clients": 3, "attack-round": 2,
        # 4 batched defenses x 2 seeds = 8 cells: divides every swept
        # device count, all on the ONE vmapped grid program (FLTrust's
        # sequential lax.map stays replicated by design and would only
        # blur the cell-axis scaling being measured)
        "defenses": ["fedavg", "median", "trimmed_mean", "krum"],
        "seeds": [1, 2], "rounds": rounds,
    })
    walls = []
    cells = None
    # 2 reps: rep 0 pays the sweep compile (reported as wall_s_cold),
    # rep 1 is the steady wall the scaling column reads
    for rep in range(2):
        scratch = os.path.join(log_path, f"mesh_matrix_{ndev}_{rep}")
        os.makedirs(scratch, exist_ok=True)
        runner = MatrixRun(
            mcfg.replace(log_path=scratch, checkpoint_dir=scratch),
            grid, use_mesh=ndev > 1)
        cells = len(runner.device_cells)
        t0 = time.perf_counter()
        runner.run(save_checkpoints=False, verbose=False)
        walls.append(round(time.perf_counter() - t0, 4))
        runner.close()
    # first rep pays the sweep compile; steady wall = the later reps
    steady = walls[1:] or walls
    wall = sum(steady) / len(steady)
    out["matrix"] = {
        "cells": cells,
        "wall_s_mean": round(wall, 4),
        "wall_s_cold": walls[0],
        "per_rep": walls,
        "rounds_per_sec_steady": round(cells * rounds / wall, 4),
    }
    return out


def run_mesh_sweep(rounds: int, log_path: str,
                   device_counts: tuple[int, ...] = (1, 2, 4, 8)) -> dict:
    """1→2→4→8 virtual-device scaling of the mesh-native executors
    (ISSUE 12): each device count runs in a FRESH subprocess whose
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` lands before
    jax initializes (device count is process-global).

    CPU-HONEST FRAMING: virtual CPU devices share one host's cores, so
    this curve proves the sharded programs are correct and bounds their
    partitioning overhead — it does NOT demonstrate speedup.  The same
    sweep run on a real multi-chip slice (the committed artifact's
    ``armed_for`` note) measures true scaling; re-run when the TPU
    tunnel returns."""
    import os
    import re
    import subprocess
    import sys

    out: dict = {
        "config": "mesh-sweep: 64-client ICU Transformer fedavg+LIE "
                  "(threefry/shard_map) + 8-cell matrix sweep",
        "timed_rounds_per_rep": rounds,
        "device_counts": list(device_counts),
        "cpu_honest_note": (
            "virtual devices share one host's cores: this curve is a "
            "correctness-plus-overhead artifact, armed to show real "
            "scaling when re-run on a multi-chip slice"),
        "by_devices": {},
    }
    for n in device_counts:
        env = dict(os.environ)
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       env.get("XLA_FLAGS", ""))
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("ATTACKFL_LEDGER_DIR", None)  # only the parent appends
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--mesh-child", str(n), "--rounds", str(rounds)],
            capture_output=True, text=True, env=env, timeout=1800,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode != 0:
            raise RuntimeError(
                f"mesh-sweep child for {n} device(s) failed "
                f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}")
        child = json.loads(proc.stdout.strip().splitlines()[-1])
        assert child["devices"] == n, child
        out["by_devices"][str(n)] = child
    base = out["by_devices"][str(device_counts[0])]
    for workload in ("fused", "matrix"):
        ref = base[workload]["rounds_per_sec_steady"]
        out[f"{workload}_speedup"] = {
            str(n): round(
                out["by_devices"][str(n)][workload]["rounds_per_sec_steady"]
                / ref, 4)
            for n in device_counts}
    return out


def measure_compile_cache(cfg, n_rounds: int, cache_dir: str) -> dict:
    """First-run vs warm-cache compile cost of the fused round program.

    Enables the persistent compilation cache, compiles + runs the scan
    once (cold unless the cache dir is already warm), then drops the
    in-process jit caches (jax.clear_caches) and compiles again through a
    FRESH Simulator — the second compile must be served from the on-disk
    cache, standing in for a process restart."""
    import jax

    from attackfl_tpu.telemetry.xla import (compile_cache_stats,
                                            enable_compile_cache)
    from attackfl_tpu.training.engine import Simulator

    enable_compile_cache(cache_dir)

    def one_pass() -> dict:
        before = compile_cache_stats()
        sim = Simulator(cfg)
        state = sim.init_state()
        t0 = time.perf_counter()
        state, metrics = sim.run_scan(state, n_rounds)
        jax.block_until_ready(metrics)
        total = time.perf_counter() - t0
        sim.close()
        after = compile_cache_stats()
        return {
            "compile_plus_run_s": round(total, 3),
            "backend_compile_s": round(
                after["backend_compile_seconds"]
                - before["backend_compile_seconds"], 3),
            "cache_retrieval_s": round(
                after["cache_retrieval_seconds"]
                - before["cache_retrieval_seconds"], 3),
            "cache_hits": after["cache_hits"] - before["cache_hits"],
            "cache_misses": after["cache_misses"] - before["cache_misses"],
        }

    cold = one_pass()
    jax.clear_caches()  # drop in-memory jit caches; disk cache survives
    warm = one_pass()
    return {"cache_dir": cache_dir, "rounds": n_rounds,
            "first_run": cold, "warm_cache": warm,
            "compile_seconds_saved": round(
                cold["backend_compile_s"] - warm["backend_compile_s"], 3)}


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", type=int, default=None,
                        help="single BASELINE config 1-5 (default: headline suite)")
    parser.add_argument("--backend", choices=["xla", "pallas"], default=None)
    parser.add_argument("--dtype", choices=["float32", "bfloat16"], default=None,
                        help="compute dtype for the xla local-training "
                             "backend (mesh.compute-dtype)")
    parser.add_argument("--hyper-update", choices=["sequential", "batched"],
                        default=None,
                        help="hyper-mode server update variant (config 2): "
                             "reference-faithful O(C) sequential scan vs "
                             "one batched Adam step per round")
    parser.add_argument("--clients", type=int, default=None)
    parser.add_argument("--rounds", type=int, default=4,
                        help="timed rounds per measurement")
    parser.add_argument("--north-star", action="store_true",
                        help="measure ONLY the 1000-client north-star row")
    parser.add_argument("--e2e-rounds", type=int, default=None, metavar="N",
                        help="measure ONLY an N-round end-to-end run_fast "
                             "(compile + run) of the headline config")
    parser.add_argument("--skip-north-star", action="store_true")
    parser.add_argument("--deadline", type=float, default=2400.0,
                        help="whole-run wall-clock budget (s); on expiry the "
                             "bench prints best-so-far JSON and exits 3 "
                             "instead of hanging on a wedged TPU dispatch")
    parser.add_argument("--trace", type=str, default=None,
                        help="capture a jax.profiler trace of the timed "
                             "section into this directory (single-row mode)")
    parser.add_argument("--pipeline-compare", action="store_true",
                        help="measure ONLY steady-state rounds/s of the "
                             "synchronous default vs pipeline=True + async "
                             "checkpointing on the same config")
    parser.add_argument("--depth-sweep", action="store_true",
                        help="measure ONLY the depth-vs-throughput curve "
                             "of the depth-k pipelined executor (k in "
                             "{0,1,2,4,8}, alternating-order paired "
                             "means) plus the ledger-driven `auto` pick "
                             "validation (--rounds rounds per rep)")
    parser.add_argument("--numerics-overhead", action="store_true",
                        help="measure ONLY steady-state rounds/s of the "
                             "pipelined executor with telemetry.numerics "
                             "off vs on (the in-graph metric set), plus "
                             "the bit-identical-params check")
    parser.add_argument("--matrix-compare", action="store_true",
                        help="measure ONLY the serial 45-run sweep vs the "
                             "batched scenario-matrix program (5 attacks x "
                             "9 defenses, cold + warm walls, paired means; "
                             "--rounds rounds per cell)")
    parser.add_argument("--hotspots-matrix", action="store_true",
                        help="measure ONLY the profiled op-level "
                             "attribution of the warm dispatch paths: "
                             "one warm serial cell vs the warm batched "
                             "sweep vs a fedavg-only batched control, "
                             "each mined for host-bound fraction and "
                             "the robust-aggregation branch share "
                             "(evidence on the BENCH_MATRIX 0.61x "
                             "lax.switch hypothesis; --rounds rounds)")
    parser.add_argument("--contention", action="store_true",
                        help="measure ONLY the multi-tenant contention "
                             "bench: a 6-job mixed-priority workload "
                             "burst-submitted to the preemptive "
                             "scheduler vs serialized oldest-first "
                             "dispatch (one slot, alternating-order "
                             "paired means, packer cost-contract "
                             "evidence in the detail)")
    parser.add_argument("--contention-jobs", type=int, default=6,
                        help="jobs per batch for --contention")
    parser.add_argument("--contention-reps", type=int, default=3,
                        help="timed rep pairs for --contention")
    parser.add_argument("--matrix-seeds", type=int, default=1,
                        help="seeds per cell for --matrix-compare")
    parser.add_argument("--compile-cache", nargs="?", type=str, default=None,
                        const="/tmp/attackfl_compile_cache", metavar="DIR",
                        help="measure ONLY first-run vs warm-cache compile "
                             "seconds of the fused round program "
                             "(persistent compilation cache in DIR; "
                             "composes with --config/--clients/--rounds; "
                             "default workload: BASELINE config 1)")
    parser.add_argument("--mesh-sweep", action="store_true",
                        help="measure ONLY the 1/2/4/8 virtual-device "
                             "scaling of the mesh-native executors "
                             "(shard_map fused + cell-sharded matrix; "
                             "one subprocess per device count — XLA's "
                             "device count is process-global)")
    parser.add_argument("--mesh-child", type=int, default=None,
                        metavar="N", help=argparse.SUPPRESS)
    args = parser.parse_args()

    if args.mesh_child is not None:
        # mesh-sweep subprocess: XLA_FLAGS already pinned by the parent
        print(json.dumps(measure_mesh_child(args.rounds,
                                            "/tmp/attackfl_bench")))
        return

    if sum(map(bool, (args.config is not None and args.compile_cache is None,
                      args.north_star, args.e2e_rounds is not None,
                      args.pipeline_compare, args.numerics_overhead,
                      args.depth_sweep, args.matrix_compare,
                      args.hotspots_matrix,
                      args.mesh_sweep, args.contention,
                      args.compile_cache is not None))) > 1:
        parser.error("--config / --north-star / --e2e-rounds / "
                     "--pipeline-compare / --numerics-overhead / "
                     "--depth-sweep / --matrix-compare / --hotspots-matrix "
                     "/ --mesh-sweep / "
                     "--contention / --compile-cache are exclusive")
    single = (args.config is not None or args.north_star
              or args.e2e_rounds is not None or args.pipeline_compare
              or args.numerics_overhead or args.depth_sweep
              or args.matrix_compare or args.mesh_sweep
              or args.contention or args.hotspots_matrix
              or args.compile_cache is not None)
    if not single and (args.backend or args.clients or args.trace or args.dtype
                       or args.hyper_update):
        parser.error("--backend/--clients/--dtype/--hyper-update/--trace "
                     "apply to a single measurement; add --config N / "
                     "--north-star / --e2e-rounds")
    if args.clients and args.config is None and args.compile_cache is None:
        parser.error("--clients applies to --config rows")
    if args.hyper_update and args.config != 2:
        parser.error("--hyper-update applies to --config 2 (hyper mode)")
    if args.e2e_rounds is not None and args.backend:
        parser.error("--e2e-rounds measures the xla run_fast path; --backend "
                     "does not apply")

    if args.north_star:
        metric_name = "fl_rounds_per_sec_1000c"
    elif args.pipeline_compare:
        metric_name = "fl_pipeline_vs_sync_rounds_per_sec"
    elif args.numerics_overhead:
        metric_name = "fl_numerics_on_rounds_per_sec"
    elif args.depth_sweep:
        metric_name = "fl_depth_sweep_rounds_per_sec"
    elif args.matrix_compare:
        metric_name = "fl_matrix_vs_serial_sweep"
    elif args.hotspots_matrix:
        metric_name = "fl_hotspots_matrix_attribution"
    elif args.contention:
        metric_name = "fl_contention_sched_vs_serial"
    elif args.mesh_sweep:
        metric_name = "fl_mesh_sweep_scaling"
    elif args.compile_cache is not None:
        metric_name = "fl_compile_cache_warm_vs_cold_s"
    elif args.e2e_rounds is not None:
        metric_name = f"fl_e2e_{args.e2e_rounds}_rounds_per_sec"
    elif args.config is not None:
        metric_name = f"fl_rounds_per_sec_config{args.config}"
    else:
        metric_name = "fl_rounds_per_sec_100c"
    cancel_watchdog = tpu_init_watchdog(metric_name)

    # Whole-run deadline: a TPU dispatch can wedge indefinitely when the
    # axon tunnel drops mid-run (observed: blocked in an RPC that neither
    # returns nor delivers SIGINT).  Emit whatever was measured so the
    # driver still records a JSON line.
    partial: dict = {}

    def _deadline():
        import os
        best = [(k, v["rounds_per_sec"]) for k, v in
                partial.get("backends_100c", {}).items()
                if isinstance(v, dict) and "rounds_per_sec" in v]
        # single-measurement modes write into `partial` directly
        # (measure(..., progress=partial) / run_fast(progress=partial)) —
        # pick up a completed rate, an interim host-path rate, or an
        # incl-compile rate (best-so-far beats an unconditional 0.0; the
        # incl-compile rates get their own vs label, ADVICE r3 #3)
        incl_compile = False
        for k in ("rounds_per_sec", "interim_rounds_per_sec",
                  "interim_rounds_per_sec_incl_compile",
                  "warmup_rounds_per_sec_incl_compile"):
            if k in partial:
                best.append((k, partial[k]))
                incl_compile = k.endswith("incl_compile")
                break
        value = max((r for _, r in best), default=0.0)
        vs_key = ("vs_north_star_incl_compile" if incl_compile
                  else "vs_baseline")
        from attackfl_tpu.telemetry import metric_line

        print(json.dumps(metric_line(
            metric_name, value, unit="rounds/s",
            **{vs_key: round(value / NORTH_STAR_ROUNDS_PER_SEC, 4)},
            detail={**partial,
                    "error": f"deadline {args.deadline:.0f}s expired "
                             "(TPU dispatch wedged?); partial results"},
        )), flush=True)
        os._exit(3)

    import threading

    deadline_timer = threading.Timer(args.deadline, _deadline)
    deadline_timer.daemon = True
    deadline_timer.start()

    import jax

    from attackfl_tpu.parallel.mesh import is_tpu_backend

    on_tpu = is_tpu_backend()  # axon registers as "axon", not "tpu"
    cancel_watchdog()

    from attackfl_tpu.telemetry import metric_line

    def finish(res: dict, value_key: str = "rounds_per_sec",
               vs_key: str = "vs_baseline") -> None:
        # vs_key: --e2e-rounds divides an including-compile rate by the
        # steady-state north-star constant; label it distinctly so table
        # consumers don't compare incompatible denominators (ADVICE r3 #3)
        deadline_timer.cancel()
        line = metric_line(
            metric_name, res[value_key], unit="rounds/s",
            **{vs_key: round(res[value_key] / NORTH_STAR_ROUNDS_PER_SEC, 4)},
            detail=res,
        )
        ledger_append(line)
        print(json.dumps(line))

    if args.numerics_overhead:
        deadline_timer.cancel()
        res = measure_numerics_overhead(args.rounds, "/tmp/attackfl_bench")
        partial.update(res)
        line = metric_line(
            metric_name, res["metrics_on"]["rounds_per_sec_steady"],
            unit="rounds/s",
            overhead_pct=res["overhead_pct"],
            bit_identical_params=res["bit_identical_params"],
            detail=res,
        )
        ledger_append(line)
        print(json.dumps(line))
        return

    if args.depth_sweep:
        deadline_timer.cancel()
        res = measure_depth_sweep(args.rounds, "/tmp/attackfl_bench")
        partial.update(res)
        best = res["by_depth"][str(res["measured_optimum_depth"])]
        line = metric_line(
            metric_name, best["rounds_per_sec_steady"], unit="rounds/s",
            measured_optimum_depth=res["measured_optimum_depth"],
            auto_depth=(res.get("auto_pick") or {}).get("depth"),
            auto_within_one_step=res.get("auto_within_one_step"),
            detail=res,
        )
        ledger_append(line)
        print(json.dumps(line))
        return

    if args.mesh_sweep:
        deadline_timer.cancel()
        res = run_mesh_sweep(args.rounds, "/tmp/attackfl_bench")
        partial.update(res)
        top = str(max(res["device_counts"]))
        line = metric_line(
            metric_name, res["fused_speedup"][top], unit="x",
            matrix_speedup=res["matrix_speedup"][top],
            devices=res["device_counts"],
            detail=res,
        )
        ledger_append(line)
        print(json.dumps(line))
        return

    if args.contention:
        deadline_timer.cancel()
        res = measure_contention("/tmp/attackfl_bench",
                                 jobs=args.contention_jobs,
                                 reps=args.contention_reps)
        partial.update(res)
        line = metric_line(
            metric_name, res["scheduler"]["throughput_jobs_per_s"],
            unit="jobs/s",
            vs_baseline=res["throughput_ratio"],
            wait_ratio=res["wait_ratio"],
            detail=res,
        )
        ledger_append(line)
        print(json.dumps(line))
        return

    if args.matrix_compare:
        deadline_timer.cancel()
        res = measure_matrix_compare(args.rounds, "/tmp/attackfl_bench",
                                     seeds=args.matrix_seeds)
        partial.update(res)
        line = metric_line(
            metric_name, res["speedup_cold"], unit="x",
            speedup_warm=res["speedup_warm"],
            compile_once_saving_s=res["compile_once_saving_s"],
            detail=res,
        )
        ledger_append(line)
        print(json.dumps(line))
        return

    if args.hotspots_matrix:
        deadline_timer.cancel()
        res = measure_hotspots_matrix(args.rounds, "/tmp/attackfl_bench")
        partial.update(res)
        line = metric_line(
            metric_name, res["aggregation_branch_share"], unit="share",
            hostbound=res["hostbound"],
            verdict=res["verdict"],
            detail=res,
        )
        ledger_append(line)
        print(json.dumps(line))
        return

    if args.pipeline_compare:
        deadline_timer.cancel()
        res = measure_pipeline_compare(args.rounds, "/tmp/attackfl_bench")
        partial.update(res)
        line = metric_line(
            metric_name, res["pipelined_async_ckpt"]["rounds_per_sec_steady"],
            unit="rounds/s",
            vs_sync=res["speedup"],
            detail=res,
        )
        ledger_append(line)
        print(json.dumps(line))
        return

    if args.compile_cache is not None:
        # default workload: BASELINE config 1 with shrunk per-round data —
        # the object of measurement is COMPILE seconds (the program is the
        # same scan body; data sizes only stretch the timed run portion,
        # which on a CPU box would dwarf the compile split being proven)
        if args.config is not None:
            cfg = make_config(args.config)
        else:
            cfg = make_config(1).replace(
                num_data_range=(256, 512), train_size=4096, test_size=1024)
        if args.clients:
            cfg = cfg.replace(total_clients=args.clients)
        if args.backend:
            cfg = cfg.replace(local_backend=args.backend)
        if args.dtype:
            cfg = _with_dtype(cfg, args.dtype)
        res = measure_compile_cache(cfg, max(args.rounds, 2), args.compile_cache)
        deadline_timer.cancel()
        line = metric_line(
            metric_name, res["warm_cache"]["backend_compile_s"], unit="s",
            cold_backend_compile_s=res["first_run"]["backend_compile_s"],
            detail=res,
        )
        ledger_append(line)
        print(json.dumps(line))
        return

    if args.north_star:  # 1000-client row (BASELINE.json target workload)
        cfg = north_star_config()
        if args.backend:
            cfg = cfg.replace(local_backend=args.backend)
        if args.dtype:
            cfg = _with_dtype(cfg, args.dtype)
        partial["config"] = "north star: 1000 clients, 200 LIE attackers"
        res = measure(cfg, 2, trace_dir=args.trace, progress=partial)
        res["vs_north_star"] = round(
            res["rounds_per_sec"] / NORTH_STAR_ROUNDS_PER_SEC, 4)
        finish(res)
        return

    if args.e2e_rounds is not None:  # full run incl. compile (VERDICT r2 #4)
        from attackfl_tpu.training.engine import Simulator

        cfg = make_config(4).replace(num_round=args.e2e_rounds)
        if args.dtype:
            cfg = _with_dtype(cfg, args.dtype)
        partial["config"] = (f"headline config 4, {args.e2e_rounds} rounds "
                             "end-to-end incl. compile")
        sim = Simulator(cfg)
        t0 = time.time()
        _, hist = sim.run_fast(save_checkpoints=False, verbose=False,
                               progress=partial)
        total = time.time() - t0
        ok = sum(1 for h in hist if h["ok"])
        res = {"total_s": round(total, 1), "ok_rounds": ok,
               "rounds_per_sec_incl_compile": round(ok / total, 4)}
        auc = hist[-1].get("roc_auc")
        if auc is not None and auc == auc:  # NaN-guard: keep JSON strict
            res["roc_auc_final"] = round(auc, 4)
        finish(res, value_key="rounds_per_sec_incl_compile",
               vs_key="vs_north_star_incl_compile")
        return

    if args.config is not None:  # single-row mode (BASELINE.md table filling)
        cfg = make_config(args.config)
        if args.clients:
            cfg = cfg.replace(total_clients=args.clients)
        if args.backend:
            cfg = cfg.replace(local_backend=args.backend)
        if args.dtype:
            cfg = _with_dtype(cfg, args.dtype)
        if args.hyper_update:
            cfg = cfg.replace(hyper_update_mode=args.hyper_update)
        partial["config"] = f"BASELINE config {args.config}"
        res = measure(cfg, args.rounds, trace_dir=args.trace, progress=partial)
        finish(res)
        return

    # ---- headline suite (driver default) --------------------------------
    detail: dict = {
        "config": "ICU TransformerModel, 100 clients, FedAvg + 20 LIE attackers",
        "baseline_note": (
            "north star = 1000 clients x 100 rounds < 60 s on v4-8 "
            "(4 chips => 250 clients/chip); this chip runs the FULL "
            "1000-client workload in north_star_1000c"
        ),
    }
    results = {}
    partial.update(detail)
    partial["backends_100c"] = results
    cfg4 = make_config(4)
    results["xla"] = measure(cfg4, args.rounds)
    if on_tpu:
        # bf16 local training rides the MXU's native dtype
        # (mesh.compute-dtype; master weights/Adam stay f32 — local.py)
        try:
            results["xla_bf16"] = measure(
                _with_dtype(cfg4, "bfloat16"), args.rounds)
        except Exception as e:  # noqa: BLE001
            results["xla_bf16"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        # the Pallas fused kernel is TPU-only (interpret mode is a CPU
        # correctness path, not a perf path — ops/fused_step.py)
        try:
            results["pallas"] = measure(
                cfg4.replace(local_backend="pallas"), args.rounds)
        except Exception as e:  # noqa: BLE001 — bench must survive kernel regressions
            results["pallas"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    detail["backends_100c"] = results

    best_name, best = max(
        ((k, v) for k, v in results.items() if "rounds_per_sec" in v),
        key=lambda kv: kv[1]["rounds_per_sec"],
    )
    detail["best_backend"] = best_name
    detail["roc_auc_final"] = best.get("roc_auc")
    detail["seconds_per_round"] = best["seconds_per_round"]

    # north star is a TPU-scale workload (1000 clients, full reference
    # hyperparameters) — off-TPU it would grind a CPU box for hours.
    # It rides whichever backend variant won the 100-client comparison.
    if not args.skip_north_star and on_tpu:
        try:
            ns_cfg = north_star_config()
            if best_name == "pallas":
                ns_cfg = ns_cfg.replace(local_backend="pallas")
            elif best_name == "xla_bf16":
                ns_cfg = _with_dtype(ns_cfg, "bfloat16")
            ns = measure(ns_cfg, 2)
            ns["backend"] = best_name
            ns["vs_north_star"] = round(
                ns["rounds_per_sec"] / NORTH_STAR_ROUNDS_PER_SEC, 4)
            detail["north_star_1000c"] = ns
        except Exception as e:  # noqa: BLE001
            detail["north_star_1000c"] = {"error": f"{type(e).__name__}: {e}"[:300]}

    deadline_timer.cancel()
    line = metric_line(
        metric_name, best["rounds_per_sec"], unit="rounds/s",
        vs_baseline=round(best["rounds_per_sec"] / NORTH_STAR_ROUNDS_PER_SEC, 4),
        detail=detail,
    )
    ledger_append(line)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
