"""Benchmark: FL rounds/sec on the BASELINE.md headline configuration.

Workload (BASELINE.json config 4 family): ICU TransformerModel, 100
clients, FedAvg, LIE attackers at genuine-rate 0.5, full reference
hyperparameters (5 local epochs, batch 128, 12k-15k samples/client/round —
config.yaml:17-20,31-37), validation on.  The entire round — per-client
Adam training vmapped over the client axis, attack synthesis, weighted
aggregation, ROC-AUC validation — runs as jitted XLA programs on the TPU.

Prints ONE JSON line:
  {"metric": "fl_rounds_per_sec_100c", "value": N, "unit": "rounds/s",
   "vs_baseline": N}

vs_baseline is measured against the driver's north-star rate
(1000 clients x 100 rounds in < 60 s on a v4-8 => 1.667 rounds/s;
/root/repo/BASELINE.json) — the reference itself publishes no numbers
(BASELINE.md), so the north star is the only quantitative anchor.
"""

from __future__ import annotations

import json
import time

import jax

NORTH_STAR_ROUNDS_PER_SEC = 100.0 / 60.0  # BASELINE.json north star


def main() -> None:
    from attackfl_tpu.config import AttackSpec, Config
    from attackfl_tpu.training.engine import Simulator

    cfg = Config(
        num_round=5,
        total_clients=100,
        mode="fedavg",
        model="TransformerModel",
        data_name="ICU",
        num_data_range=(12000, 15000),
        epochs=5,
        batch_size=128,
        lr=0.004,
        clip_grad_norm=1.0,
        genuine_rate=0.5,
        validation=True,
        train_size=20000,
        test_size=4000,
        attacks=(AttackSpec(mode="LIE", num_clients=20, attack_round=2, args=(0.74,)),),
        scan_unroll=4,
        log_path="/tmp/attackfl_bench",
    )
    sim = Simulator(cfg)
    n_rounds = 4

    # warmup: run the same n-round fused scan once (compiles it), excluded
    # from timing
    state = sim.init_state()
    state, metrics = sim.run_scan(state, n_rounds)
    jax.block_until_ready(metrics)
    assert all(map(bool, metrics["ok"])), f"warmup rounds failed: {metrics}"

    t0 = time.perf_counter()
    state, metrics = sim.run_scan(state, n_rounds)
    jax.block_until_ready(metrics)
    elapsed = time.perf_counter() - t0
    rounds_per_sec = n_rounds / elapsed
    assert all(map(bool, metrics["ok"])), f"timed rounds failed: {metrics}"
    metrics = {k: v[-1] for k, v in metrics.items()}

    print(json.dumps({
        "metric": "fl_rounds_per_sec_100c",
        "value": round(rounds_per_sec, 4),
        "unit": "rounds/s",
        "vs_baseline": round(rounds_per_sec / NORTH_STAR_ROUNDS_PER_SEC, 4),
        "detail": {
            "config": "ICU TransformerModel, 100 clients, FedAvg + 20 LIE attackers",
            "roc_auc_final": round(float(metrics.get("roc_auc", float("nan"))), 4),
            "seconds_per_round": round(elapsed / n_rounds, 4),
        },
    }))


if __name__ == "__main__":
    main()
