import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax; jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
import jax.numpy as jnp, numpy as np, optax
from attackfl_tpu.models.icu import TransformerModel
from attackfl_tpu.ops import fused_step as fs

model = TransformerModel(seq1_fast=True)
rng = jax.random.PRNGKey(0)
C, B, N = 8, 16, 64
vit = jax.random.normal(jax.random.PRNGKey(1), (N, 7))
labs = jax.random.normal(jax.random.PRNGKey(2), (N, 16))
lab = (jax.random.uniform(jax.random.PRNGKey(3), (N,)) > 0.5).astype(jnp.float32)
dataset = {"vitals": vit, "labs": labs, "label": lab}

params = model.init(rng, vit[:1], labs[:1])["params"]
stacked = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (C,)+x.shape), params)

# pack/unpack roundtrip
gp = fs.pack_params(stacked)
rt = fs.unpack_params(gp, stacked)
for (pa, a), (pb, b) in zip(jax.tree_util.tree_leaves_with_path(stacked), jax.tree_util.tree_leaves_with_path(rt)):
    assert np.allclose(a, b), pa
print("pack/unpack roundtrip OK")

# one epoch, dropout off, vs JAX reference (same perm schedule)
keys = jax.random.split(jax.random.PRNGKey(9), C)
idx = jnp.stack([jax.random.permutation(jax.random.PRNGKey(100+i), N)[:48] for i in range(C)])
mask = jnp.ones((C, 48), bool)
EPOCHS = 2
upd = fs.build_fused_local_update(dataset, epochs=EPOCHS, batch_size=B, lr=0.004,
                                  clip_grad_norm=1.0, dropout=(0,0,0), g_clients=8, interpret=True)
new_p, ok, loss = upd(params, keys, idx, mask)
print("kernel ok:", np.asarray(ok).all(), "loss:", np.asarray(loss)[:3])

# mirror JAX implementation (no dropout, same perm/Adam/clip)
def loss_fn(p, bvit, blabs, by, bm):
    probs = model.apply({"params": p}, bvit, blabs)[:, 0]
    probs = jnp.clip(probs, 1e-7, 1-1e-7)
    per = -(by*jnp.log(probs) + (1-by)*jnp.log(1-probs))
    return jnp.sum(per*bm)/jnp.maximum(jnp.sum(bm), 1.0)
tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adam(0.004))
def one_client(p, key, cidx, cmask):
    opt = tx.init(p)
    eks = jax.random.split(key, EPOCHS)
    hi = cidx.shape[0]; nb = -(-hi//B); pad = nb*B - hi
    losses = []
    for e in range(EPOCHS):
        k_perm, _ = jax.random.split(eks[e])
        perm = jax.random.permutation(k_perm, hi)
        bidx = jnp.pad(cidx[perm], (0,pad)).reshape(nb,B)
        bmask = jnp.pad(cmask[perm].astype(jnp.float32), (0,pad)).reshape(nb,B)
        el = 0.0
        for j in range(nb):
            l, g = jax.value_and_grad(loss_fn)(p, vit[bidx[j]], labs[bidx[j]], lab[bidx[j]], bmask[j])
            u, opt = tx.update(g, opt, p)
            p = optax.apply_updates(p, u)
            el += l
        losses.append(el/nb)
    return p, losses[-1]
ref_p0, ref_loss0 = one_client(params, keys[0], idx[0], mask[0])

kp0 = jax.tree.map(lambda x: x[0], new_p)
flat_k = jnp.concatenate([x.ravel() for x in jax.tree.leaves(kp0)])
flat_r = jnp.concatenate([x.ravel() for x in jax.tree.leaves(ref_p0)])
diff = float(jnp.abs(flat_k - flat_r).max())
print(f"client-0 param maxdiff vs jax.grad reference: {diff:.2e}")
print(f"loss kernel={float(loss[0]):.6f} ref={float(ref_loss0):.6f}")
assert diff < 2e-4, diff
assert abs(float(loss[0]) - float(ref_loss0)) < 1e-4
print("KERNEL MATH MATCHES AUTODIFF")
